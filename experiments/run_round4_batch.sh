#!/bin/sh
# Round-4 evidence chain (single CPU core — jobs must serialize):
#   1. FedAvg at reference shard sizes (hw1_fl --matched-shards)
#   2. pp_schedules rerun (adds the 16-layer 8-stage interleaved row)
#   3. hw1b pipeline-topology loss curves (pp3 1000 iters, dp2_pp3 600)
#   4. plots + PARITY.md regeneration
# Each stage appends/owns its CSV; a kill between stages loses only the
# stage in flight. Logs to experiments/results/round4_batch.log.
set -x
cd "$(dirname "$0")/.."
LOG=experiments/results/round4_batch.log
{
  echo "=== matched shards $(date) ==="
  python -m experiments.hw1_fl --matched-shards --cpu
  echo "=== pp_schedules $(date) ==="
  python -m experiments.pp_schedules
  echo "=== hw1b pp3 $(date) ==="
  python -m experiments.hw1b_llm --iters 1000 --configs pp3 --append --cpu
  echo "=== hw1b dp2_pp3 $(date) ==="
  python -m experiments.hw1b_llm --iters 600 --configs dp2_pp3 --append --cpu
  echo "=== plots + parity $(date) ==="
  python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
from experiments import plots, parity_report
plots.main()
parity_report.main()
EOF
  echo "=== done $(date) ==="
} > "$LOG" 2>&1
