"""Live SLO monitor: tail a telemetry stream, flag rolling-window breaches.

The watch-it-while-it-runs half of the observability layer (ISSUE 8): the
event stream and obs_report explain a run after the fact; this tool reads
the SAME stream while the run is alive and raises ``slo_violation`` events
the moment a rolling-window objective breaks. Pure stdlib + the telemetry
read helpers — never imports jax — so it runs as a sidecar (or inside the
watchdog, which embeds ``SLOMonitor`` as a health signal next to the
heartbeat).

Objectives (each enabled by passing its threshold):
- ``--ttft-p99``   p99 time-to-first-token (s) over the window
  (``request_done.ttft_s``);
- ``--queue-p99``  p99 queue wait (s) over the window;
- ``--min-tps``    sustained tokens/sec floor — violated only while work
  is OUTSTANDING (enqueued > done), so an idle server is not "stalled";
- ``--max-skip-rate``  StepGuard skips per training step over the window
  (``fault`` counter deltas / ``step`` event step counts);
- ``--heartbeat-stale``  seconds since the heartbeat moved (live mode
  reads heartbeat.json next to the stream; check mode compares the last
  beat to the last event);
- ``--slo-mfu``    MFU floor over the window — achieved FLOP/s from the
  ``compile`` events' HLO flops (normalized per step by each event's own
  ``steps_per_dispatch``, so ragged tail-chunk programs don't skew the
  window) × the window's step count ÷ the window's step time, against
  the manifest's recorded roofline peaks (ROOFLINE.md numbers on chip,
  the calibrated CPU baseline on fallback; schema v5). Caveat, same as
  bench.py's FLOP crosscheck: on jaxlibs whose ``cost_analysis`` counts
  a ``lax.scan`` body once (this container's 0.4.36), a fused K-step
  program's flops read as ONE step's, so chunked-mode MFU is biased low
  by ~K — set the floor from the same stream's observed steady-state
  values, not from first principles;
- ``--slo-gradnorm``  grad-norm spike-rate ceiling: the fraction of the
  window's ``numerics`` samples whose global grad norm exceeds
  ``--gradnorm-factor`` × the window median (the drift signal that
  precedes a StepGuard skip);
- ``--slo-headroom``  OOM-headroom floor (schema v9 ``memory`` events):
  the free fraction of the ``--device-bytes`` budget left by the
  window's PEAK sampled ``device_bytes`` (params + optimizer moments +
  residuals + window + KV pool — telemetry/memory.py's census). Peak,
  not latest: a pool that spikes into the red between samples of calm
  is the OOM precursor this objective exists to catch. Requires
  ``--device-bytes`` (the per-device budget to judge against — an HBM
  size on chip, an explicit budget in CI);
- ``--class-slo NAME:ttft_p99=S[,queue_p99=S]`` (repeatable) — PER-CLASS
  objectives over the multi-tenant fleet's ``request_done`` events
  (schema v6 ``tenant`` tags, serving/frontend.py TrafficClass):
  each class gets its own rolling p99 windows, and a breach is reported
  as ``<class>:ttft_p99_s`` so one tenant's misses never hide in a
  fleet-wide percentile. The summary additionally carries a
  ``breakdown`` of run-total per-class AND per-engine latency aggregates
  (the ``engine`` tags the fleet scheduler stamps), so an N-engine
  stream yields per-engine verdicts next to the aggregate one.

Two modes:
- **live** (default): follow the growing file (incremental reads, torn
  final line buffered until its newline arrives — the tailer never
  misparses a mid-write line), evaluate every ``--poll``, print and (with
  ``--emit``, default ON live) append ``slo_violation`` events to the
  stream — O_APPEND keeps the writer's lines and ours from interleaving,
  and ``iter_runs`` keeps the runs apart. Stops at ``--duration``, or at
  the stream's ``run_end`` once nothing is outstanding.
- **--check**: replay a COMPLETE stream in event time (no wall clock),
  evaluating once per quarter-window; nonzero exit when any objective was
  breached — the CI mode tier1.yml runs over the serving smoke's stream.

Example (the serving smoke's stream):
    python -m experiments.slo_monitor serving-telemetry --check \\
        --ttft-p99 5.0 --min-tps 10
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ddl25spring_tpu.telemetry.events import EventLog, read_events
from ddl25spring_tpu.telemetry.heartbeat import read_heartbeat
from ddl25spring_tpu.telemetry.introspect import FlightRecorder
from ddl25spring_tpu.telemetry.registry import percentile


class StreamTailer:
    """Incremental JSONL reader for a growing file.

    Keeps a byte offset and buffers a torn final line until its newline
    arrives — a mid-``write()`` line is never misparsed, the same
    tolerance as ``read_events`` but without re-reading the file each
    poll. ``from_end=True`` starts at the CURRENT end of file: the
    watchdog monitors only what happens after it attaches, so a dead
    run's leftovers (its never-completed request_enqueue events) cannot
    poison a fresh monitor's outstanding-work counters. A file that
    SHRANK is handled per mode: the default resets to 0 and re-reads (a
    recycled dir — duplicate events are harmless to a rolling window,
    silence about a new run is not), while ``from_end`` re-attaches at
    the new end — the common shrink there is a relaunched writer's
    EventLog healing a torn fragment by a few bytes, and a reset to 0
    would replay the whole dead-run history ``from_end`` exists to
    skip."""

    def __init__(self, path: str, *, from_end: bool = False):
        self.path = path
        self._from_end = from_end
        self._offset = 0
        if from_end:
            # Attach after the last NEWLINE, not at raw EOF: if the file
            # currently ends in a dead writer's torn fragment, a
            # relaunching EventLog will heal it by truncating to exactly
            # that newline — an attach at raw EOF would then sit past the
            # truncation point and (after the file regrows) read from the
            # middle of a new line, losing its first event.
            try:
                with open(path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    back = min(size, 1 << 16)
                    f.seek(size - back)
                    nl = f.read(back).rfind(b"\n")
                    self._offset = size - back + nl + 1 if nl != -1 else 0
            except OSError:
                pass                      # no file yet: start at 0
        self._buf = b""

    def poll(self) -> List[Dict[str, Any]]:
        try:
            size = os.stat(self.path).st_size
            if size < self._offset:
                self._offset = size if self._from_end else 0
                self._buf = b""
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                data = f.read()
        except OSError:
            return []
        if not data:
            return []
        self._offset += len(data)
        lines = (self._buf + data).split(b"\n")
        self._buf = lines.pop()        # b"" when data ended in a newline
        events = []
        for line in lines:
            if not line.strip():
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue               # sealed fragment / corruption: skip
            if isinstance(e, dict):
                events.append(e)
        return events


@dataclass
class SLOConfig:
    """Thresholds; ``None`` disables an objective."""
    window_s: float = 30.0
    ttft_p99_s: Optional[float] = None
    queue_p99_s: Optional[float] = None
    min_tokens_per_sec: Optional[float] = None
    max_skip_rate: Optional[float] = None
    heartbeat_stale_s: Optional[float] = None
    # Run-health objectives (schema v5 numerics/compile events).
    min_mfu: Optional[float] = None
    max_gradnorm_spike_rate: Optional[float] = None
    gradnorm_spike_factor: float = 10.0
    # Speculative-decoding acceptance floor (schema v7 ``speculate``
    # events): accepted/proposed draft tokens over the window. A
    # degenerate draft decays acceptance toward 0 (at the tokens-per-
    # dispatch level, toward 1/(k+1) of the window) — a THROUGHPUT
    # regression the tok/s floor may not catch on a lightly-loaded
    # fleet, so it is its own objective, not a silent slowdown.
    min_acceptance_rate: Optional[float] = None
    # OOM-headroom floor (schema v9 ``memory`` events): minimum free
    # fraction of ``device_budget_bytes`` left by the window's peak
    # sampled ``device_bytes``. Both must be set for the objective to
    # arm — a floor without a budget has nothing to judge against.
    min_headroom_frac: Optional[float] = None
    device_budget_bytes: Optional[float] = None
    # Per-traffic-class objectives (schema v6 ``tenant`` tags):
    # {class: {"ttft_p99_s": s, "queue_p99_s": s}} — the
    # serving.frontend.class_slos shape. Violations are keyed
    # "<class>:<objective>".
    per_class: Optional[Dict[str, Dict[str, float]]] = None


class SLOMonitor:
    """Rolling-window SLO state machine: ``feed`` events (any order of
    types; timestamps from their ``t`` field), then ``evaluate(now)``.

    A violation is reported on the ok→breached TRANSITION per objective
    (and again if it re-breaches after recovering), not on every poll —
    a sustained breach is one incident, not one event per second. The
    currently-breached set is ``active``; every incident ever seen is in
    ``violations``."""

    def __init__(self, cfg: SLOConfig, emit: Optional[EventLog] = None):
        self.cfg = cfg
        self.emit = emit
        self._ttft: deque = deque()     # (t, seconds)
        self._wait: deque = deque()     # (t, seconds)
        self._tokens: deque = deque()   # (t, count)
        self._token_events = False      # stream has per-token granularity
        self.first_token_t: Optional[float] = None
        self._skips: deque = deque()    # (t, count)
        self._steps: deque = deque()    # (t, count)
        # Run-health state (schema v5): dispatch timing from non-warmup
        # step events, program flops from compile events, peaks from the
        # manifest, grad norms from numerics samples. Flops are held
        # PER STEP — each compile event's flops divided by the step count
        # that event itself carries — so a tail-chunk program (smaller
        # flops AND smaller window) normalizes the same as the full-K one
        # and last-compile-wins cannot skew the floor.
        self._dts: deque = deque()      # (t, steps, dt_s)
        self._gradnorms: deque = deque()  # (t, grad_norm)
        self._spec: deque = deque()     # (t, proposed, accepted)
        self._mem: deque = deque()      # (t, device_bytes) — schema v9
        self._flops_per_step: Optional[float] = None
        self._peak_flops: Optional[float] = None
        # Per-class rolling windows (one ttft + one wait deque per class
        # with a configured SLO) and run-total per-class / per-engine
        # accumulators for the summary breakdown — totals, not windows:
        # the breakdown is a run verdict, the windows are the live alarm.
        self._cls_ttft: Dict[str, deque] = {}
        self._cls_wait: Dict[str, deque] = {}
        self._by_class: Dict[str, dict] = {}
        self._by_engine: Dict[Any, dict] = {}
        self.enqueued = 0
        self.done = 0
        self.run_ended = False
        self.first_event_t: Optional[float] = None
        self.last_event_t: Optional[float] = None
        self.active: Dict[str, dict] = {}
        self.violations: List[dict] = []

    def feed(self, events: List[Dict[str, Any]]) -> None:
        for e in events:
            t = e.get("t")
            if not isinstance(t, (int, float)):
                continue
            self.first_event_t = (t if self.first_event_t is None
                                  else min(self.first_event_t, t))
            self.last_event_t = (t if self.last_event_t is None
                                 else max(self.last_event_t, t))
            etype = e.get("type")
            if etype == "request_enqueue":
                self.enqueued += 1
            elif etype == "request_token":
                if not self._token_events:
                    # First per-token event: from here tokens are counted
                    # at token granularity, never ALSO at done granularity
                    # (a request's tokens always precede its done, so no
                    # done was ever counted before this flips).
                    self._token_events = True
                    self._tokens.clear()
                self._tokens.append((t, 1))
                if self.first_token_t is None or t < self.first_token_t:
                    self.first_token_t = t
            elif etype == "request_done":
                self.done += 1
                if not self._token_events and isinstance(e.get("tokens"),
                                                         int):
                    # Streams recorded with Scheduler(token_events=False)
                    # still carry throughput at completion granularity —
                    # without this, the tok/s floor would read a healthy
                    # quiet-stream server as permanently stalled.
                    self._tokens.append((t, e["tokens"]))
                    if self.first_token_t is None or t < self.first_token_t:
                        self.first_token_t = t
                if isinstance(e.get("ttft_s"), (int, float)):
                    self._ttft.append((t, e["ttft_s"]))
                if isinstance(e.get("queue_wait_s"), (int, float)):
                    self._wait.append((t, e["queue_wait_s"]))
                self._feed_done_tags(t, e)
            elif etype == "fault":
                counters = e.get("counters") or {}
                skips = counters.get("skipped_steps", 0)
                if isinstance(skips, int) and skips > 0:
                    self._skips.append((t, skips))
            elif etype == "step":
                steps = e.get("steps")
                if isinstance(steps, int) and steps > 0:
                    self._steps.append((t, steps))
                    if (not e.get("warmup")
                            and isinstance(e.get("dt_s"), (int, float))
                            and e["dt_s"] > 0):
                        self._dts.append((t, steps, e["dt_s"]))
            elif etype == "manifest":
                peaks = e.get("peaks") or {}
                if isinstance(peaks.get("flops_per_sec"), (int, float)):
                    self._peak_flops = peaks["flops_per_sec"]
            elif etype == "compile":
                if isinstance(e.get("flops"), (int, float)) and e["flops"] > 0:
                    spd = e.get("steps_per_dispatch")
                    spd = spd if isinstance(spd, int) and spd > 0 else 1
                    self._flops_per_step = e["flops"] / spd
            elif etype == "numerics":
                if isinstance(e.get("grad_norm"), (int, float)):
                    self._gradnorms.append((t, e["grad_norm"]))
            elif etype == "speculate":
                if (isinstance(e.get("proposed"), int)
                        and isinstance(e.get("accepted"), int)
                        and e["proposed"] > 0):
                    self._spec.append((t, e["proposed"], e["accepted"]))
            elif etype == "memory":
                if isinstance(e.get("device_bytes"), (int, float)) \
                        and e["device_bytes"] >= 0:
                    self._mem.append((t, e["device_bytes"]))
            elif etype == "run_end":
                self.run_ended = True

    # Per-(class/engine) breakdown samples kept per group: ``done`` counts
    # stay exact, but the latency lists are bounded — the live monitor is
    # a days-long sidecar, and unbounded per-request accumulation is
    # exactly the leak this tool exists to catch in others. At the cap
    # the percentiles become most-recent-window figures (still exact for
    # CI-scale --check replays, which stay far below it).
    BREAKDOWN_CAP = 10_000

    def _feed_done_tags(self, t: float, e: Dict[str, Any]) -> None:
        """Per-class windows (only classes with a configured SLO) and
        run-total class/engine breakdown accumulators, from one
        ``request_done``'s ``tenant``/``engine`` tags (schema v6)."""
        ttft = e.get("ttft_s")
        wait = e.get("queue_wait_s")
        cls = e.get("tenant")
        if isinstance(cls, str) and self.cfg.per_class \
                and cls in self.cfg.per_class:
            if isinstance(ttft, (int, float)):
                self._cls_ttft.setdefault(cls, deque()).append((t, ttft))
            if isinstance(wait, (int, float)):
                self._cls_wait.setdefault(cls, deque()).append((t, wait))
        for key, agg in ((cls, self._by_class),
                         (e.get("engine"), self._by_engine)):
            if key is None:
                continue
            rec = agg.setdefault(
                key, {"done": 0, "ttft": deque(maxlen=self.BREAKDOWN_CAP),
                      "wait": deque(maxlen=self.BREAKDOWN_CAP)})
            rec["done"] += 1
            if isinstance(ttft, (int, float)):
                rec["ttft"].append(ttft)
            if isinstance(wait, (int, float)):
                rec["wait"].append(wait)

    def breakdown(self) -> Dict[str, Any]:
        """Run-total per-class and per-engine latency aggregates — the
        summary's group-by view of the same stream the rolling windows
        alarm on (keys stringified for JSON)."""
        def agg(groups):
            return {str(k): {
                "done": rec["done"],
                "ttft_p99_s": (percentile(rec["ttft"], 99)
                               if rec["ttft"] else None),
                "queue_p99_s": (percentile(rec["wait"], 99)
                                if rec["wait"] else None),
            } for k, rec in sorted(groups.items(), key=lambda kv:
                                   str(kv[0]))}
        return {"per_class": agg(self._by_class),
                "per_engine": agg(self._by_engine)}

    def _prune(self, now: float) -> None:
        horizon = now - self.cfg.window_s
        for dq in (self._ttft, self._wait, self._tokens, self._skips,
                   self._steps, self._dts, self._gradnorms, self._spec,
                   self._mem,
                   *self._cls_ttft.values(), *self._cls_wait.values()):
            while dq and dq[0][0] < horizon:
                dq.popleft()

    def evaluate(self, now: float,
                 heartbeat: Optional[dict] = None) -> List[dict]:
        """Measure every enabled objective over [now - window, now];
        returns the NEW violations (transitions into breach)."""
        self._prune(now)
        cfg = self.cfg
        measured: Dict[str, tuple] = {}   # slo -> (value, threshold)
        if cfg.ttft_p99_s is not None and self._ttft:
            v = percentile([x for _, x in self._ttft], 99)
            if v > cfg.ttft_p99_s:
                measured["ttft_p99_s"] = (v, cfg.ttft_p99_s)
        if cfg.queue_p99_s is not None and self._wait:
            v = percentile([x for _, x in self._wait], 99)
            if v > cfg.queue_p99_s:
                measured["queue_p99_s"] = (v, cfg.queue_p99_s)
        for cls, limits in (cfg.per_class or {}).items():
            # Per-class windows: a quiet class has an empty window and no
            # verdict (idle ≠ breached — same posture as the global
            # objectives), a busy one is judged against ITS thresholds.
            for slo, dq in (("ttft_p99_s", self._cls_ttft.get(cls)),
                            ("queue_p99_s", self._cls_wait.get(cls))):
                limit = limits.get(slo)
                if limit is None or not dq:
                    continue
                v = percentile([x for _, x in dq], 99)
                if v > limit:
                    measured[f"{cls}:{slo}"] = (v, limit)
        if (cfg.min_tokens_per_sec is not None
                and self.enqueued > self.done):
            # Outstanding work is what makes a low rate a STALL rather
            # than an idle lull. Two regimes:
            # - no token has EVER arrived: that is startup (XLA compile),
            #   not a throughput deficit — grant one full window from the
            #   stream's birth before calling it a stall (a compile
            #   longer than the window is indistinguishable from one);
            # - tokens have flowed: judge the floor over the OBSERVED
            #   span since the first token, capped at the window — a
            #   partial window must not deflate a healthy rate, and the
            #   pre-first-token compile gap must not count against it.
            if self.first_token_t is None:
                if (self.first_event_t is not None
                        and now - self.first_event_t > cfg.window_s):
                    measured["tokens_per_sec"] = (0.0,
                                                  cfg.min_tokens_per_sec)
            else:
                span = min(cfg.window_s,
                           max(now - self.first_token_t, 1e-9))
                v = sum(n for _, n in self._tokens) / span
                if v < cfg.min_tokens_per_sec:
                    measured["tokens_per_sec"] = (v, cfg.min_tokens_per_sec)
        if (cfg.min_mfu is not None and self._dts
                and self._flops_per_step and self._peak_flops):
            # Achieved FLOP/s over the window's step events: per-step
            # program flops × steps ÷ step seconds (per-step, so chunked
            # runs with ragged tail programs normalize correctly).
            steps = sum(s for _, s, _ in self._dts)
            secs = sum(d for _, _, d in self._dts)
            if secs > 0 and steps > 0:
                v = (self._flops_per_step * steps / secs
                     / self._peak_flops)
                if v < cfg.min_mfu:
                    measured["mfu"] = (v, cfg.min_mfu)
        if (cfg.max_gradnorm_spike_rate is not None
                and len(self._gradnorms) >= 4):
            # Spike = a sample above factor × the window MEDIAN (robust
            # to the spikes themselves); at least 4 samples so a lone
            # sample can never be its own baseline.
            norms = sorted(x for _, x in self._gradnorms)
            median = norms[len(norms) // 2]
            if median > 0:
                spikes = sum(x > cfg.gradnorm_spike_factor * median
                             for _, x in self._gradnorms)
                v = spikes / len(self._gradnorms)
                if v > cfg.max_gradnorm_spike_rate:
                    measured["gradnorm_spike_rate"] = (
                        v, cfg.max_gradnorm_spike_rate)
        if cfg.min_acceptance_rate is not None and self._spec:
            # Windowed acceptance over verify dispatches. Idle (no
            # speculate events in the window) is not a breach — same
            # posture as the latency objectives; a DEGENERATE draft keeps
            # proposing and failing, which is exactly what lands here.
            prop = sum(p for _, p, _ in self._spec)
            acc = sum(a for _, _, a in self._spec)
            if prop > 0:
                v = acc / prop
                if v < cfg.min_acceptance_rate:
                    measured["spec_acceptance_rate"] = (
                        v, cfg.min_acceptance_rate)
        if (cfg.min_headroom_frac is not None and cfg.device_budget_bytes
                and self._mem):
            # Headroom = free fraction of the budget at the window's PEAK
            # sample (an idle window is no verdict, same as the latency
            # objectives). Can go negative: a census already over budget
            # reads as negative headroom, unambiguously breached.
            peak = max(b for _, b in self._mem)
            v = 1.0 - peak / cfg.device_budget_bytes
            if v < cfg.min_headroom_frac:
                measured["headroom_frac"] = (v, cfg.min_headroom_frac)
        if cfg.max_skip_rate is not None and self._skips:
            steps = sum(n for _, n in self._steps)
            skips = sum(n for _, n in self._skips)
            v = skips / max(steps, skips)    # skipped steps consumed data
            if v > cfg.max_skip_rate:
                measured["guard_skip_rate"] = (v, cfg.max_skip_rate)
        if cfg.heartbeat_stale_s is not None and heartbeat is not None \
                and isinstance(heartbeat.get("time"), (int, float)):
            v = now - heartbeat["time"]
            if v > cfg.heartbeat_stale_s:
                measured["heartbeat_stale_s"] = (v, cfg.heartbeat_stale_s)

        fresh = []
        for slo, (value, threshold) in measured.items():
            record = {"slo": slo, "value": value, "threshold": threshold,
                      "window_s": cfg.window_s, "t_eval": now}
            if slo not in self.active:
                fresh.append(record)
                self.violations.append(record)
                if self.emit is not None:
                    self.emit.slo_violation(**record)
            self.active[slo] = record
        for slo in list(self.active):
            if slo not in measured:
                del self.active[slo]     # recovered; a re-breach re-fires
        return fresh


def check_stream(events: List[Dict[str, Any]], cfg: SLOConfig,
                 heartbeat: Optional[dict] = None,
                 emit: Optional[EventLog] = None) -> List[dict]:
    """Offline replay for ``--check``; returns the violation list (see
    ``replay_monitor`` for the full monitor, breakdown included)."""
    return replay_monitor(events, cfg, heartbeat=heartbeat,
                          emit=emit).violations


def replay_monitor(events: List[Dict[str, Any]], cfg: SLOConfig,
                   heartbeat: Optional[dict] = None,
                   emit: Optional[EventLog] = None) -> SLOMonitor:
    """Offline replay: walk the stream in event time, evaluating every
    quarter-window and once at the end — a stream that goes SILENT
    mid-run (the stall case) is caught at that final evaluation, whose
    ``now`` is the heartbeat's last beat when that is newer than the
    last event (a dead writer's stream ends, its staleness does not).
    Returns the monitor itself: ``violations`` for the verdict,
    ``breakdown()`` for the per-class/per-engine group-by (the fleet
    smoke consumes both)."""
    monitor = SLOMonitor(cfg, emit=emit)
    events = sorted(events, key=lambda e: e.get("t", 0.0))
    last_eval = None
    for e in events:
        monitor.feed([e])
        t = e.get("t")
        if not isinstance(t, (int, float)):
            continue
        if last_eval is None:
            last_eval = t
        elif t - last_eval >= cfg.window_s / 4:
            monitor.evaluate(t, heartbeat)
            last_eval = t
    if monitor.last_event_t is not None:
        end = monitor.last_event_t
        if heartbeat is not None and isinstance(heartbeat.get("time"),
                                                (int, float)):
            end = max(end, heartbeat["time"])
        monitor.evaluate(end, heartbeat)
    return monitor


def parse_class_slo(specs) -> Optional[Dict[str, Dict[str, float]]]:
    """``--class-slo`` values ("NAME:ttft_p99=S[,queue_p99=S]") into the
    ``SLOConfig.per_class`` table."""
    names = {"ttft_p99": "ttft_p99_s", "queue_p99": "queue_p99_s"}
    per: Dict[str, Dict[str, float]] = {}
    for spec in specs or []:
        name, _, rest = spec.partition(":")
        if not name or not rest:
            raise ValueError(f"--class-slo {spec!r}: expected "
                             "NAME:ttft_p99=S[,queue_p99=S]")
        limits = {}
        for part in rest.split(","):
            k, _, v = part.partition("=")
            key = names.get(k.strip())
            if key is None or not v:
                raise ValueError(f"--class-slo {spec!r}: unknown objective "
                                 f"{k.strip()!r} (known: "
                                 f"{', '.join(names)})")
            limits[key] = float(v)
        per[name] = limits
    return per or None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="telemetry run dir (containing "
                                 "events.jsonl) or an events.jsonl path")
    ap.add_argument("--check", action="store_true",
                    help="replay the complete stream in event time; exit "
                         "1 if any objective was breached")
    ap.add_argument("--window", type=float, default=30.0,
                    help="rolling window seconds")
    ap.add_argument("--ttft-p99", type=float, default=None,
                    help="p99 TTFT ceiling (s)")
    ap.add_argument("--queue-p99", type=float, default=None,
                    help="p99 queue wait ceiling (s)")
    ap.add_argument("--min-tps", type=float, default=None,
                    help="sustained tokens/sec floor while work is "
                         "outstanding")
    ap.add_argument("--max-skip-rate", type=float, default=None,
                    help="StepGuard skipped-steps / steps ceiling")
    ap.add_argument("--heartbeat-stale", type=float, default=None,
                    help="heartbeat age ceiling (s)")
    ap.add_argument("--slo-mfu", type=float, default=None,
                    help="MFU floor over the window (achieved FLOP/s from "
                         "compile-event flops + step timing, vs the "
                         "manifest's roofline peaks)")
    ap.add_argument("--slo-acceptance", type=float, default=None,
                    help="speculative-decoding acceptance-rate floor over "
                         "the window (accepted/proposed draft tokens from "
                         "schema-v7 speculate events; a degenerate draft "
                         "is an SLO breach, not a silent slowdown)")
    ap.add_argument("--slo-headroom", type=float, default=None,
                    help="OOM-headroom floor: minimum free fraction of "
                         "--device-bytes left by the window's peak "
                         "memory-event device_bytes (schema v9)")
    ap.add_argument("--device-bytes", type=float, default=None,
                    help="per-device byte budget --slo-headroom judges "
                         "against (HBM size on chip; an explicit budget "
                         "in CI)")
    ap.add_argument("--slo-gradnorm", type=float, default=None,
                    help="grad-norm spike-rate ceiling (fraction of the "
                         "window's numerics samples above "
                         "--gradnorm-factor x the window median)")
    ap.add_argument("--gradnorm-factor", type=float, default=10.0,
                    help="spike threshold multiple of the window-median "
                         "grad norm")
    ap.add_argument("--class-slo", action="append", default=None,
                    metavar="NAME:ttft_p99=S[,queue_p99=S]",
                    help="per-traffic-class objectives (repeatable) over "
                         "the fleet's tenant-tagged request_done events; "
                         "violations key as '<class>:<objective>'")
    ap.add_argument("--poll", type=float, default=2.0,
                    help="live mode: seconds between evaluations")
    ap.add_argument("--duration", type=float, default=None,
                    help="live mode: stop after this many seconds")
    ap.add_argument("--emit", dest="emit", action="store_true",
                    default=None,
                    help="append slo_violation events to the stream "
                         "(default: on live, off under --check)")
    ap.add_argument("--no-emit", dest="emit", action="store_false")
    ap.add_argument("--out", default=None,
                    help="write the violation list as JSON here")
    a = ap.parse_args(argv)

    if os.path.isdir(a.path):
        events_path = os.path.join(a.path, "events.jsonl")
        heartbeat_path = os.path.join(a.path, "heartbeat.json")
    else:
        events_path = a.path
        heartbeat_path = os.path.join(os.path.dirname(a.path) or ".",
                                      "heartbeat.json")
    try:
        per_class = parse_class_slo(a.class_slo)
    except ValueError as e:
        ap.error(str(e))
    cfg = SLOConfig(window_s=a.window, ttft_p99_s=a.ttft_p99,
                    queue_p99_s=a.queue_p99,
                    min_tokens_per_sec=a.min_tps,
                    max_skip_rate=a.max_skip_rate,
                    heartbeat_stale_s=a.heartbeat_stale,
                    min_mfu=a.slo_mfu,
                    max_gradnorm_spike_rate=a.slo_gradnorm,
                    gradnorm_spike_factor=a.gradnorm_factor,
                    min_acceptance_rate=a.slo_acceptance,
                    min_headroom_frac=a.slo_headroom,
                    device_budget_bytes=a.device_bytes,
                    per_class=per_class)
    if a.slo_headroom is not None and not a.device_bytes:
        ap.error("--slo-headroom requires --device-bytes (the budget the "
                 "free fraction is measured against)")
    emit_default = not a.check
    emit = a.emit if a.emit is not None else emit_default
    # heal=False: we are a SIDECAR on a possibly-LIVE stream — append
    # only, never truncate what might be another writer's in-flight line.
    log = (EventLog(events_path, run_id=f"slo-{os.getpid()}", heal=False)
           if emit else None)
    if log is not None:
        # Arm a flight recorder in THIS process (the run's own recorder
        # only sees events its process emits — a sidecar's violation
        # never crosses that tap): every tailed event feeds the ring, and
        # the violation we emit dumps a postmortem bundle next to the
        # run's own (triggers narrowed to slo_violation so a fault the
        # trainer already bundled is not bundled twice).
        recorder = FlightRecorder(
            os.path.join(os.path.dirname(events_path) or ".",
                         "postmortem"),
            triggers=("slo_violation",))
        log.observers.append(recorder.observe)
    else:
        recorder = None

    def _hb():
        return (read_heartbeat(heartbeat_path)
                if os.path.exists(heartbeat_path) else None)

    if a.check:
        if not os.path.exists(events_path):
            print(f"no event stream at {events_path}", file=sys.stderr)
            return 2
        events = read_events(events_path)
        if recorder is not None:
            for e in events:          # bundle context; never re-triggers
                recorder.ingest(e)
        monitor = replay_monitor(events, cfg, heartbeat=_hb(), emit=log)
        violations = monitor.violations
    else:
        tailer = StreamTailer(events_path)
        monitor = SLOMonitor(cfg, emit=log)
        t0 = time.time()
        while True:
            fresh = tailer.poll()
            if recorder is not None:
                for e in fresh:       # bundle context; never re-triggers
                    recorder.ingest(e)
            monitor.feed(fresh)
            for v in monitor.evaluate(time.time(), _hb()):
                print(f"[slo] VIOLATION {v['slo']}: {v['value']:.4g} vs "
                      f"threshold {v['threshold']:.4g} "
                      f"(window {v['window_s']:.0f}s)", flush=True)
            if a.duration is not None and time.time() - t0 >= a.duration:
                break
            if monitor.run_ended and monitor.enqueued <= monitor.done:
                break
            time.sleep(a.poll)
        violations = monitor.violations
    if log is not None:
        log.close()

    summary = {"events_path": events_path, "window_s": cfg.window_s,
               "violations": violations, "ok": not violations,
               # Per-class/per-engine group-by of the same stream —
               # run totals, so an N-engine multi-tenant run reads as N+K
               # verdicts instead of one pooled percentile table.
               "breakdown": monitor.breakdown()}
    if a.out:
        with open(a.out, "w") as f:
            json.dump(summary, f)
            f.write("\n")
    print(json.dumps(summary))
    return 1 if (a.check and violations) else 0


if __name__ == "__main__":
    sys.exit(main())
