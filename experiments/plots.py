"""Accuracy/loss-curve rendering from the persisted experiment CSVs.

The reference re-plots its homework results from CSV dumps in notebook cells
(lab/hw03/Tea_Pula_03.ipynb cell 11; seaborn line plots in hw01 cell 27).
This is the framework's equivalent: ``python -m experiments.plots`` renders
every known results CSV under ``experiments/results/`` into PNGs next to it.
"""

from __future__ import annotations

import os
from typing import Optional

from . import common


def _mpl():
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    return plt


def _save(fig, plt, csv_name: str, out_name: Optional[str] = None) -> str:
    out = os.path.join(common.RESULTS_DIR,
                       out_name or csv_name.replace(".csv", ".png"))
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    plt.close(fig)
    return out


def plot_fl_curves(csv_name: str, out_name: Optional[str] = None,
                   group_cols=("algorithm", "N", "C")) -> Optional[str]:
    """Per-round test-accuracy curves, one line per config group."""
    import pandas as pd
    path = os.path.join(common.RESULTS_DIR, csv_name)
    if not os.path.exists(path):
        return None
    df = pd.read_csv(path)
    group_cols = [c for c in group_cols if c in df.columns]
    if not group_cols or "round" not in df.columns:
        return None
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(7, 4.5))
    for key, g in df.groupby(group_cols):
        label = "/".join(str(k) for k in (key if isinstance(key, tuple) else (key,)))
        ax.plot(g["round"], g["test_accuracy"], marker="o", ms=3, label=label)
    ax.set_xlabel("round")
    ax.set_ylabel("test accuracy")
    ax.set_title(csv_name.replace(".csv", ""))
    ax.legend(fontsize=7, ncol=2)
    ax.grid(alpha=0.3)
    return _save(fig, plt, csv_name, out_name)


def plot_loss_curve(csv_name: str, x: str, ys, out_name: Optional[str] = None,
                    group_col: Optional[str] = None) -> Optional[str]:
    """``group_col`` (e.g. hw1b's ``config``) draws one line per group —
    multi-topology CSVs would otherwise render as one zigzag polyline."""
    import pandas as pd
    path = os.path.join(common.RESULTS_DIR, csv_name)
    if not os.path.exists(path):
        return None
    df = pd.read_csv(path)
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(7, 4.5))
    groups = (df.groupby(group_col)
              if group_col and group_col in df.columns else [(None, df)])
    for gname, g in groups:
        for yc in ys:
            if yc in g.columns:
                label = yc if gname is None else f"{gname}"
                ax.plot(g[x], g[yc], label=label)
    ax.set_xlabel(x)
    ax.set_ylabel("loss")
    ax.set_title(csv_name.replace(".csv", ""))
    ax.legend(fontsize=8)
    ax.grid(alpha=0.3)
    return _save(fig, plt, csv_name, out_name)


def plot_backdoor(csv_name: str = "hw3_backdoor.csv",
                  out_name: Optional[str] = None) -> Optional[str]:
    """Two panels per defense: clean accuracy and backdoor ASR per round —
    the visual signature of the reference's cells 27-31 (undefended ASR
    climbs to ~1 while clean accuracy looks fine; robust rules pin ASR)."""
    import pandas as pd
    path = os.path.join(common.RESULTS_DIR, csv_name)
    if not os.path.exists(path):
        return None
    try:
        df = pd.read_csv(path)
    except pd.errors.EmptyDataError:
        return None
    if not {"defense", "round", "clean_accuracy",
            "backdoor_asr"} <= set(df.columns):
        return None      # partial/older schema must not sink main()'s list
    plt = _mpl()
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4.2), sharex=True)
    for d, g in df.groupby("defense", sort=False):
        g = g.sort_values("round")
        ax1.plot(g["round"], g["clean_accuracy"], marker="o", ms=3, label=d)
        ax2.plot(g["round"], g["backdoor_asr"], marker="o", ms=3, label=d)
    ax1.set_title("clean test accuracy")
    ax2.set_title("backdoor attack success rate")
    for ax in (ax1, ax2):
        ax.set_xlabel("round")
        ax.grid(alpha=0.3)
    ax2.legend(fontsize=7, ncol=2)
    return _save(fig, plt, csv_name, out_name)


def main() -> list:
    made = [
        plot_backdoor(),
        # n_train separates the 12k battery from matched-shard 60k appends.
        plot_fl_curves("hw1_fl.csv",
                       group_cols=("algorithm", "N", "C", "n_train")),
        plot_fl_curves("hw3_defenses.csv",
                       group_cols=("defense", "iid")),
        plot_fl_curves("hw3_bulyan.csv", group_cols=("k", "beta")),
        plot_fl_curves("hw3_sparsefed.csv", group_cols=("topk_fraction",)),
        plot_loss_curve("hw1b_llm_loss.csv", "iter", ["loss"],
                        group_col="config"),
        plot_loss_curve("hw2_vfl_vae.csv", "epoch", ["total", "recon", "kl"]),
    ]
    made = [m for m in made if m]
    for m in made:
        print(f"-> {m}")
    return made


if __name__ == "__main__":
    main()
