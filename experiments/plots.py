"""Accuracy/loss-curve rendering from the persisted experiment CSVs.

The reference re-plots its homework results from CSV dumps in notebook cells
(lab/hw03/Tea_Pula_03.ipynb cell 11; seaborn line plots in hw01 cell 27).
This is the framework's equivalent: ``python -m experiments.plots`` renders
every known results CSV under ``experiments/results/`` into PNGs next to it.
"""

from __future__ import annotations

import os
from typing import Optional

from . import common


def _mpl():
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    return plt


def plot_fl_curves(csv_name: str, out_name: Optional[str] = None,
                   group_cols=("algorithm", "N", "C")) -> Optional[str]:
    """Per-round test-accuracy curves, one line per config group."""
    import pandas as pd
    path = os.path.join(common.RESULTS_DIR, csv_name)
    if not os.path.exists(path):
        return None
    df = pd.read_csv(path)
    group_cols = [c for c in group_cols if c in df.columns]
    if not group_cols or "round" not in df.columns:
        return None
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(7, 4.5))
    for key, g in df.groupby(group_cols):
        label = "/".join(str(k) for k in (key if isinstance(key, tuple) else (key,)))
        ax.plot(g["round"], g["test_accuracy"], marker="o", ms=3, label=label)
    ax.set_xlabel("round")
    ax.set_ylabel("test accuracy")
    ax.set_title(csv_name.replace(".csv", ""))
    ax.legend(fontsize=7, ncol=2)
    ax.grid(alpha=0.3)
    out = os.path.join(common.RESULTS_DIR, out_name or csv_name.replace(".csv", ".png"))
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    plt.close(fig)
    return out


def plot_loss_curve(csv_name: str, x: str, ys, out_name: Optional[str] = None,
                    group_col: Optional[str] = None) -> Optional[str]:
    """``group_col`` (e.g. hw1b's ``config``) draws one line per group —
    multi-topology CSVs would otherwise render as one zigzag polyline."""
    import pandas as pd
    path = os.path.join(common.RESULTS_DIR, csv_name)
    if not os.path.exists(path):
        return None
    df = pd.read_csv(path)
    plt = _mpl()
    fig, ax = plt.subplots(figsize=(7, 4.5))
    groups = (df.groupby(group_col)
              if group_col and group_col in df.columns else [(None, df)])
    for gname, g in groups:
        for yc in ys:
            if yc in g.columns:
                label = yc if gname is None else f"{gname}"
                ax.plot(g[x], g[yc], label=label)
    ax.set_xlabel(x)
    ax.set_ylabel("loss")
    ax.set_title(csv_name.replace(".csv", ""))
    ax.legend(fontsize=8)
    ax.grid(alpha=0.3)
    out = os.path.join(common.RESULTS_DIR, out_name or csv_name.replace(".csv", ".png"))
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    plt.close(fig)
    return out


def main() -> list:
    made = [
        # n_train separates the 12k battery from matched-shard 60k appends.
        plot_fl_curves("hw1_fl.csv",
                       group_cols=("algorithm", "N", "C", "n_train")),
        plot_fl_curves("hw3_defenses.csv",
                       group_cols=("defense", "iid")),
        plot_fl_curves("hw3_bulyan.csv", group_cols=("k", "beta")),
        plot_fl_curves("hw3_sparsefed.csv", group_cols=("topk_fraction",)),
        plot_loss_curve("hw1b_llm_loss.csv", "iter", ["loss"],
                        group_col="config"),
        plot_loss_curve("hw2_vfl_vae.csv", "epoch", ["total", "recon", "kl"]),
    ]
    made = [m for m in made if m]
    for m in made:
        print(f"-> {m}")
    return made


if __name__ == "__main__":
    main()
