"""Run the full parity-evidence suite: every homework experiment battery,
then render plots. ``--quick`` shrinks datasets/rounds for smoke testing
(the committed results under experiments/results/ come from a full run).
"""

from __future__ import annotations

import argparse
import json
import time


def main(quick: bool = False, skip=(), hw1_sizes=None, hw3_sizes=None) -> dict:
    from . import generative, hw1_fl, hw1b_llm, hw2_vfl, hw3_defenses, plots

    def sized(fn, sizes):
        if sizes is None:
            return fn
        return lambda quick=False: fn(quick=quick, n_train=sizes[0],
                                      n_test=sizes[1])

    summary = {}
    stages = [
        ("hw1_fl", sized(hw1_fl.main, hw1_sizes)),
        ("hw1b_llm", hw1b_llm.main),
        ("hw2_vfl", hw2_vfl.main),
        ("hw3_defenses", sized(hw3_defenses.main, hw3_sizes)),
        ("generative", generative.main),
    ]
    for name, fn in stages:
        if name in skip:
            continue
        t0 = time.perf_counter()
        print(f"=== {name} ===")
        out = fn(quick=quick)
        summary[name] = {str(k): (round(v, 4) if isinstance(v, float) else v)
                         for k, v in out.items()}
        print(f"=== {name} done in {time.perf_counter() - t0:.1f}s ===\n")
    plots.main()
    print(json.dumps(summary, indent=1))
    return summary


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--skip", nargs="*", default=[])
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU platform (the tunneled TPU in this "
                         "container can die mid-run, taking hours of "
                         "artifacts with it; parity protocol does not "
                         "depend on the platform)")
    a = ap.parse_args()
    hw1_sizes = hw3_sizes = None
    if a.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
        # The single-core CPU platform cannot chew 60k-sample corpora in
        # reasonable time; smaller synthetic corpora keep the exact
        # N/C/E/B/lr/seed protocols (corpus size is not a parity quantity
        # on synthetic data — hw1_fl.main docstring). hw3 runs its 21-config
        # grid, so it gets the smallest corpus.
        hw1_sizes = (12000, 2000)
        hw3_sizes = (6000, 2000)
    main(quick=a.quick, skip=set(a.skip), hw1_sizes=hw1_sizes,
         hw3_sizes=hw3_sizes)
