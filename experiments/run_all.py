"""Run the full parity-evidence suite: every homework experiment battery,
then render plots. ``--quick`` shrinks datasets/rounds for smoke testing
(the committed results under experiments/results/ come from a full run).
"""

from __future__ import annotations

import argparse
import json
import time


def main(quick: bool = False, skip=()) -> dict:
    from . import generative, hw1_fl, hw1b_llm, hw2_vfl, hw3_defenses, plots

    summary = {}
    stages = [
        ("hw1_fl", hw1_fl.main),
        ("hw1b_llm", hw1b_llm.main),
        ("hw2_vfl", hw2_vfl.main),
        ("hw3_defenses", hw3_defenses.main),
        ("generative", generative.main),
    ]
    for name, fn in stages:
        if name in skip:
            continue
        t0 = time.perf_counter()
        print(f"=== {name} ===")
        out = fn(quick=quick)
        summary[name] = {str(k): (round(v, 4) if isinstance(v, float) else v)
                         for k, v in out.items()}
        print(f"=== {name} done in {time.perf_counter() - t0:.1f}s ===\n")
    plots.main()
    print(json.dumps(summary, indent=1))
    return summary


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--skip", nargs="*", default=[])
    a = ap.parse_args()
    main(quick=a.quick, skip=set(a.skip))
