#!/bin/sh
# One-shot TPU evidence capture — run when the tunnel is alive (probe first:
#   python -c "from ddl25spring_tpu.utils.probe import probe_default_platform as p; print(p())"
# ). The tunnel dies unpredictably, so this serializes every measurement
# into a single session and logs everything under experiments/results/.
#   1. bench.py          — headline sweep (flash-dhm batches, pallas-Adam,
#                          mixed-precision, XLA comparison points, decode)
#   2. longctx_bench     — train-step throughput across T=256..8192
# Each stage is already subprocess-isolated + hard-timeout wedge-proofed
# internally, so a mid-stage tunnel death loses only that stage.
set -x
cd "$(dirname "$0")/.."
TS=$(date -u +%Y%m%dT%H%M%S)
LOG=experiments/results/tpu_evidence_${TS}.log
{
  echo "=== bench.py $(date -u) ==="
  python bench.py
  echo "=== longctx_bench $(date -u) ==="
  python -m experiments.longctx_bench
  echo "=== done $(date -u) ==="
} > "$LOG" 2>&1
tail -5 "$LOG"
