"""hw1b tiny-Llama loss trajectory — the reference's DP/PP training runs.

Reproduces the committed run logs' configuration: dmodel=288, 6 heads,
6 layers, seq 256, Adam lr 8e-4, batch 3 per data shard, 5000 iterations
(reference: lab/tutorial_1b/primer/intro.py:7-23). The reference logs show
loss 10.517 → ≈6.08-6.25 over 5000 iters (lab/out_b1_2.txt) and the DP×PP
variant 10.517/10.551 → ≈5.78-6.25 (lab/out_b2_*.txt).

This environment has no TinyStories download, so the stream falls back to
the in-repo synthetic grammar (data/tokens.py) — the curve's *shape* (init
≈ ln(32000) ≈ 10.4, fast early decay) is comparable; absolute perplexity is
corpus-dependent. Every 10th-iteration loss lands in
``experiments/results/hw1b_llm_loss.csv`` with the provenance column.
"""

from __future__ import annotations

import argparse
from typing import Dict

from ddl25spring_tpu.config import LlamaConfig, TrainConfig

from . import common

# The reference's committed-run topologies (config label -> TrainConfig
# fields). b1 = 3-stage microbatched PP (out_b1_2.txt: batch 3 in
# microbatches of 1); b2 = 2 pipelines x 3 stages with the second
# pipeline's stream offset (out_b2_*.txt).
CONFIGS = {
    "dp1": dict(data=1, stage=1),
    "pp3": dict(data=1, stage=3, microbatches=3),
    "dp2_pp3": dict(data=2, stage=3, microbatches=3),
}


def _run_config(name: str, iters: int, sink, provenance: str,
                checkpoint_dir: str = None, faults: str = "",
                fault_seed: int = 0, guard: bool = False,
                telemetry_dir: str = None, steps_per_dispatch: int = 1,
                zero1: bool = False, elastic: bool = False,
                numerics_every: int = 0, wire: str = "fp32",
                overlap_microbatches: int = 0, comm_buckets: int = 1,
                dcn: int = 1, wire_dcn: str = "") -> Dict[str, float]:
    from ddl25spring_tpu.train.llm import train_llm_dp, train_llm_pp

    topo = CONFIGS[name]
    if topo["stage"] > 1 and (dcn > 1 or wire_dcn):
        # Still DP-trainer-only: the hierarchical DCN tiers (the PP mesh
        # has no two-level data axis). Everything else —
        # --steps-per-dispatch, --zero1, --wire, --overlap-microbatches,
        # --numerics-every, and now --elastic (ISSUE 20: a stage loss
        # re-partitions layers onto fewer stages; a loss with a surviving
        # stage column drops the data row) — composes on PP configs too.
        # --elastic × --numerics-every on any config stays a named error
        # (train_llm_pp/dp raise it).
        raise ValueError(f"--dcn/--wire-dcn need a DP config (got {name})")
    train_cfg = TrainConfig(iters=iters, steps_per_dispatch=steps_per_dispatch,
                            numerics_every=numerics_every, wire=wire,
                            overlap_microbatches=overlap_microbatches,
                            comm_buckets=comm_buckets,
                            dcn=dcn, wire_dcn=wire_dcn,
                            **topo)  # batch 3/shard, Adam 8e-4
    model_cfg = LlamaConfig(dtype="bfloat16")
    label = f"{name}_b{train_cfg.data * train_cfg.batch_size}_seq256_adam8e-4"
    if steps_per_dispatch != 1:
        label += f"_k{steps_per_dispatch}"
    if zero1:
        label += "_zero1"
    if wire != "fp32":
        label += f"_{wire}"
    if overlap_microbatches:
        label += f"_ring_m{overlap_microbatches}"
    if comm_buckets > 1:
        label += f"_buckets{comm_buckets}"
    if dcn > 1:
        label += f"_hier{dcn}x{train_cfg.data}_{wire_dcn or 'fp32'}"
    log_every = max(1, min(iters // 10, 25))
    kw = {}
    if checkpoint_dir is not None:
        # Watchdogged runs: resume from the latest checkpoint, save often,
        # and stream rows into the CSV as they happen — a killed run loses
        # at most sink_every iterations of record (a retried segment
        # re-writes identical rows; dedupe_csv cleans the overlap).
        # Per-config subdir: configs have differently-shaped/sharded states,
        # so sharing one orbax dir across them would restore garbage.
        import os
        kw = dict(checkpoint_dir=os.path.join(checkpoint_dir, name),
                  checkpoint_every=50,
                  loss_sink=lambda it, loss: sink.write(
                      {"iter": it, "loss": loss, "data": provenance,
                       "config": label}))
    if faults or guard or elastic:
        # Chaos/guarded/elastic runs (resilience layer): inject the
        # scheduled faults, wrap the step in a StepGuard, and/or arm the
        # elastic replica-loss recovery; counters print at the end so
        # the run's survival is attributable, not anecdotal.
        from ddl25spring_tpu.config import ResilienceConfig
        kw["resilience"] = ResilienceConfig(guard=guard, faults=faults,
                                            fault_seed=fault_seed,
                                            elastic=elastic)
    telemetry = None
    if telemetry_dir is not None:
        # Unified observability (ddl25spring_tpu/telemetry): JSONL event
        # stream + heartbeat per config (configs are separate runs — one
        # dir each, so obs_report and the watchdog's --heartbeat have an
        # unambiguous target). Render afterwards with
        #   python -m experiments.obs_report <telemetry-dir>/<config>
        import os as _os

        from ddl25spring_tpu.telemetry import Telemetry
        telemetry = Telemetry(_os.path.join(telemetry_dir, name))
        kw["telemetry"] = telemetry
    try:
        if zero1:
            kw["aggregation"] = "zero1"
        if topo["stage"] > 1:
            report = train_llm_pp(model_cfg, train_cfg, log_every=log_every,
                                  **kw)
        else:
            report = train_llm_dp(model_cfg, train_cfg, log_every=log_every,
                                  **kw)
    finally:
        if telemetry is not None:
            telemetry.close()
            print(f"{name}: telemetry -> {telemetry.out_dir}", flush=True)
    if report.resilience is not None and (faults or guard or elastic):
        print(f"{name}: resilience counters "
              f"{ {k: v for k, v in report.resilience.as_dict().items() if v} }",
              flush=True)
    for rec in report.remeshes:
        topo_note = ""
        if rec.get("old_shape") and rec.get("new_shape"):
            topo_note = (f" [{rec['old_shape'][0]}x{rec['old_shape'][1]} -> "
                         f"{rec['new_shape'][0]}x{rec['new_shape'][1]} on "
                         f"the {rec.get('axis', 'data')} axis]")
        print(f"{name}: remesh {rec['old_world']} -> {rec['new_world']}"
              f"{topo_note} via {rec['path']} in {rec['seconds']:.3f}s "
              f"({rec['steps_replayed']} steps replayed)", flush=True)
    if not report.losses:
        return {}  # resumed past the end; nothing new to record
    # Resume offset (0 for a fresh run). NOT iters - len(losses): a
    # preempted run's losses end at the preempt step, not at iters.
    base = report.start_step
    if checkpoint_dir is None:  # sink mode already wrote its rows
        for it in range(0, len(report.losses), 10):
            sink.write({"iter": base + it, "loss": report.losses[it],
                        "data": provenance, "config": label})
        sink.write({"iter": base + len(report.losses) - 1,
                    "loss": report.losses[-1],
                    "data": provenance, "config": label})
    print(f"{name}: loss {report.losses[0]:.3f} -> {report.losses[-1]:.3f} "
          f"over iters {base}..{base + len(report.losses)} "
          f"({report.tokens_per_sec:.0f} tok/s) [{provenance}]", flush=True)
    return {f"{name}_first": report.losses[0],
            f"{name}_last": report.losses[-1],
            f"{name}_tokens_per_sec": report.tokens_per_sec}


def main(quick: bool = False, iters: int = 5000,
         configs=("dp1",), append: bool = False,
         checkpoint_dir: str = None, faults: str = "",
         fault_seed: int = 0, guard: bool = False,
         telemetry_dir: str = None, steps_per_dispatch: int = 1,
         zero1: bool = False, elastic: bool = False,
         numerics_every: int = 0, wire: str = "fp32",
         overlap_microbatches: int = 0, comm_buckets: int = 1,
         dcn: int = 1, wire_dcn: str = "") -> Dict[str, float]:
    """``configs`` picks topologies from CONFIGS; the multi-device ones need
    >= 6 (virtual) devices — run_all keeps the dp1 default so the suite works
    on a single real chip, and the pipeline rows are appended by
    ``python -m experiments.hw1b_llm --configs pp3 dp2_pp3 --append``."""
    import os

    from ddl25spring_tpu.utils.tracing import ResultSink

    provenance = common.tinystories_provenance()
    if checkpoint_dir is not None and not append:
        # A resumed run only re-emits rows from its checkpoint onward; a
        # fresh (replacing) sink would silently truncate the curve's head.
        raise ValueError("--checkpoint-dir requires --append: a resumed run "
                         "cannot rebuild the CSV rows before its checkpoint")
    if quick:
        iters = 50
    if append:
        sink = ResultSink(os.path.join(common.RESULTS_DIR,
                                       "hw1b_llm_loss.csv"))
    else:
        sink = common.sink("hw1b_llm_loss.csv")
    out: Dict[str, float] = {}
    for name in configs:
        out.update(_run_config(name, iters, sink, provenance,
                               checkpoint_dir=checkpoint_dir, faults=faults,
                               fault_seed=fault_seed, guard=guard,
                               telemetry_dir=telemetry_dir,
                               steps_per_dispatch=steps_per_dispatch,
                               zero1=zero1, elastic=elastic,
                               numerics_every=numerics_every, wire=wire,
                               overlap_microbatches=overlap_microbatches,
                               comm_buckets=comm_buckets,
                               dcn=dcn, wire_dcn=wire_dcn))
    print(f"-> {sink.path}")
    # run_all compatibility: single-config calls keep the old summary keys.
    if len(configs) == 1 and f"{configs[0]}_first" in out:
        n = configs[0]
        out = {"first": out[f"{n}_first"], "last": out[f"{n}_last"],
               "tokens_per_sec": out[f"{n}_tokens_per_sec"]}
    return out


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--iters", type=int, default=5000)
    ap.add_argument("--configs", nargs="*", default=["dp1"],
                    choices=sorted(CONFIGS))
    ap.add_argument("--append", action="store_true",
                    help="append to the committed CSV instead of replacing")
    ap.add_argument("--cpu", action="store_true",
                    help="pin CPU and force enough virtual devices for the "
                         "multi-stage configs")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="orbax checkpoint/resume dir — lets a watchdog "
                         "kill and relaunch a wedged virtual-mesh run "
                         "without losing progress (saves every 50 iters)")
    ap.add_argument("--faults", default="",
                    help="resilience FaultPlan spec, e.g. "
                         "'nan_grad@10,preempt@25' (implies --guard makes "
                         "sense; see ddl25spring_tpu/resilience/faults.py)")
    ap.add_argument("--fault-seed", type=int, default=0)
    ap.add_argument("--guard", action="store_true",
                    help="wrap the train step in a StepGuard (skip "
                         "non-finite steps, EMA spike detection, rollback)")
    ap.add_argument("--telemetry-dir", default=None,
                    help="write a JSONL event stream + heartbeat per config "
                         "under this dir (telemetry/); point the watchdog's "
                         "--heartbeat at <dir>/<config>/heartbeat.json and "
                         "render with python -m experiments.obs_report")
    ap.add_argument("--steps-per-dispatch", type=int, default=1,
                    help="fuse K training steps into one compiled dispatch "
                         "(lax.scan over a [K, B, T] window — dp.make_multi_"
                         "step / pp.make_pipeline_multi_step; loss "
                         "trajectory bit-identical to K=1, host work "
                         "quantized to chunk edges; works on DP AND PP "
                         "configs)")
    ap.add_argument("--zero1", action="store_true",
                    help="ZeRO-1 sharded weight update (dp.make_zero1_step: "
                         "reduce-scatter grads, Adam on each replica's 1/N "
                         "slice, all-gather params — composes with "
                         "--steps-per-dispatch; on PP configs it shards "
                         "the data axis of DP×PP and needs "
                         "--overlap-microbatches >= 1)")
    ap.add_argument("--numerics-every", type=int, default=0,
                    help="in-jit numerics summaries (telemetry/"
                         "introspect.py): emit a per-layer-group "
                         "grad/param/update-norm event every N steps; "
                         "0 disables (bitwise-free — losses identical on "
                         "vs off; PP configs get stage-stacked groups)")
    ap.add_argument("--wire", default="fp32",
                    choices=["fp32", "bf16", "int8_ef"],
                    help="gradient-sync wire format (parallel/compress.py); "
                         "composes with --zero1/--steps-per-dispatch only "
                         "through --overlap-microbatches >= 1 (the ring "
                         "driver; on PP configs the ring carries the "
                         "DP×PP data-axis sync)")
    ap.add_argument("--overlap-microbatches", type=int, default=0,
                    help="ACCO-style overlapped ring driver (parallel/"
                         "compress.py; pp.make_pipeline_overlap_* on PP "
                         "configs): split each step into M microbatches "
                         "and overlap microbatch k+1's grad compute with "
                         "microbatch k's ppermute ring reduce-scatter, "
                         "in-flight chunks in --wire's format; 1 = "
                         "no-split compressed ring, 0 = legacy paths")
    ap.add_argument("--comm-buckets", type=int, default=1,
                    help="bucketed backward (ISSUE 19): split each "
                         "microbatch's ring into N VJP-emission-ordered "
                         "buckets so the first ppermute hop dispatches "
                         "before the full gradient materializes; total "
                         "wire bytes invariant in N (needs "
                         "--overlap-microbatches >= 1; composes with "
                         "--wire/--zero1/--steps-per-dispatch on DP, PP "
                         "and hierarchical configs; recorded in the run "
                         "manifest)")
    ap.add_argument("--dcn", type=int, default=1,
                    help="hierarchical DP: --dcn islands of --data-sized "
                         "ICI tiers bridged by DCN (hier_data_mesh); the "
                         "two-level ring driver runs with --wire on the "
                         "ICI tier and --wire-dcn across DCN (needs "
                         "--overlap-microbatches >= 1); DP configs only")
    ap.add_argument("--wire-dcn", default="",
                    choices=["", "fp32", "bf16", "int8_ef"],
                    help="DCN-tier wire format of the two-level "
                         "hierarchical collectives (int8_ef = the "
                         "compress-where-scarce headline)")
    ap.add_argument("--elastic", action="store_true",
                    help="elastic recovery (resilience/elastic.py): "
                         "survive device loss (inject with --faults "
                         "'device_loss@K') by re-meshing onto the "
                         "survivors and resharding state; on PP configs "
                         "a loss with a surviving stage column drops the "
                         "data row, otherwise layers re-partition onto "
                         "fewer stages")
    a = ap.parse_args()
    if a.cpu:
        from ._cpu_pin import pin_cpu_virtual

        # NOTE: topologies with ~6 collective participants can wedge
        # stochastically on this host (mode 3 in _cpu_pin — no runtime
        # fix exists); drive them through experiments/watchdog.py with
        # --checkpoint-dir so a killed run resumes.
        pin_cpu_virtual()
    main(quick=a.quick, iters=a.iters, configs=a.configs, append=a.append,
         checkpoint_dir=a.checkpoint_dir, faults=a.faults,
         fault_seed=a.fault_seed, guard=a.guard,
         telemetry_dir=a.telemetry_dir,
         steps_per_dispatch=a.steps_per_dispatch, zero1=a.zero1,
         elastic=a.elastic, numerics_every=a.numerics_every, wire=a.wire,
         overlap_microbatches=a.overlap_microbatches,
         comm_buckets=a.comm_buckets, dcn=a.dcn, wire_dcn=a.wire_dcn)
