"""hw1b tiny-Llama loss trajectory — the reference's DP/PP training runs.

Reproduces the committed run logs' configuration: dmodel=288, 6 heads,
6 layers, seq 256, Adam lr 8e-4, batch 3 per data shard, 5000 iterations
(reference: lab/tutorial_1b/primer/intro.py:7-23). The reference logs show
loss 10.517 → ≈6.08-6.25 over 5000 iters (lab/out_b1_2.txt) and the DP×PP
variant 10.517/10.551 → ≈5.78-6.25 (lab/out_b2_*.txt).

This environment has no TinyStories download, so the stream falls back to
the in-repo synthetic grammar (data/tokens.py) — the curve's *shape* (init
≈ ln(32000) ≈ 10.4, fast early decay) is comparable; absolute perplexity is
corpus-dependent. Every 10th-iteration loss lands in
``experiments/results/hw1b_llm_loss.csv`` with the provenance column.
"""

from __future__ import annotations

import argparse
from typing import Dict

from ddl25spring_tpu.config import LlamaConfig, TrainConfig
from ddl25spring_tpu.train.llm import train_llm_dp

from . import common


def main(quick: bool = False, iters: int = 5000) -> Dict[str, float]:
    provenance = common.tinystories_provenance()
    if quick:
        iters = 50
    sink = common.sink("hw1b_llm_loss.csv")
    train_cfg = TrainConfig(iters=iters)  # batch 3, seq 256, Adam 8e-4
    model_cfg = LlamaConfig(dtype="bfloat16")
    report = train_llm_dp(model_cfg, train_cfg, log_every=max(1, iters // 10))
    for it in range(0, len(report.losses), 10):
        sink.write({"iter": it, "loss": report.losses[it], "data": provenance,
                    "config": "dp1_b3_seq256_adam8e-4"})
    sink.write({"iter": len(report.losses) - 1, "loss": report.losses[-1],
                "data": provenance, "config": "dp1_b3_seq256_adam8e-4"})
    print(f"loss {report.losses[0]:.3f} -> {report.losses[-1]:.3f} over "
          f"{iters} iters ({report.tokens_per_sec:.0f} tok/s) [{provenance}]")
    print(f"-> {sink.path}")
    return {"first": report.losses[0], "last": report.losses[-1],
            "tokens_per_sec": report.tokens_per_sec}


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--iters", type=int, default=5000)
    a = ap.parse_args()
    main(quick=a.quick, iters=a.iters)
