"""hw2 VFL experiments — permutation seeds, client scaling, VFL-VAE.

Reproduces the reference's homework-2 battery (lab/hw02/Tea_Pula_HW2.ipynb):
- cells 2-6:  4-client VFL on heart.csv, 300 epochs, B=64 — final test
  accuracy 84.8-85.3% across 3 seeded feature permutations.
- cell 15:   client scaling 2→10 with the even partitioner — accuracy
  declines from ≈85.3% toward ≈77%.
- cell 23:   the min-2-features partitioner — up to 90.7% at 2 clients,
  ≈82-84% at 8-10.
- cell 40:   VFL-VAE, 4 clients × latent 4, 1000 epochs — final total loss
  ≈4.10 (recon 3.97 + KL 0.128).

heart.csv is REAL in this environment (read from the reference tree at
runtime), so these numbers are directly comparable. Curves land in
``experiments/results/hw2_vfl.csv`` / ``hw2_vfl_vae.csv``.
"""

from __future__ import annotations

import argparse
from typing import Dict

from ddl25spring_tpu.config import VFLConfig
from ddl25spring_tpu.train.vfl import train_vfl, train_vfl_vae

from . import common


def main(quick: bool = False) -> Dict[str, float]:
    provenance = common.heart_provenance()
    epochs = 20 if quick else 300
    finals: Dict[str, float] = {}
    sink = common.sink("hw2_vfl.csv")

    # --- 4-client VFL across 3 seeded permutations (cells 2-6) ----------
    for seed in (0, 1, 2):
        xs_tr, y_tr, xs_te, y_te, _ = common.heart_vfl_setup(
            4, "even", seed=seed)
        cfg = VFLConfig(nr_clients=4, epochs=epochs, seed=seed)
        _, rep = train_vfl(xs_tr, y_tr, xs_te, y_te, cfg)
        finals[f"vfl4/perm{seed}"] = rep.test_accuracy
        sink.write({"experiment": "vfl_4client", "partitioner": "even",
                    "nr_clients": 4, "seed": seed, "epochs": epochs,
                    "final_train_acc": rep.train_accuracies[-1],
                    "test_accuracy": rep.test_accuracy, "data": provenance})
        print(f"vfl 4 clients perm {seed}: test acc {rep.test_accuracy:.4f}")

    # --- duplicate-aware split: honest generalization numbers -----------
    # heart.csv is the Kaggle duplicate-expanded UCI set; the reference's
    # random split leaks test twins into train, so a correctly-trained model
    # scores ≈100% above. These rows use the dedup split (no test row has an
    # identical twin in train) — the number a practitioner should trust.
    for seed in (0, 1, 2):
        xs_tr, y_tr, xs_te, y_te, _ = common.heart_vfl_setup(
            4, "even", seed=seed, dedup=True)
        cfg = VFLConfig(nr_clients=4, epochs=epochs, seed=seed)
        _, rep = train_vfl(xs_tr, y_tr, xs_te, y_te, cfg)
        finals[f"vfl4-dedup/perm{seed}"] = rep.test_accuracy
        sink.write({"experiment": "vfl_4client_dedup", "partitioner": "even",
                    "nr_clients": 4, "seed": seed, "epochs": epochs,
                    "final_train_acc": rep.train_accuracies[-1],
                    "test_accuracy": rep.test_accuracy, "data": provenance})
        print(f"vfl 4 clients perm {seed} DEDUP: test acc {rep.test_accuracy:.4f}")

    # --- client scaling 2→10, even and min-2 partitioners (cells 15, 23) -
    for partitioner in ("even", "min2"):
        for n in range(2, 11):
            xs_tr, y_tr, xs_te, y_te, _ = common.heart_vfl_setup(
                n, partitioner, seed=0)
            cfg = VFLConfig(nr_clients=n, epochs=epochs, seed=0)
            _, rep = train_vfl(xs_tr, y_tr, xs_te, y_te, cfg)
            finals[f"vfl-{partitioner}/{n}"] = rep.test_accuracy
            sink.write({"experiment": "client_scaling",
                        "partitioner": partitioner, "nr_clients": n,
                        "seed": 0, "epochs": epochs,
                        "final_train_acc": rep.train_accuracies[-1],
                        "test_accuracy": rep.test_accuracy,
                        "data": provenance})
            print(f"vfl {partitioner:4s} {n:2d} clients: "
                  f"test acc {rep.test_accuracy:.4f}")

    # --- faithful-protocol battery + per-quirk attribution --------------
    finals.update(_faithful_rows(sink, provenance, epochs))

    # --- VFL-VAE (cell 40) ----------------------------------------------
    sink_v = common.sink("hw2_vfl_vae.csv")
    vae_epochs = 50 if quick else 1000
    xs_tr, _, _, _, _ = common.heart_vfl_setup(4, "even", seed=0)
    _, vrep = train_vfl_vae(xs_tr, VFLConfig(nr_clients=4, seed=0),
                            epochs=vae_epochs, client_latent=4)
    for e in range(0, vae_epochs, max(1, vae_epochs // 100)):
        sink_v.write({"epoch": e, "total": vrep.total_losses[e],
                      "recon": vrep.recon_losses[e], "kl": vrep.kl_losses[e],
                      "data": provenance})
    finals["vfl_vae/total"] = vrep.total_losses[-1]
    finals["vfl_vae/recon"] = vrep.recon_losses[-1]
    finals["vfl_vae/kl"] = vrep.kl_losses[-1]
    print(f"vfl-vae @{vae_epochs} epochs: total {vrep.total_losses[-1]:.3f} "
          f"= recon {vrep.recon_losses[-1]:.3f} + kl {vrep.kl_losses[-1]:.3f}")
    print(f"-> {sink.path}, {sink_v.path} [{provenance}]")
    return finals


def _faithful_rows(sink, provenance: str, epochs: int) -> Dict[str, float]:
    """The faithful + per-quirk battery — one implementation shared by the
    full run (main) and the in-place refresh (faithful_only).

    The reference's published 84.8-85.3% band was measured through four
    protocol quirks (train/vfl.py module docstring), dominated by the
    frozen-bottoms bug: VFLNetwork holds its bottoms in a plain Python
    list, so optim.AdamW(self.parameters()) never steps them — only the
    top model learns, on frozen random client features (vfl.py:48-50).
    `faithful` rows run the 3-permutation battery under all four quirks;
    the `quirk_*` rows toggle one at a time at seed 0.
    """
    finals: Dict[str, float] = {}

    def one(experiment: str, final_key: str, label: str, seed: int, **kw):
        xs_tr, y_tr, xs_te, y_te, _ = common.heart_vfl_setup(
            4, "even", seed=seed)
        cfg = VFLConfig(nr_clients=4, epochs=epochs, seed=seed)
        _, rep = train_vfl(xs_tr, y_tr, xs_te, y_te, cfg, **kw)
        finals[final_key] = rep.test_accuracy
        sink.write({"experiment": experiment, "partitioner": "even",
                    "nr_clients": 4, "seed": seed, "epochs": epochs,
                    "final_train_acc": rep.train_accuracies[-1],
                    "test_accuracy": rep.test_accuracy,
                    "test_accuracy_clean": rep.test_accuracy_clean,
                    "data": provenance})
        print(f"vfl 4 clients {label}: test acc {rep.test_accuracy:.4f} "
              f"(clean {rep.test_accuracy_clean:.4f})", flush=True)

    for seed in (0, 1, 2):
        one("vfl_4client_faithful", f"vfl4-faithful/perm{seed}",
            f"perm {seed} FAITHFUL", seed, faithful=True)
    quirks = {"frozen": dict(train_bottoms=False),
              "wd": dict(weight_decay=1e-2),
              "accum": dict(accumulate_epoch_grads=True),
              "evaldrop": dict(eval_dropout=True)}
    for name, kw in quirks.items():
        one(f"vfl_4client_quirk_{name}", f"vfl4-quirk/{name}",
            f"quirk={name:8s}", 0, **kw)
    return finals


def faithful_only(epochs: int = 300) -> None:
    """Rerun ONLY the faithful + quirk rows, replacing them in the committed
    CSV (the rest of the battery is untouched — identical protocol, no need
    to re-measure)."""
    import os

    import pandas as pd

    from ddl25spring_tpu.utils.tracing import ResultSink

    path = os.path.join(common.RESULTS_DIR, "hw2_vfl.csv")
    df = pd.read_csv(path)
    keep = ~df["experiment"].str.startswith(("vfl_4client_faithful",
                                             "vfl_4client_quirk"))
    df[keep].to_csv(path, index=False)
    _faithful_rows(ResultSink(path), common.heart_provenance(), epochs)


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--faithful-only", action="store_true",
                    help="rerun only the faithful/quirk rows in place")
    a = ap.parse_args()
    if a.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    if a.faithful_only:
        faithful_only()
    else:
        main(quick=a.quick)
