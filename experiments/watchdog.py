"""Stall-watchdog runner for wedge-prone virtual-mesh training runs.

The oversubscribed 1-core host can wedge an XLA-CPU collective rendezvous
mid-run (failure modes 1-3 in experiments/_cpu_pin.py; mode 3's legacy-
runtime fix still leaves a residual stochastic wedge on 6-participant
topologies). This driver makes long runs immune by construction: launch the
training command, watch its progress file (the CSV the run streams rows
into), and if the file stops growing for ``--stall-min`` minutes, kill the
process and relaunch — the run resumes from its orbax checkpoint and
re-streams only the lost tail. On success, duplicate rows from retried
segments are deduped in place.

Example (the b2-topology loss curve):
    python -m experiments.watchdog \
        --progress experiments/results/hw1b_llm_loss.csv \
        --dedupe-keys config iter -- \
        python -m experiments.hw1b_llm --cpu --configs dp2_pp3 \
        --iters 1000 --append --checkpoint-dir /tmp/ck_dp2pp3
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time


def file_size(path: str) -> int:
    try:
        return os.stat(path).st_size
    except OSError:
        return -1


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--progress", required=True,
                    help="file whose growth proves the run is alive")
    ap.add_argument("--stall-min", type=float, default=12.0,
                    help="kill+relaunch after this many minutes without "
                         "progress-file growth")
    ap.add_argument("--max-restarts", type=int, default=30)
    ap.add_argument("--dedupe-keys", nargs="*", default=None,
                    help="CSV columns identifying a row; dedupe the "
                         "progress file on success")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- then the training command")
    a = ap.parse_args()
    cmd = a.cmd[1:] if a.cmd and a.cmd[0] == "--" else a.cmd
    if not cmd:
        ap.error("no command given after --")

    poll_s = 30.0
    for attempt in range(a.max_restarts + 1):
        print(f"[watchdog] attempt {attempt}: {' '.join(cmd)}", flush=True)
        proc = subprocess.Popen(cmd)
        last_size = file_size(a.progress)
        last_change = time.time()
        while True:
            try:
                rc = proc.wait(timeout=poll_s)
                break
            except subprocess.TimeoutExpired:
                pass
            size = file_size(a.progress)
            if size != last_size:
                last_size, last_change = size, time.time()
            elif time.time() - last_change > a.stall_min * 60:
                print(f"[watchdog] no growth of {a.progress} for "
                      f"{a.stall_min} min — killing pid {proc.pid}",
                      flush=True)
                proc.kill()
                proc.wait()
                rc = None
                break
        if rc == 0:
            if a.dedupe_keys:
                from .common import dedupe_csv
                removed = dedupe_csv(a.progress, a.dedupe_keys)
                print(f"[watchdog] done; deduped {removed} retried rows",
                      flush=True)
            else:
                print("[watchdog] done", flush=True)
            return 0
        if rc is not None:
            print(f"[watchdog] command exited rc={rc}; retrying from "
                  f"checkpoint", flush=True)
    print("[watchdog] gave up after max restarts", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
