"""Stall-watchdog runner for wedge-prone virtual-mesh training runs.

The oversubscribed 1-core host can wedge an XLA-CPU collective rendezvous
mid-run (failure modes 1-3 in experiments/_cpu_pin.py; mode 3's legacy-
runtime fix still leaves a residual stochastic wedge on 6-participant
topologies). This driver makes long runs immune by construction: launch the
training command, watch its progress file (the CSV the run streams rows
into), and if the file stops growing for ``--stall-min`` minutes, kill the
process and relaunch — the run resumes from its orbax checkpoint and
re-streams only the lost tail. On success, duplicate rows from retried
segments are deduped in place.

Liveness signals (LivenessMonitor): progress-file growth, and — when the
run writes a telemetry heartbeat (``--heartbeat``, telemetry/heartbeat.py)
— the heartbeat's monotonic ``seq`` advancing. The heartbeat is the
FIRST-CLASS signal: it beats every iteration, where the CSV only grows per
sink interval (and not at all for runs without a loss sink), so a healthy
run between sink rows no longer looks stalled. Either signal moving counts
as alive; a new pid in the heartbeat also counts (a relaunch IS life).

Health signals (``--slo-events``, experiments/slo_monitor.py): point the
watchdog at the run's telemetry ``events.jsonl`` and it tails the stream
alongside the heartbeat — new events count as liveness, and the embedded
rolling-window SLOMonitor (thresholds via ``--slo-*``) distinguishes a run
that is alive-but-unhealthy from one that is merely alive: violations are
logged as they transition into breach, and with ``--slo-grace`` seconds of
SUSTAINED breach the run is killed and relaunched exactly like a stall —
a serving process emitting heartbeats while its p99 TTFT burns is a
failure the heartbeat alone can never see.

Relaunches back off exponentially with deterministic jitter
(ddl25spring_tpu/resilience/retry.py), and crash-loops are distinguished
from stalls: a process that exits nonzero within ``--crash-window`` seconds
is crashing, not wedging — after ``--crash-loop-limit`` consecutive crashes
the watchdog exits with code 3 instead of burning all ``--max-restarts``
against a broken command. Exit codes: 0 success, 1 gave up on stalls/slow
failures, 3 crash loop.

Example (the b2-topology loss curve):
    python -m experiments.watchdog \
        --progress experiments/results/hw1b_llm_loss.csv \
        --dedupe-keys config iter -- \
        python -m experiments.hw1b_llm --cpu --configs dp2_pp3 \
        --iters 1000 --append --checkpoint-dir /tmp/ck_dp2pp3
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time


def file_size(path: str) -> int:
    try:
        return os.stat(path).st_size
    except OSError:
        return -1


class LivenessMonitor:
    """Combined stall detector: progress-file growth OR heartbeat advance.

    ``poll()`` returns True when ANY enabled signal moved since the previous
    poll. The heartbeat signal is ``(pid, seq)`` — seq is the writer's
    monotonic beat counter, and pairing it with pid makes a relaunched
    writer (whose seq restarts at 1, possibly colliding with an old value)
    register as movement. A missing/torn heartbeat file reads as "no
    signal" (telemetry.heartbeat.read_heartbeat), never as an error — the
    progress file then carries liveness alone, which is exactly the
    pre-heartbeat behavior.
    """

    def __init__(self, progress_path: str,
                 heartbeat_path: "str | None" = None):
        self.progress_path = progress_path
        self.heartbeat_path = heartbeat_path
        self._size = file_size(progress_path)
        self._beat = self._read_beat()

    def _read_beat(self):
        if not self.heartbeat_path:
            return None
        # Direct module import: heartbeat.py is stdlib-only, keeping the
        # watchdog process jax-free (the package __init__'s jax-touching
        # comm re-exports are lazy, but this makes the contract explicit).
        from ddl25spring_tpu.telemetry.heartbeat import read_heartbeat
        hb = read_heartbeat(self.heartbeat_path)
        return None if hb is None else (hb.get("pid"), hb["seq"])

    def poll(self) -> bool:
        moved = False
        size = file_size(self.progress_path)
        if size != self._size:
            self._size, moved = size, True
        beat = self._read_beat()
        if beat is not None and beat != self._beat:
            self._beat, moved = beat, True
        return moved


EXIT_GAVE_UP = 1      # burned --max-restarts on stalls/slow failures
EXIT_CRASH_LOOP = 3   # consecutive immediate exits: relaunching won't help


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--progress", required=True,
                    help="file whose growth proves the run is alive")
    ap.add_argument("--heartbeat", default=None,
                    help="telemetry heartbeat file (heartbeat.json) — its "
                         "seq advancing also proves liveness, at per-"
                         "iteration rather than per-sink-row granularity")
    ap.add_argument("--stall-min", type=float, default=12.0,
                    help="kill+relaunch after this many minutes without "
                         "progress-file growth or heartbeat advance")
    ap.add_argument("--max-restarts", type=int, default=30)
    ap.add_argument("--backoff-base", type=float, default=5.0,
                    help="seconds before the first relaunch; doubles per "
                         "consecutive failure (jittered, capped 120 s)")
    ap.add_argument("--crash-window", type=float, default=5.0,
                    help="a nonzero exit within this many seconds of launch "
                         "counts as a crash, not a stall")
    ap.add_argument("--poll-s", type=float, default=30.0,
                    help="liveness-poll period: how often the child's "
                         "progress signals are re-read while it runs. The "
                         "default suits real training runs (a poll is a "
                         "stat + tail read); tests tighten it so stall "
                         "detection latency — bounded below by one poll "
                         "tick regardless of --stall-min — doesn't "
                         "dominate their wall time")
    ap.add_argument("--crash-loop-limit", type=int, default=3,
                    help="this many consecutive crashes -> exit "
                         f"{EXIT_CRASH_LOOP} (crash loop: the command is "
                         "broken, relaunching won't help)")
    ap.add_argument("--slo-events", default=None,
                    help="telemetry events.jsonl to tail: growth counts as "
                         "liveness, and the --slo-* thresholds are "
                         "evaluated over it as rolling-window health")
    ap.add_argument("--slo-window", type=float, default=30.0,
                    help="SLO rolling window (seconds)")
    ap.add_argument("--slo-ttft-p99", type=float, default=None,
                    help="p99 TTFT ceiling (s)")
    ap.add_argument("--slo-queue-p99", type=float, default=None,
                    help="p99 queue-wait ceiling (s)")
    ap.add_argument("--slo-min-tps", type=float, default=None,
                    help="sustained tokens/sec floor while work is "
                         "outstanding")
    ap.add_argument("--slo-max-skip-rate", type=float, default=None,
                    help="StepGuard skipped-steps/steps ceiling")
    ap.add_argument("--slo-mfu", type=float, default=None,
                    help="MFU floor over the window (schema v5 "
                         "compile/step events vs the manifest's roofline "
                         "peaks — slo_monitor's --slo-mfu)")
    ap.add_argument("--slo-gradnorm", type=float, default=None,
                    help="grad-norm spike-rate ceiling over the window's "
                         "numerics samples (slo_monitor's --slo-gradnorm)")
    ap.add_argument("--slo-grace", type=float, default=0.0,
                    help="kill+relaunch after this many seconds of "
                         "SUSTAINED SLO breach (0 = log violations only)")
    ap.add_argument("--dedupe-keys", nargs="*", default=None,
                    help="CSV columns identifying a row; dedupe the "
                         "progress file on success")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- then the training command")
    a = ap.parse_args()
    cmd = a.cmd[1:] if a.cmd and a.cmd[0] == "--" else a.cmd
    if not cmd:
        ap.error("no command given after --")

    from ddl25spring_tpu.resilience.retry import backoff_schedule

    # Deterministic jittered relaunch delays (resilience/retry.py) — a
    # crashing command no longer burns all --max-restarts in seconds.
    delays = backoff_schedule(a.max_restarts, base=a.backoff_base,
                              max_delay=120.0, seed=0)
    poll_s = a.poll_s
    consecutive_crashes = 0
    consecutive_failures = 0  # resets when a segment makes progress
    slo_enabled = a.slo_events is not None
    if slo_enabled:
        # Stdlib-only imports (slo_monitor never touches jax), deferred so
        # plain watchdog runs don't even read the module.
        from .slo_monitor import SLOConfig, SLOMonitor, StreamTailer
        from ddl25spring_tpu.telemetry.heartbeat import read_heartbeat
        slo_cfg = SLOConfig(window_s=a.slo_window,
                            ttft_p99_s=a.slo_ttft_p99,
                            queue_p99_s=a.slo_queue_p99,
                            min_tokens_per_sec=a.slo_min_tps,
                            max_skip_rate=a.slo_max_skip_rate,
                            min_mfu=a.slo_mfu,
                            max_gradnorm_spike_rate=a.slo_gradnorm)
    for attempt in range(a.max_restarts + 1):
        print(f"[watchdog] attempt {attempt}: {' '.join(cmd)}", flush=True)
        launched = time.time()
        proc = subprocess.Popen(cmd)
        monitor = LivenessMonitor(a.progress, a.heartbeat)
        if slo_enabled:
            # Fresh per attempt, attached at the stream's CURRENT end: a
            # relaunch must not inherit the dead run's breach state, and
            # the monitor's outstanding-work counters are cumulative — a
            # killed run's never-completed request_enqueue events would
            # otherwise arm the stall gate against the healthy relaunch
            # forever (its requests complete under NEW ids).
            tailer = StreamTailer(a.slo_events, from_end=True)
            slo = SLOMonitor(slo_cfg)
            first_breach = None
        last_change = time.time()
        progressed = False
        while True:
            try:
                rc = proc.wait(timeout=poll_s)
                break
            except subprocess.TimeoutExpired:
                pass
            moved = monitor.poll()
            if slo_enabled:
                fresh_events = tailer.poll()
                if fresh_events:
                    slo.feed(fresh_events)
                    moved = True            # a growing stream IS liveness
                hb = (read_heartbeat(a.heartbeat) if a.heartbeat else None)
                for v in slo.evaluate(time.time(), hb):
                    print(f"[watchdog] SLO VIOLATION {v['slo']}: "
                          f"{v['value']:.4g} vs {v['threshold']:.4g} "
                          f"(window {v['window_s']:.0f}s)", flush=True)
                if slo.active:
                    first_breach = first_breach or time.time()
                    if (a.slo_grace > 0
                            and time.time() - first_breach > a.slo_grace):
                        print(f"[watchdog] SLOs {sorted(slo.active)} "
                              f"breached for > {a.slo_grace:.0f}s — "
                              f"killing pid {proc.pid}", flush=True)
                        proc.kill()
                        proc.wait()
                        rc = None
                        break
                else:
                    first_breach = None
            if moved:
                last_change = time.time()
                progressed = True
            elif time.time() - last_change > a.stall_min * 60:
                print(f"[watchdog] no growth of {a.progress}"
                      + (f" and no heartbeat in {a.heartbeat}"
                         if a.heartbeat else "")
                      + f" for {a.stall_min} min — killing pid {proc.pid}",
                      flush=True)
                proc.kill()
                proc.wait()
                rc = None
                break
        if rc == 0:
            if a.dedupe_keys:
                from .common import dedupe_csv
                removed = dedupe_csv(a.progress, a.dedupe_keys)
                print(f"[watchdog] done; deduped {removed} retried rows",
                      flush=True)
            else:
                print("[watchdog] done", flush=True)
            return 0
        if rc is not None:
            elapsed = time.time() - launched
            if elapsed < a.crash_window:
                # Immediate exit: an import error, bad flag, or missing file
                # — a different failure class from the stalls this tool
                # exists for, and one a relaunch cannot fix.
                consecutive_crashes += 1
                print(f"[watchdog] command CRASHED rc={rc} after "
                      f"{elapsed:.1f}s ({consecutive_crashes}/"
                      f"{a.crash_loop_limit})", flush=True)
                if consecutive_crashes >= a.crash_loop_limit:
                    print("[watchdog] crash loop — the command fails "
                          "immediately; fix it instead of relaunching",
                          file=sys.stderr)
                    return EXIT_CRASH_LOOP
            else:
                consecutive_crashes = 0
                print(f"[watchdog] command exited rc={rc}; retrying from "
                      f"checkpoint", flush=True)
        else:
            consecutive_crashes = 0  # a stall kill is not a crash
        # Backoff doubles per CONSECUTIVE failure: a segment that grew the
        # progress file resets the ladder, so a stall after hours of healthy
        # training relaunches at --backoff-base, not at the cap.
        consecutive_failures = 1 if progressed else consecutive_failures + 1
        if attempt < a.max_restarts:
            delay = delays[min(consecutive_failures - 1, len(delays) - 1)]
            print(f"[watchdog] backing off {delay:.1f}s before relaunch",
                  flush=True)
            time.sleep(delay)
    print("[watchdog] gave up after max restarts", file=sys.stderr)
    return EXIT_GAVE_UP


if __name__ == "__main__":
    sys.exit(main())
