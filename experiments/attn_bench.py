"""XLA vs Pallas-flash attention comparison across sequence lengths.

Times forward and forward+backward of the two attention backends
(models/llama._xla_attention vs ops/flash_attention.flash_attention) at the
model's head geometry (H=6, Dh=48) on the real accelerator, holding
tokens-per-call constant. Results → ``experiments/results/attn_bench.csv``
(each row carries a ``platform`` column; a CSV is only evidence for the
``flash_min_seq`` crossover if that column says tpu — run this on the chip
and commit the output when the tunnel is up).

Measured shape of the numbers (v5e, committed CSV): the row-major flash
kernel loses below T≈4096 — it pads Dh=48 to 128 lanes on every HBM
transfer — but the dh-major variant with whole-sequence blocks
(``flash_dhm_wide``: dense [BH, Dh, T] layout, block_q=block_k=min(T,512))
wins at every swept length, from 2.5% at the canonical T=256 to 25x at
T=8192. ``LlamaConfig(attention_impl="auto")`` encodes exactly that
result (dh-major wide pallas iff T ≥ flash_min_seq=256 on TPU).
"""

from __future__ import annotations

import argparse
import time
from typing import Dict

import jax
import jax.numpy as jnp

from ddl25spring_tpu.models.llama import _xla_attention
from ddl25spring_tpu.ops.flash_attention import flash_attention

from . import common


def _sync(r):
    float(jnp.asarray(jax.tree.leaves(r)[0]).reshape(-1)[0])


def _time(f, *args, n=20) -> float:
    for _ in range(3):  # compile + settle: the tunneled platform's first
        r = f(*args)    # dispatches carry latency that pollutes 20-rep means
    _sync(r)
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*args)
    _sync(r)
    return (time.perf_counter() - t0) / n * 1e3


def main(quick: bool = False) -> Dict[str, Dict[str, float]]:
    sink = common.sink("attn_bench.csv")
    h, dh = 6, 48
    configs = [(64, 256), (16, 1024)] if quick else \
              [(64, 256), (16, 1024), (4, 4096), (1, 8192)]
    results: Dict[str, Dict[str, float]] = {}
    platform = jax.devices()[0].platform
    for b, t in configs:
        ks = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(ks[0], (b, t, h, dh), jnp.bfloat16)
        k = jax.random.normal(ks[1], (b, t, h, dh), jnp.bfloat16)
        v = jax.random.normal(ks[2], (b, t, h, dh), jnp.bfloat16)
        row: Dict[str, float] = {}
        variants = (
            ("xla", lambda q, k, v: _xla_attention(q, k, v, causal=True)),
            ("flash", lambda q, k, v: flash_attention(q, k, v, causal=True)),
            # dh-major: dense [BH, Dh, T] operand layout — the head-packing
            # lever for Dh=48 (lane padding costs the row-major kernels
            # 2.67x HBM bytes per q/k/v/o transfer).
            ("flash_dhm", lambda q, k, v: flash_attention(
                q, k, v, causal=True, dh_major=True)),
            # Whole-sequence blocks at T<=512: one grid step per (b, h),
            # no online-softmax recurrence.
            ("flash_dhm_wide", lambda q, k, v: flash_attention(
                q, k, v, causal=True, dh_major=True,
                block_q=min(q.shape[1], 512), block_k=min(q.shape[1], 512))),
        )
        for name, fn in variants:
            fwd = jax.jit(fn)
            fb = jax.jit(jax.grad(
                lambda q, k, v: fn(q, k, v).astype(jnp.float32).sum(),
                argnums=(0, 1, 2)))
            row[f"{name}_fwd_ms"] = _time(fwd, q, k, v)
            row[f"{name}_fwdbwd_ms"] = _time(fb, q, k, v)
        rec = {"batch": b, "seq": t, "heads": h, "head_dim": dh,
               "platform": platform, **{k2: round(v2, 3) for k2, v2 in row.items()}}
        sink.write(rec)
        results[f"b{b}_t{t}"] = row
        fb = {n: ms for n, ms in row.items() if n.endswith("_fwdbwd_ms")}
        winner = min(fb, key=fb.get).replace("_fwdbwd_ms", "")
        print(f"B={b:3d} T={t:5d}: " +
              "   ".join(f"{n.replace('_fwdbwd_ms', '')} f+b {ms:8.2f} ms"
                         for n, ms in fb.items()) +
              f"   ({winner} wins)", flush=True)
    print(f"-> {sink.path} [{platform}]")
    return results


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(quick=ap.parse_args().quick)
