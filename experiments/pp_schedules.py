"""Pipeline schedule measurements (GPipe / 1F1B / interleaved): step time +
compiled memory.

The reference names 1F1B but implements a naive schedule
(lab/tutorial_1b/PP/1F1B/intro_PP_1F1B.py); this framework implements GPipe
(autodiff-transposed scan), true 1F1B, and the interleaved virtual-stage
schedule (parallel/pp.py). Their gradients are bit-equivalent
(tests/test_pp.py); what differs is the resource profile:

- GPipe saves every tick's stage input for the backward replay — activation
  memory O(n_microbatches).
- 1F1B stashes at most 2·n_stages−1 microbatch inputs and rematerializes the
  stage forward in its hand-written backward — memory O(n_stages), compute
  +1 forward per microbatch (Megatron-LM's full-recompute setting). The
  matched-memory GPipe comparison point is ``remat=True``.
- interleaved (v=2 virtual chunks per stage) shrinks the bubble fraction to
  (S−1)/(v·M+S−1) at O(v·M) activation memory; its wall-clock win needs a
  real multi-chip ring (v× more, smaller ppermute hops), so on this CPU
  mesh only the memory/loss columns are meaningful.

The bench host has ONE real chip, so a multi-stage mesh cannot run on real
hardware here; measurements run on the virtual 8-device CPU mesh (wall
times are therefore *relative*, not TPU numbers) and, hardware-independent,
the XLA-compiled per-device temp-buffer sizes from ``compiled.memory_
analysis()`` — the activation-memory claim is visible there. Results →
``experiments/results/pp_schedules.csv``.

Run with the CPU pin (the same recipe as tests/conftest.py):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m experiments.pp_schedules
(python -m experiments.run_all does NOT include this module for that
reason; __main__ below applies the pin itself before importing jax.)
"""

from __future__ import annotations

import argparse
import time
from typing import Dict


def measure(n_stages: int, n_microbatches: int, *, batch_per_mb: int = 2,
            repeats: int = 5, n_layers: int = 8) -> Dict[str, Dict[str, float]]:
    import jax
    import numpy as np
    import optax

    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel import make_mesh, pp

    # 8 layers divides 2/4/8 stages; the 8-stage row needs 16 so the
    # interleaved schedule (S·v=16 chunks) exists there too.
    cfg = LlamaConfig(vocab_size=512, dmodel=64, num_heads=4,
                      n_layers=n_layers, ctx_size=64)
    devices = jax.devices()[:n_stages]
    mesh = make_mesh({"stage": n_stages}, devices=devices)
    optimizer = optax.sgd(0.1)
    tokens = jax.random.randint(
        jax.random.key(1), (batch_per_mb * n_microbatches, cfg.ctx_size), 0,
        cfg.vocab_size)

    n_chunks = 2
    schedules = ["gpipe", "1f1b"]
    if (cfg.n_layers % (n_stages * n_chunks) == 0
            and n_microbatches % n_stages == 0):
        schedules.append("interleaved")   # v=2 virtual chunks per stage
    out: Dict[str, Dict[str, float]] = {}
    for schedule in schedules:
        params = llama.init_llama(jax.random.key(0), cfg)
        if schedule == "interleaved":
            params = pp.interleave_params(params, n_stages, n_chunks)
        state = pp.init_state(mesh, params, optimizer)
        step = pp.make_pipeline_step(cfg, optimizer, mesh, n_microbatches,
                                     schedule=schedule, n_chunks=n_chunks)
        batch = pp.shard_batch(mesh, tokens)
        # The shared memory_analysis guard (telemetry/memory.py) — same
        # lower→compile the timing loop below reuses from jit's cache.
        from ddl25spring_tpu.telemetry.memory import program_memory
        mem = program_memory(step, state, batch) or {}
        temp_bytes = mem.get("temp_bytes")

        state, loss = step(state, batch)          # compile+first run
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        for _ in range(repeats):
            state, loss = step(state, batch)
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / repeats * 1e3
        out[schedule] = {"step_ms": dt,
                         "temp_bytes": float(temp_bytes or 0),
                         "loss": float(loss)}
    return out


def main(quick: bool = False) -> Dict[str, Dict[str, float]]:
    from . import common
    sink = common.sink("pp_schedules.csv")
    grid = [(2, 8)] if quick else [(2, 8), (4, 16), (8, 32)]
    results = {}
    for s, m in grid:
        n_layers = 16 if s == 8 else 8   # see measure(): interleaved needs S·v | L
        r = measure(s, m, n_layers=n_layers)
        for schedule, vals in r.items():
            sink.write({"n_stages": s, "n_microbatches": m,
                        "n_layers": n_layers,
                        "schedule": schedule, **vals})
            print(f"S={s} M={m:2d} {schedule:6s}: {vals['step_ms']:8.1f} ms  "
                  f"temp {vals['temp_bytes']/1e6:8.1f} MB  "
                  f"loss {vals['loss']:.4f}")
        results[(s, m)] = r
    print(f"-> {sink.path}")
    return results


if __name__ == "__main__":
    from ._cpu_pin import pin_cpu_virtual

    pin_cpu_virtual()
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(quick=ap.parse_args().quick)
