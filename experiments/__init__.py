"""Parity-evidence experiment harness.

Each module reproduces one of the reference's homework experiment suites at
its exact configuration, persists per-round/per-epoch curves through
``ResultSink`` CSVs under ``experiments/results/``, and prints the summary
table the reference notebook displays:

- ``hw1_fl``       — FedSGD/FedAvg N- and C-sweeps (lab/hw01/homework-1.ipynb
                     cells 27, 30).
- ``hw1b_llm``     — the 5000-iter tiny-Llama loss trajectory
                     (lab/out_b1_2.txt, lab/out_b2_*.txt).
- ``hw2_vfl``      — VFL seeds/permutations, client scaling 2→10 with the
                     even and min-2 partitioners, VFL-VAE 1000 epochs
                     (lab/hw02/Tea_Pula_HW2.ipynb cells 2-41).
- ``hw3_defenses`` — the robust-aggregation grid under 20% gradient
                     reversion + Bulyan/SparseFed sweeps
                     (lab/hw03/Tea_Pula_03.ipynb cells 3-29).
- ``generative``   — centralized heart classifier + VAE synthetic-data
                     evaluation (lab/tutorial_2a).
- ``pp_schedules`` — GPipe vs 1F1B schedule time/memory measurements.
- ``attn_bench``   — XLA vs Pallas-flash attention at long sequence lengths.
- ``plots``        — accuracy-curve rendering from the persisted CSVs
                     (lab/hw03/Tea_Pula_03.ipynb cell 11).

``python -m experiments.run_all [--quick]`` runs the whole suite; every row
is labeled with its data provenance (real vs synthetic fallback — see
``common.data_provenance``), because this environment has no network: MNIST
and TinyStories use the in-repo synthetic fallbacks unless real files are
present, while heart.csv is the real reference data.
"""
