"""Long-context train-step throughput on the real accelerator.

The reference caps sequence length at 256 (lab/tutorial_1b/primer/
intro.py:10); long context is a capability this framework adds. Two legs of
evidence already exist: standalone attention timing across sequence lengths
(experiments/attn_bench.py — the flash kernel's 25x at T=8192) and ring-
attention per-device memory scaling on the virtual mesh (experiments/
sp_bench.py). This harness closes the loop end-to-end: the full train step
(fused head+CE + Adam) at long sequence lengths on one chip, tokens held
roughly constant per step, so the tokens/s column shows how throughput decays
as T grows — i.e. what the O(T^2) attention leg costs in a real step when
the rest of the step is O(T).

Each (seq, attention) point runs in a subprocess with a hard timeout (same
wedge-proofing as bench.py: libtpu is single-client and this platform fails
by hanging). Results -> ``experiments/results/longctx_bench.csv`` with a
``platform`` column; rows are only claim-bearing when it says tpu.

Run (on the chip):
    python -m experiments.longctx_bench
"""

from __future__ import annotations

import argparse
import subprocess
import sys

# (seq_len, per-step batch): ~16k tokens/step at every row, the measured
# bench.py optimum at T=256.
GRID = [(256, 64), (1024, 16), (2048, 8), (4096, 4), (8192, 2)]
VARIANTS = {
    # "flash" pins the pallas dh-major kernel (the path config.py's "auto"
    # routes to at T>=256 on TPU); "xla" pins the dot_general+softmax path.
    # The two columns show where the quadratic [T, T] score tensor starts to
    # dominate the step and how much the flash kernel buys back.
    "flash": {"attention_impl": "pallas", "flash_dh_major": True,
              "flash_block": 512},
    "xla": {"attention_impl": "xla"},
}


def _child(variant: str, seq: int, batch: int) -> None:
    """Time one (variant, seq) train-step point; print 'tok/s step_ms'."""
    import jax

    if jax.default_backend() not in ("tpu",):
        print("no accelerator in child", file=sys.stderr)
        sys.exit(3)
    import dataclasses

    from ddl25spring_tpu.bench_utils import time_train_step
    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.parallel import make_mesh

    cfg = dataclasses.replace(
        LlamaConfig(dtype="bfloat16", ctx_size=seq), **VARIANTS[variant])
    mesh = make_mesh({"data": 1})
    steps = 10
    tps = time_train_step(mesh, cfg, batch, seq=seq, timed_steps=steps)
    print(tps, batch * seq / tps * 1e3)


def main(quick: bool = False) -> None:
    from . import common

    sink = common.sink("longctx_bench.csv")
    grid = GRID[:2] if quick else GRID
    for seq, batch in grid:
        for variant in VARIANTS:
            cmd = [sys.executable, "-m", "experiments.longctx_bench",
                   "--one", variant, str(seq), str(batch)]
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=900)
                if proc.returncode != 0:
                    raise RuntimeError(proc.stderr.strip().splitlines()[-1]
                                       if proc.stderr.strip() else "failed")
                tps, step_ms = map(float, proc.stdout.split())
            except Exception as e:
                print(f"T={seq:5d} {variant:5s}: failed "
                      f"({type(e).__name__}: {e})", flush=True)
                continue
            sink.write({"seq": seq, "batch": batch, "variant": variant,
                        "platform": "tpu", "tokens_per_sec": round(tps, 1),
                        "step_ms": round(step_ms, 3)})
            print(f"T={seq:5d} {variant:5s}: {tps:10.0f} tok/s "
                  f"({step_ms:.1f} ms/step)", flush=True)
    print(f"-> {sink.path}")


if __name__ == "__main__":
    if len(sys.argv) == 5 and sys.argv[1] == "--one":
        _child(sys.argv[2], int(sys.argv[3]), int(sys.argv[4]))
    else:
        ap = argparse.ArgumentParser()
        ap.add_argument("--quick", action="store_true")
        main(quick=ap.parse_args().quick)
