"""Render anomaly flight-recorder bundles into a triage report.

The read side of the flight recorder (telemetry/introspect.py): a
``Telemetry`` run dumps a self-contained postmortem JSON bundle under
``<telemetry-dir>/postmortem/`` the moment a ``fault``/``remesh``/
``slo_violation`` event crosses its stream. This tool — pure stdlib,
never imports jax — finds the bundles under a path and prints, per
bundle: what tripped, WHICH tree path carried the NaN (the StepGuard
attribution), the numerics state at the trip (worst-drifting layer
group, grad norms), the compile/retrace record, and the tail of recent
events. The triage recipe lives in docs/COMPONENTS.md ("Run health").

Exit codes: 0 bundles found and rendered; 2 none found (CI's chaos step
treats that as "the fault injection produced no postmortem" — a failure
of the machinery under test, not of this renderer); with ``--expect-leaf``
additionally 1 when no bundle names the given leaf path fragment.

Example:
    python -m experiments.hw1b_llm --cpu --quick --configs dp1 \\
        --faults nan_grad@8 --guard --numerics-every 4 \\
        --telemetry-dir /tmp/chaos
    python -m experiments.postmortem /tmp/chaos
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from ddl25spring_tpu.telemetry.introspect import find_bundles, load_bundle


def _fmt(v) -> str:
    return f"{v:.4g}" if isinstance(v, (int, float)) else str(v)


def render_bundle(bundle: dict, out=sys.stdout) -> None:
    p = lambda s="": print(s, file=out)  # noqa: E731
    reason = bundle.get("reason", "?")
    t = bundle.get("t")
    when = (time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(t))
            if isinstance(t, (int, float)) else "?")
    p(f"== postmortem: {reason} @ {when}  (run {bundle.get('run_id')}) "
      + "=" * 10)

    trigger = bundle.get("trigger") or {}
    if trigger:
        head = {k: v for k, v in trigger.items()
                if k not in ("schema", "run_id", "seq", "attribution")}
        p(f"trigger: {json.dumps(head, default=str)[:300]}")
    attribution = bundle.get("attribution") or trigger.get("attribution")
    if attribution:
        paths = attribution.get("nonfinite_params") or []
        p("attribution:"
          + (f" NON-FINITE leaves {paths}" if paths else "")
          + (" anomalous-update-norm" if attribution.get("anomalous")
             else "")
          + (f" update_norm={_fmt(attribution.get('update_norm'))}"))

    man = bundle.get("manifest") or {}
    if man:
        p(f"run: trainer={man.get('trainer')} platform={man.get('platform')}"
          f" mesh={man.get('mesh')} start_step={man.get('start_step')}")

    nums = bundle.get("last_numerics") or {}
    if nums:
        p(f"numerics @ it {nums.get('it')}: grad_norm "
          f"{_fmt(nums.get('grad_norm'))}  worst group "
          f"{nums.get('worst_group')} (update/param "
          f"{_fmt(nums.get('worst_update_ratio'))})")
        if nums.get("nonfinite_grads"):
            p(f"  in-jit NON-FINITE grads: {nums['nonfinite_grads']}")

    mem = bundle.get("memory") or {}
    if mem:
        # Memory census (schema v9): the last MemoryMeter sample the
        # recorder saw before the trip — what the bytes looked like when
        # things went wrong, next to the numerics that tripped.
        def _mb(k):
            v = mem.get(k)
            return f"{v / 2**20:.1f}M" if isinstance(v, (int, float)) else None
        parts = [f"{k.replace('_bytes', '')} {_mb(k)}"
                 for k in ("device_bytes", "rss_bytes", "params_bytes",
                           "opt_state_bytes", "pool_used_bytes",
                           "mirror_bytes")
                 if _mb(k) is not None]
        frag = ""
        if mem.get("holes") is not None:
            frag = (f"  frag holes={mem['holes']}"
                    f" largest_run={mem.get('largest_run')}")
        p(f"memory census ({mem.get('source', '?')}): "
          + "  ".join(parts) + frag)

    compiles = bundle.get("compiles") or []
    if compiles:
        retraces = [c for c in compiles if c.get("retrace")]
        p(f"compiles: {len(compiles)}"
          + (f"   RETRACES {len(retraces)}: "
             f"{[c.get('name') for c in retraces]}   <-- BAD"
             if retraces else ""))

    ring = bundle.get("recent_events") or []
    dropped = bundle.get("dropped_events", 0)
    p(f"recent events: {len(ring)} in ring"
      + (f" ({dropped} older dropped to fit the size cap)" if dropped
         else ""))
    for e in ring[-8:]:
        brief = {k: e.get(k) for k in ("type", "it", "loss", "slo", "name",
                                       "counters", "old_world", "new_world")
                 if e.get(k) is not None}
        p(f"  seq {e.get('seq', '?'):>5}  {json.dumps(brief, default=str)[:140]}")
    p()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="a bundle .json, a telemetry dir, or any "
                                 "dir to search for postmortem-*.json under")
    ap.add_argument("--json", action="store_true",
                    help="print a machine-readable summary list instead of "
                         "the human report")
    ap.add_argument("--expect-leaf", default=None,
                    help="exit 1 unless some bundle's attribution names a "
                         "leaf path containing this fragment (the chaos "
                         "smoke's self-check)")
    a = ap.parse_args(argv)

    if a.path.endswith(".json"):
        paths = [a.path]
    else:
        paths = find_bundles(a.path)
    if not paths:
        print(f"no postmortem bundles under {a.path}", file=sys.stderr)
        return 2

    bundles = []
    for p in paths:
        try:
            bundles.append((p, load_bundle(p)))
        except (OSError, ValueError) as e:
            print(f"{p}: unreadable ({e})", file=sys.stderr)
    if not bundles:
        return 2

    if a.json:
        summary = [{
            "path": p,
            "reason": b.get("reason"),
            "run_id": b.get("run_id"),
            "attribution": b.get("attribution"),
            "events": len(b.get("recent_events") or []),
        } for p, b in bundles]
        print(json.dumps(summary, indent=2, default=str))
    else:
        for p, b in bundles:
            print(f"-- {p}")
            render_bundle(b)

    if a.expect_leaf is not None:
        named = any(
            a.expect_leaf in path
            for _, b in bundles
            for path in ((b.get("attribution") or {})
                         .get("nonfinite_params") or []))
        if not named:
            print(f"no bundle attributes a non-finite leaf matching "
                  f"{a.expect_leaf!r}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
