"""hw1 FedSGD/FedAvg sweeps — the reference's homework-1 experiment tables.

Reproduces, at the exact reference configurations:
- N-sweep:  FedSGD & FedAvg over N ∈ {10, 50, 100} at C=0.1
  (reference: lab/hw01/homework-1.ipynb cell 27 — FedSGD 43.23/43.11/43.17%,
  FedAvg 93.22/87.93/81.33% final accuracy at 10 rounds on real MNIST).
- C-sweep:  both over C ∈ {0.01, 0.1, 0.2} at N=100
  (cell 30 — FedSGD 41.90/43.17/42.88%, FedAvg 73.41/81.33/81.92%).
- The centralized baseline (hfl_complete.py:184-223).

Defaults per the homework text (lab/homework-1.ipynb cell 5): lr=0.01, E=1,
B=100, rounds=10, IID, seed=10. Every per-round record lands in
``experiments/results/hw1_fl.csv`` with a ``data`` provenance column — in
this offline environment MNIST is the synthetic fallback, so absolute
accuracies differ from the notebook; the structural signatures (FedAvg ≫
FedSGD at 10 rounds; accuracy rising with C) are the parity evidence, plus
the exact-equivalence tests in tests/test_fl.py.
"""

from __future__ import annotations

import argparse
from typing import Dict, List, Tuple

from ddl25spring_tpu.config import FLConfig
from ddl25spring_tpu.fl import (CentralizedServer, FedAvgServer,
                                FedSgdGradientServer)
from ddl25spring_tpu.models import mnist_cnn

from . import common


def run_one(server_cls, cfg: FLConfig, sink, provenance: str, *,
            n_train: int, n_test: int) -> float:
    params, data, xt, yt = common.mnist_fl_setup(cfg, n_train=n_train,
                                                 n_test=n_test)
    server = server_cls(params, mnist_cnn.apply, data, xt, yt, cfg)
    result = server.run(cfg.rounds)
    df = result.as_df()
    df["data"] = provenance
    df["n_train"] = n_train
    for row in df.to_dict(orient="records"):
        sink.write(row)
    return result.test_accuracy[-1]


def main(quick: bool = False, n_train: int = 60000, n_test: int = 10000
         ) -> Dict[Tuple[str, int, float], float]:
    """``n_train``/``n_test`` size the (synthetic) MNIST; the committed CPU
    run uses 12000/2000 — the protocol (N/C/E/B/lr/seed/rounds) is exact,
    and with synthetic data the corpus size is not a parity quantity. Full
    60000/10000 is the default for accelerator runs."""
    sink = common.sink("hw1_fl.csv")
    provenance = common.mnist_provenance()
    if quick:
        n_train, n_test = 2000, 500
    rounds = 2 if quick else 10
    finals: Dict[Tuple[str, int, float], float] = {}

    sweeps = [(n, 0.1) for n in (10, 50, 100)] + [(100, c) for c in (0.01, 0.2)]
    for n, c in sweeps:
        for name, cls in (("fedsgd", FedSgdGradientServer),
                          ("fedavg", FedAvgServer)):
            cfg = FLConfig(nr_clients=n, client_fraction=c, rounds=rounds)
            acc = run_one(cls, cfg, sink, provenance,
                          n_train=n_train, n_test=n_test)
            finals[(name, n, c)] = acc
            print(f"{name:8s} N={n:3d} C={c:.2f}: final acc {acc:.4f}")

    # Centralized baseline takes (params, apply, x, y, xt, yt, cfg) — its own
    # signature, so it doesn't go through run_one.
    import jax

    cfg = FLConfig(rounds=rounds)
    x, y, xt, yt = common.mnist_arrays(n_train, n_test)
    server = CentralizedServer(mnist_cnn.init(jax.random.key(0)),
                               mnist_cnn.apply, x, y, xt, yt, cfg)
    result = server.run(rounds)
    df = result.as_df()
    df["data"] = provenance
    df["n_train"] = n_train
    for row in df.to_dict(orient="records"):
        sink.write(row)
    finals[("centralized", 1, 1.0)] = result.test_accuracy[-1]
    print(f"centralized: final acc {result.test_accuracy[-1]:.4f}")
    print(f"-> {sink.path} [{provenance}]")
    return finals


def matched_shards(n_test: int = 2000, rounds: int = 10,
                   algorithms: Tuple[str, ...] = ("fedavg", "fedsgd"),
                   c_sweep: bool = True) -> Dict:
    """Append the N-sweep and C-sweep at the reference's per-client shard
    sizes (n_train=60,000).

    The committed CPU run shrinks the corpus to 12,000 rows, which starves
    high-N FedAvg clients to ~1 local step per round and collapses the
    N-scaling signature (VERDICT r03 weak #2), and leaves FedSGD's
    one-gradient-per-round numbers in the noise (VERDICT r04 weak #4). Per
    the measured accuracy-vs-steps curve of the synthetic generator, both
    are shard-size effects, not generator effects — so this reruns the
    reference tables at the full n_train=60,000 (600–6,000 rows/client,
    exactly the reference's shard sizes) and appends them, labeled by their
    n_train column, next to the 12k battery:
    - N ∈ {10, 50, 100} at C=0.1, both algorithms (homework-1.ipynb cell
      27: FedSGD flat ≈43.1–43.2%, FedAvg 93.2/87.9/81.3%);
    - C ∈ {0.01, 0.2} at N=100, both algorithms (cell 30: FedSGD flat
      ≈41.9–42.9%, FedAvg C-monotone 73.4/81.3/81.9% — C=0.1 is shared
      with the N-sweep).
    """
    import os

    from ddl25spring_tpu.utils.tracing import ResultSink

    classes = {"fedavg": FedAvgServer, "fedsgd": FedSgdGradientServer}
    path = os.path.join(common.RESULTS_DIR, "hw1_fl.csv")
    # Idempotent append: combos already in the CSV with a full-length 60k
    # curve are skipped, so the battery can resume after a wall-clock kill.
    have = set()
    if os.path.exists(path):
        import pandas as pd

        df = pd.read_csv(path)
        # A combo counts as done only when its curve actually REACHED the
        # final round — raw row counts would let two stacked partial runs
        # mask an unfinished combo forever.
        last = (df[df["n_train"] == 60000]
                .groupby(["algorithm", "N", "C"])["round"].max())
        have = {key for key, r in last.items() if r >= rounds}
    sink = ResultSink(path)
    provenance = common.mnist_provenance()
    finals = {}
    sweeps = [(n, 0.1) for n in (10, 50, 100)]
    if c_sweep:
        sweeps += [(100, c) for c in (0.01, 0.2)]
    for n, c in sweeps:
        for name in algorithms:
            if (name, n, c) in have:
                print(f"{name} N={n} C={c:.2f} n_train=60000: already in "
                      "CSV, skipping", flush=True)
                continue
            cfg = FLConfig(nr_clients=n, client_fraction=c, rounds=rounds)
            acc = run_one(classes[name], cfg, sink, provenance,
                          n_train=60000, n_test=n_test)
            finals[(f"{name}-60k", n, c)] = acc
            print(f"{name} N={n:3d} C={c:.2f} n_train=60000: "
                  f"final acc {acc:.4f}", flush=True)
    return finals


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--matched-shards", action="store_true",
                    help="append the FedAvg rows at reference shard sizes")
    ap.add_argument("--cpu", action="store_true")
    a = ap.parse_args()
    if a.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    if a.matched_shards:
        matched_shards()
    else:
        main(quick=a.quick)
