"""Autoscale smoke: one diurnal traffic curve drives train⇄serve moves.

The CI-sized proof (tier1.yml) of the unified elasticity control plane
(ISSUE 16): in a SINGLE process, an elastic ZeRO-1 training run and a
two-engine serving fleet share one device pool while an ``Autoscaler``
(resilience/autoscale.py) watches the fleet router's rolling TTFT
windows. A seeded diurnal arrival curve peaks, p95 TTFT climbs past the
pressure line (0.8×SLO, BELOW the violation threshold), and the policy
drains training at a chunk edge, shrinks the mesh, and activates the
second engine; when traffic ebbs the move reverses. The trainer applies
each decision through ``scale_hook`` → ``ElasticController.resize`` —
the same bidirectional re-mesh machinery the fault path uses, with the
just-drained state pinned as the mirror so a planned move replays
nothing.

The script CHECKS the acceptance bars rather than asserting it ran:

- **zero SLO violations** — the serving clock is a deterministic tick
  counter (TTFT = queueing ticks × dt, machine-independent), and
  ``slo_monitor --check`` replays the stream against the same TTFT SLO
  the policy protected: capacity must have arrived BEFORE any rolling
  p99 breach, not after;
- **zero lost steps** — every training iteration's loss is present and
  finite, and every scale re-mesh records ``steps_replayed == 0``
  (resize-at-chunk-edge pins the mirror at the edge by construction);
- **zero retraces per world size** — each world size's training watch
  compiles fresh programs, never retraces, and every fleet engine keeps
  its zero-retrace contract through the capacity changes;
- the curve genuinely drives BOTH directions (≥1 train→serve and ≥1
  serve→train move), and each ``scale`` event (schema v8) validates.

Recovery costs land as bench rows (``remesh_seconds_scale``,
``steps_replayed_scale`` — lower is better, experiments/bench_compare.py)
in the JSON artifact; the telemetry stream (with its ``scale`` + six
``remesh``-adjacent event kinds) is written next to it for obs_report /
trace_export.

    python -m experiments.autoscale_smoke --out autoscale-smoke.json \\
        --telemetry-dir autoscale-telemetry

Exit code 0 only when every bar holds.
"""

from __future__ import annotations

import argparse
import json
import math
import sys


class _TickClock:
    """Deterministic serving clock: time is a tick count × dt, advanced
    only by the control loop. TTFT measured against it counts QUEUEING
    ticks, not wall seconds, so the pressure signal (and therefore the
    whole scale trajectory) is identical on any machine."""

    def __init__(self, dt: float):
        self.t = 0.0
        self.dt = dt

    def __call__(self) -> float:
        return self.t

    def advance(self) -> None:
        self.t += self.dt


def run(out_path: str, telemetry_dir: str = None, iters: int = 24,
        slo_s: float = 1.2) -> int:
    from ._cpu_pin import pin_cpu_virtual
    pin_cpu_virtual()

    import jax
    import numpy as np

    from ddl25spring_tpu.config import (LlamaConfig, ResilienceConfig,
                                        TrainConfig)
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel import make_mesh
    from ddl25spring_tpu.resilience.autoscale import (Autoscaler,
                                                      AutoscalePolicy,
                                                      router_ttft_p95)
    from ddl25spring_tpu.serving import PagedKVConfig, Request, ServingFleet
    from ddl25spring_tpu.telemetry import (Telemetry, read_events,
                                           validate_event)
    from ddl25spring_tpu.tokenizers import ByteTokenizer
    from ddl25spring_tpu.train.llm import train_llm_dp

    spd = 2
    edges = iters // spd
    # Same tiny trainer as elastic_smoke (dmodel=20: the 4-way and 3-way
    # ZeRO-1 padded lengths differ, so every move genuinely reshards).
    tiny = LlamaConfig(vocab_size=259, dmodel=20, num_heads=2, n_layers=2,
                      ctx_size=16)
    serve_cfg = LlamaConfig(vocab_size=97, dmodel=32, num_heads=4,
                            n_layers=2, ctx_size=32)
    paged = PagedKVConfig(num_blocks=24, block_len=4, max_blocks_per_seq=8)

    telemetry = Telemetry(telemetry_dir) if telemetry_dir else None
    events = telemetry.events if telemetry else None

    clock = _TickClock(dt=0.05)
    # window_s spans ~2 control ticks of synthetic time (edge gap 1.0s):
    # the pressure signal follows the CURRENT load, and an ebb actually
    # empties the windows instead of serving stale peak samples forever.
    fleet = ServingFleet(llama.init_llama(jax.random.PRNGKey(0), serve_cfg),
                         serve_cfg, paged, num_engines=2, num_slots=2,
                         prefill_chunk=4, events=events, token_events=False,
                         clock=clock, window_s=2.0)
    fleet.set_active(1)                      # serving starts minimal

    policy = AutoscalePolicy(ttft_slo_s=slo_s, pressure_frac=0.8,
                             ebb_frac=0.3, sustain=2, cooldown=2,
                             min_train_world=3, max_train_world=4,
                             min_serve_engines=1, max_serve_engines=2)
    scaler = Autoscaler(policy, train_world=4, serve_engines=1,
                        events=events)

    # Seeded diurnal curve: arrivals per control tick follow one day of
    # sinusoidal load across the run's chunk edges — a morning peak that
    # overwhelms one engine, an evening ebb that idles two. The trainer
    # fires the hook at every INTERIOR chunk edge (it < iters), so there
    # are edges-1 control ticks.
    ticks = edges - 1
    rng = np.random.default_rng(7)
    curve = [max(0, round(4.0 + 4.0 * math.sin(2 * math.pi * i / ticks)))
             for i in range(ticks)]
    prompts = [tuple(int(t) for t in rng.integers(1, 97, size=6))
               for _ in range(sum(curve))]

    p95_trace, rid_iter = [], iter(range(len(prompts)))

    def control_tick(it, train_world):
        """One control-plane step, run at each training chunk edge:
        advance synthetic time to this edge, inject the tick's arrivals,
        serve them to completion on the ACTIVE engines (inactive ones
        only drain), read the router's rolling TTFT windows, and let the
        policy decide."""
        clock.t += 1.0                       # inter-edge gap: windows age
        edge = it // spd - 1
        for _ in range(curve[edge] if 0 <= edge < ticks else 0):
            rid = next(rid_iter)
            fleet.submit(Request(rid=f"r{rid}", prompt=prompts[rid],
                                 max_new=6), now=clock())
        while fleet.outstanding:
            fleet.tick()
            clock.advance()
        fleet.router.harvest(clock())
        p95 = router_ttft_p95(fleet.router)
        p95_trace.append(None if p95 is None else round(p95, 4))
        decision = scaler.tick(p95, it=it)
        if decision is None:
            return None
        fleet.set_active(decision.serve_engines)
        return decision.train_world

    report = train_llm_dp(
        tiny,
        TrainConfig(batch_size=2, seq_len=16, lr=3e-3, iters=iters,
                    data=4, steps_per_dispatch=spd),
        mesh=make_mesh({"data": 4}, devices=jax.devices()[:4]),
        tokenizer=ByteTokenizer(), aggregation="zero1", log_every=0,
        resilience=ResilienceConfig(elastic=True, mirror_every=1),
        telemetry=telemetry, scale_hook=control_tick)

    directions = [d.direction for d in scaler.decisions]
    scale_records = report.remeshes
    checks = {
        "both_directions_driven": ("train_to_serve" in directions
                                   and "serve_to_train" in directions),
        "every_decision_applied": (
            bool(scale_records)
            and len(scale_records) == len(scaler.decisions)
            and scale_records[-1]["new_world"] == scaler.train_world
            and [r["direction"] == ("shrink" if d.direction ==
                                    "train_to_serve" else "grow")
                 for r, d in zip(scale_records, scaler.decisions)]
            == [True] * len(scale_records)),
        # Zero lost steps: every iteration's loss exists and is finite,
        # and no planned move replayed anything.
        "zero_lost_steps": (len(report.losses) == iters
                            and bool(np.isfinite(report.losses).all())
                            and all(r["steps_replayed"] == 0
                                    for r in scale_records)),
        "fleet_zero_retraces": all(r == 0 for r in fleet.retraces()),
        "all_requests_served": all(
            len(rec.tokens) == rec.max_new
            for rec in fleet.records.values()) and
            len(fleet.records) == sum(curve),
    }

    per_world_compiles, slo = {}, {}
    if telemetry is not None:
        telemetry.close()
        stream = read_events(telemetry.events_path)
        scale_events = [e for e in stream if e.get("type") == "scale"]
        checks["scale_events_valid"] = (
            len(scale_events) == len(scaler.decisions)
            and all(validate_event(e) == [] for e in scale_events))
        # Zero retraces PER WORLD SIZE: compile events are tagged with
        # the (world-suffixed) watch name; none may be a retrace.
        for e in stream:
            if e.get("type") == "compile":
                row = per_world_compiles.setdefault(
                    e.get("name"), {"compiles": 0, "retraces": 0})
                row["compiles"] += 1
                row["retraces"] += int(bool(e.get("retrace")))
        checks["train_zero_retraces_per_world"] = (
            per_world_compiles != {} and
            all(v["retraces"] == 0 for v in per_world_compiles.values()))
        # The SLO the policy protected, judged by the monitor that owns
        # the verdict: replay the stream, zero rolling-window breaches.
        from .slo_monitor import main as slo_main
        rc = slo_main([telemetry_dir, "--check",
                       "--ttft-p99", str(slo_s), "--no-emit"])
        violations = [e for e in read_events(telemetry.events_path)
                      if e.get("type") == "slo_violation"]
        checks["zero_slo_violations"] = rc == 0 and violations == []
        slo = {"monitor_rc": rc, "violation_events": len(violations)}

    scale_seconds = [r["seconds"] for r in scale_records]
    result = {
        "ok": all(checks.values()),
        "iters": iters,
        "ttft_slo_s": slo_s,
        "curve": curve,
        "p95_trace": p95_trace,
        "decisions": [d._asdict() for d in scaler.decisions],
        "scale_remeshes": scale_records,
        "per_world_compiles": per_world_compiles,
        "slo": slo,
        "requests_served": len(fleet.records),
        "checks": checks,
        # Recovery-cost rows for the perf trajectory (bench_compare
        # treats both prefixes as lower-is-better).
        "rows": [
            {"metric": "remesh_seconds_scale",
             "value": max(scale_seconds) if scale_seconds else 0.0,
             "platform": "cpu", "variant": "autoscale-smoke"},
            {"metric": "steps_replayed_scale",
             "value": float(sum(r["steps_replayed"]
                                for r in scale_records)),
             "platform": "cpu", "variant": "autoscale-smoke"},
        ],
    }

    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    if not result["ok"]:
        failed = [k for k, v in checks.items() if not v]
        print(f"autoscale smoke FAILED checks: {failed}", file=sys.stderr)
    return 0 if result["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default="autoscale-smoke.json",
                    help="acceptance-evidence JSON path")
    ap.add_argument("--telemetry-dir", default=None,
                    help="write the shared train+serve events.jsonl here "
                         "(render with python -m experiments.obs_report)")
    ap.add_argument("--iters", type=int, default=24)
    ap.add_argument("--ttft-slo", type=float, default=1.2,
                    help="serving TTFT SLO in (deterministic tick) "
                         "seconds — the policy scales at 0.8x this line")
    a = ap.parse_args(argv)
    return run(a.out, a.telemetry_dir, a.iters, a.ttft_slo)


if __name__ == "__main__":
    sys.exit(main())
