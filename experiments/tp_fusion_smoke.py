"""TP-fusion smoke: the TP composition column's claims, checked (ISSUE 18).

The CI-sized proof (tier1.yml) that TP now carries the fused-dispatch +
overlapped/compressed sync column, on a 4-virtual-device
``(data=2, model=2)`` CPU mesh — the pp_fusion_smoke contract applied to
the TP column:

1. the MODEL-AXIS activation wire of the relaxed PSA modes
   (TrainConfig.psa = "defer:L" / "int8_ef") is ≤ the ANALYTIC budget
   (tp.psa_sync_wire_bytes — the same formulas, stated in
   experiments/ROOFLINE.md) AND below the full-sync baseline measured
   from the SAME run family (psa="full" routes the identical sync
   positions through the telemetry wrappers, so the comparison is
   trace-measured, not hand-computed);
2. the DP×TP ring + delta-gather accounting of the composed
   ``int8_ef + zero1 + scan4`` driver (tp.make_tp_overlap_multi_step) is
   EXACT: the profile's trips × payloads equal the analytic
   K·M·(n−1)·chunk_bytes (+ per-hop scale sidecars, + K·(n−1)·chunk
   gather) formulas to the byte;
3. zero retraces across the psa × K grid (tp.make_tp_multi_step) AND the
   wire × K grid at zero1 through the overlap driver
   (introspect.CompileWatch): each config compiles exactly ONE program
   over repeated same-shape dispatches;
4. the TRAINER's compile events carry the TP window size
   (``steps_per_dispatch`` stamped per compiling call, tail chunks with
   their ACTUAL smaller window) — checked end-to-end through
   train_llm_tp + telemetry.

Wire-byte rows land in the JSON artifact in the bench_compare row shape
({"metric": "wire_bytes_model_per_train_step", ...}) — the ``wire_bytes``
prefix pins the lower-is-better direction, so the PSA wire-reduction
claim is trajectory-gated exactly like DP's and PP's. Diagnostics live IN
the JSON (the tier1 don't-clobber contract); exit 0 only when every
check holds.

    python -m experiments.tp_fusion_smoke --out tp-fusion.json
"""

from __future__ import annotations

import argparse
import json
import sys


def run(out_path: str) -> int:
    from ._cpu_pin import pin_cpu_virtual
    pin_cpu_virtual()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel import make_mesh, tp
    from ddl25spring_tpu.telemetry import introspect, measure_comm

    n, T, K = 2, 2, 4                          # data, model(tp), scan
    mesh = make_mesh({"data": n, "model": T}, devices=jax.devices()[:n * T])
    cfg = LlamaConfig(vocab_size=259, dmodel=32, num_heads=2, n_layers=4,
                      ctx_size=16)
    opt = lambda: optax.adam(1e-3)  # noqa: E731

    def fresh_params():
        return llama.init_llama(jax.random.key(0), cfg)

    bsz = 4                                    # per data shard
    batch_sds = jax.ShapeDtypeStruct((n * bsz, cfg.ctx_size), jnp.int32)
    window_sds = jax.ShapeDtypeStruct((K, n * bsz, cfg.ctx_size), jnp.int32)

    checks, rows, profiles = {}, [], {}

    # ---- PSA: measured model-axis activation wire vs analytic budget ----
    # psa="full" is the measured baseline: the same sync positions as the
    # legacy bitwise path, routed through comm.psum so the bytes are
    # visible. The relaxed modes must land ≤ their analytic budget AND
    # strictly below the measured full-sync wire — both from trace-time
    # profiles of the same model/mesh.
    def psa_wire(psa):
        state, step = tp.make_tp_step(cfg, opt(), mesh, fresh_params(),
                                      psa=psa,
                                      batch_shape=(bsz, cfg.ctx_size))
        prof = measure_comm(step, state, batch_sds)
        by = prof.by_label()
        labels = ("psa_full_sync", "psa_defer_sync", "psa_act_int8",
                  "psa_act_scale")
        wire = sum(by[l]["wire_bytes_per_device"] for l in labels
                   if l in by)
        return wire, prof

    psa_checks = {}
    full_wire, full_prof = psa_wire("full")
    profiles["tp_psa_full"] = full_prof.as_dict()
    full_budget = tp.psa_sync_wire_bytes(cfg, "full", T, bsz, cfg.ctx_size)
    psa_checks["full"] = {"measured": full_wire, "budget": full_budget,
                          "ok": full_wire == full_budget}
    rows.append({"metric": "wire_bytes_model_per_train_step",
                 "value": full_wire, "unit": "bytes/device/step",
                 "platform": "cpu", "variant": "tp2-psa-full"})
    for psa in ("defer:2", "int8_ef"):
        wire, prof = psa_wire(psa)
        budget = tp.psa_sync_wire_bytes(cfg, psa, T, bsz, cfg.ctx_size)
        psa_checks[psa] = {
            "measured": wire, "budget": budget,
            "full_sync_measured": full_wire,
            "reduction_vs_full": wire / full_wire,
            "ok": bool(wire <= budget and wire < full_wire)}
        profiles[f"tp_psa_{psa.replace(':', '')}"] = prof.as_dict()
        rows.append({"metric": "wire_bytes_model_per_train_step",
                     "value": wire, "unit": "bytes/device/step",
                     "platform": "cpu",
                     "variant": f"tp2-psa-{psa.replace(':', '')}"})
    checks["psa_wire_budget"] = {
        "modes": psa_checks,
        "ok": all(v["ok"] for v in psa_checks.values())}

    # ---- exact DP×TP ring + gather accounting vs analytic formulas ----
    cand_state, cand_step = tp.make_tp_overlap_multi_step(
        cfg, opt(), mesh, fresh_params(), aggregation="zero1",
        wire="int8_ef", overlap_microbatches=1)
    cand_prof = measure_comm(cand_step, cand_state, window_sds)
    profiles["tp_int8ef_zero1_scan4"] = cand_prof.as_dict(
        steps_per_dispatch=K)
    from ddl25spring_tpu.parallel.tp import _tp_flat_geometry
    _, _, local, _ = _tp_flat_geometry(mesh, fresh_params())
    by = cand_prof.by_label()
    got = {"ring_payload": by["tp_ring_grad_int8"]["payload_bytes"],
           "ring_scales": by["tp_ring_grad_scale"]["payload_bytes"],
           "ring_wire": by["tp_ring_grad_int8"]["wire_bytes_per_device"],
           "gather_wire":
               by["tp_delta_gather_int8"]["wire_bytes_per_device"]}
    want = {"ring_payload": K * 1 * (n - 1) * local,  # K·M·(n−1)·chunk int8
            "ring_scales": K * 1 * (n - 1) * 4,       # one fp32 per hop
            "ring_wire": K * 1 * (n - 1) * local,     # ppermute: wire==payload
            "gather_wire": K * (n - 1) * local}       # int8 delta all-gather
    checks["tp_ring_analytic"] = {"got": got, "want": want,
                                  "ok": got == want}

    # ---- zero retraces: psa × K grid through the fused scan driver ----
    rng = np.random.default_rng(0)
    psa_retraces = {}
    for psa in ("", "full", "defer:2", "int8_ef"):
        for k in (1, 2):
            state, step = tp.make_tp_multi_step(
                cfg, opt(), mesh, fresh_params(), psa=psa,
                batch_shape=(bsz, cfg.ctx_size))
            step = introspect.watch(
                step, name=f"smoke/tp-psa{psa.replace(':', '')}-k{k}",
                max_caches=1)
            window = rng.integers(
                0, cfg.vocab_size,
                size=(k, n * bsz, cfg.ctx_size)).astype(np.int32)
            loss = None
            for _ in range(3):
                state, losses = step(state,
                                     tp.shard_batch_window(mesh, window))
                loss = float(np.asarray(losses)[-1])
            psa_retraces[f"psa{psa.replace(':', '') or 'off'}-k{k}"] = {
                "compiles": len(step.compiles),
                "retraces": sum(1 for c in step.compiles if c.retrace),
                "final_loss": loss,
                "ok": bool(len(step.compiles) == 1
                           and not any(c.retrace for c in step.compiles)
                           and np.isfinite(loss))}
    checks["psa_retraces"] = {
        "grid": psa_retraces,
        "ok": all(v["ok"] for v in psa_retraces.values())}

    # ---- zero retraces: wire × K grid through the overlap driver ----
    wire_retraces = {}
    for wire in ("fp32", "bf16", "int8_ef"):
        for k in (1, 2):
            state, step = tp.make_tp_overlap_multi_step(
                cfg, opt(), mesh, fresh_params(), aggregation="zero1",
                wire=wire, overlap_microbatches=1)
            step = introspect.watch(step, name=f"smoke/tp-{wire}-k{k}",
                                    max_caches=1)
            window = rng.integers(
                0, cfg.vocab_size,
                size=(k, n * bsz, cfg.ctx_size)).astype(np.int32)
            loss = None
            for _ in range(3):
                state, losses = step(state,
                                     tp.shard_batch_window(mesh, window))
                loss = float(np.asarray(losses)[-1])
            wire_retraces[f"{wire}-k{k}"] = {
                "compiles": len(step.compiles),
                "retraces": sum(1 for c in step.compiles if c.retrace),
                "final_loss": loss,
                "ok": bool(len(step.compiles) == 1
                           and not any(c.retrace for c in step.compiles)
                           and np.isfinite(loss))}
    checks["overlap_retraces"] = {
        "grid": wire_retraces,
        "ok": all(v["ok"] for v in wire_retraces.values())}

    # ---- trainer compile events carry the TP window size ----
    # End-to-end through train_llm_tp: iters=3 at K=2 runs one full chunk
    # and one tail chunk — two compiles, stamped 2 and 1, so slo_monitor's
    # per-step MFU normalization cannot misread the tail as a full-K
    # program (the DP/PP chunked trainers' contract).
    import os
    import tempfile

    from ddl25spring_tpu.config import TrainConfig
    from ddl25spring_tpu.telemetry import Telemetry
    from ddl25spring_tpu.tokenizers import ByteTokenizer
    from ddl25spring_tpu.train.llm import train_llm_tp

    tdir = tempfile.mkdtemp(prefix="tp-fusion-smoke-")
    tel = Telemetry(tdir)
    try:
        train_llm_tp(cfg,
                     TrainConfig(batch_size=bsz, seq_len=cfg.ctx_size,
                                 iters=3, lr=3e-3, data=n, model=T,
                                 psa="int8_ef", steps_per_dispatch=2),
                     mesh=mesh, tokenizer=ByteTokenizer(), log_every=0,
                     telemetry=tel)
    finally:
        tel.close()
    compile_events = []
    with open(os.path.join(tel.out_dir, "events.jsonl")) as f:
        for line in f:
            e = json.loads(line)
            if e.get("type") == "compile" and \
                    str(e.get("name", "")).startswith("train/tp"):
                compile_events.append(e)
    stamped = sorted((e.get("steps_per_dispatch") or 0)
                     for e in compile_events)
    checks["trainer_compile_meta"] = {
        "events": [{"name": e.get("name"),
                    "steps_per_dispatch": e.get("steps_per_dispatch")}
                   for e in compile_events],
        "want_window_sizes": [1, 2],
        "ok": stamped == [1, 2]}

    ok = all(c["ok"] for c in checks.values())
    doc = {"ok": ok, "n_data": n, "tp": T, "steps_per_dispatch": K,
           "model": {"dmodel": cfg.dmodel, "n_layers": cfg.n_layers,
                     "vocab": cfg.vocab_size, "ctx": cfg.ctx_size},
           "checks": checks, "rows": rows, "profiles": profiles}
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    int8_red = checks["psa_wire_budget"]["modes"]["int8_ef"][
        "reduction_vs_full"]
    print(f"tp-fusion smoke: psa int8 model-axis wire "
          f"{int8_red:.3f}x of full sync (budget-gated), "
          f"ring accounting "
          f"{'exact' if checks['tp_ring_analytic']['ok'] else 'WRONG'}, "
          f"retraces {'clean' if checks['psa_retraces']['ok'] and checks['overlap_retraces']['ok'] else 'DIRTY'}, "
          f"compile meta "
          f"{'stamped' if checks['trainer_compile_meta']['ok'] else 'MISSING'} "
          f"-> {out_path}", file=sys.stderr)
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default="tp-fusion.json")
    a = ap.parse_args(argv)
    return run(a.out)


if __name__ == "__main__":
    sys.exit(main())
