"""Export a telemetry event stream as Chrome trace-event JSON.

The read-side bridge from the span layer (ddl25spring_tpu/telemetry/
trace.py) to real trace viewers: feed it any telemetry ``events.jsonl``
(or a run directory) and it writes a JSON file loadable in Perfetto
(https://ui.perfetto.dev — drag-and-drop) or ``chrome://tracing``. Pure
stdlib + the telemetry read helpers — never imports jax — and reuses the
torn-line-tolerant reader, so it runs against a LIVE stream (the torn
final line a crashed or mid-write writer leaves is dropped, same as every
other reader).

Mapping (the Chrome trace-event format's process/thread model):
- one *process* row per ``run_id`` (relaunches sharing a telemetry dir
  stay separate), named by a metadata event;
- one *thread* row per ``trace_id`` — a serving request, the training
  run's "train" trace, a fleet round — so each request's
  queue→prefill→decode→retire tree renders as one nested timeline;
- every closed span becomes a complete ("X") event at its tracer-clock
  microseconds; span attributes land in ``args`` (clickable in the UI);
- sparse diagnostic events (``fault``/``remesh``/``slo_violation``)
  become instant ("i") markers, anchored onto the span clock via the
  epoch-vs-span-clock offset of the run's NEAREST-in-time span (they
  carry only epoch time; a run with no spans exports no markers).
  Nearest, not first: the serving scheduler's span clock fast-forwards
  through idle gaps, so one global offset would drift by the total
  skipped idle time — the nearest span bounds the error to its own
  window.

Example (the serving smoke's telemetry):
    python -m experiments.serving_bench --telemetry-dir /tmp/serve
    python -m experiments.trace_export /tmp/serve --out trace.json
    # then load trace.json in ui.perfetto.dev
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List

from ddl25spring_tpu.telemetry.events import read_events

# Flat events rendered as instant markers on the timeline (sparse,
# diagnostic). Everything else flat is either covered by a span
# (request_*, step) or not a point in time (manifest, run_end metrics).
INSTANT_TYPES = ("fault", "remesh", "slo_violation", "scale")

# Span fields that are structure, not attributes.
_SPAN_BASE = ("schema", "run_id", "seq", "t", "type", "name", "trace_id",
              "span_id", "parent_span_id", "start_ns", "dur_ns")


def chrome_trace(events: List[Dict[str, Any]],
                 instants: bool = True) -> Dict[str, Any]:
    """Pure conversion: event list → Chrome trace-event JSON object.
    Deterministic (ids assigned in first-seen order), so equal streams
    give equal traces — the golden test in tests/test_telemetry.py pins
    the exact output for a tiny stream."""
    pids: Dict[str, int] = {}
    tids: Dict[tuple, int] = {}
    out: List[Dict[str, Any]] = []
    meta: List[Dict[str, Any]] = []
    # run_id -> sorted (epoch t, epoch-at-ns-zero offset) pairs, one per
    # span event: instants anchor via the NEAREST span in epoch time
    # (module docstring — a single global offset drifts when a tracer's
    # clock fast-forwards through idle).
    anchors: Dict[str, List[tuple]] = {}

    def pid_of(run_id: str) -> int:
        if run_id not in pids:
            pids[run_id] = len(pids) + 1
            meta.append({"ph": "M", "name": "process_name",
                         "pid": pids[run_id], "tid": 0,
                         "args": {"name": f"run {run_id}"}})
        return pids[run_id]

    def tid_of(run_id: str, trace_id: str) -> int:
        key = (run_id, trace_id)
        if key not in tids:
            tids[key] = len([k for k in tids if k[0] == run_id]) + 1
            meta.append({"ph": "M", "name": "thread_name",
                         "pid": pid_of(run_id), "tid": tids[key],
                         "args": {"name": trace_id}})
        return tids[key]

    for e in events:
        if e.get("type") != "span":
            continue
        run = e.get("run_id", "?")
        start_ns = e.get("start_ns", 0)
        dur_ns = e.get("dur_ns", 0)
        if isinstance(e.get("t"), (int, float)):
            # The span event is emitted AT span end: epoch t ≈ tracer
            # clock (start+dur) ns — one calibration point per span.
            anchors.setdefault(run, []).append(
                (e["t"], e["t"] - (start_ns + dur_ns) / 1e9))
        args = {k: v for k, v in e.items() if k not in _SPAN_BASE}
        args["span_id"] = e.get("span_id")
        if e.get("parent_span_id") is not None:
            args["parent_span_id"] = e["parent_span_id"]
        out.append({"ph": "X", "name": e.get("name", "?"), "cat": "span",
                    "ts": start_ns / 1e3, "dur": dur_ns / 1e3,
                    "pid": pid_of(run),
                    "tid": tid_of(run, e.get("trace_id", "?")),
                    "args": args})
    if instants:
        import bisect
        for pairs in anchors.values():
            pairs.sort()
        for e in events:
            etype = e.get("type")
            run = e.get("run_id", "?")
            if (etype not in INSTANT_TYPES or run not in anchors
                    or not isinstance(e.get("t"), (int, float))):
                continue
            pairs = anchors[run]
            i = bisect.bisect_left(pairs, (e["t"],))
            if i > 0 and (i == len(pairs)
                          or pairs[i][0] - e["t"] > e["t"] - pairs[i - 1][0]):
                i -= 1                      # the nearer calibration point
            args = {k: v for k, v in e.items()
                    if k not in ("schema", "run_id", "seq", "t", "type")}
            out.append({"ph": "i", "name": etype, "cat": "event", "s": "p",
                        "ts": (e["t"] - pairs[i][1]) * 1e6,
                        "pid": pid_of(run), "tid": 0, "args": args})
    out.sort(key=lambda ev: (ev["pid"], ev["tid"], ev["ts"]))
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="telemetry run dir (containing "
                                 "events.jsonl) or an events.jsonl path")
    ap.add_argument("--out", default=None,
                    help="output JSON path (default: stdout)")
    ap.add_argument("--no-instants", action="store_true",
                    help="spans only; skip fault/remesh/slo markers")
    ap.add_argument("--strict", action="store_true",
                    help="fail on malformed/invalid events")
    a = ap.parse_args(argv)

    events_path = (os.path.join(a.path, "events.jsonl")
                   if os.path.isdir(a.path) else a.path)
    if not os.path.exists(events_path):
        print(f"no event stream at {events_path}", file=sys.stderr)
        return 2
    events = read_events(events_path, strict=a.strict)
    spans = sum(1 for e in events if e.get("type") == "span")
    if not spans:
        print(f"{events_path}: no span events (a pre-v4 stream, or a "
              "run without tracing) — nothing to export", file=sys.stderr)
        return 2
    trace = chrome_trace(events, instants=not a.no_instants)
    text = json.dumps(trace, separators=(",", ":"))
    if a.out:
        with open(a.out, "w") as f:
            f.write(text + "\n")
    else:
        print(text)
    print(f"exported {spans} spans ({len(trace['traceEvents'])} trace "
          f"events) from {events_path}"
          + (f" -> {a.out}" if a.out else ""), file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
