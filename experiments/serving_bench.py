"""Serving smoke + load bench: seeded Poisson traffic through the engine(s).

The end-to-end proof of the serving subsystem (ddl25spring_tpu/serving) on
the CPU mesh, CI-runnable (tier1.yml) — drives ~100 seeded Poisson
requests with mixed prompt/output lengths through the continuous-batching
scheduler and CHECKS the acceptance bars itself:

- correctness: every request retires with exactly ``max_new`` tokens, the
  telemetry stream carries each token exactly once (zero dropped, zero
  duplicated), and a sampled subset is verified BITWISE against
  ``generate()`` run alone on that request at the same seed;
- memory: the allocator never exceeds the pool, and the pool's device
  bytes are strictly below N separate ``max_len`` caches at the observed
  peak concurrency (the paged pool's reason to exist);
- liveness: the pool is sized BELOW peak naive demand (slots × per-request
  worst case), so admissions must queue under load — completing every
  request anyway is the no-deadlock evidence.

``--speculate K`` (single-engine mode) runs the workload TWICE — plain,
then speculating with a SAME-WEIGHTS draft (greedy acceptance is
deterministically 1, which turns the tokens-per-dispatch bar into an
exact arithmetic claim instead of a statistical one) — and self-checks
the ISSUE 13 bars: identical token streams (bitwise, both runs sampled
against ``generate()``), zero retraces on BOTH engines across the
speculate on/off × k grid with the documented compile sets (2 plain /
4 speculating), acceptance rate in [0, 1] (== 1 here), and
``tokens_per_dispatch`` ≥ 2× the plain engine's at k ≥ 3, recorded in
the JSON. ``--prefix-share`` arms CoW prefix sharing on the same runs
(streams must not move); ``--gather-buckets`` narrows the decode gather
and reports the avoided bytes.

``--engines N`` (N > 1) generalizes the smoke to the SERVING FLEET
(serving/fleet.py): a two-class multi-tenant Poisson workload (priorities
+ per-class SLO targets) routed across N engines by the predicted-TTFT
router, with ``--hot-swap`` driving one MID-RUN live weight publication
through the full deploy path (params → publish-dir checkpoint →
digest-verified restore-at-saved-shapes → staggered per-engine
swap-at-token-boundary). Fleet-mode bars, on top of the single-engine
ones (bitwise parity holds at ANY engine count — routing is a latency
decision): every engine compiled exactly two programs with zero retraces
ACROSS the hot-swap, the deploy rolled out to every engine, a ``deploy``
span is present in the Perfetto export, and the per-class SLO verdict
(slo_monitor's per-class rolling windows) replays clean.

Outputs: a latency-percentile JSON (``--out``) and the request_* telemetry
JSONL (``--telemetry-dir``, rendered by ``obs_report``); exit 1 on any
failed check with the diagnostics in the JSON (tier1.yml uploads it either
way).

Example:
    python -m experiments.serving_bench --out serving-latency.json \
        --telemetry-dir /tmp/serving
    python -m experiments.serving_bench --engines 3 --hot-swap \
        --out fleet-serving.json --telemetry-dir /tmp/fleet
    python -m experiments.obs_report /tmp/serving
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _stream_no_drop_no_dup(stream, workload) -> bool:
    """The telemetry-path token contract, shared by both smokes: the
    JSONL stream must carry every (request, index) exactly once."""
    seen = {}
    for e in stream:
        if e.get("type") == "request_token":
            seen.setdefault(e["req"], []).append(e["i"])
    return all(sorted(seen.get(r.rid, [])) == list(range(r.max_new))
               for r in workload)


def _bitwise_sample(workload, recs, params, cfg, paged, *, seed, verify):
    """Sampled bitwise parity vs generate() alone (each distinct request
    shape costs one generate() compile), shared by both smokes. Returns
    (sample_size, mismatched_rids)."""
    import numpy as np

    from ddl25spring_tpu.serving import reference_stream

    rng = np.random.default_rng(seed + 1)
    sample = (list(workload) if verify >= len(workload) else
              [workload[i] for i in rng.choice(len(workload), verify,
                                               replace=False)])
    mismatches = [r.rid for r in sample
                  if reference_stream(params, cfg, paged, r)
                  != recs[r.rid].tokens]
    return len(sample), mismatches


def _build(seed: int):
    import jax

    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama

    # Reduced config, the serving analogue of bench._reduced_dp_setup: the
    # checks are structural (parity, occupancy, liveness), so model scale
    # only costs wall time.
    cfg = LlamaConfig(vocab_size=512, dmodel=64, num_heads=2, n_layers=2,
                      ctx_size=64, attention_impl="xla")
    params = llama.init_llama(jax.random.PRNGKey(seed), cfg)
    return cfg, params


def run(a) -> dict:
    import jax

    from ddl25spring_tpu.serving import (PagedKVConfig, SpecConfig,
                                         blocks_for, naive_cache_bytes,
                                         pool_bytes, run_serving,
                                         synthetic_workload)
    from ddl25spring_tpu.telemetry import Telemetry
    from ddl25spring_tpu.telemetry.events import read_events

    cfg, params = _build(a.seed)
    paged = PagedKVConfig(num_blocks=a.blocks, block_len=a.block_len,
                          max_blocks_per_seq=a.max_blocks_per_seq)
    prompt_lens, max_news = (4, 12, 24), (4, 8, 16)
    workload = synthetic_workload(
        seed=a.seed, n_requests=a.requests, rate_rps=a.rate,
        vocab_size=cfg.vocab_size, prompt_lens=prompt_lens,
        max_news=max_news, temperatures=(0.0, 0.8))

    # The liveness premise: per-request worst case × slots exceeds the
    # pool, so the run MUST queue admissions — completing anyway is the
    # no-deadlock evidence the acceptance bar asks for.
    worst = blocks_for(max(prompt_lens) + max(max_news) - 1, a.block_len)
    naive_peak_blocks = a.slots * worst
    checks = {}
    checks["pool_below_naive_demand"] = (paged.num_blocks - 1
                                         < naive_peak_blocks)

    tel = Telemetry(a.telemetry_dir) if a.telemetry_dir else None
    events = tel.events if tel else None
    if events:
        events.manifest(jax_version=jax.__version__,
                        platform=jax.default_backend(), trainer="serving",
                        slots=a.slots, blocks=a.blocks,
                        block_len=a.block_len, requests=a.requests)
    t0 = time.perf_counter()
    report = run_serving(params, cfg, paged, workload, num_slots=a.slots,
                         prefill_chunk=a.prefill_chunk, events=events,
                         prefix_share=a.prefix_share,
                         gather_buckets=a.gather_buckets)
    wall = time.perf_counter() - t0

    spec_block = None
    if a.speculate:
        # Speculative pass with a SAME-WEIGHTS draft: greedy acceptance
        # is deterministically 1 (identical logits ⇒ the argmax chain
        # always matches), so the bars are exact arithmetic, not
        # statistical claims. The tokens-per-dispatch comparison runs
        # the workload through ONE slot (arrivals at t=0, sequential):
        # batch 1 is the dispatch-bound regime the decode roofline names
        # (each token streams every weight byte), where the plain engine
        # is exactly 1 token/dispatch and speculation multiplies it by
        # the accepted window. At higher concurrency the plain engine
        # earns batching credit while speculation drains slots faster
        # than prefill refills them, so the mixed-concurrency ratio
        # conflates scheduling with the per-dispatch win — the loaded
        # figures are still reported (the Poisson run above), the BAR is
        # judged where it is well-defined.
        import dataclasses as _dc
        import os
        saturated = [_dc.replace(r, arrival=0.0) for r in workload]
        plain_sat = run_serving(
            params, cfg, paged, saturated, num_slots=1,
            prefill_chunk=a.prefill_chunk,
            prefix_share=a.prefix_share, gather_buckets=a.gather_buckets)
        # Its own telemetry stream (telemetry-dir/spec): sharing the
        # plain run's would double every (request, index) token event
        # and fail the exactly-once contract.
        spec_tel = (Telemetry(os.path.join(a.telemetry_dir, "spec"))
                    if a.telemetry_dir else None)
        spec_report = run_serving(
            params, cfg, paged, saturated, num_slots=1,
            prefill_chunk=a.prefill_chunk,
            events=spec_tel.events if spec_tel else None,
            prefix_share=a.prefix_share, gather_buckets=a.gather_buckets,
            speculate=SpecConfig(k=a.speculate, draft_params=params))
        if spec_tel:
            spec_tel.close()
            spec_stream = read_events(spec_tel.events_path)
            spec_events = [e for e in spec_stream
                           if e.get("type") == "speculate"]
            checks["spec_events_per_dispatch"] = (
                len(spec_events) == spec_report.decode_dispatches)
            checks["spec_stream_no_drop_no_dup"] = _stream_no_drop_no_dup(
                spec_stream, workload)
        # GREEDY streams are bitwise invariant across plain/speculative
        # and any admission timing (all equal generate()'s — the plain
        # run's are sampled against it below). Sampled requests are
        # distribution-correct under rejection sampling, not
        # path-identical, so they are excluded here by design.
        checks["spec_greedy_streams_identical"] = all(
            spec_report.records[r.rid].tokens == report.records[r.rid].tokens
            for r in workload if r.temperature == 0.0)
        checks["spec_zero_retraces_on_off_grid"] = (
            report.retraces == 0 and plain_sat.retraces == 0
            and spec_report.retraces == 0)
        # Documented compile sets: 2 plain, 4 speculating (prefill +
        # verify + draft's two; decode_step idles) — per bucket width
        # when the gather is narrowed.
        if not a.gather_buckets:
            checks["spec_compile_contract"] = (report.compiles == 2
                                               and spec_report.compiles == 4)
        checks["spec_acceptance_sane"] = (
            spec_report.acceptance_rate is not None
            and 0.0 <= spec_report.acceptance_rate <= 1.0)
        checks["spec_acceptance_is_one_for_same_weights"] = (
            spec_report.acceptance_rate == 1.0)
        if a.speculate >= 3:
            checks["spec_tokens_per_dispatch_2x"] = (
                spec_report.tokens_per_dispatch
                >= 2 * plain_sat.tokens_per_dispatch)
        spec_block = {
            "k": a.speculate,
            "tokens_per_dispatch": spec_report.tokens_per_dispatch,
            "tokens_per_dispatch_plain": plain_sat.tokens_per_dispatch,
            "acceptance_rate": spec_report.acceptance_rate,
            "decode_dispatches": spec_report.decode_dispatches,
            "decode_dispatches_plain": plain_sat.decode_dispatches,
            "draft_dispatches": spec_report.draft_dispatches,
            "sustained_tokens_per_sec":
                spec_report.aggregates.get("sustained_tokens_per_sec"),
        }

    recs = report.records
    checks["all_completed"] = (
        report.aggregates.get("completed") == a.requests)
    checks["token_counts_exact"] = all(
        len(recs[r.rid].tokens) == r.max_new for r in workload)

    # Zero dropped / duplicated through the TELEMETRY path too: the JSONL
    # stream must carry every (request, index) exactly once.
    if events:
        events.run_end(steps=report.aggregates.get("completed", 0),
                       wall_s=wall, **{
                           k: report.aggregates.get(k) for k in
                           ("total_tokens", "sustained_tokens_per_sec")})
        tel.close()
        stream = read_events(tel.events_path)
        checks["stream_no_drop_no_dup"] = _stream_no_drop_no_dup(stream,
                                                                 workload)

        # Span-tree completeness (ISSUE 8 acceptance bar): every request
        # reconstructs into ONE rooted tree with zero orphaned spans —
        # the scheduler's queue→prefill(+chunks)→decode→retire lifecycle
        # propagated every context correctly. And the Chrome-trace export
        # of the same stream must round-trip as valid JSON with one
        # complete ("X") event per span.
        from ddl25spring_tpu.telemetry.trace import trace_trees, tree_check
        from experiments.trace_export import chrome_trace
        trees = trace_trees(stream)
        req_trees = [trees.get(r.rid) for r in workload]
        tree_problems = []
        for r, t in zip(workload, req_trees):
            c = tree_check(t) if t is not None else None
            if c is None or c["roots"] != 1 or c["orphans"] != 0:
                tree_problems.append(r.rid)
        checks["span_trees_complete"] = not tree_problems
        n_spans = sum(1 for e in stream if e.get("type") == "span")
        exported = json.loads(json.dumps(chrome_trace(stream)))
        checks["trace_export_valid"] = (
            isinstance(exported.get("traceEvents"), list)
            and sum(1 for ev in exported["traceEvents"]
                    if ev.get("ph") == "X") == n_spans > 0)

    n_verified, mismatches = _bitwise_sample(workload, recs, params, cfg,
                                             paged, seed=a.seed,
                                             verify=a.verify)
    checks["bitwise_parity_vs_generate"] = not mismatches

    checks["pool_never_exceeded"] = (report.peak_blocks_in_use
                                     <= report.pool_blocks)
    # Retrace detector (ISSUE 9): the engine compiles exactly its two
    # programs and NEVER retraces — admission/retirement/raggedness are
    # data. A retrace here means a shape leaked into a compiled step.
    checks["zero_retraces"] = report.retraces == 0
    checks["two_compiled_programs"] = report.compiles == 2
    # Memory bar, two forms: the CONFIG-level inequality (pool < the slots
    # × max_len caches generate() would allocate for the same concurrency
    # ceiling) holds at any load; the observed-peak form only demonstrates
    # anything when the workload actually overlapped enough streams, so it
    # is asserted only when the run saturated its slots — a sparse --rate
    # must not turn "workload too light to show the win" into a failure.
    checks["kv_bytes_below_naive"] = (
        report.pool_bytes < naive_cache_bytes(cfg, a.slots,
                                              paged.max_seq_len))
    if report.peak_concurrency >= a.slots:
        checks["kv_bytes_below_naive_at_observed_peak"] = (
            report.pool_bytes < report.naive_bytes_at_peak)

    out = {
        "metric": "serving_smoke",
        "requests": a.requests,
        "slots": a.slots,
        "pool_blocks": report.pool_blocks,
        "peak_blocks_in_use": report.peak_blocks_in_use,
        "peak_concurrency": report.peak_concurrency,
        "pool_bytes": report.pool_bytes,
        "naive_bytes_at_peak": report.naive_bytes_at_peak,
        "naive_peak_blocks": naive_peak_blocks,
        "wall_s": round(wall, 3),
        "compiles": report.compiles,
        "retraces": report.retraces,
        "verified_bitwise": n_verified,
        "parity_mismatches": mismatches,
        "span_tree_problems": (tree_problems if events else None),
        "aggregates": report.aggregates,
        "tokens_per_dispatch": report.tokens_per_dispatch,
        "speculate": spec_block,
        "prefix_share": bool(a.prefix_share),
        "gather_bytes_saved": report.gather_bytes_saved,
        "checks": checks,
        "ok": all(checks.values()),
    }
    if spec_block is not None:
        # Trajectory rows for bench_compare (its ``rows`` shape):
        # tokens-per-dispatch is a THROUGHPUT-like metric — higher is
        # better, bench_compare's default direction (pinned in
        # tests/test_speculate.py) — so a draft regression that halves
        # the window gates exactly like a tok/s drop would.
        out["spec_tokens_per_dispatch"] = spec_block["tokens_per_dispatch"]
        out["rows"] = [{
            "metric": "tokens_per_dispatch",
            "value": spec_block["tokens_per_dispatch"],
            "unit": "tokens/target-dispatch",
            "platform": jax.default_backend(),
            "variant": f"spec-k{a.speculate}",
        }]
    return out


def run_fleet(a) -> dict:
    """The N-engine fleet smoke (module docstring): multi-tenant traffic,
    SLO-aware routing, one mid-run hot-swap through the deploy path."""
    import os

    import jax

    from ddl25spring_tpu.serving import (CheckpointPublisher, TrafficClass,
                                         WeightPublisher, blocks_for,
                                         class_slos, multi_tenant_workload,
                                         run_serving_fleet)
    from ddl25spring_tpu.telemetry import Telemetry
    from ddl25spring_tpu.telemetry.events import read_events
    from experiments.slo_monitor import SLOConfig, replay_monitor

    cfg, params = _build(a.seed)
    from ddl25spring_tpu.serving import PagedKVConfig
    paged = PagedKVConfig(num_blocks=a.blocks, block_len=a.block_len,
                          max_blocks_per_seq=a.max_blocks_per_seq)
    # Two tenant classes: latency-sensitive chat (higher priority, tight
    # shapes) and throughput batch (longer outputs). SLO ceilings are
    # deliberately generous — the verdict proves the per-class plumbing,
    # not the latency of a noisy CI host paying XLA compiles.
    classes = (
        TrafficClass("chat", rate_rps=a.rate * 2 / 3, prompt_lens=(4, 12),
                     max_news=(4, 8), temperatures=(0.0, 0.8), priority=1,
                     ttft_p99_s=120.0, queue_p99_s=120.0),
        TrafficClass("batch", rate_rps=a.rate / 3, prompt_lens=(12, 24),
                     max_news=(8, 16), temperatures=(0.0,), priority=0,
                     ttft_p99_s=240.0, queue_p99_s=240.0),
    )
    n_chat = (a.requests * 2) // 3
    workload = multi_tenant_workload(
        seed=a.seed, classes=classes,
        n_per_class={"chat": n_chat, "batch": a.requests - n_chat},
        vocab_size=cfg.vocab_size)

    checks = {}
    worst = blocks_for(24 + 16 - 1, a.block_len)
    checks["pool_below_naive_demand"] = (paged.num_blocks - 1
                                         < a.slots * worst)

    tel = Telemetry(a.telemetry_dir) if a.telemetry_dir else None
    events = tel.events if tel else None
    if events:
        events.manifest(jax_version=jax.__version__,
                        platform=jax.default_backend(),
                        trainer="serving-fleet", engines=a.engines,
                        slots=a.slots, blocks=a.blocks,
                        block_len=a.block_len, requests=len(workload),
                        policy=a.policy, admission=a.admission)

    # The mid-run publication, through the REAL deploy path: same weights
    # (so the bitwise bar must hold across the swap), but routed via the
    # publish-dir checkpoint, its SHA-256 digest manifest, and the
    # restore-at-saved-shapes read — not an in-process pointer pass.
    publish_after = publish_params = publish_version = None
    if a.hot_swap:
        import tempfile
        pub_dir = os.path.join(a.telemetry_dir or tempfile.mkdtemp(),
                               "publish")
        pub = CheckpointPublisher(pub_dir)
        pub(1200, params)               # "the trainer's step 1200"
        pub.close()
        got = WeightPublisher(pub_dir, params).poll()
        checks["publish_roundtrip"] = got is not None
        if got is not None:
            publish_version, publish_params = got
            publish_after = max(1, a.requests // 3)

    from ddl25spring_tpu.serving import SpecConfig
    spec = (SpecConfig(k=a.speculate, draft_params=params)
            if a.speculate else None)
    t0 = time.perf_counter()
    report = run_serving_fleet(
        params, cfg, paged, workload, num_engines=a.engines,
        num_slots=a.slots, prefill_chunk=a.prefill_chunk, events=events,
        policy=a.policy, admission=a.admission, speculate=spec,
        prefix_share=a.prefix_share,
        publish_after=publish_after, publish_params=publish_params,
        publish_version=publish_version)
    wall = time.perf_counter() - t0

    recs = report.records
    checks["all_completed"] = (report.aggregates.get("completed")
                               == len(workload))
    checks["token_counts_exact"] = all(
        len(recs[r.rid].tokens) == r.max_new for r in workload)
    checks["engines_all_used"] = all(
        agg["completed"] > 0 for agg in report.per_engine.values())
    # Each engine: exactly its documented program set (2 plain; 4 with
    # speculation — prefill + verify + the draft's two, decode idling),
    # zero retraces — ACROSS the hot-swap (an equal-shape swap is data,
    # never a shape; a target swap leaves the draft untouched).
    want_programs = 4 if a.speculate else 2
    checks["documented_programs_per_engine"] = all(
        c == want_programs for c in report.compiles)
    checks["zero_retraces_per_engine"] = all(r == 0 for r in report.retraces)
    if a.hot_swap:
        checks["deploy_rolled_out_all_engines"] = (
            sorted(d["engine"] for d in report.deploys)
            == list(range(a.engines)))

    slo = {}
    if events:
        events.run_end(steps=report.aggregates.get("completed", 0),
                       wall_s=wall, **{
                           k: report.aggregates.get(k) for k in
                           ("total_tokens", "sustained_tokens_per_sec")})
        tel.close()
        stream = read_events(tel.events_path)
        checks["stream_no_drop_no_dup"] = _stream_no_drop_no_dup(stream,
                                                                 workload)
        # Aggregate per-class SLO verdict: slo_monitor's per-class rolling
        # windows replayed over this stream (the same tool tier1.yml runs
        # as a CLI gate over the uploaded telemetry).
        monitor = replay_monitor(
            stream, SLOConfig(window_s=30.0, per_class=class_slos(classes)))
        slo = {"violations": monitor.violations,
               "breakdown": monitor.breakdown()}
        checks["per_class_slo_ok"] = not monitor.violations
        if a.hot_swap:
            # The deploy must be VISIBLE evidence: one deploy event per
            # engine in the stream, and a ``deploy`` span in the Perfetto
            # export (the acceptance bar names the export specifically).
            from experiments.trace_export import chrome_trace
            deploy_events = [e for e in stream if e.get("type") == "deploy"]
            checks["deploy_events_per_engine"] = (
                sorted(e.get("engine") for e in deploy_events)
                == list(range(a.engines)))
            exported = json.loads(json.dumps(chrome_trace(stream)))
            checks["deploy_span_in_perfetto_export"] = any(
                ev.get("ph") == "X" and ev.get("name") == "deploy"
                for ev in exported.get("traceEvents", []))

    # Bitwise parity vs generate() alone — regardless of engine count,
    # routing, priorities, or the mid-run same-weights hot-swap. With
    # speculation armed the bar applies to GREEDY streams (sampled ones
    # are distribution-correct under rejection sampling, not
    # path-identical — the documented stochastic contract).
    pool = ([r for r in workload if r.temperature == 0.0]
            if a.speculate else workload)
    n_verified, mismatches = _bitwise_sample(pool, recs, params, cfg,
                                             paged, seed=a.seed,
                                             verify=a.verify)
    checks["bitwise_parity_vs_generate"] = not mismatches

    checks["pool_never_exceeded"] = all(
        p <= report.pool_blocks for p in report.peak_blocks_per_engine)

    out = {
        "metric": "fleet_serving_smoke",
        "engines": a.engines,
        "policy": a.policy,
        "admission": a.admission,
        "requests": len(workload),
        "hot_swap": bool(a.hot_swap),
        "deploys": report.deploys,
        "pool_blocks": report.pool_blocks,
        "peak_blocks_per_engine": report.peak_blocks_per_engine,
        "compiles": report.compiles,
        "retraces": report.retraces,
        "wall_s": round(wall, 3),
        "verified_bitwise": n_verified,
        "parity_mismatches": mismatches,
        "aggregates": report.aggregates,
        "per_class": report.per_class,
        "per_engine": {str(k): v for k, v in report.per_engine.items()},
        "slo": slo,
        "checks": checks,
        "ok": all(checks.values()),
    }
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--requests", type=int, default=100)
    ap.add_argument("--rate", type=float, default=50.0,
                    help="Poisson arrival rate (requests/sec)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--blocks", type=int, default=33,
                    help="pool blocks incl. the reserved trash block")
    ap.add_argument("--block-len", type=int, default=8)
    ap.add_argument("--max-blocks-per-seq", type=int, default=8)
    ap.add_argument("--prefill-chunk", type=int, default=8)
    ap.add_argument("--verify", type=int, default=12,
                    help="requests to verify bitwise against generate()")
    ap.add_argument("--speculate", type=int, default=0, metavar="K",
                    help="single-engine mode: second pass speculating "
                         "with a same-weights draft proposing K tokens "
                         "per round; self-checks identical streams, the "
                         "compile contract, acceptance == 1 and "
                         "tokens-per-dispatch >= 2x plain (K >= 3)")
    ap.add_argument("--prefix-share", action="store_true",
                    help="arm CoW prefix sharing (streams must not move)")
    ap.add_argument("--gather-buckets", action="store_true",
                    help="narrow the decode gather to bucketed live "
                         "block counts; avoided bytes land in the JSON")
    ap.add_argument("--quick", action="store_true",
                    help="reduced request count (CI variance smoke)")
    ap.add_argument("--engines", type=int, default=1,
                    help="serving engines; > 1 runs the FLEET smoke "
                         "(multi-tenant traffic, SLO-aware router)")
    ap.add_argument("--policy", default="predicted_ttft",
                    choices=("least_loaded", "predicted_ttft"),
                    help="fleet router dispatch policy")
    ap.add_argument("--admission", default="fcfs", choices=("fcfs", "sjf"),
                    help="scheduler admission policy (fleet mode)")
    ap.add_argument("--hot-swap", action="store_true",
                    help="fleet mode: one mid-run live weight publication "
                         "through the deploy path (same weights — the "
                         "bitwise bar must hold across it)")
    ap.add_argument("--out", default=None, help="result JSON path")
    ap.add_argument("--telemetry-dir", default=None)
    a = ap.parse_args(argv)
    if a.quick:
        a.requests = min(a.requests, 30)
        a.verify = min(a.verify, 6)

    out = run_fleet(a) if a.engines > 1 else run(a)
    line = json.dumps(out)
    if a.out:
        with open(a.out, "w") as f:
            f.write(line + "\n")
    print(line)
    if not out["ok"]:
        failed = [k for k, v in out["checks"].items() if not v]
        print(f"serving smoke FAILED checks: {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
