"""tutorial-2a experiments: centralized heart classifier + VAE synthetic eval.

Reproduces:
- the centralized HeartDiseaseNN run with best-weights tracking (reference:
  lab/tutorial_2a/centralized.py:31-70 — test accuracy typically ≈85-90% on
  real heart.csv);
- the VAE synthetic-data protocol (generative-modeling.py:165-209): train
  per-class VAEs, sample synthetic rows, train evaluators on real vs
  synthetic, compare on the same real test set.

Results → ``experiments/results/generative.csv``.
"""

from __future__ import annotations

import argparse
from typing import Dict

from ddl25spring_tpu.data import tabular
from ddl25spring_tpu.train.generative import synthetic_data_eval
from ddl25spring_tpu.train.tabular import train_classifier

from . import common


def main(quick: bool = False) -> Dict[str, float]:
    provenance = common.heart_provenance()
    sink = common.sink("generative.csv")
    epochs = 20 if quick else 200

    X, y = tabular.load_heart()
    feats, _ = tabular.preprocess(X)
    x_tr, y_tr, x_te, y_te = tabular.train_test_split(feats, y, seed=0)

    _, rep = train_classifier(x_tr, y_tr, x_te, y_te, epochs=epochs, seed=0)
    sink.write({"experiment": "centralized", "epochs": epochs,
                "best_accuracy": rep.best_accuracy,
                "best_epoch": rep.best_epoch, "data": provenance})
    print(f"centralized heart: best acc {rep.best_accuracy:.4f} "
          f"@ epoch {rep.best_epoch}")

    # Honest-generalization variant: duplicate-aware split (heart.csv is the
    # duplicate-expanded UCI set; see data/tabular.train_test_split).
    xd_tr, yd_tr, xd_te, yd_te = tabular.train_test_split(feats, y, seed=0,
                                                          dedup=True)
    _, rep_d = train_classifier(xd_tr, yd_tr, xd_te, yd_te, epochs=epochs,
                                seed=0)
    sink.write({"experiment": "centralized_dedup", "epochs": epochs,
                "best_accuracy": rep_d.best_accuracy,
                "best_epoch": rep_d.best_epoch, "data": provenance})
    print(f"centralized heart (dedup split): best acc "
          f"{rep_d.best_accuracy:.4f} @ epoch {rep_d.best_epoch}")

    res = synthetic_data_eval(x_tr, y_tr, x_te, y_te,
                              evaluator_epochs=epochs, seed=0)
    sink.write({"experiment": "synthetic_eval", "epochs": epochs,
                "real_accuracy": res.real_accuracy,
                "synthetic_accuracy": res.synthetic_accuracy,
                "data": provenance})
    print(f"evaluator on real: {res.real_accuracy:.4f}  "
          f"on synthetic: {res.synthetic_accuracy:.4f}")
    print(f"-> {sink.path} [{provenance}]")
    return {"centralized": rep.best_accuracy, "real": res.real_accuracy,
            "synthetic": res.synthetic_accuracy}


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(quick=ap.parse_args().quick)
