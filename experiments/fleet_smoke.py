"""Fleet-scale FL smoke: a 100k-simulated-client FedAvg round, streamed.

The end-to-end proof of the cohort-streaming engine (ddl25spring_tpu/fl/
fleet.py, ISSUE 7 / ROADMAP item 4) on the CPU mesh, CI-runnable
(tier1.yml) — streams every one of --clients procedurally generated
clients through a fixed-width device cohort in ONE FedAvg round and
CHECKS the acceptance bars itself:

- memory: the round's resident-set growth stays under --rss-budget-mb —
  O(cohort), not O(clients) — while the vmapped path would materialize an
  estimated ``naive_resident_mb`` of client data + stacked deltas at once;
- correctness: on a small control slice the streamed round (ragged last
  cohort included) is BITWISE the vmapped reference round at equal cohort
  content, and the two-tier (edges=8) round matches the flat one within
  float-association tolerance;
- defenses at scale: Multi-Krum over cohort-streamed deltas selects
  exactly the clients the vmapped stack selects, and a timed probe runs
  the selection at a client count where the O(n²) distance matrix
  actually costs something (recorded, not asserted — CI machines vary);
- privacy: the RDP accountant's ε at realistic fleet sampling rates
  (q = 1e-4) lands in the report next to the conservative bound, so the
  deployment-shape privacy cost is a number in the CI artifact.

Outputs a result JSON (--out) and the fl_cohort/fl_tier telemetry stream
(--telemetry-dir, rendered by obs_report); exit 1 on any failed check
with the diagnostics in the JSON (tier1.yml uploads it either way).

Example:
    python -m experiments.fleet_smoke --out fleet-smoke.json \
        --telemetry-dir /tmp/fleet
    python -m experiments.obs_report /tmp/fleet
"""

from __future__ import annotations

import argparse
import json
import sys
import time

# The shared host sampler (telemetry/memory.py): one ru_maxrss reading —
# with the Linux-KiB/macOS-bytes normalization in ONE place — feeds both
# this smoke's RSS-bound check and the schema-v9 memory events below.
from ddl25spring_tpu.telemetry.memory import MemoryMeter, host_rss_bytes


def _rss_mb() -> float:
    return (host_rss_bytes() or 0) / 2**20    # MiB, as the budget is


def _leaves_equal(a, b) -> bool:
    import jax
    import numpy as np
    return all(bool((np.asarray(x) == np.asarray(y)).all())
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def _max_diff(a, b) -> float:
    import jax
    import numpy as np
    return max(float(np.abs(np.asarray(x) - np.asarray(y)).max())
               for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def run(a) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddl25spring_tpu import rng as rngmod
    from ddl25spring_tpu.config import FLConfig
    from ddl25spring_tpu.fl import (FleetConfig, FleetFedAvgServer,
                                    SyntheticFleetSource, TierPolicy,
                                    privacy_spend, vmapped_round_reference)
    from ddl25spring_tpu.fl.defenses import multi_krum, stack_flat
    from ddl25spring_tpu.telemetry import Telemetry

    features, classes = a.features, 16
    src = SyntheticFleetSource(a.clients, samples_per_client=8,
                               features=features, classes=classes,
                               seed=a.seed)
    xt, yt = src.test_set(512)

    def apply_fn(p, x, key=None):
        return x @ p["w"] + p["b"]

    params = {
        "w": 0.01 * jax.random.normal(jax.random.PRNGKey(a.seed),
                                      (features, classes)),
        "b": jnp.zeros((classes,)),
    }
    param_floats = features * classes + classes

    # Every client participates in the headline round (C=1): the streamed
    # path must shrug at a cohort list the vmapped path could never hold.
    cfg = FLConfig(nr_clients=a.clients, client_fraction=1.0, batch_size=8,
                   epochs=1, lr=0.5, rounds=1, seed=a.seed)
    naive_resident_mb = (a.clients * (8 * features + param_floats) * 4
                        ) / 1e6
    checks = {}

    tel = Telemetry(a.telemetry_dir) if a.telemetry_dir else None
    # RSS trajectory as schema-v9 memory events: one sample before the
    # round, one after — the O(cohort)-not-O(clients) claim as stream
    # records obs_report's memory section can table, not just a pass/fail
    # bit in this JSON. With no telemetry dir the meter still accumulates
    # (events=None), so the check below reads the same numbers either way.
    meter = MemoryMeter(tel.events if tel is not None else None,
                        source="fleet")
    rss_before = (meter.sample(phase="before_round").get("rss_bytes")
                  or 0) / 2**20
    fleet = FleetConfig(cohort_width=a.cohort, edges=a.edges)
    server = FleetFedAvgServer(params, apply_fn, src, xt, yt, cfg, fleet,
                               telemetry=tel)
    t0 = time.perf_counter()
    result = server.run(1)
    round_wall = time.perf_counter() - t0
    rss_delta = ((meter.sample(phase="after_round").get("rss_bytes")
                  or 0) / 2**20 - rss_before)

    acc = result.test_accuracy[-1]
    checks["round_completed"] = bool(result.rounds == 1 and np.isfinite(acc))
    checks["learned_above_chance"] = acc > 1.5 / classes
    checks["rss_bounded"] = rss_delta < a.rss_budget_mb

    # ---- control slice: streamed == vmapped reference, bitwise --------
    # Small enough to vmap (the whole point of the control), ragged on
    # purpose (80 clients at width 32 → a padded final cohort).
    ctl_cfg = FLConfig(nr_clients=a.clients, client_fraction=80 / a.clients,
                      batch_size=8, epochs=1, lr=0.5, rounds=1, seed=a.seed)
    ctl_idx = np.asarray(rngmod.sample_clients(
        ctl_cfg.seed, 0, ctl_cfg.nr_clients, ctl_cfg.clients_per_round))
    ctl_stream = FleetFedAvgServer(params, apply_fn, src, xt, yt, ctl_cfg,
                                   FleetConfig(cohort_width=32))
    got = ctl_stream._round(params, 0)
    ref = vmapped_round_reference(params, apply_fn, src, ctl_idx, ctl_cfg, 0)
    checks["control_slice_bitwise"] = _leaves_equal(got, ref)

    # Two-tier on the control slice: 8 edges vs flat. Mathematically the
    # same round; per-edge normalization re-associates the float sum, so
    # the bar is a documented tolerance, not bitwise (fl/fleet.py).
    hier = FleetFedAvgServer(params, apply_fn, src, xt, yt, ctl_cfg,
                             FleetConfig(cohort_width=32, edges=8))
    hier_diff = _max_diff(hier._round(params, 0), got)
    checks["hierarchical_matches_flat"] = hier_diff < 1e-5

    # ---- Krum at cohort scale ----------------------------------------
    # Selection correctness: the streamed [m, P] delta stack picks the
    # same Multi-Krum winners as the vmapped stack (the stacks themselves
    # are bitwise equal — that is the claim being exercised).
    kdef = FleetFedAvgServer(params, apply_fn, src, xt, yt, ctl_cfg,
                             FleetConfig(cohort_width=32))
    streamed_flat = kdef._collect_edge(params, 0, 0, ctl_idx)
    xs, ys, ms = src.cohort(ctl_idx)
    keys = jax.vmap(jax.random.key)(
        jnp.asarray(kdef.client_seeds(0, ctl_idx)))
    vm_flat = np.asarray(kdef._collect_step(
        params, jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(ms), keys))
    sel_stream = np.asarray(multi_krum(jnp.asarray(streamed_flat), 8, 16))
    sel_vmap = np.asarray(multi_krum(jnp.asarray(vm_flat), 8, 16))
    checks["krum_streamed_selection_matches"] = bool(
        (np.sort(sel_stream) == np.sort(sel_vmap)).all())

    # Retrace detector (ISSUE 9): every cohort step in this smoke — the
    # 100k-client streamed round, the control-slice server, the two-tier
    # round, the defended collect path — promises ONE compiled program
    # (ragged cohorts pad; raggedness is data). CompileWatch counts any
    # budget violation; the _cache_size()==1 invariant is the same claim
    # read off the jit cache directly. kdef._collect_step is excluded ON
    # PURPOSE: the vmapped-reference parity call above feeds it the FULL
    # control slice (a deliberate second shape — test scaffolding, not the
    # streamed path, which went through _collect_edge at cohort width).
    checks["zero_retraces"] = (
        all(s._stream_step.retraces == 0 and s._secagg_step.retraces == 0
            for s in (server, ctl_stream, hier, kdef))
        and all(s._collect_step.retraces == 0
                for s in (server, ctl_stream, hier)))
    checks["one_trace_per_stream_step"] = (
        server._stream_step._cache_size() == 1)

    # Selection-cost probe: Multi-Krum's O(n²·P) distance matrix at a
    # client count where it bites, vs a course-scale count for contrast.
    krum_probe = {}
    for n in (64, a.krum_probe_clients):
        flat = jnp.asarray(np.random.default_rng(0).normal(
            size=(n, param_floats)).astype(np.float32))
        mk = jax.jit(lambda f, n=n: multi_krum(f, n // 5, n // 4))
        jax.block_until_ready(mk(flat))          # compile
        t0 = time.perf_counter()
        jax.block_until_ready(mk(flat))
        krum_probe[f"n{n}_seconds"] = round(time.perf_counter() - t0, 4)

    # ---- RDP privacy spend at fleet sampling rates -------------------
    # q = 1e-4 is a 1k-cohort from a 10M fleet; the tight/conservative
    # gap at that q is the reason the accountant exists.
    privacy = {
        "fleet_q1e-4": privacy_spend(1.0, 10000, 1e-4),
        "this_smoke": privacy_spend(
            1.0, 10000, min(1.0, cfg.clients_per_round / a.clients)),
    }

    out = {
        "metric": "fleet_smoke",
        "clients": a.clients,
        "sampled_per_round": cfg.clients_per_round,
        "cohort_width": a.cohort,
        "edges": a.edges,
        "param_floats": param_floats,
        "round_wall_s": round(round_wall, 3),
        "test_accuracy": acc,
        "rss_delta_mb": round(rss_delta, 1),
        "rss_budget_mb": a.rss_budget_mb,
        "naive_resident_mb": round(naive_resident_mb, 1),
        "hierarchical_max_diff": hier_diff,
        "krum_probe": krum_probe,
        "privacy": privacy,
        "checks": checks,
        "ok": all(checks.values()),
    }
    if tel is not None:
        # server.run() already emitted the stream's run_end (with the
        # registry metrics snapshot obs_report renders) — a second one
        # here would shadow it, since readers take the LAST run_end.
        tel.close()
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--clients", type=int, default=100_000)
    ap.add_argument("--cohort", type=int, default=64)
    ap.add_argument("--edges", type=int, default=1)
    ap.add_argument("--features", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--rss-budget-mb", type=float, default=400.0,
                    help="max allowed resident-set growth over the round")
    ap.add_argument("--krum-probe-clients", type=int, default=512)
    ap.add_argument("--quick", action="store_true",
                    help="reduced client count (CI variance smoke)")
    ap.add_argument("--out", default=None, help="result JSON path")
    ap.add_argument("--telemetry-dir", default=None)
    a = ap.parse_args(argv)
    if a.quick:
        a.clients = min(a.clients, 20_000)

    out = run(a)
    line = json.dumps(out)
    if a.out:
        with open(a.out, "w") as f:
            f.write(line + "\n")
    print(line)
    if not out["ok"]:
        failed = [k for k, v in out["checks"].items() if not v]
        print(f"fleet smoke FAILED checks: {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
