"""Sequence-parallel (ring attention) memory scaling evidence.

The reference fixes sequence length at 256 on one device (lab/tutorial_1b/
primer/intro.py:10); long context is a capability this framework adds
(parallel/sp.py). Wall-clock on the virtual CPU mesh is meaningless, but the
XLA-compiled per-device temp-buffer size from ``compiled.memory_analysis()``
is hardware-independent — the same methodology as experiments/pp_schedules.
This sweeps ring size n_seq at fixed global sequence length and records the
per-device temp bytes of the full train step: ring attention's point is that
activations (and the per-hop [T/n, T/n] score blocks) shrink with the ring,
so context scales linearly in devices.

Results → ``experiments/results/sp_bench.csv``. Run:
    python -m experiments.sp_bench        (pins CPU + 8 virtual devices)
"""

from __future__ import annotations

import argparse
from typing import Dict


def measure(seq_len: int, n_seq: int, *, batch: int = 2) -> Dict[str, float]:
    import jax
    import optax

    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel import make_mesh, sp

    # Small width, long sequence: the quantities under test scale with T.
    cfg = LlamaConfig(vocab_size=512, dmodel=64, num_heads=4, n_layers=4,
                      ctx_size=seq_len)
    devices = jax.devices()[:n_seq]
    mesh = make_mesh({"seq": n_seq}, devices=devices)
    optimizer = optax.sgd(0.1)
    params = llama.init_llama(jax.random.key(0), cfg)
    state = sp.init_state(mesh, params, optimizer)
    step = sp.make_sp_train_step(cfg, optimizer, mesh)
    tokens = jax.random.randint(jax.random.key(1), (batch, seq_len), 0,
                                cfg.vocab_size)
    # The shared memory_analysis guard (telemetry/memory.py): a jaxlib
    # that can't account bytes degrades this bench to zeros, not a crash.
    from ddl25spring_tpu.telemetry.memory import program_memory
    mem = program_memory(step, state, sp.shard_batch(mesh, tokens)) or {}
    return {"temp_bytes": float(mem.get("temp_bytes", 0.0)),
            "argument_bytes": float(mem.get("argument_bytes", 0.0))}


def main(quick: bool = False) -> Dict[str, Dict[str, float]]:
    from . import common

    sink = common.sink("sp_bench.csv")
    grid = [(2048, (1, 2, 4))] if quick else [(2048, (1, 2, 4, 8)),
                                              (8192, (1, 2, 4, 8))]
    results: Dict[str, Dict[str, float]] = {}
    for seq_len, rings in grid:
        for n in rings:
            vals = measure(seq_len, n)
            sink.write({"seq_len": seq_len, "n_seq": n, **vals})
            results[f"t{seq_len}_n{n}"] = vals
            print(f"T={seq_len:5d} ring={n}: per-device temp "
                  f"{vals['temp_bytes']/1e6:9.1f} MB", flush=True)
    print(f"-> {sink.path}")
    return results


if __name__ == "__main__":
    from ._cpu_pin import pin_cpu_virtual

    pin_cpu_virtual()
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    main(quick=ap.parse_args().quick)
