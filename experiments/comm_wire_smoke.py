"""Comm-wire smoke: the overlapped+compressed sync's wire claim, checked.

The CI-sized proof (tier1.yml) that the ring driver's headline holds on
the CPU mesh with zero hand-waving: build the reduced bench's
``int8_ef + zero1 + scan4`` composition (parallel/compress.py
``make_overlap_multi_step``) next to the f32-allreduce baseline on the
SAME model/mesh in the SAME run, read both static comm profiles
(telemetry/comm.py — exact, trace-time), and CHECK:

1. per-train-step wire bytes of the compressed composition ≤ ~¼ of the
   f32 allreduce row (the ≥4× drop at ZeRO-1 memory parity; the small
   slack covers the per-hop fp32 scale scalars and the loss allreduce);
2. the ring accounting is EXACT: the profile's ppermute trips × chunk
   payloads equal the analytic K·M·(n−1)·chunk_bytes wire formula to the
   byte, for both the int8 payload hops and their scale sidecars;
3. zero retraces across the mode grid (wire × microbatches at zero1 ×
   scan4): each composition compiles exactly once over repeated
   same-shape dispatches, pinned through introspect.CompileWatch;
4. the HIERARCHICAL two-level mode (a hybrid dcn×data CPU mesh,
   hier_data_mesh: fp32 reduce-scatter within each ICI island, int8+EF
   across the DCN axis only) cuts the telemetry-attributed DCN-AXIS
   bytes/step to ≤ 30% of the flat f32 allreduce — the per-axis wire
   budget (``CommProfile.by_axis``), with the DCN ring's accounting
   pinned to the analytic K·M·(D−1)·chunk_bytes formula exactly, and
   zero retraces at every (islands × island_size) factorization;
5. the BUCKETED backward grid (ISSUE 19, ``comm_buckets`` ∈ {1, 2, 8}):
   each per-bucket ring leg matches its own K·M·(n−1)·size_b formula to
   the byte, the fp32 total wire and the int8 chunk legs are
   byte-invariant in the bucket count (sub-1/n chunking re-orders hops,
   it must not add payload — the int8 rings' only delta is one 4-byte
   scale per extra bucket per hop), every bucket count still clears the
   ≤ ~¼ ratio and compiles exactly once, and the overlap window is
   PROVEN in the jaxpr: at b=8 bucket 0's first ``ppermute`` hop
   carries no data dependence on the last bucket's VJP
   (``ring_overlap_evidence``), with the resulting ``overlap_fraction``
   emitted as a higher-is-better bench_compare row.

Wire-byte rows land in the JSON artifact in the bench_compare row shape
({"metric": "wire_bytes_per_train_step", ...}; the DCN budget as
"wire_bytes_dcn_per_train_step") — lower-is-better rows the comparator
gates in the right direction. Diagnostics live IN the JSON (the tier1
don't-clobber contract); exit 0 only when every check holds.

    python -m experiments.comm_wire_smoke --out comm-wire.json
"""

from __future__ import annotations

import argparse
import json
import sys


def run(out_path: str) -> int:
    from ._cpu_pin import pin_cpu_virtual
    pin_cpu_virtual()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel import compress, dp, make_mesh
    from ddl25spring_tpu.telemetry import introspect, measure_comm

    n, K = 4, 4
    mesh = make_mesh({"data": n}, devices=jax.devices()[:n])
    cfg = LlamaConfig(vocab_size=259, dmodel=32, num_heads=2, n_layers=2,
                      ctx_size=16)
    opt = lambda: optax.adam(1e-3)  # noqa: E731

    def loss_fn(p, b):
        return llama.forward_loss(p, b, cfg)

    def fresh_params():
        return llama.init_llama(jax.random.key(0), cfg)

    bsz = 2                                   # per shard
    batch_sds = jax.ShapeDtypeStruct((n * bsz, cfg.ctx_size), jnp.int32)
    window_sds = jax.ShapeDtypeStruct((K, n * bsz, cfg.ctx_size),
                                      jnp.int32)

    checks, rows, profiles = {}, [], {}

    # ---- baseline: the f32 gradient allreduce (per-step, plain DP) ----
    base_state = dp.replicate(mesh, dp.init_state(fresh_params(), opt()))
    base_step = dp.make_grad_aggregation_step(loss_fn, opt(), mesh)
    base_prof = measure_comm(base_step, base_state, batch_sds)
    base_wire = base_prof.wire_bytes_per_device_per_step
    profiles["f32_allreduce"] = base_prof.as_dict()
    rows.append({"metric": "wire_bytes_per_train_step",
                 "value": base_wire, "unit": "bytes/device/step",
                 "platform": "cpu", "variant": "f32-allreduce"})

    # ---- candidate: int8_ef + zero1 + scan4 through the ring driver ----
    cand_state, cand_step = compress.make_overlap_multi_step(
        loss_fn, opt(), mesh, fresh_params(), microbatches=1,
        wire="int8_ef", aggregation="zero1")
    cand_prof = measure_comm(cand_step, cand_state, window_sds)
    cand_wire = cand_prof.wire_bytes_per_device_per_step / K
    profiles["int8ef_zero1_scan4"] = cand_prof.as_dict(
        steps_per_dispatch=K)
    rows.append({"metric": "wire_bytes_per_train_step",
                 "value": cand_wire, "unit": "bytes/device/step",
                 "platform": "cpu", "variant": "int8ef+zero1+scan4"})

    ratio = cand_wire / base_wire
    checks["wire_ratio"] = {"value": ratio, "budget": 0.26,
                            "ok": ratio <= 0.26,
                            "f32_allreduce_bytes": base_wire,
                            "int8_ring_bytes": cand_wire}

    # ---- exact ring accounting vs the analytic formula ----
    from ddl25spring_tpu.parallel.dp import _flat_geometry
    _, _, local, _ = _flat_geometry(mesh, fresh_params())
    by = cand_prof.by_label()
    got_payload = by["ring_grad_int8"]["payload_bytes"]
    want_payload = K * 1 * (n - 1) * local * 1        # K·M·(n−1)·chunk int8
    got_scales = by["ring_grad_scale"]["payload_bytes"]
    want_scales = K * 1 * (n - 1) * 4                  # one fp32 per hop
    got_wire = by["ring_grad_int8"]["wire_bytes_per_device"]
    checks["ring_analytic"] = {
        "payload": {"got": got_payload, "want": want_payload},
        "scales": {"got": got_scales, "want": want_scales},
        # ppermute ring factor is 1 per trip: wire == payload, exactly.
        "wire_eq_payload": got_wire == got_payload,
        "ok": (got_payload == want_payload and got_scales == want_scales
               and got_wire == got_payload)}

    # ---- hierarchical mode: DCN-axis bytes vs the flat f32 allreduce ----
    # Two-level int8-across-DCN on a hybrid 2-island × 2 CPU mesh (same 4
    # devices, same model): the per-AXIS profile must show the scarce-tier
    # (dcn) wire at ≤ 30% of the flat fp32 allreduce's total — the
    # topology-aware claim, gated exactly like the flat ratio above.
    from ddl25spring_tpu.parallel.distributed import hier_data_mesh
    D, S = 2, 2
    hmesh = hier_data_mesh(D, S, devices=jax.devices()[:n])
    hier_state, hier_step = compress.make_overlap_multi_step(
        loss_fn, opt(), hmesh, fresh_params(), microbatches=1,
        wire={"ici": "fp32", "dcn": "int8_ef"}, aggregation="zero1")
    hier_prof = measure_comm(hier_step, hier_state, window_sds)
    profiles["hier_fp32ici_int8dcn_zero1_scan4"] = hier_prof.as_dict(
        steps_per_dispatch=K)
    by_axis = hier_prof.by_axis()
    dcn_wire = by_axis["dcn"]["wire_bytes_per_device"] / K
    rows.append({"metric": "wire_bytes_dcn_per_train_step",
                 "value": dcn_wire, "unit": "bytes/device/step",
                 "platform": "cpu", "variant": "hier-int8dcn+zero1+scan4"})
    rows.append({"metric": "wire_bytes_per_train_step",
                 "value": hier_prof.wire_bytes_per_device_per_step / K,
                 "unit": "bytes/device/step", "platform": "cpu",
                 "variant": "hier-int8dcn+zero1+scan4"})
    dcn_ratio = dcn_wire / base_wire
    checks["hier_dcn_ratio"] = {
        "value": dcn_ratio, "budget": 0.30, "ok": dcn_ratio <= 0.30,
        "f32_allreduce_bytes": base_wire, "dcn_axis_bytes": dcn_wire,
        "by_axis": {ax: agg["wire_bytes_per_device"] / K
                    for ax, agg in by_axis.items()}}

    # DCN ring accounting vs the analytic two-level formula, to the byte:
    # the dcn ring moves K·M·(D−1)·chunk int8 bytes (chunk = the zero1
    # local slice) + one fp32 scale per hop; the int8 delta gather's DCN
    # leg moves (D−1)·chunk more per step.
    hby = hier_prof.by_label()
    got = {"ring_payload": hby["ring_grad_dcn_int8"]["payload_bytes"],
           "ring_scales": hby["ring_grad_dcn_scale"]["payload_bytes"],
           "ring_wire": hby["ring_grad_dcn_int8"]["wire_bytes_per_device"],
           "gather_wire":
               hby["overlap_delta_gather_int8"]["wire_bytes_per_device"]}
    want = {"ring_payload": K * 1 * (D - 1) * local,
            "ring_scales": K * 1 * (D - 1) * 4,
            "ring_wire": K * 1 * (D - 1) * local,
            "gather_wire": K * (D - 1) * local}
    checks["hier_dcn_analytic"] = {"got": got, "want": want,
                                   "ok": got == want}

    # Zero retraces at every (islands × island_size) factorization of the
    # 4-device mesh — island-count changes rebuild the driver, but each
    # factorization's program compiles exactly once.
    hier_retraces = {}
    K2 = 2
    window2 = None
    for (hd, hs) in ((1, 4), (2, 2), (4, 1)):
        m = hier_data_mesh(hd, hs, devices=jax.devices()[:n])
        st, fn = compress.make_overlap_multi_step(
            loss_fn, opt(), m, fresh_params(), microbatches=1,
            wire={"ici": "fp32", "dcn": "int8_ef"}, aggregation="zero1")
        fn = introspect.watch(fn, name=f"smoke/hier-{hd}x{hs}",
                              max_caches=1)
        rng2 = np.random.default_rng(1)
        window2 = rng2.integers(
            0, cfg.vocab_size,
            size=(K2, n * bsz, cfg.ctx_size)).astype(np.int32)
        loss = None
        for _ in range(3):
            st, losses = fn(st, dp.shard_batch_window(m, window2))
            loss = float(np.asarray(losses)[-1])
        hier_retraces[f"{hd}x{hs}"] = {
            "compiles": len(fn.compiles),
            "retraces": sum(1 for c in fn.compiles if c.retrace),
            "final_loss": loss,
            "ok": bool(len(fn.compiles) == 1
                       and not any(c.retrace for c in fn.compiles)
                       and np.isfinite(loss))}
    checks["hier_retraces"] = {
        "grid": hier_retraces,
        "ok": all(v["ok"] for v in hier_retraces.values())}

    # ---- zero retraces across the mode grid (and real execution) ----
    rng = np.random.default_rng(0)
    window = rng.integers(0, cfg.vocab_size,
                          size=(K, n * bsz, cfg.ctx_size)).astype(np.int32)
    retraces = {}
    for wire in ("fp32", "bf16", "int8_ef"):
        for m in (1, 2):
            state, step = compress.make_overlap_multi_step(
                loss_fn, opt(), mesh, fresh_params(), microbatches=m,
                wire=wire, aggregation="zero1")
            step = introspect.watch(step, name=f"smoke/{wire}-m{m}",
                                    max_caches=1)
            loss = None
            for _ in range(3):
                state, losses = step(state,
                                     dp.shard_batch_window(mesh, window))
                loss = float(np.asarray(losses)[-1])
            retraces[f"{wire}-m{m}"] = {
                "compiles": len(step.compiles),
                "retraces": sum(1 for c in step.compiles if c.retrace),
                "final_loss": loss,
                "ok": bool(len(step.compiles) == 1
                           and not any(c.retrace for c in step.compiles)
                           and np.isfinite(loss))}
    checks["retraces"] = {"grid": retraces,
                          "ok": all(v["ok"] for v in retraces.values())}

    # ---- bucketed backward grid (ISSUE 19): comm_buckets ∈ {1, 2, 8} ----
    from ddl25spring_tpu.parallel.compress import (make_bucket_map,
                                                   ring_overlap_evidence)
    bucket_grid, fp32_totals, int8_chunk_totals = {}, {}, {}
    for b in (1, 2, 8):
        sizes = list(make_bucket_map(fresh_params(), n, b).sizes)
        # fp32 ring at this bucket count: trace-time profile only — the
        # TOTAL wire must be byte-identical to the unbucketed ring.
        fst, ffn = compress.make_overlap_multi_step(
            loss_fn, opt(), mesh, fresh_params(), microbatches=1,
            wire="fp32", aggregation="zero1", comm_buckets=b)
        fp32_totals[b] = measure_comm(
            ffn, fst, window_sds).wire_bytes_per_device_per_step

        # int8 ring: executed 3× under CompileWatch (zero retraces), the
        # per-bucket ring legs checked against K·M·(n−1)·size_b exactly.
        st, fn = compress.make_overlap_multi_step(
            loss_fn, opt(), mesh, fresh_params(), microbatches=1,
            wire="int8_ef", aggregation="zero1", comm_buckets=b)
        prof = measure_comm(fn, st, window_sds)
        wfn = introspect.watch(fn, name=f"smoke/int8-b{b}", max_caches=1)
        loss = None
        for _ in range(3):
            st, losses = wfn(st, dp.shard_batch_window(mesh, window))
            loss = float(np.asarray(losses)[-1])
        byb = prof.by_label()
        per_bucket, chunk_total = {}, 0
        for i, sz in enumerate(sizes):
            stem = "ring_grad" if b == 1 else f"ring_grad_b{i}"
            gp = int(byb[f"{stem}_int8"]["payload_bytes"])
            gs = int(byb[f"{stem}_scale"]["payload_bytes"])
            chunk_total += gp
            per_bucket[stem] = {
                "payload": {"got": gp, "want": K * (n - 1) * sz},
                "scales": {"got": gs, "want": K * (n - 1) * 4},
                "ok": bool(gp == K * (n - 1) * sz
                           and gs == K * (n - 1) * 4)}
        int8_chunk_totals[b] = chunk_total
        wire = prof.wire_bytes_per_device_per_step / K
        bucket_grid[f"b{b}"] = {
            "per_bucket": per_bucket,
            "wire_bytes_per_step": wire,
            "wire_ratio_vs_f32": wire / base_wire,
            "compiles": len(wfn.compiles),
            "retraces": sum(1 for c in wfn.compiles if c.retrace),
            "final_loss": loss,
            "ok": bool(all(v["ok"] for v in per_bucket.values())
                       and wire / base_wire <= 0.26
                       and len(wfn.compiles) == 1
                       and not any(c.retrace for c in wfn.compiles)
                       and np.isfinite(loss))}
        rows.append({"metric": "wire_bytes_per_train_step", "value": wire,
                     "unit": "bytes/device/step", "platform": "cpu",
                     "variant": f"int8ef+zero1+scan4-b{b}"})
    checks["bucket_grid"] = {
        "grid": bucket_grid,
        "fp32_wire_invariant": len(set(fp32_totals.values())) == 1,
        "int8_chunk_invariant": len(set(int8_chunk_totals.values())) == 1,
        "ok": (all(v["ok"] for v in bucket_grid.values())
               and len(set(fp32_totals.values())) == 1
               and len(set(int8_chunk_totals.values())) == 1)}

    # The overlap window itself, in the jaxpr (the acceptance bar):
    # bucket 0's first ppermute hop at b=8 is dataflow-independent of the
    # last bucket's VJP; unbucketed the same predicate is False — the
    # evidence is a property of the chunking, not of the tracer.
    batch1 = window[0]
    ev = {}
    for b in (1, 8):
        est, estep = compress.make_overlap_step(
            loss_fn, opt(), mesh, fresh_params(), microbatches=1,
            wire="int8_ef", aggregation="zero1", comm_buckets=b)
        ev[f"b{b}"] = ring_overlap_evidence(
            estep, est, dp.shard_batch(mesh, batch1))
    checks["overlap_evidence"] = {
        "b1": ev["b1"], "b8": ev["b8"],
        "ok": (ev["b8"]["first_hop_independent"]
               and not ev["b1"]["first_hop_independent"]
               and ev["b8"]["overlap_fraction"]
               > ev["b1"]["overlap_fraction"])}
    rows.append({"metric": "overlap_fraction",
                 "value": ev["b8"]["overlap_fraction"], "unit": "fraction",
                 "platform": "cpu", "variant": "int8ef+zero1-b8"})

    ok = all(c["ok"] for c in checks.values())
    doc = {"ok": ok, "n_devices": n, "steps_per_dispatch": K,
           "model": {"dmodel": cfg.dmodel, "n_layers": cfg.n_layers,
                     "vocab": cfg.vocab_size, "ctx": cfg.ctx_size},
           "checks": checks, "rows": rows, "profiles": profiles}
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    print(f"comm-wire smoke: ratio {ratio:.3f} (budget 0.26), "
          f"dcn ratio {dcn_ratio:.3f} (budget 0.30), "
          f"ring accounting {'exact' if checks['ring_analytic']['ok'] else 'WRONG'}, "
          f"dcn accounting {'exact' if checks['hier_dcn_analytic']['ok'] else 'WRONG'}, "
          f"buckets {'exact' if checks['bucket_grid']['ok'] else 'WRONG'}, "
          f"overlap b8 {ev['b8']['overlap_fraction']:.2f} "
          f"(first hop {'free' if ev['b8']['first_hop_independent'] else 'WAITED'}), "
          f"retraces {'clean' if checks['retraces']['ok'] and checks['hier_retraces']['ok'] else 'DIRTY'} "
          f"-> {out_path}", file=sys.stderr)
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default="comm-wire.json")
    a = ap.parse_args(argv)
    return run(a.out)


if __name__ == "__main__":
    sys.exit(main())
