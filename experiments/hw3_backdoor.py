"""Backdoor × defense battery: pixel-pattern backdoor under every defense.

Reproduces the reference's end-to-end backdoor evaluation
(lab/tutorial_3/attacks_and_defenses.ipynb cells 23-31, 50): 20% of clients
stamp the 5×3 extreme-value pattern at (3, 23) into 30% of their local
samples, relabel them to class 0, and upload 2·Δ; the server runs the hw3
protocol under each aggregation rule, and every round we record BOTH the
clean test accuracy and the attack success rate (fraction of a
fully-triggered test set classified as the backdoor label, backdoor-label
true positives excluded — metrics.backdoor_metrics, the notebook's cell-30
`confusion_matrix_backdoor` semantics).

Defenses: {none, krum, multi_krum, median, trimmed_mean, majority_sign,
clipping, sparse_fed} — the full hw3 rule set. Per-round curves land in
``experiments/results/hw3_backdoor.csv``; the final confusion matrix of the
undefended run is printed for the PARITY record (cell 31 shows column 0
absorbing the triggered mass).

Run: python -m experiments.hw3_backdoor [--quick] [--cpu]
"""

from __future__ import annotations

import argparse
from typing import Dict

import jax
import numpy as np

from ddl25spring_tpu.config import FLConfig
from ddl25spring_tpu.fl import FedAvgGradServer
from ddl25spring_tpu.fl import attacks as atk
from ddl25spring_tpu.metrics import backdoor_metrics, confusion_matrix
from ddl25spring_tpu.models import mnist_cnn

from . import common
from .hw3_defenses import HW3, MALICIOUS_FRACTION, _defense_hook

DEFENSES = ("none", "krum", "multi_krum", "median", "trimmed_mean",
            "majority_sign", "clipping", "sparse_fed")
# sparse_fed needs a top-k fraction; 0.4 is the middle of the reference's
# cell-29 sweep and the value its discussion settles on.
DEFENSE_EXTRA = {"sparse_fed": {"topk_fraction": 0.4}}


def run_one(defense: str, sink, provenance: str, *, rounds: int,
            n_train: int, n_test: int) -> Dict[str, float]:
    cfg = FLConfig(rounds=rounds, iid=True, **HW3)
    params, data, xt, yt = common.mnist_fl_setup(cfg, n_train=n_train,
                                                 n_test=n_test)
    attack = atk.PatternBackdoor()          # reference protocol defaults
    mask = atk.injection_mask(cfg.nr_clients, MALICIOUS_FRACTION, cfg.seed)
    n_mal = int(MALICIOUS_FRACTION * cfg.clients_per_round)
    extra = DEFENSE_EXTRA.get(defense, {})
    server = FedAvgGradServer(
        params, mnist_cnn.apply, data, xt, yt, cfg,
        adversary=(mask, attack),
        defense=_defense_hook(defense, n_mal, **extra))

    xt_trig = attack.trigger_test_set(xt)
    yt_np = np.asarray(yt)

    @jax.jit
    def predictions(p):
        return (mnist_cnn.apply(p, xt).argmax(-1),
                mnist_cnn.apply(p, xt_trig).argmax(-1))

    # The server's run() records clean accuracy only; the backdoor story
    # needs (clean, ASR) per round, so drive the round loop here.
    clean = asr = 0.0
    for r in range(rounds):
        server.params = server._round(server.params, r)
        preds_c, preds_t = predictions(server.params)
        clean, asr = backdoor_metrics(np.asarray(preds_c), yt_np,
                                      np.asarray(preds_t),
                                      attack.backdoor_label)
        sink.write({"defense": defense, "round": r, "clean_accuracy": clean,
                    "backdoor_asr": asr, "attack": "pattern_backdoor_20pct",
                    "n_train": n_train, "n_test": n_test,
                    "data": provenance, **extra})
    if defense == "none":
        cm = confusion_matrix(np.asarray(preds_t), yt_np, 10)
        print("undefended triggered-set confusion matrix "
              "(rows=true, col 0 = backdoor label):")
        print(cm)
    return {"clean": clean, "asr": asr}


def main(quick: bool = False, n_train: int = 6000, n_test: int = 2000
         ) -> Dict[str, float]:
    """Sizes follow the committed hw3_defenses.csv run (6000/2000 on CPU;
    protocol knobs exact — see hw1_fl.main on the reduced-corpus policy)."""
    provenance = common.mnist_provenance()
    if quick:
        n_train, n_test = 2000, 500
    rounds = 2 if quick else 10
    sink = common.sink("hw3_backdoor.csv")
    finals: Dict[str, float] = {}
    for defense in DEFENSES:
        res = run_one(defense, sink, provenance, rounds=rounds,
                      n_train=n_train, n_test=n_test)
        finals[f"{defense}/clean"] = res["clean"]
        finals[f"{defense}/asr"] = res["asr"]
        print(f"{defense:13s}: clean {res['clean']:.4f}  "
              f"ASR {res['asr']:.4f}", flush=True)
    print(f"-> {sink.path} [{provenance}]")
    return finals


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    a = ap.parse_args()
    if a.cpu:
        jax.config.update("jax_platforms", "cpu")
    main(quick=a.quick)
