"""Memory smoke: the v9 byte-accounting chain, armed end to end in CI.

The CI-sized proof (tier1.yml) of the memory observability tentpole
(ISSUE 17): ONE process runs a chunked-DP training slice and a paged
serving slice with their MemoryMeters armed, then CHECKS the acceptance
bars rather than asserting it ran:

- **zero overhead** — the metered training run's loss trajectory is
  BITWISE an unmetered twin's, and the metered serving run's token
  streams are bitwise an unmetered scheduler's (the meter is host
  bookkeeping only: no extra dispatches, no retraces — the compile
  events in the stream confirm);
- **preflight within 10%** — the manifest's config-only fit estimate
  (state + window bytes) agrees with the MEASURED ``memory_analysis``
  argument bytes stamped on the step program's compile event;
- **headroom SLO gates** — ``slo_monitor --check --slo-headroom`` over
  the emitted stream passes against a roomy ``--device-bytes`` budget
  and FAILS against one smaller than the observed peak (the breach the
  CI gate exists to catch actually fires);
- the stream's ``memory`` events validate strictly, carry both train
  and serve sources, and include the pool fragmentation census.

Peak footprints land as bench rows (``peak_*_bytes`` — lower is better,
experiments/bench_compare.py) in the JSON artifact; the telemetry stream
is written next to it for obs_report.

    python -m experiments.memory_smoke --out memory-smoke.json \\
        --telemetry-dir memory-telemetry

Exit code 0 only when every bar holds.
"""

from __future__ import annotations

import argparse
import json
import sys


def run(out_path: str, telemetry_dir: str = None, iters: int = 6) -> int:
    from ._cpu_pin import pin_cpu_virtual
    pin_cpu_virtual()

    import jax
    import numpy as np

    from ddl25spring_tpu.config import LlamaConfig, TrainConfig
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel import make_mesh
    from ddl25spring_tpu.serving import (Engine, PagedKVConfig, Request,
                                         Scheduler)
    from ddl25spring_tpu.telemetry import (Telemetry, read_events,
                                           validate_event)
    from ddl25spring_tpu.tokenizers import ByteTokenizer
    from ddl25spring_tpu.train.llm import train_llm_dp

    tiny = LlamaConfig(vocab_size=259, dmodel=16, num_heads=2, n_layers=2,
                       ctx_size=16)
    serve_cfg = LlamaConfig(vocab_size=97, dmodel=32, num_heads=4,
                            n_layers=2, ctx_size=32)
    paged = PagedKVConfig(num_blocks=24, block_len=4, max_blocks_per_seq=8)
    n, spd = 4, 2
    tc = TrainConfig(batch_size=2, seq_len=16, lr=3e-3, iters=iters,
                     data=n, steps_per_dispatch=spd)
    mesh = make_mesh({"data": n}, devices=jax.devices()[:n])
    checks = {}

    # ---- training slice: metered vs bare, bitwise ---------------------
    def train(tel):
        return train_llm_dp(tiny, tc, mesh=mesh, tokenizer=ByteTokenizer(),
                            aggregation="zero1", log_every=0, telemetry=tel)

    bare = train(None)
    telemetry = Telemetry(telemetry_dir) if telemetry_dir else Telemetry(
        out_path + ".telemetry")
    metered = train(telemetry)
    checks["train_losses_bitwise"] = (
        list(metered.losses) == list(bare.losses)
        and bool(np.isfinite(metered.losses).all()))

    # ---- serving slice: meter armed vs off, bitwise -------------------
    params = llama.init_llama(jax.random.PRNGKey(0), serve_cfg)
    rng = np.random.default_rng(3)
    workload = [Request(rid=f"r{i}",
                        prompt=tuple(int(t) for t in
                                     rng.integers(1, 97, size=4 + i % 5)),
                        max_new=3 + i % 4)
                for i in range(8)]

    def serve(events, memory_every):
        eng = Engine(params, serve_cfg, paged, 2, prefill_chunk=4)
        sched = Scheduler(eng, events=events, memory_every=memory_every)
        for req in workload:
            sched.submit(req, now=0.0)
        while sched.outstanding:
            sched.tick()
        return sched

    srv_metered = serve(telemetry.events, memory_every=2)
    srv_plain = serve(None, memory_every=0)
    checks["serve_streams_bitwise"] = all(
        srv_metered.records[r.rid].tokens == srv_plain.records[r.rid].tokens
        for r in workload)
    telemetry.close()

    # ---- the stream: valid v9 events, both sources, census fields -----
    stream = read_events(telemetry.events_path)
    mems = [e for e in stream if e.get("type") == "memory"]
    sources = {e.get("source") for e in mems}
    checks["memory_events_valid"] = (
        bool(mems) and all(validate_event(e) == [] for e in mems))
    checks["both_sources_sampled"] = {"train", "serve"} <= sources
    serve_mems = [e for e in mems if e.get("source") == "serve"]
    checks["pool_census_present"] = bool(serve_mems) and all(
        "holes" in e and "largest_run" in e and "pool_used_bytes" in e
        for e in serve_mems)

    # ---- preflight vs measured (the fit estimator's 10% bar) ----------
    manifest = next((e for e in stream if e.get("type") == "manifest"), {})
    pre = manifest.get("preflight") or {}
    measured = [e for e in stream
                if e.get("type") == "compile" and e.get("argument_bytes")
                and str(e.get("name", "")).startswith("train/")]
    fit = {}
    if pre and measured:
        predicted = pre["state_bytes"] + pre["window_bytes"]
        args = max(e["argument_bytes"] for e in measured)
        fit = {"predicted_bytes": predicted, "measured_argument_bytes": args,
               "rel_err": abs(args - predicted) / predicted}
        checks["preflight_within_10pct"] = fit["rel_err"] < 0.10
    else:
        # memory_analysis legally degrades on a drifted jaxlib — the bar
        # then is that preflight itself still produced a budget.
        checks["preflight_within_10pct"] = bool(pre)

    # Zero retraces with the meter armed (the no-extra-dispatch claim
    # read off the compile record).
    compiles = [e for e in stream if e.get("type") == "compile"]
    checks["zero_retraces"] = all(not e.get("retrace") for e in compiles)

    # ---- headroom gate: passes roomy, fails tight ---------------------
    from .slo_monitor import main as slo_main
    peak_device = max((e.get("device_bytes", 0) for e in mems), default=0)
    roomy = slo_main([telemetry.events_path, "--check", "--slo-headroom",
                      "0.2", "--device-bytes", str(peak_device * 10),
                      "--no-emit"])
    tight = slo_main([telemetry.events_path, "--check", "--slo-headroom",
                      "0.2", "--device-bytes", str(peak_device * 1.1),
                      "--no-emit"])
    checks["headroom_gate_passes_roomy_budget"] = roomy == 0
    checks["headroom_gate_catches_tight_budget"] = tight != 0

    # ---- peak rows for the perf trajectory ----------------------------
    def peak(source, field):
        vals = [e[field] for e in mems
                if e.get("source") == source
                and isinstance(e.get(field), (int, float))]
        return float(max(vals)) if vals else 0.0

    rows = [
        {"metric": "peak_device_bytes_train",
         "value": peak("train", "device_bytes"),
         "platform": "cpu", "variant": "memory-smoke"},
        {"metric": "peak_device_bytes_serve",
         "value": peak("serve", "device_bytes"),
         "platform": "cpu", "variant": "memory-smoke"},
        {"metric": "peak_pool_used_bytes",
         "value": peak("serve", "pool_used_bytes"),
         "platform": "cpu", "variant": "memory-smoke"},
    ]

    result = {
        "ok": all(checks.values()),
        "iters": iters,
        "preflight": pre,
        "fit": fit,
        "memory_events": len(mems),
        "sources": sorted(s for s in sources if s),
        "peak_device_bytes": peak_device,
        "headroom_rc": {"roomy": roomy, "tight": tight},
        "checks": checks,
        "rows": rows,
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    if not result["ok"]:
        failed = [k for k, v in checks.items() if not v]
        print(f"memory smoke FAILED checks: {failed}", file=sys.stderr)
    return 0 if result["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default="memory-smoke.json",
                    help="acceptance-evidence JSON path")
    ap.add_argument("--telemetry-dir", default=None,
                    help="write the shared train+serve events.jsonl here "
                         "(render with python -m experiments.obs_report)")
    ap.add_argument("--iters", type=int, default=6)
    a = ap.parse_args(argv)
    return run(a.out, a.telemetry_dir, a.iters)


if __name__ == "__main__":
    sys.exit(main())
