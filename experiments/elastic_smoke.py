"""Elastic re-mesh smoke: 4 → 3 (and back) on the CPU mesh, with evidence.

The CI-sized proof (tier1.yml) that the elasticity subsystem works end to
end: a 4-replica ZeRO-1 run takes a ``device_loss`` fault mid-run,
re-meshes onto 3 survivors, reshards state, and finishes — and the script
CHECKS the acceptance bar rather than asserting it ran: the post-remesh
loss sequence must be bitwise identical to a fresh 3-replica run restored
from the recovery state, and a zero-fault elastic run must be bitwise the
non-elastic trajectory. A fourth leg drives the BIDIRECTIONAL path
(ISSUE 16): ``device_loss`` then ``device_return`` walk 4 → 3 → 4, the
grow rejoins the exact device the shrink lost (pool-order restore), and
the post-grow losses must be bitwise a fresh 4-replica run restored from
the grow recovery point — scale-UP holds the same standard as shrink.

Two DP×PP legs prove the multi-axis tentpole (ISSUE 20): a 2×2 mesh
loses one device and the controller drops the victim's DATA row (pure
reshard, 2×2 → 1×2 on the data axis); a 1×4 mesh loses one device —
no data row survives whole — and the controller RE-PARTITIONS layers
onto fewer stages (1×4 → 1×2 on the stage axis, blocks re-sliced by
global coordinate id), with the post-re-partition losses bitwise a
fresh 1×2 run restored from the recovery checkpoint.

Recovery time, steps replayed, and post-remesh throughput land in a JSON
artifact (with ``rows`` that experiments/bench_compare.py judges
lower-is-better, tagged per recovery axis); the telemetry JSONL (with
its ``remesh`` events) is written next to it.

    python -m experiments.elastic_smoke --out elastic-recovery.json \
        --telemetry-dir elastic-telemetry

Exit code 0 only when all the bitwise checks hold.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile


def run(out_path: str, telemetry_dir: str = None, iters: int = 8) -> int:
    from ._cpu_pin import pin_cpu_virtual
    pin_cpu_virtual()

    import jax
    import numpy as np

    from ddl25spring_tpu.config import (LlamaConfig, ResilienceConfig,
                                        TrainConfig)
    from ddl25spring_tpu.parallel import make_mesh
    from ddl25spring_tpu.telemetry import Telemetry
    from ddl25spring_tpu.tokenizers import ByteTokenizer
    from ddl25spring_tpu.train.llm import train_llm_dp, train_llm_pp

    # dmodel=20 on purpose: 23260 params make the 4-way and 3-way ZeRO-1
    # padded lengths differ, so the shrink genuinely swaps the pad
    # (tests/test_elastic.py pins the same property).
    tiny = LlamaConfig(vocab_size=259, dmodel=20, num_heads=2, n_layers=2,
                      ctx_size=16)
    base = dict(batch_size=2, seq_len=16, lr=3e-3, iters=iters,
                steps_per_dispatch=2)
    mesh = lambda n: make_mesh({"data": n}, devices=jax.devices()[:n])

    def train(n, *, ckpt=None, res=None, tel=None, iters_=None):
        cfg = dict(base, iters=iters_ if iters_ is not None else iters)
        return train_llm_dp(
            tiny, TrainConfig(**cfg, data=n), mesh=mesh(n),
            tokenizer=ByteTokenizer(), aggregation="zero1", log_every=0,
            checkpoint_dir=ckpt, checkpoint_every=1000, resilience=res,
            telemetry=tel)

    def prune_to(src, dst, step):
        # Copy a checkpoint dir keeping only ``step``'s save, so a fresh
        # run resumes from exactly that recovery point.
        shutil.copytree(src, dst)
        for name in os.listdir(dst):
            if name.isdigit() and int(name) != step:
                shutil.rmtree(os.path.join(dst, name))
        dig = os.path.join(dst, "digests")
        for name in os.listdir(dig):
            if int(name.partition(".")[0]) != step:
                os.unlink(os.path.join(dig, name))

    work = tempfile.mkdtemp(prefix="elastic-smoke-")
    telemetry = Telemetry(telemetry_dir) if telemetry_dir else None
    try:
        # 1. zero-fault control: elastic loop == non-elastic, bitwise.
        ref4 = train(4)
        idle = train(4, res=ResilienceConfig(elastic=True))
        zero_fault_bitwise = idle.losses == ref4.losses

        # 2. the shrink: device_loss at dispatch 2 (step 4 at K=2).
        el = train(4, ckpt=os.path.join(work, "el"),
                   res=ResilienceConfig(elastic=True,
                                        faults="device_loss@2"),
                   tel=telemetry)
        rec = el.remeshes[0] if el.remeshes else None

        # 3. acceptance: fresh 3-replica run restored from the recovery
        # state walks the identical post-remesh floats.
        post_remesh_bitwise = False
        if rec is not None:
            m = rec["resume_step"]
            cmp_dir = os.path.join(work, "cmp")
            prune_to(os.path.join(work, "el"), cmp_dir, m)
            ref3 = train(3, ckpt=cmp_dir)
            post_remesh_bitwise = (ref3.start_step == m
                                   and el.losses[m:] == ref3.losses)

        # 4. the round trip (ISSUE 16 scale-up bar): device_return hands
        # the lost device back, the mesh grows 3 -> 4 on the mirror path,
        # and the post-grow floats equal a fresh 4-replica run restored
        # from the grow recovery point. 12 iters so the return (dispatch
        # 5, one prior fault's offset) lands on an interior chunk edge.
        rt = train(4, iters_=12, ckpt=os.path.join(work, "rt"),
                   res=ResilienceConfig(elastic=True, mirror_every=1,
                                        faults="device_loss@2,"
                                               "device_return@5"))
        rt_shrink = rt.remeshes[0] if len(rt.remeshes) == 2 else None
        rt_grow = rt.remeshes[1] if len(rt.remeshes) == 2 else None
        round_trip_bitwise = False
        if (rt_grow is not None and rt_grow["direction"] == "grow"
                and rt_grow["returned"] == rt_shrink["lost"]):
            g = rt_grow["resume_step"]
            rt_cmp = os.path.join(work, "rt-cmp")
            prune_to(os.path.join(work, "rt"), rt_cmp, g)
            ref4g = train(4, iters_=12, ckpt=rt_cmp)
            round_trip_bitwise = (ref4g.start_step == g
                                  and rt.losses[g:] == ref4g.losses)

        # 5./6. DP×PP legs (ISSUE 20): the same device_loss against the
        # two survivor topologies. n_layers=4 so a stage re-partition has
        # a divisor to land on (4 -> 2).
        tiny4 = tiny.replace(n_layers=4)

        def train_pp(d, s, *, ckpt=None, res=None):
            return train_llm_pp(
                tiny4, TrainConfig(**base, data=d, stage=s, microbatches=2),
                mesh=make_mesh({"data": d, "stage": s},
                               devices=jax.devices()[:d * s]),
                tokenizer=ByteTokenizer(), log_every=0,
                checkpoint_dir=ckpt, checkpoint_every=1000, resilience=res)

        # 2×2, one device lost: the victim's stage column has a surviving
        # replica, so the controller drops the DATA row — same stage
        # count, pure reshard.
        pp_d = train_pp(2, 2, res=ResilienceConfig(
            elastic=True, faults="device_loss@2"))
        pp_data = pp_d.remeshes[0] if pp_d.remeshes else None
        pp_data_ok = bool(
            pp_data is not None and pp_data["axis"] == "data"
            and pp_data["old_shape"] == [2, 2]
            and pp_data["new_shape"] == [1, 2]
            and np.isfinite(pp_d.losses).all())

        # 1×4, one device lost: no whole data row survives, so layers
        # RE-PARTITION 4 -> 2 stages; acceptance is the same bitwise bar
        # as the DP shrink — a fresh 1×2 run restored from the recovery
        # checkpoint walks identical post-re-partition floats.
        pp_s = train_pp(1, 4, ckpt=os.path.join(work, "pp"),
                        res=ResilienceConfig(elastic=True,
                                             faults="device_loss@2"))
        pp_stage = pp_s.remeshes[0] if pp_s.remeshes else None
        pp_stage_bitwise = False
        if (pp_stage is not None and pp_stage["axis"] == "stage"
                and pp_stage["new_shape"] == [1, 2]):
            m2 = pp_stage["resume_step"]
            pp_cmp = os.path.join(work, "pp-cmp")
            prune_to(os.path.join(work, "pp"), pp_cmp, m2)
            ref_pp = train_pp(1, 2, ckpt=pp_cmp)
            pp_stage_bitwise = (ref_pp.start_step == m2
                                and pp_s.losses[m2:] == ref_pp.losses)

        ok = bool(zero_fault_bitwise and post_remesh_bitwise
                  and round_trip_bitwise and rec is not None
                  and pp_data_ok and pp_stage_bitwise)
        result = {
            "ok": ok,
            "iters": iters,
            "zero_fault_bitwise": bool(zero_fault_bitwise),
            "post_remesh_bitwise": bool(post_remesh_bitwise),
            "round_trip_bitwise": bool(round_trip_bitwise),
            "pp_data_shrink_ok": pp_data_ok,
            "pp_stage_repartition_bitwise": bool(pp_stage_bitwise),
            "remesh": rec,
            "round_trip_remeshes": rt.remeshes,
            "pp_remeshes": [r for r in (pp_data, pp_stage) if r],
            "recovery_s": rec["seconds"] if rec else None,
            "steps_replayed": rec["steps_replayed"] if rec else None,
            "tokens_per_sec": el.tokens_per_sec,
            "post_remesh_tokens_per_sec": el.post_remesh_tokens_per_sec,
            "losses_finite": bool(np.isfinite(el.losses).all()
                                  and np.isfinite(rt.losses).all()),
            "resilience": {k: v for k, v in el.resilience.as_dict().items()
                           if v},
            # Recovery-cost rows for the perf trajectory (bench_compare
            # treats both prefixes as lower-is-better).
            "rows": [
                {"metric": "remesh_seconds_shrink",
                 "value": rec["seconds"] if rec else 0.0,
                 "platform": "cpu", "variant": "elastic-smoke"},
                {"metric": "steps_replayed_shrink",
                 "value": float(rec["steps_replayed"]) if rec else 0.0,
                 "platform": "cpu", "variant": "elastic-smoke"},
                {"metric": "remesh_seconds_grow",
                 "value": rt_grow["seconds"] if rt_grow else 0.0,
                 "platform": "cpu", "variant": "elastic-smoke"},
                {"metric": "steps_replayed_grow",
                 "value": (float(rt_grow["steps_replayed"])
                           if rt_grow else 0.0),
                 "platform": "cpu", "variant": "elastic-smoke"},
                # DP×PP recoveries, tagged by the axis that moved.
                {"metric": "remesh_seconds_pp_data",
                 "value": pp_data["seconds"] if pp_data else 0.0,
                 "platform": "cpu", "variant": "elastic-smoke"},
                {"metric": "steps_replayed_pp_data",
                 "value": (float(pp_data["steps_replayed"])
                           if pp_data else 0.0),
                 "platform": "cpu", "variant": "elastic-smoke"},
                {"metric": "remesh_seconds_pp_stage",
                 "value": pp_stage["seconds"] if pp_stage else 0.0,
                 "platform": "cpu", "variant": "elastic-smoke"},
                {"metric": "steps_replayed_pp_stage",
                 "value": (float(pp_stage["steps_replayed"])
                           if pp_stage else 0.0),
                 "platform": "cpu", "variant": "elastic-smoke"},
            ],
        }
    finally:
        if telemetry is not None:
            telemetry.close()
        shutil.rmtree(work, ignore_errors=True)

    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    return 0 if result["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default="elastic-recovery.json",
                    help="recovery-evidence JSON path")
    ap.add_argument("--telemetry-dir", default=None,
                    help="write the run's events.jsonl/heartbeat here "
                         "(render with python -m experiments.obs_report)")
    ap.add_argument("--iters", type=int, default=8)
    a = ap.parse_args(argv)
    return run(a.out, a.telemetry_dir, a.iters)


if __name__ == "__main__":
    sys.exit(main())
