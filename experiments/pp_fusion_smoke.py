"""PP-fusion smoke: the DP×PP composition column's claims, checked (ISSUE 14).

The CI-sized proof (tier1.yml) that the pipeline fast path carries the DP
levers without hand-waving, on a 4-virtual-device ``(data=2, stage=2)``
CPU mesh — the comm_wire_smoke contract applied to the PP column:

1. the DATA-AXIS wire of the composed ``int8_ef + zero1 + scan4`` driver
   (pp.make_pipeline_overlap_multi_step) is ≤ ~¼ of the plain DP×PP
   step's fp32 grad pmean on the SAME model/mesh (``CommProfile.by_axis``
   — the cross-STAGE hops are identical in both and excluded), per train
   step;
2. the ring + delta-gather accounting is EXACT: the profile's trips ×
   payloads equal the analytic K·M·(n−1)·chunk_bytes (+ per-hop scale
   sidecars, + K·(n−1)·chunk gather) formulas to the byte;
3. zero retraces across the composition grid — wire × K at zero1 through
   the overlap driver AND schedule × K through the plain multi-step
   driver: each (config) compiles exactly ONE program over repeated
   same-shape dispatches (introspect.CompileWatch), the documented
   one-program-per-(schedule, K) factory promise;
4. the TRAINER's compile events carry the PP window size
   (``steps_per_dispatch`` stamped per compiling call, tail chunks with
   their ACTUAL smaller window) so per-step MFU normalization stays
   honest — checked end-to-end through train_llm_pp + telemetry.

Wire-byte rows land in the JSON artifact in the bench_compare row shape
({"metric": "wire_bytes_pp_data_axis_per_train_step", ...}) — the
``wire_bytes`` prefix pins the lower-is-better direction, so the ~¼×
compressed-wire claim is trajectory-gated exactly like DP's. Diagnostics
live IN the JSON (the tier1 don't-clobber contract); exit 0 only when
every check holds.

    python -m experiments.pp_fusion_smoke --out pp-fusion.json
"""

from __future__ import annotations

import argparse
import json
import sys


def run(out_path: str) -> int:
    from ._cpu_pin import pin_cpu_virtual
    pin_cpu_virtual()

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ddl25spring_tpu.config import LlamaConfig
    from ddl25spring_tpu.models import llama
    from ddl25spring_tpu.parallel import make_mesh, pp
    from ddl25spring_tpu.telemetry import introspect, measure_comm

    n, S, K = 2, 2, 4
    mesh = make_mesh({"data": n, "stage": S}, devices=jax.devices()[:n * S])
    # 4 layers: divisible by S·v = 4, so the interleaved schedule's grid
    # entry runs on the same model as everything else.
    cfg = LlamaConfig(vocab_size=259, dmodel=32, num_heads=2, n_layers=4,
                      ctx_size=16)
    opt = lambda: optax.adam(1e-3)  # noqa: E731

    def fresh_params():
        return llama.init_llama(jax.random.key(0), cfg)

    bsz = 4                                    # per data shard
    mb = 2                                     # pipeline microbatches
    batch_sds = jax.ShapeDtypeStruct((n * bsz, cfg.ctx_size), jnp.int32)
    window_sds = jax.ShapeDtypeStruct((K, n * bsz, cfg.ctx_size), jnp.int32)

    checks, rows, profiles = {}, [], {}

    # ---- baseline: plain DP×PP step, fp32 pmean on the data axis ----
    base_state = pp.init_state(mesh, fresh_params(), opt())
    base_step = pp.make_pipeline_step(cfg, opt(), mesh, n_microbatches=mb)
    base_prof = measure_comm(base_step, base_state, batch_sds)
    base_data = base_prof.by_axis()["data"]["wire_bytes_per_device"]
    profiles["pp_f32_pmean"] = base_prof.as_dict()
    rows.append({"metric": "wire_bytes_pp_data_axis_per_train_step",
                 "value": base_data, "unit": "bytes/device/step",
                 "platform": "cpu", "variant": "dp2pp2-f32-pmean"})

    # ---- candidate: int8_ef + zero1 + scan4 through the DP×PP ring ----
    cand_state, cand_step = pp.make_pipeline_overlap_multi_step(
        cfg, opt(), mesh, fresh_params(), n_microbatches=mb,
        aggregation="zero1", wire="int8_ef", overlap_microbatches=1)
    cand_prof = measure_comm(cand_step, cand_state, window_sds)
    cand_data = cand_prof.by_axis()["data"]["wire_bytes_per_device"] / K
    profiles["pp_int8ef_zero1_scan4"] = cand_prof.as_dict(
        steps_per_dispatch=K)
    rows.append({"metric": "wire_bytes_pp_data_axis_per_train_step",
                 "value": cand_data, "unit": "bytes/device/step",
                 "platform": "cpu",
                 "variant": "dp2pp2-int8ring+zero1+scan4"})

    ratio = cand_data / base_data
    checks["pp_data_wire_ratio"] = {
        "value": ratio, "budget": 0.27, "ok": ratio <= 0.27,
        "f32_pmean_bytes": base_data, "int8_ring_bytes": cand_data}

    # ---- exact ring + gather accounting vs the analytic formulas ----
    from ddl25spring_tpu.parallel.pp import _pp_flat_geometry
    _, _, local, _ = _pp_flat_geometry(mesh, fresh_params())
    by = cand_prof.by_label()
    got = {"ring_payload": by["pp_ring_grad_int8"]["payload_bytes"],
           "ring_scales": by["pp_ring_grad_scale"]["payload_bytes"],
           "ring_wire": by["pp_ring_grad_int8"]["wire_bytes_per_device"],
           "gather_wire":
               by["pp_delta_gather_int8"]["wire_bytes_per_device"]}
    want = {"ring_payload": K * 1 * (n - 1) * local,  # K·M·(n−1)·chunk int8
            "ring_scales": K * 1 * (n - 1) * 4,       # one fp32 per hop
            "ring_wire": K * 1 * (n - 1) * local,     # ppermute: wire==payload
            "gather_wire": K * (n - 1) * local}       # int8 delta all-gather
    checks["pp_ring_analytic"] = {"got": got, "want": want,
                                  "ok": got == want}

    # ---- zero retraces: wire × K grid through the overlap driver ----
    rng = np.random.default_rng(0)
    retraces = {}
    for wire in ("fp32", "bf16", "int8_ef"):
        for k in (1, 2):
            state, step = pp.make_pipeline_overlap_multi_step(
                cfg, opt(), mesh, fresh_params(), n_microbatches=mb,
                aggregation="zero1", wire=wire, overlap_microbatches=1)
            step = introspect.watch(step, name=f"smoke/pp-{wire}-k{k}",
                                    max_caches=1)
            window = rng.integers(
                0, cfg.vocab_size,
                size=(k, n * bsz, cfg.ctx_size)).astype(np.int32)
            loss = None
            for _ in range(3):
                state, losses = step(state,
                                     pp.shard_batch_window(mesh, window))
                loss = float(np.asarray(losses)[-1])
            retraces[f"{wire}-k{k}"] = {
                "compiles": len(step.compiles),
                "retraces": sum(1 for c in step.compiles if c.retrace),
                "final_loss": loss,
                "ok": bool(len(step.compiles) == 1
                           and not any(c.retrace for c in step.compiles)
                           and np.isfinite(loss))}
    checks["overlap_retraces"] = {
        "grid": retraces,
        "ok": all(v["ok"] for v in retraces.values())}

    # ---- zero retraces: schedule × K grid through the plain driver ----
    # The one-program-per-(schedule, K) factory promise of
    # make_pipeline_multi_step, for every schedule the body lookup serves.
    sched_retraces = {}
    for schedule in ("gpipe", "1f1b", "interleaved"):
        params = fresh_params()
        if schedule == "interleaved":
            params = pp.interleave_params(params, S, 2)
        for k in (2,):
            state = pp.init_state(mesh, params, opt())
            step = pp.make_pipeline_multi_step(
                cfg, opt(), mesh, n_microbatches=mb, schedule=schedule)
            step = introspect.watch(step,
                                    name=f"smoke/pp-{schedule}-k{k}",
                                    max_caches=1)
            window = rng.integers(
                0, cfg.vocab_size,
                size=(k, n * bsz, cfg.ctx_size)).astype(np.int32)
            loss = None
            for _ in range(3):
                state, losses = step(state,
                                     pp.shard_batch_window(mesh, window))
                loss = float(np.asarray(losses)[-1])
            sched_retraces[f"{schedule}-k{k}"] = {
                "compiles": len(step.compiles),
                "retraces": sum(1 for c in step.compiles if c.retrace),
                "final_loss": loss,
                "ok": bool(len(step.compiles) == 1
                           and not any(c.retrace for c in step.compiles)
                           and np.isfinite(loss))}
    checks["multi_step_retraces"] = {
        "grid": sched_retraces,
        "ok": all(v["ok"] for v in sched_retraces.values())}

    # ---- trainer compile events carry the PP window size ----
    # End-to-end through train_llm_pp: iters=3 at K=2 runs one full chunk
    # and one tail chunk — two compiles, stamped 2 and 1, so slo_monitor's
    # per-step MFU normalization cannot misread the tail as a full-K
    # program (the DP chunked trainer's contract, tests/test_telemetry.py).
    import os
    import tempfile

    from ddl25spring_tpu.config import TrainConfig
    from ddl25spring_tpu.telemetry import Telemetry
    from ddl25spring_tpu.tokenizers import ByteTokenizer
    from ddl25spring_tpu.train.llm import train_llm_pp

    tdir = tempfile.mkdtemp(prefix="pp-fusion-smoke-")
    tel = Telemetry(tdir)
    try:
        train_llm_pp(cfg,
                     TrainConfig(batch_size=bsz, seq_len=cfg.ctx_size,
                                 iters=3, lr=3e-3, data=n, stage=S,
                                 microbatches=mb, steps_per_dispatch=2),
                     mesh=mesh, tokenizer=ByteTokenizer(), log_every=0,
                     telemetry=tel)
    finally:
        tel.close()
    compile_events = []
    with open(os.path.join(tel.out_dir, "events.jsonl")) as f:
        for line in f:
            e = json.loads(line)
            if e.get("type") == "compile" and \
                    str(e.get("name", "")).startswith("train/pp-"):
                compile_events.append(e)
    # A missing stamp (the regression this gate exists to catch) must
    # land as ok:false IN the JSON, not a TypeError sorting None.
    stamped = sorted((e.get("steps_per_dispatch") or 0)
                     for e in compile_events)
    checks["trainer_compile_meta"] = {
        "events": [{"name": e.get("name"),
                    "steps_per_dispatch": e.get("steps_per_dispatch")}
                   for e in compile_events],
        "want_window_sizes": [1, 2],
        "ok": stamped == [1, 2]}

    ok = all(c["ok"] for c in checks.values())
    doc = {"ok": ok, "n_data": n, "n_stages": S, "steps_per_dispatch": K,
           "model": {"dmodel": cfg.dmodel, "n_layers": cfg.n_layers,
                     "vocab": cfg.vocab_size, "ctx": cfg.ctx_size},
           "checks": checks, "rows": rows, "profiles": profiles}
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
    print(f"pp-fusion smoke: data-axis ratio {ratio:.3f} (budget 0.27), "
          f"ring accounting "
          f"{'exact' if checks['pp_ring_analytic']['ok'] else 'WRONG'}, "
          f"retraces {'clean' if checks['overlap_retraces']['ok'] and checks['multi_step_retraces']['ok'] else 'DIRTY'}, "
          f"compile meta "
          f"{'stamped' if checks['trainer_compile_meta']['ok'] else 'MISSING'} "
          f"-> {out_path}", file=sys.stderr)
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default="pp-fusion.json")
    a = ap.parse_args(argv)
    return run(a.out)


if __name__ == "__main__":
    sys.exit(main())
