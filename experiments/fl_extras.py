"""Measured evidence for the parity-plus FL capabilities.

Three batteries, all on the same 10-client MNIST setup the hw1 harness
uses (synthetic fallback offline; the comparisons are repo-internal so
provenance does not confound them):

1. **FedProx vs FedAvg on the non-IID split** — per-round accuracy at
   μ ∈ {0, 0.01, 0.1}; μ=0 doubles as the exact-FedAvg control.
2. **DP-FedAvg utility vs privacy** — final accuracy at noise multiplier
   z ∈ {0, 0.05, 0.1} with BOTH privacy bounds recorded alongside: the
   conservative advanced-composition ε (fl.privacy.dp_epsilon) and the
   tight subsampled-RDP ε (fl.privacy.dp_epsilon_tight, amplification at
   the run's client fraction C).
3. **Secure aggregation utility cost** — SecAgg vs the plain clipped
   round: the per-round accuracies should be equal up to the fixed-point
   grid (the committed CSV is the measured record of "masking is free").

Results → ``experiments/results/fl_extras.csv``. Run:
    python -m experiments.fl_extras [--quick]
"""

from __future__ import annotations

import argparse
from typing import Dict

from ddl25spring_tpu.config import FLConfig
from ddl25spring_tpu.fl import (DPFedAvgServer, FedProxServer, dp_epsilon,
                                dp_epsilon_tight)
from ddl25spring_tpu.fl.secure_agg import SecureAggFedAvgServer
from ddl25spring_tpu.models import mnist_cnn

from . import common


def _run(server, sink, provenance: str, rounds: int, n_train: int,
         **extra) -> float:
    """``extra`` values may be callables (round_1based -> value) — used for
    the per-round cumulative privacy-spend columns; scalars broadcast."""
    result = server.run(rounds)
    df = result.as_df()
    df["data"] = provenance
    df["n_train"] = n_train
    for k, v in extra.items():
        df[k] = [v(int(r)) for r in df["round"]] if callable(v) else v
    for row in df.to_dict(orient="records"):
        sink.write(row)
    return result.test_accuracy[-1]


def main(quick: bool = False, n_train: int = 4000, n_test: int = 1000
         ) -> Dict[str, float]:
    """n_train defaults to 4,000 (vs hw1's 12,000): every comparison here
    is repo-internal (FedProx vs its own μ=0, DP vs its own z=0, SecAgg vs
    its own clipped control), so corpus size scales wall-clock without
    touching the claims; the n_train column records it."""
    provenance = common.mnist_provenance()
    sink = common.sink("fl_extras.csv")
    rounds = 3 if quick else 10
    if quick:
        n_train, n_test = 1000, 300
    out: Dict[str, float] = {}

    # -- 1. FedProx vs FedAvg, non-IID ---------------------------------
    cfg = FLConfig(nr_clients=10, client_fraction=0.3, batch_size=50,
                   epochs=2, lr=0.05, rounds=rounds, seed=10, iid=False)
    for mu in (0.0, 0.01, 0.1):
        params, data, xt, yt = common.mnist_fl_setup(cfg, n_train=n_train,
                                                     n_test=n_test)
        acc = _run(FedProxServer(params, mnist_cnn.apply, data, xt, yt, cfg,
                                 mu=mu),
                   sink, provenance, rounds, n_train, mu=mu)
        out[f"fedprox_mu{mu}"] = acc
        print(f"fedprox non-IID mu={mu}: {acc:.3f}", flush=True)

    # -- 2. DP-FedAvg utility vs epsilon --------------------------------
    cfg_dp = FLConfig(nr_clients=10, client_fraction=0.3, batch_size=50,
                      epochs=1, lr=0.05, rounds=rounds, seed=10)
    # z ≤ 0.1 traces the utility cliff; z=1.0 is the protocol-realistic
    # privacy point where the subsampled-RDP bound actually bites
    # (ε_tight ≈ 7.9 vs ε_advcomp ≈ 20.2 at C=0.3, T=10).
    for z in (0.0, 0.05, 0.1, 1.0):
        params, data, xt, yt = common.mnist_fl_setup(cfg_dp, n_train=n_train,
                                                     n_test=n_test)
        # Cumulative privacy spend after each round — per-row, so the CSV
        # reads as a (utility, ε-so-far) trajectory.
        eps = dp_epsilon(z, rounds) if z > 0 else float("inf")
        eps_t = (dp_epsilon_tight(z, rounds, cfg_dp.client_fraction)
                 if z > 0 else float("inf"))
        acc = _run(DPFedAvgServer(params, mnist_cnn.apply, data, xt, yt,
                                  cfg_dp, clip_norm=5.0, noise_multiplier=z),
                   sink, provenance, rounds, n_train,
                   noise_multiplier=z,
                   epsilon=(lambda r, z=z: round(dp_epsilon(z, r), 2))
                   if z > 0 else float("inf"),
                   epsilon_tight=(lambda r, z=z: round(dp_epsilon_tight(
                       z, r, cfg_dp.client_fraction), 2))
                   if z > 0 else float("inf"))
        out[f"dp_z{z}"] = acc
        print(f"dp-fedavg z={z} (final eps={eps:.1f}, tight {eps_t:.1f}): "
              f"{acc:.3f}", flush=True)

    # -- 3. SecAgg vs plain clipped round --------------------------------
    for label, mk in (("secagg", lambda p, d, xt, yt: SecureAggFedAvgServer(
                          p, mnist_cnn.apply, d, xt, yt, cfg_dp,
                          clip_norm=5.0, bits=20)),
                      ("clipped", lambda p, d, xt, yt: DPFedAvgServer(
                          p, mnist_cnn.apply, d, xt, yt, cfg_dp,
                          clip_norm=5.0, noise_multiplier=0.0))):
        params, data, xt, yt = common.mnist_fl_setup(cfg_dp, n_train=n_train,
                                                     n_test=n_test)
        acc = _run(mk(params, data, xt, yt), sink, provenance, rounds,
                   n_train, variant=label)
        out[label] = acc
        print(f"{label}: {acc:.3f}", flush=True)

    print(f"-> {sink.path}")
    return out


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--cpu", action="store_true")
    a = ap.parse_args()
    if a.cpu:
        from ._cpu_pin import pin_cpu_virtual

        pin_cpu_virtual()
    main(quick=a.quick)
