"""Render a telemetry run report from a JSONL event stream.

The read side of the telemetry layer (ddl25spring_tpu/telemetry): given a
run directory (or an events.jsonl path directly), print a human report —
manifest, per-collective comm volume, step-time percentiles, phase
breakdown, fault counters, FL round summary, heartbeat status. Pure
stdlib + the telemetry read helpers; never imports jax, so it runs
instantly next to (or instead of) a live training process.

Example:
    python -m experiments.hw1b_llm --cpu --quick --telemetry-dir /tmp/obs
    python -m experiments.obs_report /tmp/obs/dp1
"""

from __future__ import annotations

import argparse
import os
import re
import sys
import time

# Submodule imports keep this report jax-free (the package __init__ is
# also safe — its comm.py re-exports are lazy — but importing exactly what
# is used makes the no-jax contract explicit).
from ddl25spring_tpu.telemetry.events import iter_runs, read_events
from ddl25spring_tpu.telemetry.heartbeat import read_heartbeat
from ddl25spring_tpu.telemetry.introspect import attainment
from ddl25spring_tpu.telemetry.registry import percentile
from ddl25spring_tpu.telemetry.trace import trace_trees, tree_check


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{n:,.0f} B"
        n /= 1024
    return f"{n:,.1f} GiB"


def _section(title: str) -> None:
    print(f"\n== {title} " + "=" * max(0, 60 - len(title)))


def _fmt_num(v) -> str:
    """Device-derived metrics (loss, accuracy) reach the stream as the
    strings "nan"/"inf" when non-finite (EventLog keeps the JSONL strict)
    — exactly the runs this report exists to diagnose, so print them
    instead of crashing on the float format spec."""
    return f"{v:.4f}" if isinstance(v, (int, float)) else str(v)


def _print_violation(e: dict) -> None:
    print(f"  {e.get('slo', '?'):20s} "
          f"{_fmt_num(e.get('value'))} vs threshold "
          f"{_fmt_num(e.get('threshold'))} "
          f"(window {_fmt_num(e.get('window_s'))}s)")


def report_run(events: list, heartbeat_path: str = None) -> None:
    """Print the report for ONE run_id's event list."""
    if events and all(e.get("type") == "slo_violation" for e in events):
        # A sidecar slo_monitor appends its violations under its OWN
        # run_id (iter_runs keeps writers apart); render them as the
        # monitor's verdict on the stream, not as a crashed run.
        _section(f"slo violations (monitor {events[0].get('run_id')})")
        for e in events:
            _print_violation(e)
        return
    by_type = {}
    for e in events:
        # .get: non-strict mode keeps parseable-but-typeless lines; the
        # tolerant reader buckets them under None rather than crashing.
        by_type.setdefault(e.get("type"), []).append(e)

    manifest = (by_type.get("manifest") or [None])[0]
    run_end = (by_type.get("run_end") or [None])[-1]
    steps = by_type.get("step", [])
    faults = by_type.get("fault", [])
    rounds = by_type.get("fl_round", [])
    cohorts = by_type.get("fl_cohort", [])
    tiers = by_type.get("fl_tier", [])
    remeshes = by_type.get("remesh", [])
    req_enq = by_type.get("request_enqueue", [])
    req_pre = by_type.get("request_prefill", [])
    req_tok = by_type.get("request_token", [])
    req_done = by_type.get("request_done", [])

    _section("run")
    print(f"run_id: {events[0].get('run_id')}   events: {len(events)}")
    if manifest:
        for k in ("trainer", "platform", "jax_version", "n_devices", "mesh",
                  "start_step"):
            if manifest.get(k) is not None:
                print(f"{k}: {manifest[k]}")
        # The activation-sync mode (TrainConfig.psa) changes what the
        # model-axis wire rows below MEAN — echo it whenever set so a
        # profile reader never compares a relaxed-sync run against a
        # full-sync one without noticing.
        psa = (manifest.get("train_cfg") or {}).get("psa")
        if psa:
            print(f"psa: {psa}")
        # Likewise the bucketed backward (ISSUE 19): the per-label rows
        # below split into per-bucket ring legs under comm_buckets > 1,
        # and a reader comparing dispatch counts across runs needs to
        # know the bucket count up front.
        cb = (manifest.get("train_cfg") or {}).get("comm_buckets")
        if isinstance(cb, int) and cb > 1:
            print(f"comm_buckets: {cb}")

    comm = (manifest or {}).get("comm")
    if comm:
        _section("comm volume (static, per step)")
        print(f"payload: {_fmt_bytes(comm['payload_bytes_per_step'])}   "
              f"wire/device: "
              f"{_fmt_bytes(comm['wire_bytes_per_device_per_step'])}")
        for label, agg in sorted(comm["collectives"].items(),
                                 key=lambda kv: -kv[1]["payload_bytes"]):
            print(f"  {label:28s} {agg['op']:12s} axis={agg['axis']}"
                  f"({agg['axis_size']})  x{agg['calls']:<5d} "
                  f"payload {_fmt_bytes(agg['payload_bytes']):>12s}  "
                  f"wire {_fmt_bytes(agg['wire_bytes_per_device']):>12s}")
        # Per-mesh-axis attribution (hierarchical collectives): the DCN
        # row IS the scarce-tier wire budget, and the MODEL row is the
        # PSA activation-sync budget (tp.psa_sync_wire_bytes) — so a
        # single-axis TP manifest still renders the table. Absent on
        # pre-PR-12 manifests — skip silently.
        # Per-bucket ring dispatch counts (ISSUE 19 bucketed backward):
        # fold the per-label ``*ring_grad_b<N>*`` legs into per-axis
        # bucket tallies so the wire-budget table shows how many times
        # each bucket's ring dispatched — the sub-1/n chunking's dispatch
        # overhead, next to the bytes it re-orders.
        bucket_calls = {}
        for label, agg in comm["collectives"].items():
            m = re.search(r"ring_grad_b(\d+)", str(label))
            if m:
                per_ax = bucket_calls.setdefault(agg.get("axis"), {})
                b = int(m.group(1))
                per_ax[b] = per_ax.get(b, 0) + agg.get("calls", 0)
        axes = comm.get("axes")
        if axes and (len(axes) > 1 or "model" in axes or bucket_calls):
            print("per-axis wire budget:")
            for ax, agg in sorted(axes.items(),
                                  key=lambda kv:
                                  -kv[1]["wire_bytes_per_device"]):
                per_ts = agg.get("wire_bytes_per_device_per_train_step")
                print(f"  axis {ax:6s}({agg['axis_size']})  x"
                      f"{agg['calls']:<5d} payload "
                      f"{_fmt_bytes(agg['payload_bytes']):>12s}  wire "
                      f"{_fmt_bytes(agg['wire_bytes_per_device']):>12s}"
                      + (f"  ({_fmt_bytes(per_ts)}/step)"
                         if per_ts is not None else ""))
                bk = bucket_calls.get(ax)
                if bk:
                    counts = sorted(set(bk.values()))
                    detail = (f"x{counts[0]} dispatches each"
                              if len(counts) == 1 else
                              "  ".join(f"b{b}:x{c}"
                                        for b, c in sorted(bk.items())))
                    print(f"    bucketed ring: {len(bk)} buckets  "
                          f"{detail}")

    if steps:
        _section("steps")
        # Per-step seconds from the event stream's (dt_s, steps) deltas —
        # events are emitted every step_every iterations, so dt_s/steps is
        # the mean over that window; the distribution is over windows.
        # Warmup-flagged windows (compile/replay in dt_s) are excluded.
        dts = [e["dt_s"] / e["steps"] for e in steps
               if e.get("steps") and not e.get("warmup")]
        losses = [e["loss"] for e in steps if e.get("loss") is not None]
        print(f"step events: {len(steps)}   "
              f"iters {steps[0]['it']}..{steps[-1]['it']}")
        if losses:
            print(f"loss: {_fmt_num(losses[0])} -> {_fmt_num(losses[-1])}")
        if dts:
            print("step time: " + "  ".join(
                f"p{q:g}={percentile(dts, q) * 1e3:.1f}ms"
                for q in (50, 95, 99)) + f"  n={len(dts)} windows")

    if req_enq or req_pre or req_done or req_tok:
        # Serving section (schema v2 request_* events, serving/scheduler.py;
        # schema v6 tags them per engine). Runs with no serving events skip
        # this silently — training and serving streams share one schema,
        # not one workload. Percentile tables group PER ENGINE: an
        # N-engine fleet's streams must not pool into one table (each
        # engine has its own pool, so "peak blocks in use" pooled across
        # engines would compare apples to a sum of oranges), with the
        # fleet-wide aggregate kept as the headline. Untagged (pre-v6 /
        # single-engine) events group under one unlabeled engine, which
        # renders exactly the old single-table output.
        _section("serving")
        print(f"requests: {len(req_enq)} enqueued   {len(req_pre)} admitted"
              f"   {len(req_done)} done   {len(req_tok)} token events")

        def _latency_lines(done_events, indent=""):
            waits = [e["queue_wait_s"] for e in done_events
                     if isinstance(e.get("queue_wait_s"), (int, float))]
            ttfts = [e["ttft_s"] for e in done_events
                     if isinstance(e.get("ttft_s"), (int, float))]
            for label, vals in (("queue wait", waits), ("ttft", ttfts)):
                if vals:
                    print(indent + f"{label}: " + "  ".join(
                        f"p{q:g}={percentile(vals, q) * 1e3:.1f}ms"
                        for q in (50, 95, 99)) + f"  n={len(vals)}")

        _latency_lines(req_done)
        total_tokens = sum(e["tokens"] for e in req_done
                           if isinstance(e.get("tokens"), int))
        if req_done and req_pre:
            # Busy-span throughput from the stream's own timestamps:
            # first admission -> last completion (fleet-wide).
            span = max(e["t"] for e in req_done) - min(e["t"] for e in req_pre)
            if span > 0:
                print(f"sustained: {total_tokens / span:,.1f} tok/s "
                      f"({total_tokens} tokens over {span:.2f}s busy span)")
        engines = sorted({e.get("engine") for e in req_pre + req_done
                          if e.get("engine") is not None})
        if engines:
            for eid in engines:
                mine = [e for e in req_done if e.get("engine") == eid]
                blocks = [e["blocks_in_use"] for e in req_pre + req_done
                          if e.get("engine") == eid
                          and isinstance(e.get("blocks_in_use"), int)]
                print(f"engine {eid}: {len(mine)} done"
                      + (f"   peak blocks in use {max(blocks)}"
                         if blocks else ""))
                _latency_lines(mine, indent="  ")
        else:
            blocks = [e["blocks_in_use"] for e in req_pre + req_done
                      if isinstance(e.get("blocks_in_use"), int)]
            if blocks:
                print(f"peak blocks in use: {max(blocks)}")
        tenants = sorted({e.get("tenant") for e in req_done
                          if isinstance(e.get("tenant"), str)})
        if len(tenants) > 1:
            for cls in tenants:
                mine = [e for e in req_done if e.get("tenant") == cls]
                print(f"class {cls}: {len(mine)} done")
                _latency_lines(mine, indent="  ")
        specs = by_type.get("speculate", [])
        if specs:
            # Speculative decoding (schema v7, serving/speculate.py): one
            # event per verify dispatch. Acceptance = accepted/proposed
            # draft tokens; tokens-per-dispatch = tokens the target's
            # verify dispatches landed (the dispatch-bound decode
            # headline) — a rate near 1/(k+1) of the emitted window means
            # the draft is degenerate (slo_monitor's acceptance floor).
            prop = sum(e.get("proposed", 0) for e in specs)
            acc = sum(e.get("accepted", 0) for e in specs)
            emitted = sum(e.get("emitted", 0) for e in specs
                          if isinstance(e.get("emitted"), int))
            ks = sorted({e.get("k") for e in specs
                         if isinstance(e.get("k"), int)})
            line = (f"speculate: {len(specs)} verify dispatches"
                    + (f"   k={'/'.join(map(str, ks))}" if ks else ""))
            if prop:
                line += f"   acceptance {acc}/{prop} = {acc / prop:.3f}"
            if emitted:
                line += f"   tokens/dispatch {emitted / len(specs):.2f}"
            print(line)

    routes = by_type.get("route", [])
    deploys = by_type.get("deploy", [])
    if routes or deploys:
        # Fleet section (schema v6, serving/fleet.py + serving/deploy.py):
        # router decisions and live weight rollouts.
        _section("serving fleet (routing / deploys)")
        if routes:
            per_engine = {}
            for e in routes:
                per_engine[e.get("engine")] = \
                    per_engine.get(e.get("engine"), 0) + 1
            policy = next((e.get("policy") for e in routes
                           if e.get("policy")), "?")
            print(f"routed: {len(routes)} requests under {policy}   "
                  + "  ".join(f"engine {k}: {v}"
                              for k, v in sorted(per_engine.items(),
                                                 key=lambda kv:
                                                 str(kv[0]))))
        for e in deploys:
            print(f"  deploy version {e.get('version')} -> "
                  f"engine {e.get('engine', '?')}  "
                  f"({e.get('in_flight', 0)} in flight, "
                  f"{e.get('queued', 0)} queued across the swap)")

    nums = by_type.get("numerics", [])
    if nums:
        # Numerics section (schema v5, telemetry/introspect.py): the
        # in-jit run-health samples. Pre-v5 streams simply have no
        # ``numerics`` events and skip this silently.
        _section("numerics (in-jit run health)")
        gnorms = [e["grad_norm"] for e in nums
                  if isinstance(e.get("grad_norm"), (int, float))]
        print(f"samples: {len(nums)}   iters "
              f"{nums[0].get('it')}..{nums[-1].get('it')}"
              + (f"   grad_norm {_fmt_num(gnorms[0])} -> "
                 f"{_fmt_num(gnorms[-1])}" if gnorms else ""))
        # Worst-drifting layer group: widest max/min spread of the
        # update/param ratio across the run's samples — the knob that
        # moves before a spike becomes a StepGuard skip.
        spread = {}
        for e in nums:
            for g, d in (e.get("groups") or {}).items():
                r = d.get("update_ratio")
                if isinstance(r, (int, float)) and r > 0:
                    lo, hi = spread.get(g, (r, r))
                    spread[g] = (min(lo, r), max(hi, r))
        drifts = sorted(((hi / lo, g, lo, hi)
                         for g, (lo, hi) in spread.items() if lo > 0),
                        reverse=True)
        for d, g, lo, hi in drifts[:3]:
            print(f"  {g:16s} update/param ratio {lo:.3g} .. {hi:.3g} "
                  f"(x{d:.2f} drift)")
        bad = [e for e in nums if e.get("nonfinite_grads")]
        for e in bad:
            print(f"  it {e.get('it', '?'):>6}: NON-FINITE grads in "
                  f"{e['nonfinite_grads']}   <-- BAD")

    compiles = by_type.get("compile", [])
    if compiles:
        # Compile/retrace section (schema v5, introspect.CompileWatch).
        _section("compile / retrace")
        by_name = {}
        for e in compiles:
            agg = by_name.setdefault(e.get("name", "?"),
                                     {"n": 0, "s": 0.0, "retraces": 0,
                                      "flops": None, "bytes": None})
            agg["n"] += 1
            if isinstance(e.get("seconds"), (int, float)):
                agg["s"] += e["seconds"]
            if e.get("retrace"):
                agg["retraces"] += 1
            if isinstance(e.get("flops"), (int, float)):
                agg["flops"] = e["flops"]
            if isinstance(e.get("bytes_accessed"), (int, float)):
                agg["bytes"] = e["bytes_accessed"]
        for name, agg in sorted(by_name.items()):
            line = (f"  {name:28s} compiles {agg['n']:<3d} "
                    f"{agg['s']:8.2f}s total")
            if agg["flops"]:
                line += f"  {agg['flops'] / 1e6:,.1f} MFLOP/dispatch"
            if agg["retraces"]:
                line += f"  RETRACES {agg['retraces']}   <-- BAD"
            print(line)

    peaks = (manifest or {}).get("peaks")
    if compiles and peaks:
        # Attainment section: what each dispatch ACHIEVED vs the roofline
        # peaks the manifest recorded (ROOFLINE.md numbers on chip, the
        # calibrated baseline on CPU fallback). Numerators: the compiled
        # program's HLO flops/bytes normalized PER STEP by the compile
        # event's own steps_per_dispatch (same rule as slo_monitor — a
        # ragged tail chunk's smaller program must not be costed as a
        # full-K one), then scaled by each dispatch's step count (the
        # parent ``dispatch`` span's ``steps``); denominator: the
        # ``compute`` span durations.
        prog = next((e for e in reversed(compiles)
                     if isinstance(e.get("flops"), (int, float))
                     and e["flops"] > 0), None)
        span_events = by_type.get("span", [])
        by_span_id = {e.get("span_id"): e for e in span_events}
        # ``compiled``-stamped spans (the trainer marks a dispatch whose
        # call compiled — warmup, tail-chunk shapes) are excluded: a
        # compile-dominated interval is not an attainment sample.
        computes = [e for e in span_events
                    if e.get("name") == "compute"
                    and not e.get("compiled")
                    and isinstance(e.get("dur_ns"), (int, float))
                    and e["dur_ns"] > 0]
        if prog is not None and computes:
            _section("attainment (vs roofline peaks)")
            spd = prog.get("steps_per_dispatch")
            spd = spd if isinstance(spd, int) and spd > 0 else 1
            flops_step = prog["flops"] / spd
            bytes_step = (prog["bytes_accessed"] / spd
                          if isinstance(prog.get("bytes_accessed"),
                                        (int, float)) else None)
            mfus, gbs = [], []
            for s in computes:
                parent = by_span_id.get(s.get("parent_span_id"), {})
                steps = parent.get("steps")
                steps = steps if isinstance(steps, int) and steps > 0 else 1
                att = attainment(flops_step * steps,
                                 (bytes_step * steps
                                  if bytes_step is not None else None),
                                 s["dur_ns"] / 1e9, peaks)
                if att["mfu"] is not None:
                    mfus.append(att["mfu"])
                if att["bytes_per_sec"] is not None:
                    gbs.append(att["bytes_per_sec"] / 1e9)
            print(f"program: {prog.get('name')}   "
                  f"{flops_step / 1e6:,.1f} MFLOP/step   "
                  f"peaks: {peaks.get('source', '?')}")
            if mfus:
                print("mfu: " + "  ".join(
                    f"p{q:g}={percentile(mfus, q):.4f}"
                    for q in (50, 99)) + f"  n={len(mfus)} dispatches")
            if gbs:
                print("memory: " + "  ".join(
                    f"p{q:g}={percentile(gbs, q):.2f} GB/s"
                    for q in (50, 99)))

    mems = by_type.get("memory", [])
    preflight = (manifest or {}).get("preflight")
    if mems or preflight:
        # Memory section (schema v9, telemetry/memory.py): the preflight
        # fit estimate, the measured compiled footprint it cross-checks
        # against (the latest compile event's memory_analysis bytes —
        # argument bytes ARE the resident state+window, the comparable
        # quantity), and the live meter's sampled peaks per source.
        _section("memory")
        if preflight:
            parts = "  ".join(
                f"{k.replace('_bytes', '')}={_fmt_bytes(preflight[k])}"
                for k in ("params_bytes", "opt_state_bytes",
                          "residual_bytes", "window_bytes",
                          "kv_pool_bytes")
                if isinstance(preflight.get(k), (int, float))
                and preflight[k] > 0)
            print(f"preflight (per device, world "
                  f"{preflight.get('n_data', '?')}): "
                  f"{_fmt_bytes(preflight.get('device_bytes', 0))}   "
                  + parts)
            # The preflight estimates the TRAINER's footprint, so prefer
            # a train/-namespaced compile for the cross-check; a stream
            # with only serving compiles falls back to the latest.
            accounted = [e for e in compiles
                         if isinstance(e.get("argument_bytes"),
                                       (int, float))]
            measured = next(
                (e for e in reversed(accounted)
                 if str(e.get("name", "")).startswith("train/")),
                accounted[-1] if accounted else None)
            if measured is not None and isinstance(
                    preflight.get("state_bytes"), (int, float)):
                predicted = (preflight["state_bytes"]
                             + preflight.get("window_bytes", 0))
                arg = measured["argument_bytes"]
                rel = (abs(arg - predicted) / predicted if predicted
                       else None)
                print(f"measured ({measured.get('name', '?')}): args "
                      f"{_fmt_bytes(arg)}  temp "
                      f"{_fmt_bytes(measured.get('temp_bytes', 0))}  "
                      f"peak {_fmt_bytes(measured.get('device_bytes', 0))}"
                      + (f"   vs preflight {rel:+.1%}"
                         if rel is not None else ""))
        if mems:
            by_source = {}
            for e in mems:
                by_source.setdefault(e.get("source", "?"), []).append(e)
            for source, evs in sorted(by_source.items()):
                peaks_ = {}
                for e in evs:
                    for k, v in e.items():
                        if (k.endswith("_bytes")
                                and isinstance(v, (int, float))):
                            peaks_[k] = max(peaks_.get(k, 0), v)
                last = evs[-1]
                line = f"  {source:8s} samples {len(evs):<5d}"
                for k in ("device_bytes", "rss_bytes", "pool_used_bytes",
                          "mirror_bytes"):
                    if k in peaks_:
                        line += (f"  peak {k.replace('_bytes', '')} "
                                 f"{_fmt_bytes(peaks_[k])}")
                if isinstance(last.get("holes"), int):
                    line += (f"  frag holes={last['holes']} "
                             f"largest_run={last.get('largest_run', '?')}")
                print(line)

    spans = by_type.get("span", [])
    if spans:
        # Traces section (schema v4 span events, telemetry/trace.py): the
        # causal structure behind the flat percentiles above. The
        # self-check line is the layer auditing itself — orphans (a span
        # naming a parent the stream never closed) and imbalance
        # (children outlasting their parent) are propagation bugs, and a
        # report that silently rendered them would hide exactly the class
        # of defect tracing exists to expose.
        _section("traces")
        trees = trace_trees(events)
        checks = {tid: tree_check(t) for tid, t in trees.items()}
        orphans = sum(c["orphans"] for c in checks.values())
        imbalanced = sum(c["imbalanced"] for c in checks.values())
        print(f"spans: {len(spans)}   traces: {len(trees)}   "
              f"self-check: {orphans} orphaned, {imbalanced} imbalanced"
              + ("" if not (orphans or imbalanced) else "   <-- BAD"))
        # Per-request breakdown over traces rooted in a single "request"
        # span (the serving trees; the train/fleet traces have per-
        # dispatch/per-round roots and are better read in Perfetto).
        reqs = {tid: t["roots"][0] for tid, t in trees.items()
                if len(t["roots"]) == 1
                and t["roots"][0].get("name") == "request"}
        if reqs:
            durs = sorted((r.get("dur_ns", 0), tid)
                          for tid, r in reqs.items())
            total_ms = [d / 1e6 for d, _ in durs]
            print(f"request spans: {len(reqs)}   total: " + "  ".join(
                f"p{q:g}={percentile(total_ms, q):.1f}ms"
                for q in (50, 95, 99)))
            # Critical path of the slowest p99 request: which child spans
            # its end-to-end time actually went to.
            p99 = percentile([d for d, _ in durs], 99)
            dur, tid = next((d, t) for d, t in durs if d >= p99)
            tree, root = trees[tid], reqs[tid]
            kids = tree["children"].get(root.get("span_id"), [])
            print(f"slowest p99 request: {tid}  "
                  f"{dur / 1e6:.1f}ms end-to-end")
            for k in kids:
                pct = 100 * k.get("dur_ns", 0) / max(dur, 1)
                n_sub = len(tree["children"].get(k.get("span_id"), []))
                print(f"  {k.get('name', '?'):14s} "
                      f"{k.get('dur_ns', 0) / 1e6:9.2f}ms  {pct:5.1f}%"
                      + (f"  ({n_sub} children)" if n_sub else ""))

    slo_events = by_type.get("slo_violation", [])
    if slo_events:
        _section("slo violations")
        for e in slo_events:
            _print_violation(e)

    if remeshes:
        _section("remesh (elastic recoveries)")
        for e in remeshes:
            lost = e.get("lost")
            # Multi-axis meshes (DP×PP) tag each remesh with the axis that
            # moved and the (D, S) factorization old -> new; a "stage"
            # axis means a layer re-partition (state re-sliced by
            # coordinate id), "data" a pure row-drop/grow reshard.
            old_s, new_s = e.get("old_shape"), e.get("new_shape")
            topo = ""
            if old_s and new_s:
                axis = e.get("axis", "data")
                kind = ("re-partition" if axis == "stage" else "reshard")
                topo = (f"  [{old_s[0]}x{old_s[1]} -> "
                        f"{new_s[0]}x{new_s[1]}, {axis} axis: {kind}]")
            print(f"  step {e.get('it', '?'):>6}: "
                  f"{e.get('old_world', '?')} -> {e.get('new_world', '?')} "
                  f"devices"
                  + (f" (lost {lost})" if lost else "")
                  + topo
                  + f"  via {e.get('path', '?')}"
                  + (f"  {e['seconds']:.3f}s lost"
                     if isinstance(e.get("seconds"), (int, float)) else "")
                  + (f"  {e['steps_replayed']} steps replayed"
                     if e.get("steps_replayed") is not None else ""))

    scales = by_type.get("scale", [])
    if scales:
        # Autoscaler section (schema v8 ``scale`` events,
        # resilience/autoscale.py): the control plane's decision stream.
        # Each event carries the POST-transition allocation; the re-mesh
        # that applied it is the ``remesh`` event whose detection step is
        # the decision's iteration, which is where the transition's cost
        # (seconds) lives.
        _section("scale (autoscaler)")
        by_detect = {e.get("detected_at"): e for e in remeshes}
        for e in scales:
            applied = by_detect.get(e.get("it"))
            print(f"  it {e.get('it', '?'):>6}: "
                  f"{e.get('direction', '?'):14s} -> "
                  f"train {e.get('train_world', '?')} / "
                  f"serve {e.get('serve_engines', '?')} engines  "
                  f"({e.get('signal', '?')} {_fmt_num(e.get('value'))})"
                  + (f"  applied in {applied['seconds']:.3f}s"
                     if applied and isinstance(applied.get("seconds"),
                                               (int, float)) else ""))
        allocs = [f"{e.get('train_world', '?')}t/"
                  f"{e.get('serve_engines', '?')}s" for e in scales]
        print(f"  allocation over time: ... -> " + " -> ".join(allocs))

    if rounds:
        _section("fl rounds")
        accs = [r["test_accuracy"] for r in rounds
                if r.get("test_accuracy") is not None]
        walls = [r["wall_s"] for r in rounds if r.get("wall_s") is not None]
        print(f"rounds: {len(rounds)}")
        if accs:
            print(f"test accuracy: {_fmt_num(accs[0])} -> "
                  f"{_fmt_num(accs[-1])}")
        if walls:
            print("round time: " + "  ".join(
                f"p{q:g}={percentile(walls, q):.3f}s" for q in (50, 95, 99)))

    if cohorts or tiers:
        # Fleet-scale FL section (schema v3 fl_cohort / fl_tier events,
        # fl/fleet.py): how the cohort-streaming rounds moved bytes
        # through the edge/server tiers. Runs without fleet events skip
        # this silently, same as the serving section.
        _section("fl fleet (cohort streaming)")
        if cohorts:
            clients = [e["clients"] for e in cohorts
                       if isinstance(e.get("clients"), int)]
            print(f"cohort dispatches: {len(cohorts)}"
                  + (f"   clients/cohort p50="
                     f"{percentile(clients, 50):.0f} "
                     f"max={max(clients)}" if clients else ""))
        by_tier = {}
        for e in tiers:
            agg = by_tier.setdefault(e.get("tier", "?"),
                                     {"rounds": 0, "bytes": 0, "inputs": 0})
            agg["rounds"] += 1
            if isinstance(e.get("payload_bytes"), (int, float)):
                agg["bytes"] += e["payload_bytes"]
            agg["inputs"] += (e.get("clients") or e.get("inputs") or 0)
        for tier, agg in by_tier.items():
            print(f"  tier {tier:8s} rounds {agg['rounds']:<4d} "
                  f"inputs {agg['inputs']:<8d} "
                  f"payload {_fmt_bytes(agg['bytes'])}")

    metrics = (run_end or {}).get("metrics") or {}
    phase = {k: v for k, v in metrics.get("gauges", {}).items()
             if k.startswith("phase/") and k.endswith("_s")}
    if phase:
        _section("phase breakdown")
        total = sum(phase.values())
        for k, v in sorted(phase.items(), key=lambda kv: -kv[1]):
            name = k[len("phase/"):-len("_s")]
            pct = 100 * v / total if total else 0
            print(f"  {name:12s} {v:10.3f}s  {pct:5.1f}%")

    counters = {k: v for k, v in metrics.get("counters", {}).items()
                if k.startswith("faults/") and v}
    if faults or counters:
        _section("faults")
        for e in faults:
            print(f"  it {e.get('it', e.get('round', '?')):>6}: "
                  f"{e['counters']}")
        if counters:
            print(f"  totals: "
                  f"{ {k[len('faults/'):]: int(v) for k, v in counters.items()} }")
    elif run_end:
        print("\nfaults: none recorded")

    hists = metrics.get("histograms", {})
    if hists:
        _section("metrics (run_end snapshot)")
        for name, h in sorted(hists.items()):
            print(f"  {name:16s} n={h['count']:<6d} mean={h['mean']:.4g}  "
                  f"p50={h['p50']:.4g}  p95={h['p95']:.4g}  "
                  f"p99={h['p99']:.4g}  max={h['max']:.4g}")

    if run_end:
        _section("run end")
        for k in ("steps", "preempted", "remeshes", "tokens_per_sec",
                  "post_remesh_tokens_per_sec", "wall_s",
                  "final_accuracy"):
            if run_end.get(k) is not None:
                print(f"{k}: {run_end[k]}")
    else:
        print("\nNO run_end event — the run is live, was killed, or "
              "crashed mid-stream.")

    if heartbeat_path:
        hb = read_heartbeat(heartbeat_path)
        _section("heartbeat")
        if hb is None:
            print("no readable heartbeat")
        else:
            age = time.time() - hb.get("time", 0)
            print(f"pid {hb.get('pid')}  step {hb.get('step')}  "
                  f"seq {hb.get('seq')}  phase {hb.get('phase', '-')}  "
                  f"age {age:.1f}s")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="telemetry run dir (containing "
                                 "events.jsonl) or an events.jsonl path")
    ap.add_argument("--strict", action="store_true",
                    help="fail on malformed/invalid events instead of "
                         "skipping them")
    a = ap.parse_args(argv)

    if os.path.isdir(a.path):
        events_path = os.path.join(a.path, "events.jsonl")
        heartbeat_path = os.path.join(a.path, "heartbeat.json")
        if not os.path.exists(heartbeat_path):
            heartbeat_path = None
    else:
        events_path = a.path
        heartbeat_path = None
    if not os.path.exists(events_path):
        print(f"no event stream at {events_path}", file=sys.stderr)
        return 2
    events = read_events(events_path, strict=a.strict)
    if not events:
        print(f"{events_path}: empty event stream", file=sys.stderr)
        return 2
    # The heartbeat file belongs to the LATEST writer — attaching it to
    # every run in a multi-run stream (relaunches share the dir) would
    # make dead runs look alive.
    runs = list(iter_runs(events))
    for i, run in enumerate(runs):
        report_run(run, heartbeat_path if i == len(runs) - 1 else None)
    return 0


if __name__ == "__main__":
    sys.exit(main())
