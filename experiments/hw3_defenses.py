"""hw3 robust-aggregation grid under 20% gradient reversion.

Reproduces the reference's homework-3 experiment battery
(lab/hw03/Tea_Pula_03.ipynb):
- cells 3-9:  {none, krum, multi-krum, majority-sign} × {IID, non-IID}
  10-round accuracy curves under 20% AttackerGradientReversion, at the hw3
  setting lr=0.02, B=200, C=0.2, E=2, seed=42 (N=100 ⇒ 20 clients/round,
  4 malicious per round in expectation).
- cell 18: Bulyan over k ∈ {10, 14, 18} × β ∈ {0.2, 0.4, 0.6}.
- cell 29: SparseFed over top-k ∈ {20, 40, 60, 80}%.

Per-round curves land in ``experiments/results/hw3_defenses.csv`` /
``hw3_bulyan.csv`` / ``hw3_sparsefed.csv`` (the notebook's cell-11 CSV-dump
idiom); render with ``python -m experiments.plots``.
"""

from __future__ import annotations

import argparse
from functools import partial
from typing import Dict, Optional

import jax

from ddl25spring_tpu.config import FLConfig
from ddl25spring_tpu.fl import FedAvgGradServer
from ddl25spring_tpu.fl import attacks as atk
from ddl25spring_tpu.fl import defenses as dfn
from ddl25spring_tpu.models import mnist_cnn

from . import common

# hw3 setting (Tea_Pula_03.ipynb cell 3): the attack analysis deliberately
# runs hotter than homework-1 defaults.
HW3 = dict(nr_clients=100, client_fraction=0.2, batch_size=200, epochs=2,
           lr=0.02, seed=42)
MALICIOUS_FRACTION = 0.2
# The reference's Bulyan sweep (cell 18) — one source of truth for both the
# full battery (main) and the resume path (complete_bulyan).
BULYAN_KS = (10, 14, 18)
BULYAN_BETAS = (0.2, 0.4, 0.6)


def _defense_hook(name: str, n_mal: int, **kw):
    """Map a defense name to the (deltas, weights) -> aggregate hook."""
    if name == "none":
        return None
    if name == "krum":
        return dfn.selection_defense(dfn.krum, n_malicious=n_mal)
    if name == "multi_krum":
        return dfn.selection_defense(dfn.multi_krum, n_malicious=n_mal,
                                     k=kw.get("k", 10))
    if name == "majority_sign":
        return dfn.coordinate_defense(dfn.majority_sign)
    if name == "median":
        return dfn.coordinate_defense(dfn.coordinate_median)
    if name == "trimmed_mean":
        return dfn.coordinate_defense(dfn.trimmed_mean,
                                      beta=kw.get("beta", 0.2))
    if name == "clipping":
        return dfn.coordinate_defense(dfn.norm_clipping)
    if name == "bulyan":
        return dfn.coordinate_defense(dfn.bulyan, n_malicious=n_mal,
                                      k=kw["k"], beta=kw["beta"])
    if name == "sparse_fed":
        return dfn.coordinate_defense(dfn.sparse_fed,
                                      topk_fraction=kw["topk_fraction"])
    raise ValueError(name)


def run_one(defense: str, iid: bool, sink, provenance: str, *, rounds: int,
            n_train: int, n_test: int, extra: Optional[dict] = None) -> float:
    extra = extra or {}
    cfg = FLConfig(rounds=rounds, iid=iid, **HW3)
    params, data, xt, yt = common.mnist_fl_setup(cfg, n_train=n_train,
                                                 n_test=n_test)
    mask = atk.injection_mask(cfg.nr_clients, MALICIOUS_FRACTION, cfg.seed)
    n_mal = int(MALICIOUS_FRACTION * cfg.clients_per_round)
    server = FedAvgGradServer(
        params, mnist_cnn.apply, data, xt, yt, cfg,
        adversary=(mask, atk.GradientReversion(scale=5.0)),
        defense=_defense_hook(defense, n_mal, **extra))
    result = server.run(cfg.rounds)
    df = result.as_df()
    df["data"] = provenance
    df["n_train"] = n_train
    df["n_test"] = n_test
    df["defense"] = defense
    df["iid"] = iid
    df["attack"] = "gradient_reversion_20pct"
    for k, v in extra.items():
        df[k] = v
    for row in df.to_dict(orient="records"):
        sink.write(row)
    return result.test_accuracy[-1]


def main(quick: bool = False, n_train: int = 60000, n_test: int = 10000
         ) -> Dict[str, float]:
    """See hw1_fl.main on n_train/n_test: the committed CPU run uses
    6000/2000 (run_all --cpu; synthetic MNIST; protocol knobs exact)."""
    provenance = common.mnist_provenance()
    if quick:
        n_train, n_test = 2000, 500
    rounds = 2 if quick else 10
    finals: Dict[str, float] = {}

    # --- the defense × split grid (cells 3-9) ---------------------------
    sink = common.sink("hw3_defenses.csv")
    for defense in ("none", "krum", "multi_krum", "majority_sign"):
        for iid in (True, False):
            acc = run_one(defense, iid, sink, provenance, rounds=rounds,
                          n_train=n_train, n_test=n_test)
            finals[f"{defense}/{'iid' if iid else 'noniid'}"] = acc
            print(f"{defense:13s} {'IID' if iid else 'non-IID':7s}: "
                  f"final acc {acc:.4f}")

    # --- Bulyan k × β (cell 18) -----------------------------------------
    sink_b = common.sink("hw3_bulyan.csv")
    ks = (10,) if quick else BULYAN_KS
    betas = (0.2,) if quick else BULYAN_BETAS
    for k in ks:
        for beta in betas:
            acc = run_one("bulyan", True, sink_b, provenance, rounds=rounds,
                          n_train=n_train, n_test=n_test,
                          extra={"k": k, "beta": beta})
            finals[f"bulyan/k{k}/b{beta}"] = acc
            print(f"bulyan k={k} beta={beta}: final acc {acc:.4f}")

    # --- SparseFed top-k% (cell 29) -------------------------------------
    sink_s = common.sink("hw3_sparsefed.csv")
    topks = (0.4,) if quick else (0.2, 0.4, 0.6, 0.8)
    for tk in topks:
        acc = run_one("sparse_fed", True, sink_s, provenance, rounds=rounds,
                      n_train=n_train, n_test=n_test,
                      extra={"topk_fraction": tk})
        finals[f"sparse_fed/{int(tk*100)}pct"] = acc
        print(f"sparse_fed top-{int(tk*100)}%: final acc {acc:.4f}")

    print(f"-> {sink.path}, {sink_b.path}, {sink_s.path} [{provenance}]")
    return finals


def complete_bulyan(n_train: int = 6000, n_test: int = 2000,
                    rounds: int = 10) -> Dict[str, float]:
    """Run only the Bulyan grid cells missing from the committed CSV.

    The full reference grid is k ∈ {10,14,18} × β ∈ {0.2,0.4,0.6}
    (Tea_Pula_03.ipynb cell 18); a wall-clock-limited run can leave the
    committed ``hw3_bulyan.csv`` partial. This appends the absent cells at
    the same sizes instead of re-running the whole battery.
    """
    import os

    import pandas as pd

    from ddl25spring_tpu.utils.tracing import ResultSink

    path = os.path.join(common.RESULTS_DIR, "hw3_bulyan.csv")
    have = set()
    if os.path.exists(path):
        df = pd.read_csv(path)
        # A cell counts as done only with its full per-round curve; cells a
        # wall-clock kill truncated mid-run are dropped and re-run whole.
        cells = df.assign(_k=df["k"].astype(int),
                          _b=df["beta"].astype(float).round(2))
        counts = cells.groupby(["_k", "_b"]).size()
        have = {kb for kb, c in counts.items() if c >= rounds}
        partial = {kb for kb, c in counts.items() if c < rounds}
        if partial:
            keep = ~cells.set_index(["_k", "_b"]).index.isin(partial)
            df[keep].to_csv(path, index=False)
            print(f"dropped partial cells {sorted(partial)}", flush=True)
        n_train = int(df["n_train"].iloc[0])  # match the committed run
        if "n_test" in df.columns and df["n_test"].notna().any():
            # header-widened rows predating the n_test column are blank
            n_test = int(df["n_test"].dropna().iloc[0])
    sink_b = ResultSink(path)  # append; common.sink() would truncate
    provenance = common.mnist_provenance()
    finals: Dict[str, float] = {}
    for k in BULYAN_KS:
        for beta in BULYAN_BETAS:
            if (k, round(beta, 2)) in have:
                continue
            acc = run_one("bulyan", True, sink_b, provenance, rounds=rounds,
                          n_train=n_train, n_test=n_test,
                          extra={"k": k, "beta": beta})
            finals[f"bulyan/k{k}/b{beta}"] = acc
            print(f"bulyan k={k} beta={beta}: final acc {acc:.4f}",
                  flush=True)
    return finals


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--complete-bulyan", action="store_true",
                    help="append only the missing Bulyan k×beta cells")
    ap.add_argument("--cpu", action="store_true")
    a = ap.parse_args()
    if a.cpu:
        jax.config.update("jax_platforms", "cpu")
    if a.complete_bulyan:
        complete_bulyan()
    else:
        main(quick=a.quick)
