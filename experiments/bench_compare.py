"""Perf-trajectory comparator over committed BENCH_r*.json headlines.

The repo commits one ``BENCH_rNN.json`` per growth round (the driver's
wrapper: ``{"parsed": {headline row}, "tail": <bench.py stdout>, ...}``)
plus ``BASELINE.json``; tier1.yml additionally produces a per-PR
``bench-headline.json`` (raw ``bench.py`` stdout in DDL25_BENCH_QUICK
mode). This tool — pure stdlib, no jax — reads any mix of those formats,
prints the trajectory per (metric, platform, variant) group, and exits
nonzero when the newest comparable row regresses more than
``--max-regression`` percent against the best committed row of the SAME
platform tag: CPU-fallback numbers must never be judged against a TPU
row (the committed history mixes both — see ROADMAP "Perf trajectory").
Rows are direction-aware: throughput-like metrics regress downward, while
``wire_bytes_*`` / ``payload_bytes_*`` rows (the comm-wire smoke's) are
lower-is-better and gate when the candidate RISES above the best (lowest)
committed row — see ``lower_is_better``.

``--warn-only`` (how tier1.yml runs it, over the reduced bench smoke)
prints the verdict but always exits 0: the QUICK-mode smoke is noisy by
design, so CI gets visibility without a flaky gate; the strict mode is
for hardware rounds.

Example:
    python -m experiments.bench_compare --candidate bench-headline.json \\
        --max-regression 20 --warn-only
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple


# Attainment-style FIELDS on headline rows, promoted to their own
# comparable rows. Higher is better for both (like every row here), but
# they are only meaningful same-platform — an MFU measured against the
# calibrated CPU baseline must NEVER gate against the TPU round-4 0.310
# — so a derived row REQUIRES an explicit platform tag: a row without
# one gets no derived entry rather than landing in a "None" bucket both
# platforms would share.
DERIVED_FIELDS = ("mfu", "attainment")

# Direction map. Most headline rows are throughput-like (higher is
# better) — that default covers ``tokens_per_dispatch`` (the serving
# bench's speculative-decode row: MORE tokens per target dispatch is the
# win, so a draft regression gates like a tok/s drop) — but the
# comm-wire smoke's byte rows regress UPWARD — more bytes is worse — and
# judging them higher-is-better would wave a wire-bytes regression
# through as an "improvement". A metric whose name starts with one of
# these prefixes is compared against the best (LOWEST) committed row and
# gates when the candidate rises above it by more than the budget.
# ``remesh_seconds`` / ``steps_replayed`` are the elasticity smokes'
# recovery-cost rows (elastic_smoke / autoscale_smoke): slower re-mesh or
# more re-trained steps is the regression. ``peak_`` covers the memory
# smoke's footprint rows (``peak_device_bytes_*`` / ``peak_rss_bytes_*``,
# schema v9): a run whose peak bytes grew is the memory regression the
# observability tentpole exists to catch. ``wire_bytes`` also pins the
# TP-fusion smoke's ``wire_bytes_model_per_train_step`` rows (ISSUE 18):
# the model-axis activation wire under the PSA modes must only ever
# trend DOWN vs the committed history, same as the data-axis ring rows.
# ``overlap_fraction`` (the comm-wire smoke's bucketed-backward row,
# ISSUE 19: the share of ring hops whose dispatch is
# dataflow-independent of the not-yet-materialized tail of the gradient)
# is deliberately NOT in this tuple — MORE overlap is the win, so it
# keeps the higher-is-better default and gates when the candidate's
# overlap window SHRINKS below the best committed row (pinned in
# tests/test_experiments.py).
LOWER_IS_BETTER_PREFIXES = ("wire_bytes", "payload_bytes",
                            "remesh_seconds", "steps_replayed", "peak_")


def lower_is_better(metric: str) -> bool:
    """True for metrics where a SMALLER value is the better one."""
    return str(metric).startswith(LOWER_IS_BETTER_PREFIXES)


def parse_rows(path: str) -> List[Dict[str, Any]]:
    """Headline rows from one file, tolerating all three shapes: the
    driver wrapper (``parsed``, plus any JSON lines in ``tail``), raw
    bench.py stdout (human lines interleaved with JSON rows), or a bare
    row object. A row is any JSON object with ``metric`` and a numeric
    ``value``. Rows carrying a numeric ``mfu``/``attainment`` field AND a
    platform tag additionally yield a derived row per field (see
    ``DERIVED_FIELDS``)."""
    with open(path) as f:
        text = f.read()
    rows: List[Dict[str, Any]] = []

    def _add(obj):
        if (isinstance(obj, dict) and "metric" in obj
                and isinstance(obj.get("value"), (int, float))):
            rows.append(obj)
            for fld in DERIVED_FIELDS:
                v = obj.get(fld)
                if (isinstance(v, (int, float)) and v > 0
                        and obj.get("platform") is not None):
                    rows.append({"metric": fld, "value": float(v),
                                 "platform": obj["platform"],
                                 "variant": obj.get("variant")})

    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict):
        _add(doc)
        _add(doc.get("parsed"))
        # Smoke artifacts (e.g. comm-wire.json) carry a "rows" list of
        # row objects — the comm-wire smoke's wire-byte rows enter the
        # trajectory through here.
        rows_field = doc.get("rows")
        if isinstance(rows_field, list):
            for obj in rows_field:
                _add(obj)
        text = doc.get("tail") or ""
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                _add(json.loads(line))
            except ValueError:
                pass
    # De-dup (the wrapper's parsed row usually re-appears in its tail).
    seen, out = set(), []
    for r in rows:
        key = json.dumps(r, sort_keys=True)
        if key not in seen:
            seen.add(key)
            out.append(r)
    return out


def _fmt_val(v: float) -> str:
    """Throughput rows are 6-digit integers; derived mfu/attainment rows
    live in [0, 1] — one format hides the latter as 0.0."""
    return f"{v:>14,.1f}" if abs(v) >= 10 else f"{v:>14.4f}"


def row_key(row: Dict[str, Any]) -> Tuple[str, str, str]:
    """Comparability key: rows measured on different platforms (or bench
    variants) are different experiments, not a trajectory."""
    return (str(row.get("metric")), str(row.get("platform")),
            str(row.get("variant")))


def compare(files: List[str], candidate: Optional[str],
            max_regression_pct: float) -> Tuple[List[str], List[str]]:
    """Returns (report lines, regression messages). Regressions are
    judged candidate-vs-best-committed per key; with no candidate, the
    newest committed file is judged against the best of the older ones."""
    history: Dict[Tuple[str, str, str], List[Tuple[str, float]]] = {}
    ordered = sorted(files)
    for path in ordered:
        for row in parse_rows(path):
            history.setdefault(row_key(row), []).append(
                (os.path.basename(path), float(row["value"])))
    cand_rows: Dict[Tuple[str, str, str], Tuple[str, float]] = {}
    if candidate:
        for row in parse_rows(candidate):
            cand_rows[row_key(row)] = (os.path.basename(candidate),
                                       float(row["value"]))

    lines, regressions = [], []
    keys = sorted(set(history) | set(cand_rows))
    for key in keys:
        metric, platform, variant = key
        lines.append(f"{metric} [{platform} / {variant}]")
        traj = history.get(key, [])
        prev = None
        for name, value in traj:
            delta = ("" if prev in (None, 0)
                     else f"  ({100 * (value - prev) / prev:+.1f}%)")
            lines.append(f"  {name:24s} {_fmt_val(value)}{delta}")
            prev = value
        judged = cand_rows.get(key)
        baseline_pool = traj
        if judged is None and len(traj) >= 2:
            judged, baseline_pool = traj[-1], traj[:-1]
        if judged is not None and baseline_pool:
            lower = lower_is_better(metric)
            best_name, best = (min if lower else max)(
                baseline_pool, key=lambda nv: nv[1])
            name, value = judged
            delta_pct = 100 * (value - best) / best if best else 0.0
            # "How much worse", direction-aware: for lower-is-better rows
            # a POSITIVE delta (more bytes) is the regression.
            worse_pct = delta_pct if lower else -delta_pct
            verdict = "ok"
            if worse_pct > max_regression_pct:
                verdict = "REGRESSION"
                regressions.append(
                    f"{metric} [{platform} / {variant}]: {name} = "
                    f"{value:,.1f} is {worse_pct:.1f}% "
                    f"{'above' if lower else 'below'} best "
                    f"committed {best:,.1f} ({best_name}) — budget "
                    f"{max_regression_pct:.0f}%")
            lines.append(f"  {name:24s} {_fmt_val(value)}  "
                         f"({delta_pct:+.1f}% vs best {best_name}) "
                         f"[{verdict}]")
        elif judged is not None:
            name, value = judged
            lines.append(f"  {name:24s} {_fmt_val(value)}  "
                         "(no comparable committed row — new "
                         "platform/variant, nothing to judge against)")
    return lines, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("files", nargs="*",
                    help="committed bench JSONs (default: BENCH_r*.json "
                         "in the repo root / cwd)")
    ap.add_argument("--candidate", default=None,
                    help="the row under judgment (e.g. the CI smoke's "
                         "bench-headline.json)")
    ap.add_argument("--max-regression", type=float, default=20.0,
                    help="tolerated drop (percent) vs the best committed "
                         "same-platform row")
    ap.add_argument("--warn-only", action="store_true",
                    help="print the verdict but always exit 0 (CI smoke "
                         "mode: QUICK-bench noise must not gate merges)")
    a = ap.parse_args(argv)

    files = a.files or sorted(glob.glob("BENCH_r*.json"))
    if not files and not a.candidate:
        print("no BENCH_r*.json found and no --candidate given",
              file=sys.stderr)
        return 2
    lines, regressions = compare(files, a.candidate, a.max_regression)
    print("\n".join(lines) if lines else "no comparable rows found")
    if regressions:
        for r in regressions:
            print(f"REGRESSION: {r}", file=sys.stderr)
        return 0 if a.warn_only else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
