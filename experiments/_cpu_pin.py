"""Pin the CPU platform with virtual devices — shared __main__ boilerplate.

Must be called BEFORE the first jax device use (this module itself imports
jax only inside the function, after setting XLA_FLAGS, so importing it is
side-effect free). Env vars alone do not work in this container: its
sitecustomize imports jax at interpreter start, so the platform pin has to
go through jax.config.
"""

from __future__ import annotations

import os


def pin_cpu_virtual(n_devices: int = 8) -> None:
    os.environ.setdefault("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += \
            f" --xla_force_host_platform_device_count={n_devices}"
    import jax

    jax.config.update("jax_platforms", "cpu")
