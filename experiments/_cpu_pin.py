"""Pin the CPU platform with virtual devices — shared __main__ boilerplate.

Must be called BEFORE the first jax device use (this module itself imports
jax only inside the function, after setting XLA_FLAGS, so importing it is
side-effect free). Env vars alone do not work in this container: its
sitecustomize imports jax at interpreter start, so the platform pin has to
go through jax.config.
"""

from __future__ import annotations

import os

# Virtual multi-device CPU hardening, shared by the experiments runner,
# tests/conftest.py, and __graft_entry__'s dryrun child. Two distinct
# failure modes on an oversubscribed (1-core) host, both observed on the
# 6-device DP×PP run:
#
# 1. STARVATION: a device busy computing reaches its collective long after
#    its peers. XLA-CPU's default 40 s rendezvous *termination* timeout
#    (rendezvous.cc) assumes a core per participant and aborts the process;
#    raise it and the stuck-warning window (the flags below).
# 2. DEADLOCK: with async dispatch, consecutive train steps overlap in
#    flight, and their cross-module collectives can interleave into a
#    rendezvous that never completes (wedged at a ppermute with 5/6
#    arrivals at both 40 s and 1200 s). No timeout fixes this one —
#    dispatch must be serialized (`jax_cpu_enable_async_dispatch=False`,
#    applied in pin_cpu_virtual / conftest / the dryrun child).
# 3. POOL STARVATION (thunk runtime): even with 1+2 applied, the thunk
#    executor runs collective thunks on a shared Eigen pool whose size on
#    this 1-core host (~4 workers) is below a 6-participant topology. A
#    blocking rendezvous parks a worker, so once every worker holds a
#    waiting collective the remaining replicas can never arrive: the
#    6-device DP×PP run wedged within ~100 iters at a cross-module
#    ppermute with 4/6 arrivals (exactly the pool size), 0% CPU. The
#    3-participant pp3 run fits the pool and never wedges. Fix: the
#    legacy (non-thunk) runtime executes each replica on its own thread,
#    so blocked collectives time-share instead of exhausting a pool —
#    ``legacy_collectives=True`` below; measured 50-iter dp2_pp3 smoke
#    runs clean at ~106 tok/s where the thunk runtime deadlocked.
COLLECTIVE_TIMEOUT_FLAGS = (
    " --xla_cpu_collective_timeout_seconds=1200"
    " --xla_cpu_collective_call_terminate_timeout_seconds=1200")
LEGACY_RUNTIME_FLAG = " --xla_cpu_use_thunk_runtime=false"


def pin_cpu_virtual(n_devices: int = 8,
                    legacy_collectives: bool = False) -> None:
    os.environ.setdefault("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += \
            f" --xla_force_host_platform_device_count={n_devices}"
    if "collective" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += COLLECTIVE_TIMEOUT_FLAGS
    if legacy_collectives and "thunk_runtime" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += LEGACY_RUNTIME_FLAG  # mode 3 above
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)  # mode 2 above
