"""Pin the CPU platform with virtual devices — shared __main__ boilerplate.

Must be called BEFORE the first jax device use (this module itself imports
jax only inside the function, after setting XLA_FLAGS, so importing it is
side-effect free). Env vars alone do not work in this container: its
sitecustomize imports jax at interpreter start, so the platform pin has to
go through jax.config.
"""

from __future__ import annotations

import os

# Virtual multi-device CPU hardening, shared by the experiments runner,
# tests/conftest.py, and __graft_entry__'s dryrun child. Two distinct
# failure modes on an oversubscribed (1-core) host, both observed on the
# 6-device DP×PP run:
#
# 1. STARVATION: a device busy computing reaches its collective long after
#    its peers. XLA-CPU's default 40 s rendezvous *termination* timeout
#    (rendezvous.cc) assumes a core per participant and aborts the process;
#    raise it and the stuck-warning window (the flags below).
# 2. DEADLOCK: with async dispatch, consecutive train steps overlap in
#    flight, and their cross-module collectives can interleave into a
#    rendezvous that never completes (wedged at a ppermute with 5/6
#    arrivals at both 40 s and 1200 s). No timeout fixes this one —
#    dispatch must be serialized (`jax_cpu_enable_async_dispatch=False`,
#    applied in pin_cpu_virtual / conftest / the dryrun child).
# 3. RESIDUAL STOCHASTIC WEDGE: even with 1+2 applied, the 6-participant
#    DP×PP topology still wedges within ~100 iterations at a cross-module
#    ppermute with 4-5/6 arrivals and 0% CPU — the thunk executor runs
#    collective thunks on a shared worker pool that a blocking rendezvous
#    can park, and on this host the pool is smaller than 6. (The
#    3-participant pp3 topology fits and never wedges; a 50-iter
#    6-participant smoke can pass by luck.) There is NO runtime-level fix
#    in this XLA build — the legacy non-thunk runtime is gone
#    (``--xla_cpu_use_thunk_runtime`` warns "no longer supported" and is a
#    no-op). Long runs on big virtual topologies must instead be made
#    kill-safe: orbax checkpoint/resume + incremental CSV sinking +
#    ``experiments/watchdog.py`` (kill on progress stall, relaunch,
#    resume).
COLLECTIVE_TIMEOUT_FLAGS = (
    " --xla_cpu_collective_timeout_seconds=1200"
    " --xla_cpu_collective_call_terminate_timeout_seconds=1200")


def collective_timeout_flags() -> str:
    """COLLECTIVE_TIMEOUT_FLAGS iff this jaxlib's XLA accepts them, else "".

    XLA *aborts the process* (parse_flags_from_env.cc) on any unknown flag in
    XLA_FLAGS, at the first backend creation — so on a jaxlib build where
    these flags were renamed/removed, passing them unconditionally kills
    every test and experiment at startup instead of hardening them. Probe
    once per jaxlib version in a subprocess (the only way to observe an
    abort-on-parse) and cache the verdict in the temp dir.
    """
    import subprocess
    import sys
    import tempfile

    try:
        import jaxlib
        ver = jaxlib.__version__
    except Exception:
        ver = "unknown"
    cache = os.path.join(tempfile.gettempdir(),
                         f"ddl25_xla_flagprobe_{ver}")
    try:
        with open(cache) as f:
            return COLLECTIVE_TIMEOUT_FLAGS if f.read().strip() == "1" else ""
    except OSError:
        pass
    env = dict(os.environ,
               XLA_FLAGS=COLLECTIVE_TIMEOUT_FLAGS.strip(),
               JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms','cpu'); "
             "jax.devices()"],
            env=env, capture_output=True, timeout=120)
        ok = proc.returncode == 0
    except Exception:
        # Transient probe failure (timeout under load, fork pressure): skip
        # the flags for THIS run but do not cache the verdict — only a
        # definitive rejection proves the jaxlib refuses them.
        return ""
    if not ok and b"flag" not in (proc.stderr + proc.stdout).lower():
        # Nonzero exit that never mentions a flag (OOM kill, MemoryError
        # during jax import, half-installed package) is transient, not a
        # rejection — XLA's parse_flags abort always names the unknown flag.
        # Don't poison the per-jaxlib cache with it.
        return ""
    try:
        with open(cache, "w") as f:
            f.write("1" if ok else "0")
    except OSError:
        pass
    return COLLECTIVE_TIMEOUT_FLAGS if ok else ""


def pin_cpu_virtual(n_devices: int = 8) -> None:
    os.environ.setdefault("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += \
            f" --xla_force_host_platform_device_count={n_devices}"
    if "collective" not in os.environ["XLA_FLAGS"]:
        os.environ["XLA_FLAGS"] += collective_timeout_flags()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)  # mode 2 above
