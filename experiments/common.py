"""Shared experiment plumbing: data setup, provenance labels, result sinks."""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

import jax
import numpy as np

from ddl25spring_tpu.config import FLConfig
from ddl25spring_tpu.data import mnist, tabular
from ddl25spring_tpu.fl import federate
from ddl25spring_tpu.fl.federated_data import FederatedDataset
from ddl25spring_tpu.models import mnist_cnn
from ddl25spring_tpu.utils.tracing import ResultSink

RESULTS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")


def sink(name: str) -> ResultSink:
    os.makedirs(RESULTS_DIR, exist_ok=True)
    path = os.path.join(RESULTS_DIR, name)
    if os.path.exists(path):
        os.remove(path)  # each runner owns its file; re-runs replace it
    return ResultSink(path)


def dedupe_csv(path: str, key_cols: List[str]) -> int:
    """Drop exact-duplicate rows by ``key_cols`` (keep first), preserving
    order. Watchdogged resume runs re-emit identical rows for the overlap
    between the last checkpoint and the kill point; this cleans them.
    Returns the number of rows removed."""
    import csv

    with open(path) as f:
        rows = list(csv.DictReader(f))
    seen, kept = set(), []
    for r in rows:
        k = tuple(r.get(c) for c in key_cols)
        if k in seen:
            continue
        seen.add(k)
        kept.append(r)
    if len(kept) < len(rows):
        # Atomic: this runs in the watchdog's kill-prone environment — a
        # truncating in-place rewrite could lose the whole CSV.
        from ddl25spring_tpu.utils.tracing import atomic_write_csv
        atomic_write_csv(path, list(rows[0].keys()), kept)
    return len(rows) - len(kept)


def mnist_provenance() -> str:
    """Whether load_mnist() will return real IDX files or the synthetic
    fallback (mirrors its search order)."""
    for d in (os.environ.get("DDL_MNIST_DIR"), "data/mnist"):
        if d and os.path.isdir(d):
            return "mnist-real"
    return "mnist-synthetic"


def heart_provenance() -> str:
    for c in (os.environ.get("DDL_HEART_CSV"), *tabular._SEARCH):
        if c and os.path.exists(c):
            return "heart-real"
    return "heart-synthetic"


def tinystories_provenance() -> str:
    from ddl25spring_tpu.data import tokens
    for c in (os.environ.get("DDL_TINYSTORIES"), *tokens._DEFAULT_CORPUS):
        if c and os.path.exists(c):
            return "tinystories-real"
    return "tinystories-synthetic"


def mnist_arrays(n_train: int = 60000, n_test: int = 10000):
    """(x, y, test_x, test_y) normalized with the reference's constants."""
    x_raw, y, xt_raw, yt = mnist.load_mnist(n_train=n_train, n_test=n_test,
                                            seed=0)
    return (mnist.normalize(x_raw), y.astype(np.int32),
            mnist.normalize(xt_raw), yt.astype(np.int32))


def mnist_fl_setup(cfg: FLConfig, *, n_train: int = 60000, n_test: int = 10000
                   ) -> Tuple[dict, FederatedDataset, np.ndarray, np.ndarray]:
    """(init_params, federated train data, test_x, test_y) at the reference's
    MNIST setup: normalize with (0.1307, 0.3081), split IID or the
    sort-into-2N-shards non-IID scheme, stack on the client axis."""
    x, y, xt, yt = mnist_arrays(n_train, n_test)
    subsets = mnist.split(y, cfg.nr_clients, iid=cfg.iid, seed=cfg.seed)
    data = federate(x, y, subsets)
    params = mnist_cnn.init(jax.random.key(0))
    return params, data, xt, yt


def heart_vfl_setup(nr_clients: int, partitioner: str = "base", *,
                    seed: int = 0, min_features: int = 2,
                    dedup: bool = False):
    """(xs_train, y_train, xs_test, y_test, names) vertically partitioned.

    ``partitioner``: "base" (the tutorial's 4-way fixed split becomes an even
    deal over base features), "even", or "min2" — hw2's two policies.
    ``dedup``: duplicate-aware split (see tabular.train_test_split) — the
    honest-generalization variant alongside the reference's leaky protocol.
    """
    X, y = tabular.load_heart()
    feats, names = tabular.preprocess(X)
    x_tr, y_tr, x_te, y_te = tabular.train_test_split(feats, y, seed=seed,
                                                      dedup=dedup)
    if partitioner == "even":
        parts = tabular.split_features_evenly(names, nr_clients, seed=seed)
    elif partitioner == "min2":
        parts = tabular.split_features_with_minimum(
            names, nr_clients, min_features=min_features, seed=seed)
    else:
        parts = tabular.split_features_evenly(names, nr_clients)
    xs_tr = [x_tr[:, p] for p in parts]
    xs_te = [x_te[:, p] for p in parts]
    return xs_tr, y_tr, xs_te, y_te, names
