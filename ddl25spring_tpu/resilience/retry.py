"""Retry with exponential backoff and deterministic jitter.

The framework's one retry implementation, applied where IO meets the
kill-prone world: orbax checkpoint save/restore (checkpoint.py), the native
tokenstream build/dlopen (data/native.py), and anything experiments want to
harden. Deterministic by construction — the jitter stream is seeded, so a
test (or a bit-reproducible run) sees the same delay schedule every time.

Delays follow ``base * 2**attempt``, capped at ``max_delay``, each scaled by
a jitter factor drawn uniformly from ``[1 - jitter, 1 + jitter]``. Sleeping
is injectable (``sleep=``) so tests assert the schedule without waiting.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Iterable, List, Optional, Tuple, Type

import numpy as np


def backoff_schedule(attempts: int, *, base: float = 0.1,
                     max_delay: float = 30.0, jitter: float = 0.25,
                     seed: int = 0) -> List[float]:
    """The deterministic delay sequence ``retry_call`` sleeps between tries:
    ``min(base·2^i, max_delay) · U[1-jitter, 1+jitter]`` with a seeded RNG.
    Exposed for tests and for callers that drive their own loops
    (experiments/watchdog.py)."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(attempts):
        delay = min(base * (2.0 ** i), max_delay)
        out.append(delay * float(rng.uniform(1.0 - jitter, 1.0 + jitter)))
    return out


def retry_call(fn: Callable, *args,
               attempts: int = 3,
               base: float = 0.1,
               max_delay: float = 30.0,
               jitter: float = 0.25,
               seed: int = 0,
               retry_on: Tuple[Type[BaseException], ...] = (Exception,),
               on_retry: Optional[Callable[[int, BaseException], None]] = None,
               sleep: Callable[[float], None] = time.sleep,
               **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying up to ``attempts`` total tries
    on ``retry_on`` exceptions with exponential backoff + seeded jitter.

    ``on_retry(attempt_idx, exc)`` fires before each sleep — the hook the
    callers use to count retries into ResilienceStats. The final failure
    re-raises the last exception unchanged. KeyboardInterrupt/SystemExit are
    never swallowed (they are not Exception subclasses).
    """
    attempts = max(1, attempts)
    delays = backoff_schedule(attempts - 1, base=base, max_delay=max_delay,
                              jitter=jitter, seed=seed)
    last: Optional[BaseException] = None
    for i in range(attempts):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            last = e
            if i == attempts - 1:
                raise
            if on_retry is not None:
                on_retry(i, e)
            sleep(delays[i])
    raise last  # unreachable; keeps type checkers honest


def with_retry(attempts: int = 3, *, base: float = 0.1,
               max_delay: float = 30.0, jitter: float = 0.25, seed: int = 0,
               retry_on: Tuple[Type[BaseException], ...] = (Exception,),
               on_retry: Optional[Callable[[int, BaseException], None]] = None,
               sleep: Callable[[float], None] = time.sleep) -> Callable:
    """Decorator form of ``retry_call`` with the same semantics."""

    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return retry_call(fn, *args, attempts=attempts, base=base,
                              max_delay=max_delay, jitter=jitter, seed=seed,
                              retry_on=retry_on, on_retry=on_retry,
                              sleep=sleep, **kwargs)
        return wrapped
    return deco
