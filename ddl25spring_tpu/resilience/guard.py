"""StepGuard: self-healing wrapper around any jitted train step.

``step_fn(state, batch) -> (state, loss)`` in, same signature out, plus:

- **all-finite check** on the loss and the updated parameters (the image of
  the gradients through the optimizer — a NaN/Inf gradient poisons every
  coordinate any standard update rule touches);
- **skip-and-count**: a bad step is discarded — the returned state is
  numerically identical to the pre-step state — and ``stats.skipped_steps``
  increments, so the fault is visible without being fatal;
- **EMA update-norm anomaly detector**: a step whose parameter-delta norm
  exceeds ``anomaly_factor`` × the running EMA (after ``ema_warmup`` good
  steps) is treated as a spike (exploding gradient, corrupted allreduce)
  and skipped even though it is finite;
- **rollback**: after ``max_consecutive_bad`` consecutive bad steps, restore
  the newest valid checkpoint (via ``Checkpointer.restore``'s
  corrupt-step fallback) instead of skipping forever. Rollback restores
  *weights only*; the caller's loop (and its data stream) continues forward,
  so the faulted window's batches are consumed-not-learned — skip-and-count
  semantics extended to a window, keeping checkpoint step indices equal to
  stream positions (what deterministic resume requires; see
  train/llm.py:_run_loop).

Fault-free transparency: on a good step the guard returns ``step_fn``'s
outputs untouched, so a guarded run is bit-identical to an unguarded one
(asserted in tests/test_resilience.py). The cost is one defensive device
copy of the state per step — required because every step factory in
parallel/ donates its input buffers (``donate_argnums=(0,)``), so the
pre-step state would otherwise be unreadable for skip/rollback — plus one
host sync for the finiteness verdict. Both are measured, not guessed:
``measure_overhead`` reports the fault-free guard tax, and bench.py carries
it in the headline JSON.

For a sync-free in-step alternative (skip only, no EMA/rollback), see
``parallel/dp.py``'s ``guard_nonfinite`` — the post-allreduce finiteness
guard fused into the step itself (the zero1 variant adds a 4-byte psum so
every replica agrees on the verdict before applying its slice update).

Chunked stepping (train/llm.py ``steps_per_dispatch`` > 1): the guard
wraps the fused K-step driver unchanged — ``loss`` is then the scan's [K]
per-step vector and the verdict/skip/rollback granularity is one DISPATCH.
A bad dispatch skips (consumes-not-learns) all K of its steps, which is
why ``stats.skipped_steps`` counts ``loss.size`` train steps per skip
while ``anomalies``/``rollbacks`` stay per-event; the EMA detector learns
chunk-level update norms, consistent within a run because the chunk size
is fixed. Stream-position step indexing is untouched, so resume stays
deterministic.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ..metrics import ResilienceStats


def _tree_copy(tree):
    """Defensive device copy — the donation shield."""
    return jax.tree.map(
        lambda x: jnp.array(x, copy=True) if isinstance(x, jax.Array) else x,
        tree)


@jax.jit
def _verdict(old_params, new_params, loss):
    """(all_finite, update_l2_norm) in one fused program."""
    finite = jnp.isfinite(loss).all()
    sq = jnp.zeros((), jnp.float32)
    for o, n in zip(jax.tree.leaves(old_params), jax.tree.leaves(new_params)):
        d = (n - o).astype(jnp.float32)
        finite &= jnp.all(jnp.isfinite(n))
        sq += jnp.sum(d * d)
    return finite, jnp.sqrt(sq)


class StepGuard:
    """Wraps a train step with skip / anomaly / rollback self-healing.

    Parameters
    ----------
    step_fn: the jitted step, ``(state, batch) -> (state, loss)``. ``state``
        must expose ``.params`` (every TrainState in parallel/ does).
    ckpt: optional ``checkpoint.Checkpointer`` — enables rollback to the
        newest valid on-disk step after ``max_consecutive_bad`` consecutive
        bad steps. Without it the guard skips indefinitely.
    stats: a ``metrics.ResilienceStats`` to count into (one is created if
        omitted; read it back via ``guard.stats``).
    max_consecutive_bad: K — consecutive bad steps before rollback.
    ema_decay / anomaly_factor / ema_warmup: update-norm anomaly detector.
        The EMA only learns from good steps and only fires after
        ``ema_warmup`` of them; ``anomaly_factor <= 0`` disables it.
    """

    def __init__(self, step_fn: Callable, *,
                 ckpt=None,
                 stats: Optional[ResilienceStats] = None,
                 max_consecutive_bad: int = 3,
                 ema_decay: float = 0.98,
                 anomaly_factor: float = 10.0,
                 ema_warmup: int = 20):
        self._step_fn = step_fn
        self._ckpt = ckpt
        self.stats = stats if stats is not None else ResilienceStats()
        self.max_consecutive_bad = max_consecutive_bad
        self.ema_decay = ema_decay
        self.anomaly_factor = anomaly_factor
        self.ema_warmup = ema_warmup
        self._ema: Optional[float] = None
        self._good_steps = 0
        self._consecutive_bad = 0
        self._last_trip: Optional[dict] = None

    def pop_trip(self) -> Optional[dict]:
        """Attribution of the most recent bad step, then clears it: which
        leaf paths of the REJECTED state carried NaN/Inf, whether the loss
        was non-finite, the update norm vs the EMA. The training loop
        attaches this to the ``fault`` event it emits, which is what lets
        a flight-recorder bundle NAME the faulted leaf instead of
        reporting "nonfinite somewhere"."""
        trip, self._last_trip = self._last_trip, None
        return trip

    def __call__(self, state, batch) -> Tuple[Any, jnp.ndarray]:
        old = _tree_copy(state)          # survives the step's donation
        new_state, out = self._step_fn(state, batch)
        # Instrumented steps (telemetry/introspect.py) return
        # (loss, NumericsSummary); the guard verdicts on the loss and
        # passes the pair through untouched either way.
        loss = out[0] if isinstance(out, tuple) else out
        finite, upd_norm = _verdict(old.params, new_state.params, loss)
        ok = bool(finite)
        anomalous = False
        if (ok and self.anomaly_factor > 0 and self._ema is not None
                and self._good_steps >= self.ema_warmup):
            anomalous = float(upd_norm) > self.anomaly_factor * self._ema
        if ok and not anomalous:
            u = float(upd_norm)
            self._ema = (u if self._ema is None
                         else self.ema_decay * self._ema
                         + (1.0 - self.ema_decay) * u)
            self._good_steps += 1
            self._consecutive_bad = 0
            return new_state, out
        # Bad step: count, skip (numerically a no-op), maybe roll back.
        # A chunked dispatch (vector loss) skips loss.size train steps.
        if anomalous:
            self.stats.anomalies += 1
        else:
            self.stats.skipped_steps += int(getattr(loss, "size", 1) or 1)
        # Attribution on the fault path only (it syncs the rejected
        # params): name WHICH leaves went non-finite before the poisoned
        # state is dropped — after the skip the only copy is gone.
        try:
            from ..telemetry.introspect import nonfinite_leaves
            import numpy as np
            self._last_trip = {
                "anomalous": anomalous,
                "loss_nonfinite": not bool(
                    np.isfinite(np.asarray(loss)).all()),
                "update_norm": float(upd_norm),
                "nonfinite_params": nonfinite_leaves(new_state.params),
            }
        except Exception:
            self._last_trip = None
        self._consecutive_bad += 1
        if (self._ckpt is not None
                and self._consecutive_bad >= self.max_consecutive_bad):
            try:
                restored = self._ckpt.restore(old)
            except FileNotFoundError:
                return old, out           # nothing on disk yet; keep skipping
            self.stats.rollbacks += 1
            self._consecutive_bad = 0
            return restored, out
        return old, out


def measure_overhead(make_state_and_step, batch, *, steps: int = 20,
                     warmup: int = 3) -> Tuple[float, ResilienceStats]:
    """Fault-free guard tax: time ``steps`` raw steps vs ``steps`` guarded
    steps of the same factory output and return
    ``(100 · (t_guarded / t_raw − 1), guard_stats)`` — the stats being
    all-zero is the evidence the measurement really was fault-free.

    ``make_state_and_step()`` must return a fresh ``(state, step_fn)`` pair
    per call (fresh, because the step donates its state and the two timings
    must not share buffers). Used by bench.py so the headline JSON carries
    the guard's measured cost rather than a claim.
    """
    import time

    stats = ResilienceStats()

    def run(guarded: bool) -> float:
        state, step = make_state_and_step()
        fn = StepGuard(step, stats=stats) if guarded else step
        loss = None
        for _ in range(warmup):
            state, loss = fn(state, batch)
        if loss is not None:
            float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = fn(state, batch)
        float(loss)
        return time.perf_counter() - t0

    t_raw = run(False)
    t_guarded = run(True)
    return 100.0 * (t_guarded / max(t_raw, 1e-9) - 1.0), stats
