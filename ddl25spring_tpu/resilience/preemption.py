"""Cooperative preemption handling: SIGTERM → force-save → clean exit.

Preemptible capacity (and this repo's own watchdogged virtual-mesh runs)
delivers SIGTERM, not a polite API call. ``PreemptionHandler`` converts the
signal into a flag the training loop polls at step boundaries; the loop then
force-saves a resumable checkpoint and returns instead of dying mid-write.
The handler chains to any previously installed handler on exit, and is a
no-op off the main thread (Python only delivers signals to the main thread,
and installing handlers elsewhere raises).

Usage (what train/llm.py's ``_run_loop`` does)::

    with PreemptionHandler() as pre:
        for it in ...:
            if pre.requested:
                ckpt.save(it, state, force=True); ckpt.wait()
                break
            state, loss = step(state, batch)
"""

from __future__ import annotations

import signal
import threading
from typing import List, Optional


class PreemptionHandler:
    """Installs handlers for ``signals`` (default: SIGTERM) that set a flag.

    Re-entrant as a context manager (install/restore is exact), readable via
    ``.requested``. A second signal while the flag is already set falls
    through to the previous handler — so a stuck force-save can still be
    killed by a second TERM.
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self._signals = tuple(signals)
        self._prev: List = []
        self._event = threading.Event()
        self._depth = 0

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def _handle(self, signum, frame):
        if self._event.is_set():
            prev = dict(zip(self._signals, self._prev)).get(signum)
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signum, signal.SIG_DFL)
                signal.raise_signal(signum)
            return
        self._event.set()

    def __enter__(self) -> "PreemptionHandler":
        if threading.current_thread() is not threading.main_thread():
            return self  # signals never arrive here; stay a passive flag
        self._depth += 1
        if self._depth == 1:  # nested re-entry keeps the outer install
            self._prev = [signal.signal(s, self._handle)
                          for s in self._signals]
        return self

    def __exit__(self, *exc) -> None:
        if threading.current_thread() is not threading.main_thread():
            return  # the matching __enter__ installed nothing
        if self._depth > 0:
            self._depth -= 1
            if self._depth == 0:
                for s, prev in zip(self._signals, self._prev):
                    signal.signal(s, prev)
