"""SLO-driven autoscaler: the policy half of the elasticity control plane.

The mechanism half already exists — the trainer can shrink AND grow its
data mesh at a chunk edge with bitwise-reproducible state
(resilience/elastic.py ``ElasticController.resize``), and the serving
fleet can activate/drain engines without dropping a stream
(serving/fleet.py ``ServingFleet.set_active``). This module decides WHEN
to move capacity between the two, from the same signal
experiments/slo_monitor.py issues verdicts over: the rolling-window TTFT
the fleet's router already keeps per engine.

Policy (``AutoscalePolicy``), deliberately boring:

====================  ====================================================
signal                action
====================  ====================================================
p95 TTFT >= pressure  sustained ``sustain`` ticks -> move ``step`` replicas
(pressure_frac·SLO)   train -> serve (drain training at the chunk edge,
                      shrink the mesh, activate engines)
p95 TTFT <= ebb       sustained ``sustain`` ticks -> move ``step`` engines
(ebb_frac·SLO), or    serve -> train (drain engines, grow the mesh)
no traffic at all
====================  ====================================================

Two properties make the smoke's "zero SLO violations" bar honest rather
than lucky:

- The scale-out trigger fires at ``pressure_frac`` (default 0.8) of the
  SLO, BELOW the violation threshold — capacity arrives while requests
  are still inside their budget, not after they have missed it.
- ``cooldown`` ticks of enforced inaction after every move stop the
  classic autoscaler failure mode (flapping: the post-move window still
  holds pre-move samples, which would immediately re-trigger).

``Autoscaler.tick`` is a pure policy step: it reads one measurement and
returns a ``ScaleDecision`` (or None). It never touches the trainer or
the fleet — the caller wires decisions into the trainer's
``scale_hook`` (train_llm_dp/_pp/_tp all take one) and
``ServingFleet.set_active`` (experiments/autoscale_smoke.py is the
reference wiring). On a multi-axis mesh ``train_world`` counts DATA
rows: ``ElasticController.resize`` grows/shrinks the data axis only, so
a PP trainer at (D, S) moves S devices per data row and a planned
resize never re-partitions stages. Keeping the
loop mechanism-free means it is trivially deterministic: same
measurement sequence -> same decision sequence, which is what lets the
smoke pin its scale trajectory.

Telemetry (schema v8): every decision emits one ``scale`` event carrying
the POST-transition allocation plus the triggering signal and value —
experiments/obs_report.py renders the section, trace_export.py drops
instant markers on the Perfetto timeline.

This module is imported at ``resilience`` package scope and therefore
must stay jax-free (stdlib + dataclasses only).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, NamedTuple, Optional

from ..telemetry.events import EventLog


@dataclass(frozen=True)
class AutoscalePolicy:
    """Thresholds and guard rails for ``Autoscaler``.

    ``ttft_slo_s`` is the serving SLO the whole loop protects (same
    number slo_monitor's ``--ttft`` takes). ``pressure_frac`` /
    ``ebb_frac`` scale it into the scale-out / scale-in trigger lines;
    pressure MUST be < 1.0 or the trigger only fires after a violation
    has already happened. ``sustain`` consecutive ticks must agree before
    a move; ``cooldown`` ticks are skipped after one. ``step`` replicas
    move per decision. The ``min_``/``max_`` bounds are hard walls — a
    decision that would cross one is simply not made (training never
    drains below ``min_train_world``; serving never below
    ``min_serve_engines``).

    ``min_headroom_frac`` > 0 arms the MEMORY guard rail (schema v9's
    headroom SLO, telemetry/memory.py): a train→serve move is vetoed
    while the caller-supplied pool headroom (min free fraction across
    the engines the move would activate — ``ServingFleet.pool_headroom``)
    sits below it. Latency pressure never justifies scaling serving up
    into KV pools that cannot fit the load — that converts an SLO miss
    into admission stalls (or an OOM on a real accelerator)."""

    ttft_slo_s: float
    max_train_world: int
    max_serve_engines: int
    pressure_frac: float = 0.8
    ebb_frac: float = 0.3
    sustain: int = 2
    cooldown: int = 2
    min_train_world: int = 1
    min_serve_engines: int = 1
    step: int = 1
    min_headroom_frac: float = 0.0

    def __post_init__(self):
        if not self.ttft_slo_s > 0:
            raise ValueError(f"ttft_slo_s={self.ttft_slo_s} must be > 0")
        if not 0 < self.pressure_frac < 1:
            raise ValueError(
                f"pressure_frac={self.pressure_frac} must be in (0, 1) — "
                "at >= 1 the autoscaler only reacts AFTER an SLO violation")
        if not 0 <= self.ebb_frac < self.pressure_frac:
            raise ValueError(
                f"ebb_frac={self.ebb_frac} must be in [0, pressure_frac) — "
                "overlapping bands would scale both ways on one signal")
        if self.sustain < 1 or self.cooldown < 0 or self.step < 1:
            raise ValueError(
                f"sustain={self.sustain} (>=1), cooldown={self.cooldown} "
                f"(>=0), step={self.step} (>=1)")
        if not 1 <= self.min_train_world <= self.max_train_world:
            raise ValueError(
                f"need 1 <= min_train_world={self.min_train_world} <= "
                f"max_train_world={self.max_train_world}")
        if not 1 <= self.min_serve_engines <= self.max_serve_engines:
            raise ValueError(
                f"need 1 <= min_serve_engines={self.min_serve_engines} <= "
                f"max_serve_engines={self.max_serve_engines}")
        if not 0 <= self.min_headroom_frac < 1:
            raise ValueError(
                f"min_headroom_frac={self.min_headroom_frac} must be in "
                "[0, 1) — a fraction of pool capacity, and requiring a "
                "FULLY free pool would veto every scale-out")


class ScaleDecision(NamedTuple):
    """One capacity move, POST-transition allocation (matches the
    ``scale`` telemetry event's required fields)."""

    direction: str      # "train_to_serve" | "serve_to_train"
    train_world: int    # training data-parallel world AFTER the move
    serve_engines: int  # active serving engines AFTER the move
    signal: str         # "ttft_pressure" | "traffic_ebb"
    value: float        # the p95 TTFT that triggered it (0.0 for idle)


class Autoscaler:
    """Streak-and-cooldown policy loop over a TTFT measurement feed.

    Holds the control plane's view of the allocation (``train_world``,
    ``serve_engines``); ``tick`` advances it. The caller is responsible
    for actually applying each returned ``ScaleDecision`` — the loop
    assumes every decision it makes lands (experiments/autoscale_smoke.py
    applies them at the trainer's next chunk edge via ``scale_hook``, so
    the view and the mesh agree at every decision point)."""

    def __init__(self, policy: AutoscalePolicy, *, train_world: int,
                 serve_engines: int, events: Optional[EventLog] = None,
                 log_fn=print):
        p = policy
        if not p.min_train_world <= train_world <= p.max_train_world:
            raise ValueError(f"train_world={train_world} outside policy "
                             f"[{p.min_train_world}, {p.max_train_world}]")
        if not p.min_serve_engines <= serve_engines <= p.max_serve_engines:
            raise ValueError(f"serve_engines={serve_engines} outside policy "
                             f"[{p.min_serve_engines}, {p.max_serve_engines}]")
        self.policy = p
        self.train_world = int(train_world)
        self.serve_engines = int(serve_engines)
        self.decisions: List[ScaleDecision] = []
        self.events = events
        self.log_fn = log_fn
        self._hot = 0       # consecutive ticks at/above the pressure line
        self._ebb = 0       # consecutive ticks at/below the ebb line
        self._cool = 0      # ticks of enforced inaction remaining

    def tick(self, ttft_p95_s: Optional[float],
             it: Optional[int] = None,
             headroom_frac: Optional[float] = None
             ) -> Optional[ScaleDecision]:
        """One policy step. ``ttft_p95_s`` is the current rolling p95 TTFT
        (None = no completed requests in the window, which reads as ebb:
        an idle fleet is over-provisioned by definition). ``it`` tags the
        telemetry event with the training iteration. ``headroom_frac`` is
        the memory guard-rail feed (``ServingFleet.pool_headroom`` of the
        POST-move active set): with ``policy.min_headroom_frac`` armed, a
        train→serve move is vetoed while headroom sits below the floor —
        the streak keeps accumulating, so the move fires the first tick
        the pool drains enough. None (no feed) never vetoes. Returns the
        decision to apply, or None."""
        p = self.policy
        hot = (ttft_p95_s is not None
               and ttft_p95_s >= p.pressure_frac * p.ttft_slo_s)
        ebb = (ttft_p95_s is None
               or ttft_p95_s <= p.ebb_frac * p.ttft_slo_s)
        # Streaks accumulate THROUGH cooldown (pressure that persists
        # across a move should act the first tick cooldown expires), but
        # decisions do not.
        self._hot = self._hot + 1 if hot else 0
        self._ebb = self._ebb + 1 if ebb else 0
        if self._cool > 0:
            self._cool -= 1
            return None
        want_out = (self._hot >= p.sustain
                    and self.train_world - p.step >= p.min_train_world
                    and self.serve_engines + p.step <= p.max_serve_engines)
        starved = (want_out and p.min_headroom_frac > 0
                   and headroom_frac is not None
                   and headroom_frac < p.min_headroom_frac)
        if want_out and not starved:
            decision = ScaleDecision(
                "train_to_serve", self.train_world - p.step,
                self.serve_engines + p.step, "ttft_pressure",
                float(ttft_p95_s))
        elif starved:
            if self.log_fn is not None:
                self.log_fn(f"[autoscale] train_to_serve vetoed: pool "
                            f"headroom {headroom_frac:.2f} < floor "
                            f"{p.min_headroom_frac:.2f} — not scaling "
                            "serving into a pool that can't fit it")
            return None
        elif (self._ebb >= p.sustain
                and self.serve_engines - p.step >= p.min_serve_engines
                and self.train_world + p.step <= p.max_train_world):
            decision = ScaleDecision(
                "serve_to_train", self.train_world + p.step,
                self.serve_engines - p.step, "traffic_ebb",
                0.0 if ttft_p95_s is None else float(ttft_p95_s))
        else:
            return None
        self.train_world = decision.train_world
        self.serve_engines = decision.serve_engines
        self._hot = self._ebb = 0
        self._cool = p.cooldown
        self.decisions.append(decision)
        if self.events is not None:
            self.events.scale(direction=decision.direction,
                              train_world=decision.train_world,
                              serve_engines=decision.serve_engines,
                              signal=decision.signal, value=decision.value,
                              **({} if it is None else {"it": int(it)}))
        if self.log_fn is not None:
            self.log_fn(f"[autoscale] {decision.direction} on "
                        f"{decision.signal} (p95 ttft "
                        f"{decision.value * 1e3:.1f} ms vs slo "
                        f"{p.ttft_slo_s * 1e3:.1f} ms) -> train_world="
                        f"{decision.train_world} serve_engines="
                        f"{decision.serve_engines}")
        return decision


def router_ttft_p95(router) -> Optional[float]:
    """Current fleet-wide p95 TTFT from a serving ``Router``'s per-engine
    rolling windows (the same windows ``predicted_ttft`` routing reads).
    None when no window holds a sample. Call ``router.harvest(now)``
    first to fold freshly completed requests in and expire old ones."""
    from ..telemetry.registry import percentile
    vals = [ttft for window in router._ttft for _, ttft in window]
    return percentile(vals, 95.0) if vals else None
