"""Elastic data parallelism: survive replica loss mid-run.

PR 1's resilience layer heals runs whose *topology never changes* — bad
steps are skipped, corrupt checkpoints rolled past, SIGTERM resumed. This
module removes that assumption for the DP trainer: when a data-parallel
replica dies mid-run (injected via the ``device_loss`` FaultPlan kind, or
any caller raising ``ReplicaLossError``), the run drains at the chunk
edge, re-meshes onto the survivors, reshards params + N-way ZeRO-1
optimizer state to the M-way layout, re-splits the batch stream at the
exact stream position, and resumes — instead of dying with the replica.
ZeRO-1 (PR 3) is what makes this non-trivial: optimizer moments are
physically sharded N ways, so 1/N of them lived on the dead replica and
recovery onto M survivors is genuine cross-topology state resharding
(all-gather-then-rescatter, ``parallel.dp.reshard_state``), not a restart.

Recovery paths, fastest first:

- **mirror** (fast): a host-RAM last-good snapshot taken at chunk edges
  (``ResilienceConfig.mirror_every``). The snapshot IS the all-gather —
  ``np.asarray`` on each sharded leaf materializes every replica's slice
  on host — so recovery is a pad-swap + device_put onto the survivors.
  With ``mirror_every=1`` nothing is replayed.
- **checkpoint** (slow): no mirror → restore the newest valid step through
  ``Checkpointer``'s cross-topology path (saved-shape restore + reshard on
  load), then re-train forward from it.

Either way the recovered state is persisted back to the checkpoint dir in
the NEW layout immediately (a second failure must not redo the
cross-topology work), the stream is rebuilt at width M and replayed to the
recovery position (a fresh M-replica run's data order, exactly), and the
step function is rebuilt at the new world size with fault/guard wrappers
re-applied at the absolute dispatch index.

Bidirectional: the same machinery runs in REVERSE when capacity comes
back. ``device_return`` faults (→ ``ReplicaReturnSignal``) or an
autoscaler decision (``resize``, resilience/autoscale.py) grow M→N
through ``parallel.mesh.rejoin_mesh`` — devices re-enter at their
original pool order, the mirror/checkpoint state reshards UP (the same
``reshard_state`` pad-swap, run toward more shards), the stream re-splits
at width N, and the fault wrapper resumes at the absolute dispatch index.
Shrink and grow are one code path (``_remesh``) differing only in how the
new mesh is chosen.

Correctness bar (pinned in tests/test_elastic.py): bitwise. Zero faults →
the elastic loop's losses equal the non-elastic path's; after an N→M
shrink (or an M→N grow) the continued trajectory equals a fresh M- (N-)
replica run restored from the same state — both directions, both
recovery paths.

Scope: data-only meshes (gradient / zero1 aggregation — plus the
int8-ring overlap drivers, whose EF residual trees reshard alongside the
ZeRO-1 moments via ``reshard_state``'s ring-residual pre-pass), DP×PP
meshes (the pipeline trainer's overlap drivers — victims index the flat
2-D device grid, and ``survivor_submesh`` prefers dropping the victims'
data rows whole; when no complete row survives, layers RE-PARTITION over
the survivors at the largest stage count dividing ``n_layers``, and
``pp.repartition_stage_state`` rewrites the ``(data, stage)`` moment/EF
stacks through topology-invariant coordinate ids), and the TP trainer's
PSA activation-EF state across data-axis resizes (the ``act_residual``
row rule). A ``model``-axis loss remains unrecoverable — the Megatron
column/row layout is not layer-sliced — and is rejected loudly
(``parallel.mesh.survivor_submesh``), as is a 3-axis data×stage×model
mesh.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, NamedTuple, Optional, Tuple

from ..telemetry.trace import Tracer
from .faults import ReplicaLossError, ReplicaReturnSignal


@dataclass
class RemeshRecord:
    """Accounting for one topology change (shrink OR grow) — lands in
    ``LLMTrainReport.remeshes``, the telemetry ``remesh`` event, and the
    elastic/autoscale smokes' recovery JSON."""

    detected_at: int       # stream position of the interrupted dispatch
    resume_step: int       # stream position training resumed from
    dispatch: int          # absolute dispatch index of the interruption
    old_world: int         # TOTAL device count (== data world on DP meshes)
    new_world: int
    lost: List[int] = field(default_factory=list)
    path: str = "mirror"   # "mirror" (host-RAM fast path) | "checkpoint"
    seconds: float = 0.0   # drain → resharded-and-replayed wall time
    steps_replayed: int = 0  # detected_at - resume_step (re-trained steps)
    direction: str = "shrink"   # "shrink" | "grow"
    returned: List[int] = field(default_factory=list)  # rejoined pool slots
    # Which mesh axis the re-mesh moved ("data" reshard vs "stage"
    # re-partition) and the (D, S) factorization either side of it — the
    # DP×PP accounting. On a data-only mesh: axis="data", shapes (D, 1).
    axis: str = "data"
    old_shape: Tuple[int, int] = (0, 1)
    new_shape: Tuple[int, int] = (0, 1)

    def as_dict(self) -> dict:
        return {"detected_at": self.detected_at,
                "resume_step": self.resume_step,
                "dispatch": self.dispatch,
                "old_world": self.old_world, "new_world": self.new_world,
                "lost": list(self.lost), "path": self.path,
                "seconds": self.seconds,
                "steps_replayed": self.steps_replayed,
                "direction": self.direction,
                "returned": list(self.returned),
                "axis": self.axis,
                "old_shape": list(self.old_shape),
                "new_shape": list(self.new_shape)}


class Resume(NamedTuple):
    """What the training loop swaps in after a recovery."""
    mesh: Any
    n_data: int
    state: Any
    step_fn: Callable
    window_shard_fn: Callable
    batches: Any           # stream iterator, already replayed to ``step``
    step: int              # stream position to resume from
    record: RemeshRecord


class ElasticController:
    """The drain → re-mesh → reshard → resume state machine.

    The training loop owns the iteration; the controller owns everything
    topology: the host-RAM mirror, victim selection, survivor submesh
    construction, state resharding, stream re-split/replay, step-function
    rebuild, and recovery accounting. Wiring (train/llm.py):

    - ``build(mesh) -> (template_state, raw_step_fn, window_shard_fn)``
      builds the trainer's window step on an arbitrary data mesh; the
      template's freshly initialized state supplies the M-way
      shapes/shardings recovery reshards into.
    - ``rewrap(raw_step_fn, start) -> step_fn`` re-applies the fault plan
      (at absolute dispatch index ``start`` — already-fired faults must
      not re-fire) and a fresh StepGuard (its EMA detector re-warms on
      the new topology's update norms).
    - ``make_batches(n_shards) -> iterator`` rebuilds the stream at the
      new width; the controller replays it to the recovery position so
      the data order is exactly a fresh M-replica run's.

    ``note_edge(step, state)`` is the loop's post-dispatch hook: every
    ``mirror_every``-th chunk edge it refreshes the host mirror (one
    device→host sync of the full state; ``mirror_every=0`` disables the
    fast path). ``recover(err, ...)`` runs the state machine and returns a
    ``Resume``; it raises ``err`` back when recovery is impossible (no
    mirror AND no restorable checkpoint).
    """

    def __init__(self, mesh, *, build: Callable, rewrap: Callable,
                 make_batches: Callable, ckpt=None, mirror_every: int = 1,
                 layer_divisor: Optional[int] = None,
                 stats=None, telemetry=None, log_fn: Callable = print):
        self.mesh = mesh
        # The run's original full device pool: the grow path can only
        # restore capacity the run started with, and pool order is what
        # makes a full shrink-then-grow round trip land devices back in
        # their original replica slots (the 4→3→4 bitwise bar). On a
        # DP×PP mesh the pool SHAPE is the original (D, S) factorization a
        # full rejoin reshapes straight back into, and ``layer_divisor``
        # (the model's n_layers) is what the stage re-partition's
        # factorization choice divides.
        self._pool = list(mesh.devices.flatten())
        self._pool_shape = tuple(int(s) for s in mesh.devices.shape)
        self._layer_divisor = (int(layer_divisor)
                               if layer_divisor is not None else None)
        self._build = build
        self._rewrap = rewrap
        self._make_batches = make_batches
        self._ckpt = ckpt
        self.mirror_every = int(mirror_every)
        self._stats = stats
        self._telemetry = telemetry
        # Recovery phases as a span tree (telemetry/trace.py): a ``remesh``
        # root on the run's "train" trace with rebuild/restore/persist/
        # replay children, so the trace timeline shows WHERE a recovery's
        # seconds went next to the dispatch spans it interrupted.
        self._tracer = (Tracer(telemetry.events)
                        if telemetry is not None else None)
        self._log = log_fn
        self._mirror: Optional[Tuple[int, Any]] = None  # (step, host state)
        self._edges = 0
        self.records: List[RemeshRecord] = []

    # ------------------------------------------------------------- mirror

    def note_edge(self, step: int, state) -> None:
        """Chunk-edge hook: refresh the last-good host mirror on schedule.
        The first call (the loop's pre-training seed at ``start_step``)
        always mirrors, so a loss on the very first dispatch is
        recoverable without a checkpoint."""
        if self.mirror_every <= 0:
            return
        if self._mirror is None or self._edges % self.mirror_every == 0:
            from ..parallel import dp
            self._mirror = (step, dp.host_snapshot(state))
        self._edges += 1

    @property
    def mirror_step(self) -> Optional[int]:
        return self._mirror[0] if self._mirror is not None else None

    def mirror_bytes(self) -> int:
        """Host RAM held by the last-good mirror (numpy nbytes walk —
        jax-free, no device sync). The memory meter stamps this onto the
        elastic loop's chunk-edge ``memory`` events so the recovery
        state's footprint is a number, not a guess."""
        from ..telemetry.memory import np_tree_bytes
        return np_tree_bytes(self._mirror[1]) if self._mirror else 0

    # ----------------------------------------------------------- recovery

    def absent(self) -> List[int]:
        """Pool positions of devices currently OUT of the mesh — the
        capacity a grow can reclaim. Empty until a shrink happens."""
        current = set(self.mesh.devices.flatten())
        return [i for i, d in enumerate(self._pool) if d not in current]

    @staticmethod
    def _dxs(mesh) -> Tuple[int, int]:
        """A mesh's (data, non-data) factorization — (D, S) on DP×PP,
        (D, 1) on a data-only mesh."""
        d = int(mesh.shape.get("data", 1))
        s = 1
        for a, sz in mesh.shape.items():
            if a != "data":
                s *= int(sz)
        return d, s

    def recover(self, err: ReplicaLossError, *, failed_at: int,
                dispatch: int) -> Resume:
        """Re-mesh onto the survivors and hand back a resumable world.

        ``failed_at`` is the stream position of the dispatch that died
        (its first step index); ``dispatch`` its absolute dispatch index —
        the rebuilt fault wrapper continues the schedule from
        ``dispatch + 1``, so already-delivered faults never re-fire and
        later-scheduled ones keep their absolute positions.

        Victims index the FLAT (data-major) device grid — on a data-only
        mesh that is the replica index exactly as before; on DP×PP device
        ``i`` is stage ``i % S`` of data row ``i // S``, and
        ``survivor_submesh`` picks the survivor topology (data row-drop
        when possible, else layer re-partition)."""
        from ..parallel.mesh import survivor_submesh

        old_world = int(self.mesh.devices.size)
        lost = err.victims(old_world)
        if not lost:
            # A 1-replica world has no survivors to re-mesh onto (victims'
            # ≥1-survivor clamp returns empty there): the loss is the whole
            # run, and pretending otherwise would be a vacuous "recovery"
            # onto the dead replica itself.
            raise err
        try:
            new_mesh = survivor_submesh(self.mesh, lost,
                                        layer_divisor=self._layer_divisor)
        except ValueError as e:
            # No recoverable survivor topology (e.g. a model-axis loss, or
            # no stage count divides n_layers): the loss kills the run,
            # same contract as the 1-replica case — re-raise the ORIGINAL
            # fault with the topology verdict chained for the postmortem.
            raise err from e
        self._log(f"replica loss at step {failed_at} (dispatch {dispatch}): "
                  f"lost {lost} of {old_world}; re-meshing onto "
                  f"{int(new_mesh.devices.size)} of the "
                  f"{old_world - len(lost)} survivors")
        return self._remesh(new_mesh, failed_at=failed_at, dispatch=dispatch,
                            lost=lost, returned=[], direction="shrink",
                            err=err)

    def grow(self, sig: ReplicaReturnSignal, *, failed_at: int,
             dispatch: int) -> Resume:
        """Scale-UP re-mesh: previously-lost capacity came back. The
        signal's seeded ``arrivals`` picks which absent pool slots rejoin;
        the new mesh restores pool order (``rejoin_mesh``), state reshards
        M→N through the same mirror/checkpoint paths as ``recover``, and
        the same bitwise bar applies (post-grow losses == a fresh N-replica
        run restored from the same state)."""
        from ..parallel.mesh import rejoin_mesh

        old_world = int(self.mesh.devices.size)
        absent = self.absent()
        arrivals = sig.arrivals(absent)
        if not arrivals:
            raise RuntimeError(
                f"device_return at dispatch {dispatch}: no capacity is "
                f"absent (world {old_world}, pool {len(self._pool)}) — a "
                "return must follow a loss; fix the chaos spec") from sig
        returned = [self._pool[i] for i in arrivals]
        new_mesh = rejoin_mesh(self.mesh, returned, pool=self._pool,
                               pool_shape=self._pool_shape,
                               layer_divisor=self._layer_divisor)
        self._log(f"replica return at step {failed_at} "
                  f"(dispatch {dispatch}): pool slots {arrivals} rejoin; "
                  f"re-meshing onto {int(new_mesh.devices.size)} devices")
        return self._remesh(new_mesh, failed_at=failed_at, dispatch=dispatch,
                            lost=[], returned=arrivals, direction="grow",
                            err=sig)

    def resize(self, new_world: int, *, state, at_step: int,
               dispatch: int) -> Optional[Resume]:
        """Capacity-change re-mesh (NOT fault-triggered): the autoscaler's
        entry point. Shrinks release the highest-indexed replicas (their
        devices become ``absent`` capacity another tenant can use); grows
        reclaim absent pool slots lowest-first. Returns None when the mesh
        is already at ``new_world`` — a no-op resize must not cost a
        reshard.

        ``state`` is the state the loop just drained at chunk edge
        ``at_step``: it is snapshotted as the mirror HERE, so the resize
        resumes from exactly this position — zero steps replayed, zero
        lost — regardless of the mirror cadence. Call only between
        dispatches (the drain-at-chunk-edge contract).

        ``new_world`` targets the DATA axis: on a data-only mesh that is
        the replica count exactly as before; on DP×PP a shrink releases
        the highest data ROWS whole (S devices each, the pure-reshard
        path — a planned resize never re-partitions layers) and a grow
        reclaims ``Δ·S`` absent pool slots lowest-first."""
        from ..parallel import dp
        from ..parallel.mesh import rejoin_mesh, survivor_submesh

        old_data, s2 = self._dxs(self.mesh)
        old_world = int(self.mesh.devices.size)
        new_world = int(new_world)
        if new_world == old_data:
            return None
        # A capacity change is planned, not a failure: the just-drained
        # state IS last-good, and pinning the mirror at the edge makes
        # resume_step == at_step (steps_replayed == 0) by construction.
        self._mirror = (at_step, dp.host_snapshot(state))
        if new_world < 1:
            raise ValueError(f"resize to {new_world} replicas: the training "
                             "mesh cannot shrink below 1")
        if new_world * s2 > len(self._pool):
            raise ValueError(f"resize to {new_world} data rows of {s2} "
                             f"device(s) exceeds the run's device pool "
                             f"({len(self._pool)})")
        if new_world < old_data:
            # Flat indices of the released rows (row r spans [r·S, (r+1)·S)).
            lost = list(range(new_world * s2, old_data * s2))
            new_mesh = survivor_submesh(self.mesh, lost,
                                        layer_divisor=self._layer_divisor)
            self._log(f"resize at step {at_step}: releasing data rows "
                      f"{list(range(new_world, old_data))} "
                      f"({old_data} -> {new_world})")
            return self._remesh(new_mesh, failed_at=at_step,
                                dispatch=dispatch, lost=lost, returned=[],
                                direction="shrink",
                                err=RuntimeError(
                                    f"resize {old_data}->{new_world} at "
                                    f"step {at_step} found no recoverable "
                                    "state (no mirror, no checkpoint)"))
        arrivals = self.absent()[:(new_world - old_data) * s2]
        if len(arrivals) < (new_world - old_data) * s2:
            raise ValueError(f"resize to {new_world} data rows: only "
                             f"{len(arrivals)} pool slots are absent "
                             f"(need {(new_world - old_data) * s2})")
        returned = [self._pool[i] for i in arrivals]
        new_mesh = rejoin_mesh(self.mesh, returned, pool=self._pool,
                               pool_shape=self._pool_shape,
                               layer_divisor=self._layer_divisor)
        self._log(f"resize at step {at_step}: pool slots {arrivals} "
                  f"rejoin ({old_data} -> {new_world})")
        return self._remesh(new_mesh, failed_at=at_step, dispatch=dispatch,
                            lost=[], returned=arrivals, direction="grow",
                            err=RuntimeError(
                                f"resize {old_data}->{new_world} at step "
                                f"{at_step} found no recoverable state "
                                "(no mirror, no checkpoint)"))

    def _remesh(self, new_mesh, *, failed_at: int, dispatch: int,
                lost: List[int], returned: List[int], direction: str,
                err: BaseException) -> Resume:
        """The shared drain → re-mesh → reshard → replay → resume machinery
        behind ``recover`` (shrink), ``grow`` and ``resize`` (either way).
        ``err`` is raised back when recovery is impossible (no mirror AND
        no restorable checkpoint)."""
        from ..parallel import dp

        t0 = time.perf_counter()
        old_shape = self._dxs(self.mesh)
        new_shape = self._dxs(new_mesh)
        old_world = int(self.mesh.devices.size)
        new_world = int(new_mesh.devices.size)
        new_data = new_shape[0]
        # Which axis moved: a stage-count change is a layer re-partition,
        # anything else is a data-axis reshard (row drop / rejoin).
        axis = "stage" if new_shape[1] != old_shape[1] else "data"
        self._beat(failed_at, "remesh")
        rroot = (self._tracer.start("remesh", trace="train", it=failed_at,
                                    old_world=old_world,
                                    new_world=new_world,
                                    axis=axis, direction=direction)
                 if self._tracer is not None else None)

        def _span(name):
            if rroot is not None:
                return self._tracer.span(name, parent=rroot.ctx)
            return contextlib.nullcontext()

        with _span("rebuild"):
            template, raw_step, window_shard = self._build(new_mesh)
        if self._mirror is not None:
            resume_step, host_state = self._mirror
            with _span("restore"):
                state = dp.reshard_state(host_state, template)
            path = "mirror"
        elif self._ckpt is not None:
            try:
                with _span("restore"):
                    state = self._ckpt.restore(template)
            except FileNotFoundError:
                if rroot is not None:
                    rroot.end(error=True)
                raise err from None     # nothing recoverable on disk either
            resume_step = int(self._ckpt.restored_step)
            path = "checkpoint"
        else:
            if rroot is not None:
                rroot.end(error=True)
            raise err                   # no mirror, no checkpoint: fatal

        if self._ckpt is not None:
            # Persist the M-way layout NOW: a second loss (or a plain
            # preemption) must restore cross-topology work, not redo it.
            # overwrite: step ``resume_step`` on disk is the N-way lineage.
            with _span("persist"):
                self._ckpt.save(resume_step, state, force=True,
                                overwrite=True)

        with _span("replay"):
            batches = self._make_batches(new_data)
            last_beat = 0.0
            for i in range(resume_step):    # stream replay at the new width
                next(batches)
                now = time.perf_counter()
                if now - last_beat >= 0.5:
                    self._beat(i, "remesh")
                    last_beat = now

        step_fn = self._rewrap(raw_step, start=dispatch + 1)
        self.mesh = new_mesh
        self._edges = 0
        self._mirror = None
        if self.mirror_every > 0:
            self.note_edge(resume_step, state)

        if rroot is not None:
            rroot.end(path=path, steps_replayed=failed_at - resume_step)
        rec = RemeshRecord(
            detected_at=failed_at, resume_step=resume_step,
            dispatch=dispatch, old_world=old_world, new_world=new_world,
            lost=lost, path=path, seconds=time.perf_counter() - t0,
            steps_replayed=failed_at - resume_step,
            direction=direction, returned=returned,
            axis=axis, old_shape=old_shape, new_shape=new_shape)
        self.records.append(rec)
        if self._stats is not None:
            self._stats.remeshes += 1
        if self._telemetry is not None:
            self._telemetry.events.remesh(
                old_world=old_world, new_world=new_world, lost=lost,
                path=path, it=resume_step, detected_at=failed_at,
                seconds=rec.seconds, steps_replayed=rec.steps_replayed,
                direction=direction, returned=returned,
                axis=axis, old_shape=list(old_shape),
                new_shape=list(new_shape))
        shapes = (f" [{old_shape[0]}x{old_shape[1]} -> "
                  f"{new_shape[0]}x{new_shape[1]} on the {axis} axis]"
                  if old_shape[1] > 1 or new_shape[1] > 1 else "")
        self._log(f"re-mesh ({direction}) complete in {rec.seconds:.3f}s "
                  f"via {path}{shapes}: resuming at step {resume_step} "
                  f"({rec.steps_replayed} steps to re-train)")
        return Resume(new_mesh, new_data, state, step_fn, window_shard,
                      batches, resume_step, rec)

    def _beat(self, step: int, phase: str) -> None:
        if self._telemetry is not None:
            self._telemetry.heartbeat.beat(step=step, phase=phase)
