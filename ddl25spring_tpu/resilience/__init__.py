"""Resilience layer: deterministic fault injection + self-healing loops.

Two halves that test each other (the design mirrors fl/attacks.py vs
fl/defenses.py, but for *benign* infrastructure faults instead of Byzantine
clients):

- ``faults``     — seedable ``FaultPlan``: NaN/Inf/spike gradients at chosen
                   steps, FL client drop/straggle per round, on-disk
                   checkpoint corruption, simulated SIGTERM preemption.
- ``guard``      — ``StepGuard``: all-finite + EMA-anomaly checked steps
                   with skip-and-count and rollback-to-last-good-checkpoint.
- ``retry``      — exponential backoff with seeded jitter, applied to
                   checkpoint IO and native tokenstream loading.
- ``preemption`` — SIGTERM → force-save-resumable-checkpoint → clean exit.
- ``elastic``    — ``ElasticController``: replica loss (``device_loss``
                   faults → ``ReplicaLossError``) → drain at the chunk
                   edge, re-mesh onto the survivors, reshard params +
                   ZeRO-1 optimizer state (and int8-ring EF residuals)
                   N→M, re-split the stream, resume — from a host-RAM
                   mirror (fast) or the checkpoint (slow). Bidirectional:
                   returned capacity (``device_return`` faults →
                   ``ReplicaReturnSignal``, or an autoscaler decision)
                   grows M→N through the same machinery.
- ``autoscale``  — ``Autoscaler``: SLO-driven policy loop moving replicas
                   between the training mesh and the serving fleet
                   (sustained TTFT pressure → shrink training, hand the
                   chips to serving; traffic ebb → reverse), emitting
                   schema-v8 ``scale`` events.

Counters land in ``metrics.ResilienceStats``; knobs in
``config.ResilienceConfig``. Wire-ins: train/llm.py (guarded loops),
fl/servers.py (survivor re-weighting), parallel/dp.py (in-step finiteness
guard), checkpoint.py (corrupt-step fallback, atomic best-weights),
experiments/watchdog.py (crash-loop-aware relaunch backoff).
"""

from .autoscale import (Autoscaler, AutoscalePolicy,  # noqa: F401
                        ScaleDecision)
from .elastic import (ElasticController, RemeshRecord,  # noqa: F401
                      Resume)
from .faults import (FaultEvent, FaultPlan, ReplicaLossError,  # noqa: F401
                     ReplicaReturnSignal, corrupt_latest_checkpoint,
                     parse_spec)
from .preemption import PreemptionHandler  # noqa: F401
from .retry import backoff_schedule, retry_call, with_retry  # noqa: F401

# guard imports jax at module scope; everything above is numpy/stdlib-only
# (elastic defers its parallel/ imports into recover()).
# Load it lazily (PEP 562) so jax-free supervisors — experiments/watchdog.py
# pulling in backoff_schedule — don't pay jax's import time and memory.
_GUARD_EXPORTS = ("StepGuard", "measure_overhead")
__all__ = ["Autoscaler", "AutoscalePolicy", "ElasticController",
           "FaultEvent", "FaultPlan", "RemeshRecord", "ReplicaLossError",
           "ReplicaReturnSignal", "Resume", "ScaleDecision",
           "corrupt_latest_checkpoint", "parse_spec", "PreemptionHandler",
           "backoff_schedule", "retry_call", "with_retry",
           *_GUARD_EXPORTS]


def __getattr__(name):
    if name in _GUARD_EXPORTS:
        from . import guard
        return getattr(guard, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
