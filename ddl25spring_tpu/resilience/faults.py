"""Deterministic fault injection: the adversary half of the resilience layer.

A ``FaultPlan`` is a seedable, fully deterministic schedule of benign faults
— the infrastructure counterpart of fl/attacks.py's Byzantine adversaries.
It can, at chosen steps/rounds:

- corrupt gradients (``nan_grad`` / ``inf_grad`` / ``spike_grad``) by
  wrapping a train step (`wrap_step`) so the post-update state and loss are
  poisoned exactly as a non-finite or exploded gradient would poison them;
- drop (``drop_client``) or time out (``delay_client``) FL clients for a
  round — the servers re-weight aggregation over the survivors;
- corrupt the newest checkpoint on disk (`corrupt_latest_checkpoint`);
- deliver a simulated preemption (``preempt``: SIGTERM to this process) at
  a step boundary;
- kill data-parallel replicas (``device_loss``: the wrapped step raises
  ``ReplicaLossError`` instead of dispatching, modeling the dispatch dying
  with the device — resilience/elastic.py turns it into a re-mesh onto the
  survivors);
- return previously-lost replicas (``device_return``: the wrapped step
  raises ``ReplicaReturnSignal`` instead of dispatching, modeling the
  cluster scheduler handing capacity back at a dispatch boundary —
  resilience/elastic.py turns it into a scale-UP re-mesh).

Plans parse from a compact spec string so bench.py / experiments can take
them straight off a CLI flag or config field::

    "nan_grad@10"                 NaN gradient at step 10 (all leaves)
    "nan_grad@10:3"               NaN confined to leaf #3 (1-based index in
                                  tree-flatten-with-path order — the order
                                  telemetry.introspect.leaf_paths reports;
                                  what the NaN-attribution tests inject)
    "spike_grad@5:100"            gradient scaled by 100 at step 5
    "preempt@25"                  SIGTERM delivered before step 25
    "drop_client@3:2"             2 clients vanish in round 3
    "delay_client@1:1"            1 client straggles past deadline, round 1
    "device_loss@4"               1 DP replica dies at dispatch 4
    "device_loss@4:2"             2 DP replicas die at dispatch 4
    "device_return@6"             1 lost replica comes back at dispatch 6
    "device_return@6:2"           2 lost replicas come back at dispatch 6
    "nan_grad@10,preempt@25"      comma-composed

Determinism contract: the same (spec, seed) always injects the same faults
on the same steps and picks the same client subsets — tests rely on it, and
so does "replay the incident" debugging.
"""

from __future__ import annotations

import os
import signal
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

GRAD_FAULTS = ("nan_grad", "inf_grad", "spike_grad")
CLIENT_FAULTS = ("drop_client", "delay_client")
KINDS = GRAD_FAULTS + CLIENT_FAULTS + ("preempt", "corrupt_ckpt",
                                       "device_loss", "device_return")

# Seed-stream salt for ReplicaLossError.victims — frozen at the KINDS
# length of the release that shipped device_loss, NOT len(KINDS): growing
# the kind vocabulary must never re-roll which replicas a committed
# (spec, seed) pair kills, or every pinned elastic trajectory would
# silently change out from under its test.
_VICTIM_SALT = 8


class ReplicaLossError(RuntimeError):
    """A data-parallel replica (device) died at dispatch ``step``.

    Raised by ``FaultPlan.wrap_step`` in place of running the scheduled
    dispatch — the injection-side model of a device failure surfacing as a
    failed dispatch. Anything that raises this (a real backend failure
    translated by a caller counts too) triggers the elastic recovery path
    when an ``ElasticController`` is attached (resilience/elastic.py);
    without one it propagates and kills the run, which is exactly today's
    non-elastic behavior.

    ``victims(n)`` picks WHICH of the ``n`` current devices died — a
    seeded deterministic choice (same (seed, step) → same victims, the
    FaultPlan determinism contract), always leaving at least one survivor.
    On a data-only mesh ``n`` is the replica count (the original
    contract, bit-for-bit); on a DP×PP mesh the controller passes the
    TOTAL device count and index ``i`` is stage ``i % S`` of data row
    ``i // S`` — the flat data-major grid ``survivor_submesh`` consumes,
    so a victim can orphan a stage column and force a layer
    re-partition."""

    def __init__(self, step: int, count: int = 1, seed: int = 0):
        super().__init__(f"replica loss at dispatch {step} "
                         f"({count} replica{'s' if count != 1 else ''})")
        self.step = int(step)
        self.count = max(1, int(count))
        self.seed = int(seed)

    def victims(self, n: int) -> List[int]:
        k = min(self.count, n - 1)
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, self.step, _VICTIM_SALT]))
        return sorted(int(i) for i in rng.choice(n, size=k, replace=False))


class ReplicaReturnSignal(RuntimeError):
    """Previously-lost data-parallel capacity came back at dispatch ``step``.

    The scale-UP twin of ``ReplicaLossError``: raised by
    ``FaultPlan.wrap_step`` in place of running the scheduled dispatch, so
    the grow lands exactly at a dispatch boundary with the incoming state
    buffers untouched (donation never happened) — replay-safe under the
    same ``start=`` counter contract as ``device_loss``. With an
    ``ElasticController`` attached it becomes a grow re-mesh
    (resilience/elastic.py); without one it propagates and kills the run —
    a non-elastic run has no use for returned capacity, and silently
    ignoring a scheduled event would make chaos specs lie.

    ``arrivals(lost)`` picks WHICH of the currently-lost replica slots
    come back — a seeded deterministic choice over the lost pool (same
    (seed, step, pool) → same arrivals), capped at the pool size. A
    distinct salt keeps the arrival stream independent of the victim
    stream even at a shared (seed, step)."""

    def __init__(self, step: int, count: int = 1, seed: int = 0):
        super().__init__(f"replica return at dispatch {step} "
                         f"({count} replica{'s' if count != 1 else ''})")
        self.step = int(step)
        self.count = max(1, int(count))
        self.seed = int(seed)

    def arrivals(self, lost: List[int]) -> List[int]:
        pool = sorted(int(i) for i in lost)
        k = min(self.count, len(pool))
        if k == 0:
            return []
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, self.step, _VICTIM_SALT + 1]))
        picked = rng.choice(len(pool), size=k, replace=False)
        return sorted(pool[int(i)] for i in picked)


@dataclass(frozen=True)
class FaultEvent:
    kind: str        # one of KINDS
    step: int        # train step (grad/preempt) or FL round (client faults)
    arg: float = 0.0  # spike scale / client count / unused

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(expected one of {KINDS})")


def parse_spec(spec: str) -> List[FaultEvent]:
    """``"kind@step[:arg],..."`` -> events. Whitespace-tolerant; empty spec
    -> no events."""
    events: List[FaultEvent] = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if "@" not in part:
            raise ValueError(f"fault spec {part!r} lacks '@step'")
        kind, _, rest = part.partition("@")
        step_s, _, arg_s = rest.partition(":")
        events.append(FaultEvent(kind.strip(), int(step_s),
                                 float(arg_s) if arg_s else 0.0))
    return events


@dataclass
class FaultPlan:
    """A deterministic fault schedule plus the injection mechanics.

    ``events``: what happens when. ``seed``: drives every random choice the
    plan makes (which clients drop) — two plans with equal (events, seed)
    behave identically. An empty plan injects nothing and wraps steps as
    identity, so it is safe to thread through fault-free runs.
    """

    events: List[FaultEvent] = field(default_factory=list)
    seed: int = 0

    @classmethod
    def from_spec(cls, spec: str, *, seed: int = 0) -> "FaultPlan":
        return cls(parse_spec(spec), seed=seed)

    def __bool__(self) -> bool:
        return bool(self.events)

    # ----------------------------------------------------------- queries

    def _at(self, kinds: Tuple[str, ...], step: int) -> Optional[FaultEvent]:
        for e in self.events:
            if e.kind in kinds and e.step == step:
                return e
        return None

    def grad_fault_at(self, step: int) -> Optional[FaultEvent]:
        return self._at(GRAD_FAULTS, step)

    def preempt_at(self, step: int) -> bool:
        return self._at(("preempt",), step) is not None

    def surviving_clients(self, round_idx: int,
                          sampled_idx: np.ndarray) -> Tuple[np.ndarray, int, int]:
        """(bool mask over ``sampled_idx``, n_dropped, n_stragglers) for this
        round. Which of the sampled clients vanish/straggle is a seeded
        choice over the sampled set — deterministic per (plan, round), and
        independent of array memory layout. At least one survivor is kept
        whenever possible is NOT guaranteed: a plan may kill the whole
        round; servers handle the empty round by skipping it."""
        mask = np.ones(len(sampled_idx), dtype=bool)
        dropped = stragglers = 0
        for kind in CLIENT_FAULTS:
            e = self._at((kind,), round_idx)
            if e is None:
                continue
            n = max(1, int(e.arg)) if e.arg else 1
            n = min(n, int(mask.sum()))
            rng = np.random.default_rng(
                np.random.SeedSequence([self.seed, round_idx,
                                        CLIENT_FAULTS.index(kind)]))
            victims = rng.choice(np.flatnonzero(mask), size=n, replace=False)
            mask[victims] = False
            if kind == "drop_client":
                dropped += n
            else:
                stragglers += n
        return mask, dropped, stragglers

    # --------------------------------------------------------- injection

    def device_loss_at(self, step: int) -> Optional[FaultEvent]:
        return self._at(("device_loss",), step)

    def device_return_at(self, step: int) -> Optional[FaultEvent]:
        return self._at(("device_return",), step)

    def wrap_step(self, step_fn, stats=None, *, start: int = 0):
        """Wrap ``step_fn(state, batch) -> (state, loss)`` so grad faults,
        simulated preemptions and replica losses fire at their scheduled
        steps.

        The wrapper counts calls itself (step indices are call indices from
        the wrap point; ``start`` offsets the counter so a step function
        REBUILT mid-run — elastic re-mesh — keeps absolute dispatch
        indices, instead of re-firing already-delivered faults from 0).
        ``device_loss`` raises ``ReplicaLossError`` BEFORE the step runs —
        the dispatch dies with the device, the incoming state buffers are
        untouched (donation never happened), and the elastic layer decides
        what survives. ``device_return`` raises ``ReplicaReturnSignal``
        before the step runs the same way, so a grow re-mesh lands at the
        identical dispatch boundary a loss would. Gradient faults poison the *outputs* exactly as the
        corrupted gradient would have: ``nan_grad``/``inf_grad`` make every
        updated param and the loss NaN/Inf (any standard optimizer update
        propagates a non-finite gradient into every touched coordinate);
        ``spike_grad`` re-applies the step's parameter delta scaled by
        ``arg`` (default 100x) — the update a ``arg``-times-larger gradient
        step would have produced under SGD-like geometry, which is what an
        EMA update-norm detector must catch. ``nan_grad``/``inf_grad``
        with a nonzero ``arg`` confine the poison to leaf #``arg``
        (1-based, tree-flatten-with-path order) — the targeted fault the
        NaN-leaf-attribution machinery (StepGuard.pop_trip, the flight
        recorder) is tested against. Preemption sends SIGTERM to this
        process BEFORE the step runs, modeling the scheduler's kill
        landing at a step boundary.

        Steps instrumented with in-jit numerics (telemetry/introspect.py)
        return ``(loss, summary)``; the poison lands on the loss and the
        summary rides through untouched (it describes the step the fault
        was injected AFTER — the guard's host-side attribution covers the
        poisoned state itself).
        """
        import jax
        import jax.numpy as jnp

        from .guard import _tree_copy

        counter = {"step": start}

        def wrapped(state, batch):
            step = counter["step"]
            counter["step"] += 1
            dl = self.device_loss_at(step)
            if dl is not None:
                raise ReplicaLossError(step, int(dl.arg) if dl.arg else 1,
                                       seed=self.seed)
            dr = self.device_return_at(step)
            if dr is not None:
                raise ReplicaReturnSignal(step,
                                          int(dr.arg) if dr.arg else 1,
                                          seed=self.seed)
            if self.preempt_at(step):
                os.kill(os.getpid(), signal.SIGTERM)
            e = self.grad_fault_at(step)
            old_params = None
            if e is not None and e.kind == "spike_grad":
                # Snapshot BEFORE the step: every step factory donates its
                # input state, so the pre-step params are gone afterwards.
                # Fault-free steps pay nothing.
                old_params = _tree_copy(state.params)
            new_state, out = step_fn(state, batch)
            if e is None:
                return new_state, out
            loss, aux = (out if isinstance(out, tuple) else (out, None))
            if e.kind == "spike_grad":
                scale = e.arg if e.arg else 100.0
                params = jax.tree.map(
                    lambda old, new: old + scale * (new - old),
                    old_params, new_state.params)
                loss = loss * scale
            else:
                bad = jnp.nan if e.kind == "nan_grad" else jnp.inf
                target = int(e.arg) if e.arg else 0     # 0 = every leaf
                leaves, treedef = jax.tree.flatten(new_state.params)
                params = treedef.unflatten([
                    jnp.full_like(p, bad)
                    if target in (0, i + 1) else p
                    for i, p in enumerate(leaves)])
                loss = jnp.full_like(loss, bad)
            out = (loss, aux) if aux is not None else loss
            return new_state._replace(params=params), out

        return wrapped


def corrupt_latest_checkpoint(directory: str) -> str:
    """Corrupt the newest orbax step under ``directory`` on disk: truncate
    and garble every data file in its tree (metadata files too), modeling a
    mid-write kill or disk fault. Returns the corrupted step's path.
    Deterministic: the same directory state is corrupted the same way."""
    steps = []
    for name in os.listdir(directory):
        p = os.path.join(directory, name)
        # Committed orbax step dirs are bare integers; anything else
        # ("8.orbax-checkpoint-tmp-...", metadata dirs) is not a step and
        # must not be selected — corrupting a leftover tmp dir would leave
        # the real latest intact and the injected fault would test nothing.
        if os.path.isdir(p) and name.isdigit():
            steps.append((int(name), p))
    if not steps:
        raise FileNotFoundError(f"no checkpoint steps under {directory}")
    _, latest = max(steps)
    corrupted = False
    for root, _, files in os.walk(latest):
        for fname in files:
            path = os.path.join(root, fname)
            size = os.path.getsize(path)
            with open(path, "r+b" if size else "wb") as f:
                f.truncate(size // 2)
                f.seek(0, os.SEEK_END)
                f.write(b"\x00CORRUPT\x00")
            corrupted = True
    if not corrupted:
        raise FileNotFoundError(f"no files to corrupt under {latest}")
    return latest
