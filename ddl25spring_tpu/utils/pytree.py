"""Pytree helpers.

The reference flattens all model gradients into one contiguous CPU tensor for
its allreduce bucket (reference: lab/tutorial_1b/DP/gradient_aggr/
intro_DP_GA.py:55-66) and the Byzantine defenses operate on flat update
vectors (attacks_and_defenses.ipynb cell 34). Here those become pure pytree ↔
flat-vector transforms that are jit/vmap friendly.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

PyTree = Any


def flatten(tree: PyTree) -> Tuple[jnp.ndarray, Callable[[jnp.ndarray], PyTree]]:
    """Pytree -> (flat vector, unflatten fn)."""
    return ravel_pytree(tree)


def tree_add(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: PyTree, b: PyTree) -> PyTree:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(tree: PyTree, s) -> PyTree:
    return jax.tree.map(lambda x: x * s, tree)


def tree_zeros_like(tree: PyTree) -> PyTree:
    return jax.tree.map(jnp.zeros_like, tree)


def tree_weighted_sum(trees: PyTree, weights: jnp.ndarray) -> PyTree:
    """Weighted sum over a leading stacked axis: each leaf has shape
    [n, ...]; returns Σ_i w_i · leaf_i. This is the FedAvg aggregation
    (reference: hfl_complete.py:366-374) as a pure reduction."""
    def leaf(x):
        w = weights.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
        return (x * w).sum(axis=0)

    return jax.tree.map(leaf, trees)


def tree_weighted_fold(trees: PyTree, weights: jnp.ndarray,
                       init: PyTree = None) -> PyTree:
    """Sequential (index-order) weighted sum over the leading stacked axis:
    a left fold ``acc += w_i · leaf_i`` via lax.scan, starting from ``init``
    (zeros when omitted).

    Same value as ``tree_weighted_sum`` up to float association — but the
    fold's association is FIXED by the stream order, where XLA may
    re-associate ``(x*w).sum(0)`` differently per axis length. Three exact
    properties follow, which the FL aggregation discipline (fl/servers.py,
    fl/fleet.py) is built on:

    - a zero-weight row is an exact no-op (selected around, not added), so
      padding a cohort/survivor set to a fixed compiled width is invisible;
    - folding a stream of chunks, each starting from the previous chunk's
      carry, is bitwise the one-shot fold — cohort streaming at ANY width
      equals the all-clients-resident path;
    - the result does not depend on how many padded rows ride along.
    """
    if init is None:
        init = jax.tree.map(
            lambda x: jnp.zeros(x.shape[1:], x.dtype), trees)

    def step(acc, row):
        tree_i, w_i = row
        acc = jax.tree.map(
            lambda a, x: jnp.where(w_i != 0, a + w_i.astype(a.dtype) * x, a),
            acc, tree_i)
        return acc, None

    acc, _ = jax.lax.scan(step, init, (trees, weights))
    return acc


def tree_stack(trees) -> PyTree:
    """List of pytrees -> single pytree with leading stacked axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_unstack(tree: PyTree):
    """Inverse of tree_stack: pytree with leading axis n -> list of n pytrees."""
    leaves, treedef = jax.tree.flatten(tree)
    n = leaves[0].shape[0]
    return [jax.tree.unflatten(treedef, [leaf[i] for leaf in leaves]) for i in range(n)]


def tree_index(tree: PyTree, i) -> PyTree:
    """Select index ``i`` along every leaf's leading axis (jit-safe)."""
    return jax.tree.map(lambda x: x[i], tree)


def param_count(tree: PyTree) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(tree))


def global_norm(tree: PyTree) -> jnp.ndarray:
    return jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in jax.tree.leaves(tree)))


def tree_cast(tree: PyTree, dtype) -> PyTree:
    return jax.tree.map(lambda x: x.astype(dtype), tree)
