# Deliberately NO eager submodule imports: utils.probe must be importable
# without pulling jax into the process (bench.py probes the platform in a
# subprocess BEFORE its own jax import; an import-time accelerator-runtime
# wedge would otherwise hang the caller). Import submodules explicitly:
# ``from ddl25spring_tpu.utils import pytree``.
