"""Tracing compatibility shims + CSV result persistence.

The tracing/profiling half of this module moved to
``ddl25spring_tpu/telemetry/trace.py`` (the ISSUE-8 span layer): ``Spans``,
``StepTimer`` and ``device_trace`` are re-exported here unchanged so
existing imports keep working, but there is now ONE tracing path — the
span Tracer feeds the same ``Spans`` accumulators the registry absorbs,
and ``device_trace`` additionally bridges host spans onto the XLA profiler
timeline. New code should import from ``telemetry.trace`` directly.

What still lives here is result persistence:

- ``atomic_write_csv``: temp-file + ``os.replace`` CSV rewrite.
- ``ResultSink``: append experiment records (RunResult or dicts) to CSV.
"""

from __future__ import annotations

import contextlib
import csv
import os
import threading
from typing import Any, Dict, List, Optional

from ..telemetry.trace import Spans, StepTimer, device_trace  # noqa: F401

__all__ = ["Spans", "StepTimer", "device_trace", "atomic_write_csv",
           "ResultSink"]


def atomic_write_csv(path: str, fieldnames: List[str],
                     rows: List[Dict[str, Any]]) -> None:
    """Rewrite a CSV atomically: temp file in the same directory +
    ``os.replace``, preserving the original's mode, with the temp file
    unlinked on failure. The one implementation of this dance — used by
    ResultSink's header widening and experiments.common.dedupe_csv, both of
    which run in environments where processes get killed mid-write."""
    import tempfile

    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               suffix=".csv.tmp")
    try:
        with os.fdopen(fd, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=fieldnames, restval="")
            writer.writeheader()
            writer.writerows(rows)
        if os.path.exists(path):
            os.chmod(tmp, os.stat(path).st_mode & 0o7777)
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


class ResultSink:
    """Append-only CSV sink for experiment records.

    Accepts dicts or RunResult-like objects (anything with ``as_df``); the
    CSV header is taken from the first record (reference idiom: results
    persisted to CSV for re-plotting, hw03 cells 11, 18, 29).

    Thread-safe within one process: concurrent ``write`` calls (training
    thread + watchdog/monitor thread) serialize on a lock, so a
    header-widening rewrite can never interleave with another append and
    drop rows (pinned in tests/test_telemetry.py).
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fieldnames: Optional[List[str]] = None
        if os.path.exists(path):
            with open(path, newline="") as f:
                reader = csv.reader(f)
                self._fieldnames = next(reader, None)

    def write(self, record: Any) -> None:
        if hasattr(record, "as_df"):
            for row in record.as_df().to_dict(orient="records"):
                self._locked_write_row(row)
        else:
            self._locked_write_row(dict(record))

    def _locked_write_row(self, row: Dict[str, Any]) -> None:
        with self._lock:
            self._write_row(row)

    def _write_row(self, row: Dict[str, Any]) -> None:
        new_file = self._fieldnames is None
        if new_file:
            self._fieldnames = list(row.keys())
        extra = [k for k in row if k not in self._fieldnames]
        if extra:
            # Widen: rewrite the file under the union header instead of
            # silently dropping the new fields. Pure-csv round-trip (no type
            # inference mangling existing values), atomic so a crash
            # mid-widen cannot lose prior records.
            self._fieldnames = self._fieldnames + extra
            if os.path.exists(self.path):
                with open(self.path, newline="") as f:
                    old_rows = list(csv.DictReader(f))
                atomic_write_csv(self.path, self._fieldnames, old_rows)
        with open(self.path, "a", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=self._fieldnames,
                                    restval="")
            if new_file:
                writer.writeheader()
            writer.writerow(row)

    def read_df(self):
        import pandas as pd
        return pd.read_csv(self.path)
