"""Tracing, profiling, and result persistence.

The reference's observability is hand-rolled perf_counter spans accumulated
into RunResult phases (reference: lab/tutorial_1a/hfl_complete.py:350-358,
369-371) plus shell-level `$SECONDS` prints (homework_1_b1.sh:3,13) and CSV
dumps from notebooks (lab/hw03/Tea_Pula_03.ipynb cell 11). This module is
the framework equivalent, plus the TPU-native layer the reference lacks:
`jax.profiler` device traces viewable in TensorBoard/Perfetto.

- ``Spans``: named wall-clock accumulators (setup/update/aggregate phases).
- ``device_trace``: context manager around jax.profiler.trace.
- ``StepTimer``: per-step timing with proper block_until_ready semantics —
  async dispatch makes naive perf_counter spans lie on TPU.
- ``ResultSink``: append experiment records (RunResult or dicts) to CSV.
"""

from __future__ import annotations

import contextlib
import csv
import os
import threading
import time
from collections import defaultdict
from typing import Any, Dict, Iterator, List, Optional

import jax


class Spans:
    """Named wall-clock accumulators, the RunResult phase-accounting helper.

    Thread-safe: a watchdog/monitoring thread and the training thread may
    accumulate into one instance concurrently (the lock covers the
    read-modify-write of the accumulators, not the timed block itself).

    >>> spans = Spans()
    >>> with spans("update"):
    ...     do_work()
    >>> spans.total("update")
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._acc: Dict[str, float] = defaultdict(float)
        self._count: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def __call__(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._acc[name] += dt
                self._count[name] += 1

    def total(self, name: str) -> float:
        with self._lock:
            return self._acc[name]

    def count(self, name: str) -> int:
        with self._lock:
            return self._count[name]

    def as_dict(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._acc)

    def reset(self) -> None:
        with self._lock:
            self._acc.clear()
            self._count.clear()


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """jax.profiler device trace (XLA ops, HBM, ICI) → TensorBoard-readable
    trace in ``log_dir``. The TPU-native upgrade of the reference's
    perf_counter-only accounting (SURVEY.md §5.1)."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Per-step timing that is honest under async dispatch: ``tick`` blocks
    on the step's outputs before reading the clock.

    ``tick()`` before ``start()`` raises instead of silently recording a
    0.0 step (the old behavior poisoned means with zeros — percentile
    consumers in telemetry.MetricsRegistry would inherit the lie).
    Thread-safe for the same reason as Spans."""

    def __init__(self):
        self.times: List[float] = []
        self._t0: Optional[float] = None
        self._lock = threading.Lock()

    def start(self) -> None:
        with self._lock:
            self._t0 = time.perf_counter()

    def tick(self, *outputs) -> float:
        for out in outputs:
            jax.block_until_ready(out)
        now = time.perf_counter()
        with self._lock:
            if self._t0 is None:
                raise RuntimeError(
                    "StepTimer.tick() before start(): the interval has no "
                    "beginning — call start() once before the timed loop")
            dt = now - self._t0
            self.times.append(dt)
            self._t0 = now
        return dt

    @property
    def mean(self) -> float:
        with self._lock:
            return sum(self.times) / max(len(self.times), 1)


def atomic_write_csv(path: str, fieldnames: List[str],
                     rows: List[Dict[str, Any]]) -> None:
    """Rewrite a CSV atomically: temp file in the same directory +
    ``os.replace``, preserving the original's mode, with the temp file
    unlinked on failure. The one implementation of this dance — used by
    ResultSink's header widening and experiments.common.dedupe_csv, both of
    which run in environments where processes get killed mid-write."""
    import tempfile

    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               suffix=".csv.tmp")
    try:
        with os.fdopen(fd, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=fieldnames, restval="")
            writer.writeheader()
            writer.writerows(rows)
        if os.path.exists(path):
            os.chmod(tmp, os.stat(path).st_mode & 0o7777)
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


class ResultSink:
    """Append-only CSV sink for experiment records.

    Accepts dicts or RunResult-like objects (anything with ``as_df``); the
    CSV header is taken from the first record (reference idiom: results
    persisted to CSV for re-plotting, hw03 cells 11, 18, 29).

    Thread-safe within one process: concurrent ``write`` calls (training
    thread + watchdog/monitor thread) serialize on a lock, so a
    header-widening rewrite can never interleave with another append and
    drop rows (pinned in tests/test_telemetry.py).
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._fieldnames: Optional[List[str]] = None
        if os.path.exists(path):
            with open(path, newline="") as f:
                reader = csv.reader(f)
                self._fieldnames = next(reader, None)

    def write(self, record: Any) -> None:
        if hasattr(record, "as_df"):
            for row in record.as_df().to_dict(orient="records"):
                self._locked_write_row(row)
        else:
            self._locked_write_row(dict(record))

    def _locked_write_row(self, row: Dict[str, Any]) -> None:
        with self._lock:
            self._write_row(row)

    def _write_row(self, row: Dict[str, Any]) -> None:
        new_file = self._fieldnames is None
        if new_file:
            self._fieldnames = list(row.keys())
        extra = [k for k in row if k not in self._fieldnames]
        if extra:
            # Widen: rewrite the file under the union header instead of
            # silently dropping the new fields. Pure-csv round-trip (no type
            # inference mangling existing values), atomic so a crash
            # mid-widen cannot lose prior records.
            self._fieldnames = self._fieldnames + extra
            if os.path.exists(self.path):
                with open(self.path, newline="") as f:
                    old_rows = list(csv.DictReader(f))
                atomic_write_csv(self.path, self._fieldnames, old_rows)
        with open(self.path, "a", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=self._fieldnames,
                                    restval="")
            if new_file:
                writer.writeheader()
            writer.writerow(row)

    def read_df(self):
        import pandas as pd
        return pd.read_csv(self.path)
