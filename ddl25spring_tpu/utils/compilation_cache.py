"""Persistent XLA compilation-cache enablement, gated per jaxlib version.

The tier-1 suite compiles the same tiny-model programs over and over across
test processes; the persistent cache (``jax_compilation_cache_dir``) turns
those recompiles into disk loads (~28% wall-time measured on the suite) —
relief the 870 s CI budget needs.

It is NOT safe everywhere: on jaxlib 0.4.36 (this container) reloading a
cached executable whose input buffers are donated SEGFAULTS the CPU
backend — reproduced in the trainer-resume tests, and every step factory in
parallel/ donates its state. So enablement is gated on the jaxlib version:
known-bad 0.4.x builds decline and run exactly as before; newer builds
(CI installs current jax) get the cache. One probe, one place — the same
degrade-don't-abort posture as experiments/_cpu_pin.py's XLA-flag probe.
"""

from __future__ import annotations

import os
import tempfile
from typing import Optional

# First generation where the donated-input reload path is trusted. 0.4.36
# is reproducibly bad (see module docstring); no 0.4.x build has been
# cleared, so the gate is conservative: 0.5+ only.
_MIN_SAFE = (0, 5, 0)


def _jaxlib_version() -> tuple:
    try:
        import jaxlib
        return tuple(int(p) for p in jaxlib.__version__.split(".")[:3])
    except Exception:
        return (0, 0, 0)


def compilation_cache_supported() -> bool:
    """True when this jaxlib is trusted to reload donated-input executables
    from the persistent cache without crashing (see module docstring)."""
    return _jaxlib_version() >= _MIN_SAFE


def enable_compilation_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Enable the persistent compilation cache when safe on this jaxlib.

    ``cache_dir`` defaults to ``$DDL25_COMPILATION_CACHE_DIR`` or a stable
    path under the system tempdir (stable, so separate test/bench processes
    in one session share warm entries; CI scopes it to the runner's
    tempdir via the env var). Returns the directory in use, or None when
    the gate declined — callers treat None as "run exactly as before".
    Never raises: cache trouble must not sink a test session or a bench.
    """
    if not compilation_cache_supported():
        return None
    try:
        import jax
        cache_dir = (cache_dir
                     or os.environ.get("DDL25_COMPILATION_CACHE_DIR")
                     or os.path.join(tempfile.gettempdir(),
                                     "ddl25-xla-cache"))
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        return cache_dir
    except Exception:
        return None
