"""Subprocess probe of the default jax platform.

The tunneled TPU in the bench environment can wedge so that every jax op in
the calling process — even ``jax.devices()`` — hangs forever. Anything that
must not hang (the headline bench, the driver's multichip dryrun) therefore
asks a THROWAWAY subprocess what the default platform looks like: a wedged
runtime times the probe out, a broken one crashes it, and either way the
caller survives and can pin the CPU platform instead.

Parsing takes the LAST stdout line: this container's sitecustomize can emit
warnings before the probed value.
"""

from __future__ import annotations

import subprocess
import sys
from typing import Optional, Tuple


def probe_default_platform(timeout: float = 180.0
                           ) -> Tuple[Optional[str], int]:
    """Returns (platform_name, device_count) of the default jax backend,
    or (None, 0) if the probe times out, crashes, or prints garbage."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(d[0].platform, len(d))"],
            capture_output=True, text=True, timeout=timeout)
    except (subprocess.TimeoutExpired, OSError):
        return None, 0
    if out.returncode != 0:
        return None, 0
    try:
        platform, n = out.stdout.strip().splitlines()[-1].split()
        return platform, int(n)
    except (ValueError, IndexError):
        return None, 0
