"""Multi-host distributed runtime: the framework's gloo/MPI replacement.

The reference's distributed backend is torch.distributed over gloo with
localhost rendezvous via MASTER_ADDR/MASTER_PORT env vars and one OS process
per rank (reference: lab/tutorial_1b/DP/gradient_aggr/intro_DP_GA.py:11-15;
SURVEY.md §2.11). The TPU-native equivalent is one JAX process per HOST (not
per device): `jax.distributed.initialize` performs the rendezvous, after
which `jax.devices()` spans every chip in the slice/pod and the SAME
single-program mesh code runs unchanged — collectives ride ICI within a
slice and DCN between hosts. No ranks in user code, no sockets, no tags.

`hybrid_mesh` builds the two-tier topology explicitly: DCN-connected axes
(across hosts — put data parallelism here; it communicates once per step)
outer, ICI-connected axes (within a slice — model/stage/seq/expert axes,
which communicate per layer) inner.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import AXES


def _is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` with a fallback for jax builds
    that predate it (same API-drift posture as parallel/_compat.py): the
    distributed client living in jax's global state is the signal."""
    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:
        return False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Multi-host rendezvous — the `init_process_group` analog.

    With no arguments, reads the standard env vars (JAX_COORDINATOR_ADDRESS
    etc.) or the TPU metadata server, mirroring the reference's
    MASTER_ADDR/MASTER_PORT convention (intro_DP_GA.py:12-14) without
    per-rank processes. Safe to call on single-host (no-op there).

    MUST run before anything touches the XLA backend — so this guard checks
    only is_initialized() and the env vars; calling e.g. jax.process_count()
    here would itself initialize the backend and make the rendezvous
    impossible.
    """
    if _is_initialized():
        return
    kw = {}
    if coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        kw["coordinator_address"] = (coordinator_address or
                                     os.environ["JAX_COORDINATOR_ADDRESS"])
    if num_processes or os.environ.get("JAX_NUM_PROCESSES"):
        kw["num_processes"] = int(num_processes or
                                  os.environ["JAX_NUM_PROCESSES"])
    if process_id is not None or os.environ.get("JAX_PROCESS_ID"):
        kw["process_id"] = int(process_id if process_id is not None
                               else os.environ["JAX_PROCESS_ID"])
    if not kw:
        return  # single-host, nothing to rendezvous
    jax.distributed.initialize(**kw)


def hybrid_mesh(ici_axes: Dict[str, int], dcn_axes: Dict[str, int],
                *, devices: Optional[Sequence] = None) -> Mesh:
    """Two-tier mesh: ``dcn_axes`` split across hosts (slow, once-per-step
    collectives — data parallelism), ``ici_axes`` within each host/slice
    (fast, per-layer collectives — model/stage/seq/expert).

    Axis ordering in the result follows mesh.AXES so the train-step factories
    (dp/pp/tp/sp/ep) work unchanged on the hybrid mesh.
    """
    from jax.experimental import mesh_utils

    dcn_names = [a for a in AXES if a in dcn_axes] + \
                [a for a in dcn_axes if a not in AXES]
    ici_names = [a for a in AXES if a in ici_axes] + \
                [a for a in ici_axes if a not in AXES]
    overlap = set(dcn_names) & set(ici_names)
    assert not overlap, f"axes cannot span both tiers: {overlap}"

    if devices is None and jax.process_count() > 1:
        # create_hybrid_device_mesh wants same-rank shapes composed
        # elementwise; our tiers are disjoint, so pad each with 1s — the
        # elementwise product is then exactly [*dcn_shape, *ici_shape].
        dev_array = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=[1] * len(dcn_names) + [ici_axes[a] for a in ici_names],
            dcn_mesh_shape=[dcn_axes[a] for a in dcn_names] + [1] * len(ici_names),
        )
    else:
        devices = list(devices if devices is not None else jax.devices())
        shape = [dcn_axes[a] for a in dcn_names] + \
                [ici_axes[a] for a in ici_names]
        need = int(np.prod(shape))
        assert need <= len(devices), (shape, len(devices))
        dev_array = np.asarray(devices[:need]).reshape(shape)

    names = tuple(dcn_names + ici_names)
    # Reorder to canonical AXES order for train-step factory compatibility.
    order = sorted(range(len(names)),
                   key=lambda i: (AXES.index(names[i])
                                  if names[i] in AXES else len(AXES)))
    dev_array = np.transpose(np.asarray(dev_array), order)
    return Mesh(dev_array, tuple(names[i] for i in order))


def hier_data_mesh(islands: int, island_size: int, *,
                   devices: Optional[Sequence] = None) -> Mesh:
    """Two-tier DATA-parallel mesh: ``islands`` ICI islands of
    ``island_size`` replicas each, bridged by DCN — axes ``("dcn",
    "data")`` with island-major device order (replica (d, s) = device
    d·island_size + s). This is the substrate of the hierarchical
    collectives (parallel/compress.py): full-precision reduction inside
    each island's ``data`` axis, a compressed exchange across ``dcn``
    only — wire compression spent exactly where bandwidth is scarce.

    Multi-host: delegates to ``hybrid_mesh`` so the ``dcn`` axis really
    spans hosts (``create_hybrid_device_mesh``). Single-process (the CPU
    test mesh): the first islands·island_size devices, island-major —
    the SAME logical topology, so every factorization is testable on the
    virtual mesh."""
    return hybrid_mesh({"data": island_size}, {"dcn": islands},
                       devices=devices)


def process_info() -> Dict[str, int]:
    """Host-level identity (the replacement for the reference's rank arg)."""
    return {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
