"""Tensor (model) parallelism: Megatron-style sharded transformer blocks.

The reference has NO tensor parallelism — its closest analog is the VFL
bottom/top model split (SURVEY.md §2.10 marks TP "Absent", optional
parity-plus). This module adds it TPU-first: attention heads and the SwiGLU
hidden dimension are sharded over a ``model`` mesh axis, the two row-sharded
projections (wo, w_down) produce partial sums, and one ``lax.psum`` per
sub-layer combines them over ICI — the classic Megatron f/g collective
pattern, expressed through shard_map.

Sharding layout (per block; leading [n_layers] axis never sharded here):
- wq, wk, wv:      [L, D, D]  column-sharded  P(None, None, "model")
  → each device computes num_heads / tp local heads end-to-end.
- wo:              [L, D, D]  row-sharded     P(None, "model", None)
  → partial [B,T,D] outputs, psum over "model" (inside llama.attention).
- w_gate, w_up:    [L, D, F]  column-sharded; w_down [L, F, D] row-sharded,
  psum inside llama.mlp.
- norms, embedding, lm_head: replicated (their grads are psum-ed instead).

Gradient accounting: the per-shard loss is scaled by 1/tp before
differentiation. Every shard's loss copy depends on every shard's weight
slice (through the psums), so differentiating the unscaled replicated loss
would count each path tp times; with the 1/tp scaling, sharded-leaf grads
come out exact locally and replicated-leaf grads become exact after a psum
over ``model``. Composes with data parallelism on a ``(data, model)`` mesh.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry import comm
from ._compat import shard_map

from ..config import LlamaConfig
from ..models import llama
from ..ops import causal_lm_loss
from .dp import TrainState, sharded_opt_init

_COL = {"wq", "wk", "wv", "w_gate", "w_up"}   # shard last dim (output cols)
_ROW = {"wo", "w_down"}                        # shard middle dim (input rows)


def param_specs(params: dict) -> dict:
    """Megatron PartitionSpecs for the stacked-block Llama tree."""
    def block_spec(name):
        def spec(_):
            if name in _COL:
                return P(None, None, "model")
            if name in _ROW:
                return P(None, "model", None)
            return P()
        return spec

    specs = {}
    for k, v in params.items():
        if k == "blocks":
            specs[k] = {name: jax.tree.map(block_spec(name), leaf)
                        for name, leaf in v.items()}
        else:
            specs[k] = jax.tree.map(lambda _: P(), v)
    return specs


def shard_params(mesh: Mesh, params: dict) -> dict:
    specs = param_specs(params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def init_state(mesh: Mesh, params: dict,
               optimizer: optax.GradientTransformation) -> TrainState:
    params = shard_params(mesh, params)
    opt_state = sharded_opt_init(mesh, params, optimizer, param_specs(params))
    step = jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P()))
    return TrainState(params, opt_state, step)


def _tp_loss(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
             tp: int) -> jnp.ndarray:
    """Per-shard body: full loss / tp (see module docstring on why /tp)."""
    h = llama.embed(params, tokens, cfg)
    h = llama.blocks_apply(params["blocks"], h, cfg, tp_axis="model")
    logits = llama.head(params, h, cfg)
    return causal_lm_loss(logits, tokens) / tp


def _sharded_mask(grads: dict) -> dict:
    """Bool pytree marking leaves that are model-sharded (complete locally)
    vs replicated (partial grads needing a psum over ``model``)."""
    return {
        outer: ({name: jax.tree.map(lambda _: name in _COL or name in _ROW, leaf)
                 for name, leaf in v.items()} if outer == "blocks"
                else jax.tree.map(lambda _: False, v))
        for outer, v in grads.items()
    }


def make_tp_train_step(cfg: LlamaConfig, optimizer: optax.GradientTransformation,
                       mesh: Mesh) -> Callable:
    """jit-compiled train step on a ``(data?, model)`` mesh.

    ``step(state, tokens)``: tokens [B, T] sharded over ``data`` if present,
    replicated over ``model`` (every TP shard sees the full local batch).
    The grad computation runs under shard_map (explicit psums); the optimizer
    update runs at jit level where GSPMD keeps opt-state shardings aligned
    with the param shardings (same split as parallel.pp.make_pipeline_step).
    """
    tp = mesh.shape["model"]
    has_data = mesh.shape.get("data", 1) > 1

    def sharded_grads(params: dict, tokens):
        loss, grads = jax.value_and_grad(_tp_loss)(params, tokens, cfg, tp)
        mask = _sharded_mask(grads)
        # Telemetry note: the in-forward f/g psums inside llama.attention/
        # mlp run under value_and_grad — autodiff synthesizes their
        # transposes, which trace-time accounting cannot see (documented in
        # telemetry/comm.py). The post-AD reductions below are exact.
        grads = jax.tree.map(
            lambda g, s: g if s else comm.psum(g, "model",
                                               label="tp_replicated_grads"),
            grads, mask)
        loss = loss * tp                          # undo the 1/tp scaling
        if has_data:
            grads = comm.pmean(grads, "data", label="grad_allreduce")
            loss = comm.pmean(loss, "data", label="loss_allreduce")
        return loss, grads

    def step(state: TrainState, tokens):
        pspecs = param_specs(state.params)
        loss, grads = shard_map(
            sharded_grads, mesh=mesh,
            in_specs=(pspecs, P("data") if has_data else P()),
            out_specs=(P(), pspecs),
            check_vma=False,
        )(state.params, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return jax.jit(step, donate_argnums=(0,))


def tp_forward(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
               mesh: Mesh) -> jnp.ndarray:
    """Full logits via tensor-parallel forward (tests/eval); cached on
    (cfg, mesh)."""
    return _tp_forward_fn(cfg, mesh)(params, tokens)


@functools.cache
def _tp_forward_fn(cfg: LlamaConfig, mesh: Mesh) -> Callable:
    def body(params, tokens):
        h = llama.embed(params, tokens, cfg)
        h = llama.blocks_apply(params["blocks"], h, cfg, tp_axis="model")
        return llama.head(params, h, cfg)

    def fn(params, tokens):
        return shard_map(
            body, mesh=mesh,
            in_specs=(param_specs(params), P()),
            out_specs=P(),
            check_vma=False,
        )(params, tokens)

    return jax.jit(fn)


from .mesh import shard_batch  # noqa: E402,F401  (shared batch placement)
