"""Tensor (model) parallelism: Megatron-style sharded transformer blocks.

The reference has NO tensor parallelism — its closest analog is the VFL
bottom/top model split (SURVEY.md §2.10 marks TP "Absent", optional
parity-plus). This module adds it TPU-first: attention heads and the SwiGLU
hidden dimension are sharded over a ``model`` mesh axis, the two row-sharded
projections (wo, w_down) produce partial sums, and one ``lax.psum`` per
sub-layer combines them over ICI — the classic Megatron f/g collective
pattern, expressed through shard_map.

Sharding layout (per block; leading [n_layers] axis never sharded here):
- wq, wk, wv:      [L, D, D]  column-sharded  P(None, None, "model")
  → each device computes num_heads / tp local heads end-to-end.
- wo:              [L, D, D]  row-sharded     P(None, "model", None)
  → partial [B,T,D] outputs, psum over "model" (inside llama.attention).
- w_gate, w_up:    [L, D, F]  column-sharded; w_down [L, F, D] row-sharded,
  psum inside llama.mlp.
- norms, embedding, lm_head: replicated (their grads are psum-ed instead).

Gradient accounting: the per-shard loss is scaled by 1/tp before
differentiation. Every shard's loss copy depends on every shard's weight
slice (through the psums), so differentiating the unscaled replicated loss
would count each path tp times; with the 1/tp scaling, sharded-leaf grads
come out exact locally and replicated-leaf grads become exact after a psum
over ``model``. Composes with data parallelism on a ``(data, model)`` mesh.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import nn
from ..telemetry import comm
from ._compat import shard_map

from ..config import LlamaConfig
from ..models import llama
from ..ops import causal_lm_loss
from .dp import TrainState, apply_optimizer, sharded_opt_init

_COL = {"wq", "wk", "wv", "w_gate", "w_up"}   # shard last dim (output cols)
_ROW = {"wo", "w_down"}                        # shard middle dim (input rows)


def param_specs(params: dict) -> dict:
    """Megatron PartitionSpecs for the stacked-block Llama tree."""
    def block_spec(name):
        def spec(_):
            if name in _COL:
                return P(None, None, "model")
            if name in _ROW:
                # No trailing None: XLA normalizes output shardings to the
                # trailing-None-free form, and a device_put'd input with
                # the unnormalized spec would be a DIFFERENT jit cache
                # signature — one spurious re-lowering on the second
                # donated dispatch (the zero-retrace gate in
                # experiments/tp_fusion_smoke.py pins this).
                return P(None, "model")
            return P()
        return spec

    specs = {}
    for k, v in params.items():
        if k == "blocks":
            specs[k] = {name: jax.tree.map(block_spec(name), leaf)
                        for name, leaf in v.items()}
        else:
            specs[k] = jax.tree.map(lambda _: P(), v)
    return specs


def shard_params(mesh: Mesh, params: dict) -> dict:
    specs = param_specs(params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def init_state(mesh: Mesh, params: dict,
               optimizer: optax.GradientTransformation) -> TrainState:
    params = shard_params(mesh, params)
    opt_state = sharded_opt_init(mesh, params, optimizer, param_specs(params))
    step = jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P()))
    return TrainState(params, opt_state, step)


def _tp_loss(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
             tp: int) -> jnp.ndarray:
    """Per-shard body: full loss / tp (see module docstring on why /tp)."""
    h = llama.embed(params, tokens, cfg)
    h = llama.blocks_apply(params["blocks"], h, cfg, tp_axis="model")
    logits = llama.head(params, h, cfg)
    return causal_lm_loss(logits, tokens) / tp


def _sharded_mask(grads: dict) -> dict:
    """Bool pytree marking leaves that are model-sharded (complete locally)
    vs replicated (partial grads needing a psum over ``model``)."""
    return {
        outer: ({name: jax.tree.map(lambda _: name in _COL or name in _ROW, leaf)
                 for name, leaf in v.items()} if outer == "blocks"
                else jax.tree.map(lambda _: False, v))
        for outer, v in grads.items()
    }


def make_tp_train_step(cfg: LlamaConfig, optimizer: optax.GradientTransformation,
                       mesh: Mesh) -> Callable:
    """jit-compiled train step on a ``(data?, model)`` mesh.

    ``step(state, tokens)``: tokens [B, T] sharded over ``data`` if present,
    replicated over ``model`` (every TP shard sees the full local batch).
    The grad computation runs under shard_map (explicit psums); the optimizer
    update runs at jit level where GSPMD keeps opt-state shardings aligned
    with the param shardings (same split as parallel.pp.make_pipeline_step).
    """
    tp = mesh.shape["model"]
    has_data = mesh.shape.get("data", 1) > 1

    def sharded_grads(params: dict, tokens):
        loss, grads = jax.value_and_grad(_tp_loss)(params, tokens, cfg, tp)
        mask = _sharded_mask(grads)
        # Telemetry note: the in-forward f/g psums inside llama.attention/
        # mlp run under value_and_grad — autodiff synthesizes their
        # transposes, which trace-time accounting cannot see (documented in
        # telemetry/comm.py). The post-AD reductions below are exact.
        grads = jax.tree.map(
            lambda g, s: g if s else comm.psum(g, "model",
                                               label="tp_replicated_grads"),
            grads, mask)
        loss = loss * tp                          # undo the 1/tp scaling
        if has_data:
            grads = comm.pmean(grads, "data", label="grad_allreduce")
            loss = comm.pmean(loss, "data", label="loss_allreduce")
        return loss, grads

    def step(state: TrainState, tokens):
        pspecs = param_specs(state.params)
        loss, grads = shard_map(
            sharded_grads, mesh=mesh,
            in_specs=(pspecs, P("data") if has_data else P()),
            out_specs=(P(), pspecs),
            check_vma=False,
        )(state.params, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return jax.jit(step, donate_argnums=(0,))


def tp_forward(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
               mesh: Mesh) -> jnp.ndarray:
    """Full logits via tensor-parallel forward (tests/eval); cached on
    (cfg, mesh)."""
    return _tp_forward_fn(cfg, mesh)(params, tokens)


@functools.cache
def _tp_forward_fn(cfg: LlamaConfig, mesh: Mesh) -> Callable:
    def body(params, tokens):
        h = llama.embed(params, tokens, cfg)
        h = llama.blocks_apply(params["blocks"], h, cfg, tp_axis="model")
        return llama.head(params, h, cfg)

    def fn(params, tokens):
        return shard_map(
            body, mesh=mesh,
            in_specs=(param_specs(params), P()),
            out_specs=P(),
            check_vma=False,
        )(params, tokens)

    return jax.jit(fn)


# ------------------------------------- partially-synchronized activations
#
# "Tensor-Parallelism with Partially Synchronized Activations" (PAPERS.md,
# arXiv 2506.19645): the two per-layer activation all-reduces of the
# Megatron forward sit on the critical path of every TP step, and they can
# be relaxed — deferred across layers, or compressed with error feedback —
# at a bounded quality cost. The modes below keep the relaxation additive:
# ``psa=""`` routes through ``llama.blocks_apply(tp_axis="model")``
# unchanged (the bitwise reference), and every relaxed mode reuses
# ``llama.attention``/``llama.mlp`` with ``tp_axis=None`` — the partial
# (un-psummed) per-shard outputs — applying its own sync externally, so
# the model code carries no PSA logic. Analytic model-axis wire budgets
# are in ``psa_sync_wire_bytes`` and gated by experiments/tp_fusion_smoke.


def _parse_psa(psa: str, n_layers: int) -> Tuple[str, int]:
    """Validate a ``TrainConfig.psa`` string → ``(mode, defer_period)``
    with mode ∈ {"", "full", "defer", "int8_ef"}."""
    if psa in ("", "full", "int8_ef"):
        return psa, 0
    if psa.startswith("defer:"):
        try:
            period = int(psa.split(":", 1)[1])
        except ValueError:
            period = 0
        if period < 1:
            raise ValueError(f"bad PSA defer period in {psa!r}: want "
                             "'defer:L' with integer L >= 1")
        if n_layers % period:
            raise ValueError(
                f"psa='defer:{period}' needs n_layers divisible by the "
                f"defer period (got n_layers={n_layers}) — the last layer "
                "group must end on a sync boundary or shards never agree")
        return "defer", period
    raise ValueError(f"unknown psa mode {psa!r}: expected '', 'full', "
                     "'defer:L' or 'int8_ef'")


def psa_sync_wire_bytes(cfg: LlamaConfig, psa: str, tp: int,
                        batch: int, seq: int) -> int:
    """Analytic per-device per-step MODEL-axis activation-sync wire bytes
    for one forward pass, exactly as telemetry/comm.py accounts the
    forward sync collectives (backward-sync bytes are AD-synthesized
    transposes on every mode — the documented under-count; the ratio
    between modes is therefore measured on a consistent basis):

    - ""/"full":  2L psums of the [B, T, D] activation → 2L · 2(tp−1)/tp
      · B·T·D·itemsize.
    - "defer:P":  one boundary psum per P layers → (L/P) · 2(tp−1)/tp
      · B·T·D·itemsize — a 1/(2P) reduction.
    - "int8_ef":  2L int8 all-gathers (+ a 4-byte scale gather each) →
      2L · (tp−1) · (B·T·D + 4) — ~tp/8 of full sync.

    ``psa=""`` shares the full-sync formula: the wire is identical, it is
    just invisible to telemetry (raw in-model psum)."""
    mode, period = _parse_psa(psa, cfg.n_layers)
    act = batch * seq * cfg.dmodel
    item = jnp.dtype(cfg.dtype).itemsize
    if mode == "int8_ef":
        return 2 * cfg.n_layers * (tp - 1) * (act + 4)
    syncs = (cfg.n_layers // period) if mode == "defer" else 2 * cfg.n_layers
    return int(syncs * (2 * (tp - 1) / tp) * act * item)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _psum_ste(y, summed, axis_name):
    """Swap a shard's partial sub-layer output ``y`` for the externally
    combined ``summed`` on the forward pass, while the backward pass keeps
    the EXACT psum's transpose (itself a psum under shard_map semantics).
    The 1/tp Megatron gradient accounting of the module docstring then
    carries over to the compressed sync unchanged: gradients are computed
    as if the sync were a true ``lax.psum`` of the partials."""
    return summed


def _psum_ste_fwd(y, summed, axis_name):
    return summed, None


def _psum_ste_bwd(axis_name, _, ct):
    # Raw lax.psum on purpose: telemetry counts FORWARD sync wire only, so
    # the backward-sync bytes stay the same documented under-count as the
    # full-sync path's autodiff-synthesized transposes (telemetry/comm.py)
    # — recording them here would inflate the compressed mode's measured
    # bytes against a baseline that cannot see its own.
    return lax.psum(ct, axis_name), jnp.zeros_like(ct)


_psum_ste.defvjp(_psum_ste_fwd, _psum_ste_bwd)


def _psa_int8_sync(y, res, comm_scale: int):
    """One compressed activation sync over ``model``: each shard quantizes
    its EF-compensated partial ``y + res`` to int8 (compress.py's
    symmetric per-tensor rule), all-gathers (q, s) from every shard and
    sums the dequantized partials locally — the cross-shard combine at
    ~tp/8 of the psum's wire. Returns ``(combined, residual')`` with the
    new per-shard quantization error feeding the next step's sync."""
    from .compress import _int8_encode
    c = lax.stop_gradient(y.astype(jnp.float32) + res)
    q, s, new_res = _int8_encode(c)
    q_all = comm.all_gather(q, "model", label="psa_act_int8",
                            scale=comm_scale)
    s_all = comm.all_gather(s[None], "model", tiled=True,
                            label="psa_act_scale", scale=comm_scale)
    summed = jnp.einsum("i,i...->...", s_all, q_all.astype(jnp.float32))
    return _psum_ste(y, summed.astype(y.dtype), "model"), new_res


def _psa_blocks_apply(blocks, h, cfg: LlamaConfig, tp: int, mode: str,
                      period: int, act_res, comm_scale: int = 1):
    """The PSA transformer stack: ``llama.blocks_apply`` with the per-sub-
    layer model-axis sync performed per ``mode``. Returns ``(h, act_res')``
    — the residual tree is None except under ``mode="int8_ef"``.

    - ``""``:     the in-model raw-psum path, bitwise the legacy forward.
    - ``"full"``: the SAME sync positions through ``comm.psum`` — value-
      identical (one lax.psum per sub-layer either way), but the model-axis
      activation wire becomes visible to trace-time accounting. This is the
      smoke's same-run full-sync baseline.
    - ``"defer"``: no sync inside a group of ``period`` layers — each shard
      evolves its hidden state from its OWN partial sub-layer outputs —
      then one boundary correction ``psum(h) − (tp−1)·h0``: every shard
      carried ``h0`` plus its local contributions, so the correction is
      exactly ``h0 + Σ_shards(local contributions)`` — each sub-layer
      contribution (computed from per-shard partial inputs, the PSA
      relaxation) counted once, and all shards agree at every boundary.
    - ``"int8_ef"``: per-sub-layer compressed sync (``_psa_int8_sync``)
      with the [L, 2, B, T, D] error-feedback residual tree threaded as
      scan xs and returned updated.
    """
    if mode == "":
        return llama.blocks_apply(blocks, h, cfg, tp_axis="model"), act_res
    t = h.shape[1]
    cos, sin = llama.rope_angles(jnp.arange(t), cfg.head_dim, cfg.rope_theta)
    n_layers = jax.tree.leaves(blocks)[0].shape[0]

    if mode == "full":
        def layer(block, c, cos, sin):
            a = llama.attention(
                block, nn.rmsnorm(block["attn_norm"], c, eps=cfg.norm_eps),
                cfg, cos, sin)
            c = c + comm.psum(a, "model", label="psa_full_sync",
                              scale=n_layers * comm_scale)
            m = llama.mlp(
                block, nn.rmsnorm(block["mlp_norm"], c, eps=cfg.norm_eps))
            return c + comm.psum(m, "model", label="psa_full_sync",
                                 scale=n_layers * comm_scale)

        fn = jax.checkpoint(layer) if cfg.remat else layer

        def body(carry, block):
            return fn(block, carry, cos, sin), None

        out, _ = lax.scan(body, h, blocks)
        return out, act_res

    if mode == "defer":
        n_groups = n_layers // period
        grouped = jax.tree.map(
            lambda x: x.reshape((n_groups, period) + x.shape[1:]), blocks)

        def layer(block, c, cos, sin):
            return llama.block_apply(block, c, cfg, cos, sin)  # partials

        fn = jax.checkpoint(layer) if cfg.remat else layer

        def group(carry, gblocks):
            h0 = carry

            def inner(c, block):
                return fn(block, c, cos, sin), None

            hp, _ = lax.scan(inner, h0, gblocks)
            hp = comm.psum(hp, "model", label="psa_defer_sync",
                           scale=n_groups * comm_scale)
            return hp - (tp - 1) * h0, None

        out, _ = lax.scan(group, h, grouped)
        return out, act_res

    # mode == "int8_ef"
    def layer(block, res_pair, c, cos, sin):
        a = llama.attention(
            block, nn.rmsnorm(block["attn_norm"], c, eps=cfg.norm_eps),
            cfg, cos, sin)
        a, r0 = _psa_int8_sync(a, res_pair[0], n_layers * comm_scale)
        c = c + a
        m = llama.mlp(
            block, nn.rmsnorm(block["mlp_norm"], c, eps=cfg.norm_eps))
        m, r1 = _psa_int8_sync(m, res_pair[1], n_layers * comm_scale)
        return c + m, jnp.stack([r0, r1])

    fn = jax.checkpoint(layer) if cfg.remat else layer

    def body(carry, xs):
        block, res_pair = xs
        return fn(block, res_pair, carry, cos, sin)

    out, new_res = lax.scan(body, h, (blocks, act_res))
    return out, new_res


def _tp_psa_loss(params: dict, tokens, cfg: LlamaConfig, tp: int,
                 mode: str, period: int, act_res, comm_scale: int = 1):
    """``_tp_loss`` with the activation sync per PSA mode; returns
    ``(loss/tp, act_res')`` (aux threads the EF residuals out of
    value_and_grad — they are stop-gradiented at the sync)."""
    h = llama.embed(params, tokens, cfg)
    h, new_res = _psa_blocks_apply(params["blocks"], h, cfg, tp, mode,
                                   period, act_res, comm_scale)
    logits = llama.head(params, h, cfg)
    return causal_lm_loss(logits, tokens) / tp, new_res


class TPActState(NamedTuple):
    """TrainState + the PSA activation error-feedback residual tree of
    ``psa="int8_ef"``: ``[n_data, tp, L, 2, B_local, T, D]`` fp32 sharded
    ``P(data?, "model")`` — each (data, model) shard compensates the
    quantization error of its OWN partial activations (slot [l, 0] = layer
    l's attention output, [l, 1] = its MLP output). Rides the K-step scan
    carry and the checkpointed state tree, so the accumulated error
    survives fused dispatch, chunk-edge checkpoints and a preempt/resume
    cycle exactly (pinned in tests/test_tp.py)."""
    params: Any
    opt_state: Any
    step: jnp.ndarray
    act_residual: Any


def _act_residual_setup(mesh: Mesh, cfg: LlamaConfig,
                        batch_shape: Optional[Tuple[int, int]]):
    """Zero activation-EF residual + its PartitionSpec. The residual is
    sized by the LOCAL (per data shard) batch, which the factory cannot
    infer — callers pass ``batch_shape=(per_shard_batch, seq_len)``."""
    if batch_shape is None:
        raise ValueError(
            "psa='int8_ef' carries a per-(model shard, sub-layer) "
            "activation EF residual sized by the local batch — pass "
            "batch_shape=(per_data_shard_batch, seq_len) to the factory")
    b, t = batch_shape
    has_data = mesh.shape.get("data", 1) > 1
    n_data = mesh.shape.get("data", 1)
    tp = mesh.shape["model"]
    spec = P("data", "model") if has_data else P(None, "model")
    res = jax.device_put(
        jnp.zeros((n_data, tp, cfg.n_layers, 2, b, t, cfg.dmodel),
                  jnp.float32),
        NamedSharding(mesh, spec))
    return res, spec


# ------------------------------------------- shared-body step factories
#
# ``make_tp_train_step`` above is kept byte-for-byte as the reference
# path (optimizer at jit level). The factories below share ONE per-shard
# body between the per-step and the K-step scan driver — the
# dp._make_local_grad_step / pp._make_pp_local_step convention — so
# per-step and fused dispatch cannot drift and their bitwise equality at
# any K is structural (pinned at K∈{1,4} in tests/test_tp.py).


def _make_tp_local_step(cfg: LlamaConfig, optimizer, *, tp: int,
                        has_data: bool, mode: str, period: int,
                        comm_scale: int = 1, numerics=None) -> Callable:
    """The per-shard TP train-step body shared by ``make_tp_step`` and
    ``make_tp_multi_step``. Runs under shard_map over (data?, model); the
    optimizer applies to each shard's LOCAL param slice — valid for
    elementwise optimizers (the ZeRO-1 slice-commuting argument,
    ops/adam.py), which is every optimizer this repo ships. With
    ``psa=""`` the gradient computation is bitwise ``make_tp_train_step``'s
    and the elementwise update matches the jit-level one coordinate for
    coordinate (pinned in tests/test_tp.py)."""
    ef = mode == "int8_ef"

    def local_step(state, tokens):
        act_res = state.act_residual[0, 0] if ef else None
        (loss, new_res), grads = jax.value_and_grad(
            _tp_psa_loss, has_aux=True)(state.params, tokens, cfg, tp,
                                        mode, period, act_res, comm_scale)
        mask = _sharded_mask(grads)
        grads = jax.tree.map(
            lambda g, s: g if s else comm.psum(g, "model",
                                               label="tp_replicated_grads",
                                               scale=comm_scale),
            grads, mask)
        loss = loss * tp                          # undo the 1/tp scaling
        if has_data:
            grads = comm.pmean(grads, "data", label="grad_allreduce",
                               scale=comm_scale)
            loss = comm.pmean(loss, "data", label="loss_allreduce",
                              scale=comm_scale)
        params, opt_state = apply_optimizer(optimizer, grads,
                                            state.opt_state, state.params)
        step = state.step + 1
        if ef:
            new_state = TPActState(params, opt_state, step,
                                   new_res[None, None])
        else:
            new_state = TrainState(params, opt_state, step)
        if numerics is not None:
            summary = numerics.summarize(state.params, grads, params)
            return new_state, (loss, summary)
        return new_state, loss

    return local_step


def _tp_state_specs(state, mode: str, res_spec):
    """shard_map PartitionSpecs for a (TrainState | TPActState) under the
    Megatron layout, computed from the traced state's tree structure only
    (the pp._opt_specs rule)."""
    from .pp import _opt_specs
    pspecs = param_specs(state.params)
    ospecs = _opt_specs(state.opt_state, state.params, pspecs)
    if mode == "int8_ef":
        return TPActState(pspecs, ospecs, P(), res_spec)
    return TrainState(pspecs, ospecs, P())


def make_tp_step(cfg: LlamaConfig, optimizer: optax.GradientTransformation,
                 mesh: Mesh, params, *, psa: str = "",
                 batch_shape: Optional[Tuple[int, int]] = None,
                 numerics=None):
    """Per-step shared-body TP driver on a ``(data?, model)`` mesh:
    returns ``(state, step)`` with ``step(state, tokens) -> (state, loss)``
    — a ``TPActState`` under ``psa="int8_ef"`` (activation EF residuals in
    the checkpointed tree), a plain TrainState otherwise.

    ``psa`` selects the activation sync mode (``TrainConfig.psa``;
    semantics in ``_psa_blocks_apply``): ``""`` and ``"full"`` are bitwise
    the legacy ``make_tp_train_step`` path, ``"defer:L"``/``"int8_ef"``
    hold the pinned convergence bars of tests/test_tp.py.

    ``numerics`` (a ``make_tp_numerics`` handle) arms the in-jit summary:
    the step then returns ``(state, (loss, NumericsSummary))`` — extra
    OUTPUTS only, losses/params bitwise on vs off."""
    tp = mesh.shape["model"]
    has_data = mesh.shape.get("data", 1) > 1
    mode, period = _parse_psa(psa, cfg.n_layers)
    state = init_state(mesh, params, optimizer)
    res_spec = None
    if mode == "int8_ef":
        res, res_spec = _act_residual_setup(mesh, cfg, batch_shape)
        state = TPActState(state.params, state.opt_state, state.step, res)
    local_step = _make_tp_local_step(cfg, optimizer, tp=tp,
                                     has_data=has_data, mode=mode,
                                     period=period, numerics=numerics)

    def step(state, tokens):
        state_specs = _tp_state_specs(state, mode, res_spec)
        out_specs = (state_specs,
                     ((P(), numerics.summary_specs()) if numerics is not None
                      else P()))
        return shard_map(
            local_step, mesh=mesh,
            in_specs=(state_specs, P("data") if has_data else P()),
            out_specs=out_specs,
            check_vma=False,
        )(state, tokens)

    return state, jax.jit(step, donate_argnums=(0,))


def make_tp_multi_step(cfg: LlamaConfig,
                       optimizer: optax.GradientTransformation,
                       mesh: Mesh, params, *, psa: str = "",
                       batch_shape: Optional[Tuple[int, int]] = None,
                       numerics=None):
    """Fused K-step TP driver: ``step(state, window) -> (state, losses)``
    with ``window`` a device-resident ``[K, B, T]`` token window
    (``shard_batch_window``) run in ONE compiled, donated dispatch — the
    dp.make_multi_step / pp.make_pipeline_multi_step shape carried to the
    model axis. The scanned body IS ``make_tp_step``'s
    (``_make_tp_local_step``), so the loss sequence and final params are
    BITWISE identical to K per-step calls at any K (pinned at K∈{1,4});
    per-train-step wire is unchanged — collectives record at ``scale=K``
    per dispatch and ``CommProfile.as_dict(steps_per_dispatch=K)``
    normalizes. Under ``psa="int8_ef"`` the activation EF residuals ride
    the scan carry, so error feedback is exact across fused steps.

    K is read from the window's static leading dim at trace time — one
    returned callable serves every chunk size (a tail chunk of k < K
    steps is one more legitimate compile, stamped by the trainer's
    CompileWatch)."""
    tp = mesh.shape["model"]
    has_data = mesh.shape.get("data", 1) > 1
    mode, period = _parse_psa(psa, cfg.n_layers)
    state = init_state(mesh, params, optimizer)
    res_spec = None
    if mode == "int8_ef":
        res, res_spec = _act_residual_setup(mesh, cfg, batch_shape)
        state = TPActState(state.params, state.opt_state, state.step, res)

    def step(state, window):
        state_specs = _tp_state_specs(state, mode, res_spec)

        def multi(st, win):
            local_step = _make_tp_local_step(
                cfg, optimizer, tp=tp, has_data=has_data, mode=mode,
                period=period, comm_scale=win.shape[0], numerics=numerics)
            return lax.scan(local_step, st, win)

        out_specs = (state_specs,
                     ((P(), numerics.summary_specs(stacked=True))
                      if numerics is not None else P()))
        return shard_map(
            multi, mesh=mesh,
            in_specs=(state_specs, P(None, "data") if has_data else P()),
            out_specs=out_specs,
            check_vma=False,
        )(state, window)

    return state, jax.jit(step, donate_argnums=(0,))


# --------------------------------------------- model-axis agreed numerics

def make_tp_numerics(params, mesh: Mesh, *, psum_data: bool = False):
    """In-jit numerics for the TP step bodies (``TrainConfig.
    numerics_every``, telemetry/introspect.py).

    Under TP each shard holds a SLICE of every column/row-sharded block
    leaf and a full copy of the rest, so per-group sums of squares need a
    psum over ``model`` to be global — and the replicated leaves would
    then count tp times. Fix: replicated leaves are pre-scaled by
    tp^(−1/2) before squaring (their psum then telescopes back to the
    exact single-copy value), sharded leaves pass through (their local
    squares SUM to the global), and the whole summary psums over
    ``model`` — every shard agrees on exact global stats, so the summary
    out-spec is plainly replicated.

    ``psum_data=True`` additionally agrees grad stats and the finite mask
    over ``data`` (the overlap/ring path, where local gradients differ
    per data shard — same RMS-style Σ-over-shards semantics as the DP
    drivers'); param/update stats are data-replicated either way and psum
    over ``model`` only. Extra OUTPUTS only — losses/params bitwise on vs
    off (pinned in tests/test_tp.py)."""
    from ..telemetry import introspect

    tp = mesh.shape["model"]
    base = introspect.make_summarizer(params)
    scale = tp ** -0.5
    mask = _sharded_mask(params)
    grad_axes = ("data", "model") if psum_data else ("model",)

    def _prescale(tree):
        return jax.tree.map(lambda x, s: x if s else x * scale, tree, mask)

    def summarize(params_, grads, new_params):
        s = base.summarize(_prescale(params_), _prescale(grads),
                           _prescale(new_params))
        # Raw lax collectives on purpose — observability tax, not payload
        # (the introspect.make_summarizer accounting rule).
        return introspect.NumericsSummary(
            grad_sq=lax.psum(s.grad_sq, grad_axes),
            param_sq=lax.psum(s.param_sq, ("model",)),
            update_sq=lax.psum(s.update_sq, ("model",)),
            grad_finite=lax.psum(jnp.logical_not(s.grad_finite)
                                 .astype(jnp.int32), grad_axes) == 0)

    class _TPHandle(introspect.NumericsHandle):
        def summary_specs(self, stacked: bool = False):
            """Replicated on every shard — the model-axis psums above agree
            the stats, so per-step [G] and K-scanned [K, G] leaves both
            carry the plain spec."""
            return introspect.NumericsSummary(P(), P(), P(), P())

    return _TPHandle(base.groups, base.paths, summarize)


# --------------------------------------------- DP×TP data-axis ring drivers
#
# The same composition step PP took in pp.py's overlap drivers, now on a
# (data, model) mesh: each (d, m) shard flattens its LOCAL param tree —
# the model-sharded block slices plus the model-replicated embed/head/
# norms, the same flat length on every model shard — rings the data axis
# with the compressed/overlapped machinery (compress.ring_reduce_scatter,
# int8 + EF residuals, ZeRO-1 sliced updates), and gathers fresh slices
# back. Under shard_map a collective over ``data`` runs independently per
# model coordinate, so the ring needs no model-axis awareness; the one
# cross-axis step is that model-REPLICATED leaf grads psum over ``model``
# BEFORE flattening (each model shard contributes its partial), exactly as
# the plain TP step does. Moments and EF residuals gain a model axis
# ([n_data, tp, ...], sharded P("data", "model")) because each (data,
# model) shard compensates its OWN slice's quantization error.
#
# Cross-model caveat (shared with pp.py's stage-replicated leaves under
# int8): the int8 scale is per flat chunk, and chunks mix model-sharded
# and model-replicated coordinates, so replicated coordinates can apply
# per-model-shard deltas differing by up to one int8 step — bounded by
# the per-(data, model) EF residuals, and zero under fp32/bf16 wire or
# zero1's fp32 param gather. DATA replicas stay bitwise in sync in every
# mode (everyone applies the same gathered deltas; pinned in
# tests/test_tp.py).


def _tp_flat_geometry(mesh: Mesh, params):
    """Padded flat-vector geometry of the LOCAL per-model-shard param tree
    — the unit the DP×TP data-axis zero1/ring sync operates on. Column/
    row-sharded block leaves contribute 1/tp of their elements, everything
    else its full size; every model shard's local tree has the same flat
    length, so the geometry is SPMD-consistent across the model axis.
    Returns ``(n, pad, local, total)`` with n = the ``data`` axis size and
    total = the per-model-shard param count."""
    n = mesh.shape.get("data", 1)
    tp = mesh.shape["model"]
    total = 0
    for k, v in params.items():
        if k == "blocks":
            for name, leaf in v.items():
                size = sum(int(x.size) for x in jax.tree.leaves(leaf))
                total += size // tp if (name in _COL or name in _ROW) else size
        else:
            total += sum(int(x.size) for x in jax.tree.leaves(v))
    pad = (-total) % n
    local = (total + pad) // n
    return n, pad, local, total


def _tp_bucket_map(mesh: Mesh, params, comm_buckets: int):
    """The DP×TP ``BucketMap``: ``compress.make_bucket_map`` over the
    PER-MODEL-SHARD leaf geometry (col/row block leaves at 1/tp, full
    stacked [L] layer depth) — the tree the shard_map body flattens.
    Returns None at ``comm_buckets == 1`` (the legacy path)."""
    from .compress import make_bucket_map

    if int(comm_buckets) < 1:
        raise ValueError(
            f"comm_buckets must be >= 1 (got {comm_buckets})")
    if int(comm_buckets) == 1:
        return None
    n = mesh.shape.get("data", 1)
    tp = mesh.shape["model"]

    def leaf_local(path, leaf):
        key = getattr(path[0], "key", None) if path else None
        if key == "blocks":
            name = getattr(path[1], "key", None) if len(path) > 1 else None
            size = int(leaf.size)
            if name in _COL or name in _ROW:
                size //= tp
            return size, int(leaf.shape[0])
        return int(leaf.size), None

    return make_bucket_map(params, n, comm_buckets, leaf_local=leaf_local)


def _tp_overlap_setup(optimizer, mesh: Mesh, params, wire: str,
                      aggregation: str, psa: str, n_layers: int,
                      comm_buckets: int = 1):
    """State + shard specs + flat geometry for the DP×TP overlap drivers.

    ZeRO-1 moments live as ``[n_data, tp, local]`` global arrays sharded
    ``P("data", "model")`` — each (d, m) shard owns the moments of model
    shard m's d-th flat slice; int8 EF residuals get the same layout
    (ring: ``[n, tp, n·local]``; gather: ``[n, tp, local]``).
    ``comm_buckets > 1`` (the bucketed backward, ``_tp_bucket_map``)
    turns moments and residuals into per-bucket tuples, mirroring the DP
    driver's layout rule with the (data, model) shard axes kept."""
    mode, period = _parse_psa(psa, n_layers)
    if aggregation not in ("gradient", "zero1"):
        raise ValueError("the DP×TP overlap driver supports gradient/zero1 "
                         f"aggregation only (got {aggregation!r})")
    if wire not in ("fp32", "bf16", "int8_ef"):
        raise ValueError(f"unknown wire format {wire!r}")
    if "data" not in mesh.axis_names:
        raise ValueError("the DP×TP overlap driver needs a mesh with a "
                         "'data' axis (size 1 is fine) — build it with "
                         'make_mesh({"data": d, "model": t})')
    if mesh.shape.get("dcn", 1) > 1:
        raise ValueError("the DP×TP overlap driver runs the flat data ring "
                         "only; the hierarchical (dcn x data) tier is the "
                         "DP trainer's (parallel/compress.py)")
    if mesh.shape.get("model", 1) < 2:
        raise ValueError("the DP×TP overlap driver needs model >= 2 — on a "
                         "model=1 mesh the flat DP ring driver "
                         "(parallel/compress.py) is the same machinery "
                         "without the model axis")
    if mode == "int8_ef":
        raise ValueError(
            "psa='int8_ef' × the overlap ring driver is deferred: the "
            "activation EF residual tree does not yet thread the "
            "OverlapEFState scan carry — use psa in {'', 'full', "
            "'defer:L'} with the ring, or psa='int8_ef' on the non-overlap "
            "TP factories (make_tp_step / make_tp_multi_step)")
    n, pad, local, total = _tp_flat_geometry(mesh, params)
    bm = _tp_bucket_map(mesh, params, comm_buckets)
    specs = param_specs(params)
    sharded = shard_params(mesh, params)
    step0 = jax.device_put(jnp.zeros((), jnp.int32),
                           NamedSharding(mesh, P()))
    tp = mesh.shape["model"]
    dshard = P("data", "model")
    if aggregation == "zero1":
        def _specs_for(sz):
            abstract = jax.eval_shape(
                optimizer.init, jax.ShapeDtypeStruct((sz,), jnp.float32))
            return jax.tree.map(
                lambda x: dshard if getattr(x, "ndim", 0) >= 1 else P(),
                abstract)

        opt_specs = (_specs_for(local) if bm is None else
                     tuple(_specs_for(sz) for sz in bm.sizes))

        def local_init(p):
            from ..utils import pytree as pt
            from .compress import _bucket_vectors
            if bm is None:
                flat = jnp.pad(pt.flatten(p)[0].astype(jnp.float32),
                               (0, pad))
                mine = [lax.dynamic_slice_in_dim(
                    flat, lax.axis_index("data") * local, local)]
            else:
                vecs = _bucket_vectors(bm, p)
                mine = [lax.dynamic_slice_in_dim(
                            vecs[b], lax.axis_index("data") * bm.sizes[b],
                            bm.sizes[b])
                        for b in range(bm.nbuckets)]
            # Vector leaves gain the (data, model) shard axes; scalars
            # (count) replicate — every shard steps them identically.
            opts = [jax.tree.map(
                        lambda x: (x[None, None]
                                   if getattr(x, "ndim", 0) >= 1 else x),
                        optimizer.init(m)) for m in mine]
            return opts[0] if bm is None else tuple(opts)

        opt_state = jax.jit(shard_map(
            local_init, mesh=mesh, in_specs=(specs,),
            out_specs=opt_specs, check_vma=False))(sharded)
        state = TrainState(sharded, opt_state, step0)
    else:
        from .pp import _opt_specs
        opt_state = sharded_opt_init(mesh, sharded, optimizer, specs)
        opt_specs = _opt_specs(opt_state, sharded, specs)
        state = TrainState(sharded, opt_state, step0)
    if wire == "int8_ef":
        from .compress import OverlapEFState

        def _zeros(shape):
            return jax.device_put(jnp.zeros(shape, jnp.float32),
                                  NamedSharding(mesh, dshard))

        if bm is None:
            ring_res = _zeros((n, tp, n * local))
            gather_res = _zeros((n, tp, local))
            ring_specs = gather_specs = dshard
        else:
            ring_res = tuple(_zeros((n, tp, n * sz)) for sz in bm.sizes)
            gather_res = tuple(_zeros((n, tp, sz)) for sz in bm.sizes)
            ring_specs = gather_specs = (dshard,) * bm.nbuckets
        state = OverlapEFState(state.params, state.opt_state, state.step,
                               ring_res, gather_res)
        state_specs = OverlapEFState(specs, opt_specs, P(),
                                     ring_specs, gather_specs)
    else:
        state_specs = TrainState(specs, opt_specs, P())
    return state, state_specs, n, pad, local, total, mode, period, bm


def _make_tp_overlap_local_step(cfg: LlamaConfig, optimizer, *, tp: int,
                                mode: str, period: int, n: int, pad: int,
                                local: int, total: int, microbatches: int,
                                wire: str, aggregation: str,
                                comm_scale: int = 1,
                                bucket_map=None,
                                numerics=None) -> Callable:
    """The per-shard DP×TP overlapped step body shared by
    ``make_tp_overlap_step`` and ``make_tp_overlap_multi_step`` — the
    ``_make_pp_overlap_local_step`` structure with the TP loss: the local
    batch splits into M sync-microbatches; each runs the PSA forward and
    psums its model-REPLICATED leaf grads over ``model``; microbatch m−1's
    flat gradient rides the ppermute ring over ``data`` (wire-formatted,
    per-(shard, chunk) error feedback) in the same trace positions as
    microbatch m's compute — the ACCO overlap, now under TP. Reduced
    chunks accumulate in fp32 on the owner; zero1 updates the owned slice
    and gathers fresh params (int8 delta gather under ``wire="int8_ef"``),
    gradient aggregation gathers the reduced gradient and applies the
    replicated update.

    Numerics contract mirrors the flat driver's: M>1 re-associates, so
    equivalence vs ``make_tp_step`` is fp32-tolerance; M=1 fp32 differs
    only by ring-vs-XLA reduction order.

    ``bucket_map`` (``_tp_bucket_map``, None for the legacy path) selects
    the bucketed backward: per-bucket ring vectors under labels
    ``tp_ring_grad_b{b}``, per-(data, model)-shard per-bucket EF/moment
    tuples, gather legs kept as ONE collective — the compress.py bucketing
    contract verbatim, with the model-agreed scale rule intact per
    bucket."""
    from ..utils import pytree as pt
    from .compress import (_bucket_slices, _bucket_vectors, _int8_encode,
                           _scatter_buckets, ring_reduce_scatter)

    M = microbatches
    bm = bucket_map
    B = bm.nbuckets if bm is not None else 1
    ef = wire == "int8_ef"
    # Model-agreed int8 scales (compress._int8_encode docstring): the flat
    # vector mixes model-cell-specific col/row shards with model-REPLICATED
    # leaves, so per-cell scales would decode the replicated entries
    # differently per cell and drift the model replicas apart — pinned by
    # tests/test_tp.py's replica-sync and preempt/resume tests.
    ssync = "model" if tp > 1 else None

    def _ring(pending, ring_res, bucket=None):
        label = ("tp_ring_grad" if bucket is None
                 else f"tp_ring_grad_b{bucket}")
        return ring_reduce_scatter(pending, "data", wire=wire,
                                   residual=ring_res, label=label,
                                   comm_scale=comm_scale,
                                   scale_sync_axis=ssync)

    def _ring_all(pending, ring_res):
        if bm is None:
            return _ring(pending, ring_res)
        reds, new_res = [], []
        for b in range(B):
            red_b, r_b = _ring(pending[b],
                               ring_res[b] if ef else None, b)
            reds.append(red_b)
            new_res.append(r_b)
        return jnp.concatenate(reds), new_res

    def local_step(state, tokens):
        params = state.params
        if tokens.shape[0] % M:
            raise ValueError(f"local batch {tokens.shape[0]} not divisible "
                             f"by overlap_microbatches={M}")
        micro = tokens.reshape((M, -1) + tokens.shape[1:])
        if not ef:
            ring_res = None
        elif bm is None:
            ring_res = state.ring_residual[0, 0]
        else:
            ring_res = [r[0, 0] for r in state.ring_residual]
        acc = jnp.zeros((local,), jnp.float32)
        loss_sum = jnp.zeros((), jnp.float32)
        gacc = None
        pending = None
        for m in range(M):
            (l, _), g = jax.value_and_grad(_tp_psa_loss, has_aux=True)(
                params, micro[m], cfg, tp, mode, period, None, comm_scale)
            g = jax.tree.map(
                lambda gr, s: gr if s else comm.psum(
                    gr, "model", label="tp_replicated_grads",
                    scale=comm_scale),
                g, _sharded_mask(g))
            loss_sum = loss_sum + (l * tp).astype(jnp.float32)
            if numerics is not None:
                # Extra OUTPUT only: the fp32 grad accumulator feeds the
                # summary, never the ring — losses/params bitwise on/off.
                gacc = (jax.tree.map(lambda x: x.astype(jnp.float32), g)
                        if gacc is None else
                        jax.tree.map(lambda a, b: a + b.astype(jnp.float32),
                                     gacc, g))
            if pending is not None:
                # Microbatch m−1's ring rides alongside microbatch m's
                # forward/backward (the call above): independent dataflow.
                red, ring_res = _ring_all(pending, ring_res)
                acc = acc + red
            pending = (_bucket_vectors(bm, g) if bm is not None else
                       jnp.pad(pt.flatten(g)[0].astype(jnp.float32),
                               (0, pad)))
        red, ring_res = _ring_all(pending, ring_res)
        acc = acc + red
        g_mine = acc / (n * M)      # mean over data shards and microbatches
        loss = comm.pmean(loss_sum / M, "data", label="loss_allreduce",
                          scale=comm_scale)

        raw_flat, unravel = pt.flatten(params)
        if bm is None:
            flat_p = jnp.pad(raw_flat.astype(jnp.float32), (0, pad))
            pvecs = None
        else:
            # Bucketed: per-bucket param-side flat views — the owned slice
            # is the concat of per-bucket chunks, in ring coordinate order.
            flat_p = None
            pvecs = _bucket_vectors(bm, params)
        gather_res = None
        gres = None
        if ef:
            gres = (jnp.concatenate([r[0, 0]
                                     for r in state.gather_residual])
                    if bm is not None else state.gather_residual[0, 0])
        shard = lax.axis_index("data")
        if aggregation == "zero1":
            if bm is None:
                p_mine = lax.dynamic_slice_in_dim(flat_p, shard * local,
                                                  local)
                # Local moment view: [1, 1, local] (data, model)-sharded
                # vector leaves squeeze to the flat slice; scalars pass.
                opt_local = jax.tree.map(
                    lambda x: x[0, 0] if getattr(x, "ndim", 0) >= 3 else x,
                    state.opt_state)
                new_p_mine, opt_local = apply_optimizer(optimizer, g_mine,
                                                        opt_local, p_mine)
                opt_state = jax.tree.map(
                    lambda x: (x[None, None] if getattr(x, "ndim", 0) >= 1
                               else x), opt_local)
            else:
                # One optimizer apply per bucket against the per-bucket
                # moments; elementwise updates make the concat
                # value-identical to the single-slice apply.
                p_chunks = [lax.dynamic_slice_in_dim(
                    pvecs[b], shard * bm.sizes[b], bm.sizes[b])
                    for b in range(B)]
                new_chunks, opts = [], []
                for b in range(B):
                    opt_b = jax.tree.map(
                        lambda x: (x[0, 0] if getattr(x, "ndim", 0) >= 3
                                   else x), state.opt_state[b])
                    np_b, opt_b = apply_optimizer(
                        optimizer,
                        g_mine[bm.offsets[b]:bm.offsets[b] + bm.sizes[b]],
                        opt_b, p_chunks[b])
                    new_chunks.append(np_b)
                    opts.append(jax.tree.map(
                        lambda x: (x[None, None]
                                   if getattr(x, "ndim", 0) >= 1 else x),
                        opt_b))
                p_mine = jnp.concatenate(p_chunks)
                new_p_mine = jnp.concatenate(new_chunks)
                opt_state = tuple(opts)
            vec_new = None
            if wire == "int8_ef":
                # Compressed second leg: broadcast the param DELTA int8
                # with its own EF residual (the compress.py zero1 rule —
                # fp32 moments stay exact, data replicas stay bitwise in
                # sync).
                q, s, gather_res = _int8_encode(
                    (new_p_mine - p_mine) + gres,
                    scale_sync_axis=ssync)
                q_all = comm.all_gather(q, "data", tiled=True,
                                        label="tp_delta_gather_int8",
                                        scale=comm_scale)
                s_all = comm.all_gather(s[None], "data", tiled=True,
                                        label="tp_delta_scale_gather",
                                        scale=comm_scale)
                if bm is None:
                    flat_new = flat_p + (jnp.repeat(s_all, local)
                                         * q_all.astype(jnp.float32))
                else:
                    q_slc = _bucket_slices(bm, q_all.astype(jnp.float32))
                    vec_new = [pvecs[b]
                               + jnp.repeat(s_all, bm.sizes[b]) * q_slc[b]
                               for b in range(B)]
            else:
                # bf16 wire compresses the RING leg only — the param
                # gather stays fp32 (params stay exact, compress.py rule).
                flat_new = comm.all_gather(new_p_mine, "data", tiled=True,
                                           label="tp_param_gather",
                                           scale=comm_scale)
                if bm is not None:
                    vec_new = _bucket_slices(bm, flat_new)
            if bm is None:
                new_params = unravel(
                    flat_new[:total].astype(raw_flat.dtype))
            else:
                new_params = _scatter_buckets(bm, vec_new, params)
        else:                       # replicated gradient update
            if wire == "int8_ef":
                q, s, gather_res = _int8_encode(
                    g_mine + gres, scale_sync_axis=ssync)
                q_all = comm.all_gather(q, "data", tiled=True,
                                        label="tp_grad_gather_int8",
                                        scale=comm_scale)
                s_all = comm.all_gather(s[None], "data", tiled=True,
                                        label="tp_grad_scale_gather",
                                        scale=comm_scale)
                flat_g = (jnp.repeat(s_all, local)
                          * q_all.astype(jnp.float32))
            elif wire == "bf16":
                flat_g = comm.all_gather(
                    g_mine.astype(jnp.bfloat16), "data", tiled=True,
                    label="tp_grad_gather_bf16",
                    scale=comm_scale).astype(jnp.float32)
            else:
                flat_g = comm.all_gather(g_mine, "data", tiled=True,
                                         label="tp_grad_gather",
                                         scale=comm_scale)
            if bm is None:
                grads = unravel(flat_g[:total].astype(raw_flat.dtype))
            else:
                grads = _scatter_buckets(bm, _bucket_slices(bm, flat_g),
                                         params)
            new_params, opt_state = apply_optimizer(optimizer, grads,
                                                    state.opt_state, params)
        step = state.step + 1
        if ef:
            from .compress import OverlapEFState
            if bm is not None:
                # Per-bucket storage: each bucket's stack is a contiguous
                # ordered-coordinate range (the reshard_state contract).
                ring_out = tuple(r[None, None] for r in ring_res)
                gather_out = tuple(
                    gather_res[bm.offsets[b]:bm.offsets[b] + bm.sizes[b]]
                    [None, None] for b in range(B))
            else:
                ring_out = ring_res[None, None]
                gather_out = gather_res[None, None]
            new_state = OverlapEFState(new_params, opt_state, step,
                                       ring_out, gather_out)
        else:
            new_state = TrainState(new_params, opt_state, step)
        if numerics is not None:
            summary = numerics.summarize(
                params, jax.tree.map(lambda x: x / M, gacc), new_params)
            return new_state, (loss, summary)
        return new_state, loss

    return local_step


def make_tp_overlap_step(cfg: LlamaConfig,
                         optimizer: optax.GradientTransformation,
                         mesh: Mesh, params, *,
                         aggregation: str = "zero1",
                         wire: str = "fp32",
                         overlap_microbatches: int = 1,
                         psa: str = "",
                         comm_buckets: int = 1,
                         numerics=None):
    """Per-step DP×TP composition driver: ``step(state, tokens) -> (state,
    loss)`` over a ``[n_data·B, T]`` batch sharded over ``data``, with the
    data-axis gradient sync routed through the compressed/overlapped ring
    (semantics in ``_make_tp_overlap_local_step``). Returns ``(state,
    step_fn)`` — an ``OverlapEFState`` under ``wire="int8_ef"`` (EF
    residuals in the checkpointed tree, per (data, model) shard), a plain
    TrainState otherwise, with ZeRO-1 moments sharded over
    ``(data, model)`` when ``aggregation="zero1"``. ``comm_buckets > 1``
    selects the bucketed backward (per-bucket rings inside each
    microbatch's VJP window; compress.py contract)."""
    (state, state_specs, n, pad, local, total, mode, period,
     bm) = _tp_overlap_setup(optimizer, mesh, params, wire,
                             aggregation, psa, cfg.n_layers,
                             comm_buckets=comm_buckets)
    tp = mesh.shape["model"]
    has_data = mesh.shape.get("data", 1) > 1
    local_step = _make_tp_overlap_local_step(
        cfg, optimizer, tp=tp, mode=mode, period=period, n=n, pad=pad,
        local=local, total=total, microbatches=overlap_microbatches,
        wire=wire, aggregation=aggregation, bucket_map=bm,
        numerics=numerics)
    out_specs = (state_specs,
                 ((P(), numerics.summary_specs()) if numerics is not None
                  else P()))
    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(state_specs, P("data") if has_data else P()),
        out_specs=out_specs, check_vma=False)
    return state, jax.jit(sharded, donate_argnums=(0,))


def make_tp_overlap_multi_step(cfg: LlamaConfig,
                               optimizer: optax.GradientTransformation,
                               mesh: Mesh, params, *,
                               aggregation: str = "zero1",
                               wire: str = "fp32",
                               overlap_microbatches: int = 1,
                               psa: str = "",
                               comm_buckets: int = 1,
                               numerics=None):
    """The DP×TP composition driver inside the K-step scan: ``step(state,
    window) -> (state, losses)`` with ``window`` a ``[K, n_data·B, T]``
    batch window (``shard_batch_window``) run in ONE compiled, donated
    dispatch — ZeRO-1 moments AND int8 EF residuals ride the scan carry,
    so error feedback is exact across fused steps, chunk-edge checkpoints
    and a preempt/resume cycle (pinned in tests/test_tp.py). The scanned
    body IS ``make_tp_overlap_step``'s, so the loss sequence and final
    state are bitwise-identical to K per-step calls at any K."""
    (state, state_specs, n, pad, local, total, mode, period,
     bm) = _tp_overlap_setup(optimizer, mesh, params, wire,
                             aggregation, psa, cfg.n_layers,
                             comm_buckets=comm_buckets)
    tp = mesh.shape["model"]
    has_data = mesh.shape.get("data", 1) > 1

    def multi(st, window):
        local_step = _make_tp_overlap_local_step(
            cfg, optimizer, tp=tp, mode=mode, period=period, n=n, pad=pad,
            local=local, total=total, microbatches=overlap_microbatches,
            wire=wire, aggregation=aggregation, bucket_map=bm,
            comm_scale=window.shape[0], numerics=numerics)
        return lax.scan(local_step, st, window)

    out_specs = (state_specs,
                 ((P(), numerics.summary_specs(stacked=True))
                  if numerics is not None else P()))
    sharded = shard_map(
        multi, mesh=mesh,
        in_specs=(state_specs, P(None, "data") if has_data else P()),
        out_specs=out_specs, check_vma=False)
    return state, jax.jit(sharded, donate_argnums=(0,))


def shard_batch_window(mesh: Mesh, window) -> jax.Array:
    """Device-put a [K, B, T] host batch window for the fused TP drivers:
    leading axis = K consecutive steps (replicated — every shard scans the
    same step sequence), second axis sharded over ``data`` when the mesh
    carries a real data axis (a size-1 axis normalizes to the replicated
    spec — the dp.data_partition jit-cache-stability rule); the ``model``
    axis never shards the batch (every TP shard sees the full local
    batch)."""
    spec = P(None, "data") if mesh.shape.get("data", 1) > 1 else P()
    return jax.device_put(window, NamedSharding(mesh, spec))


from .mesh import shard_batch  # noqa: E402,F401  (shared batch placement)
