"""Data parallelism: one SPMD train step over a ``data`` mesh axis.

Capability target: the reference's two DP variants —
- gradient aggregation: per-iter allreduce of flattened grads then avg+step
  (reference: lab/tutorial_1b/DP/gradient_aggr/intro_DP_GA.py:41-68);
- weight aggregation: step first, then allreduce and average the *weights*
  (intro_DP_WA.py:41-67; the reference script never writes the averaged
  weights back — a recorded bug. We implement the intended semantics.)

TPU-native shape: the barrier/flatten/all_reduce/unflatten/scale dance
(intro_DP_GA.py:53-66) collapses to ``lax.pmean(grads, "data")`` inside a
``shard_map`` — the collective lowers to one XLA all-reduce over ICI, fused
with the step. No CPU staging, no sockets, no tags.

Hot-path fusion (the headline-bench lever): ``make_multi_step`` /
``make_zero1_multi_step`` scan K steps over a device-resident
``[K, B, T]`` batch window inside ONE compiled, donated dispatch — the
per-step Python dispatch/donation overhead (dominant on the oversubscribed
CPU fallback, measurable on accelerators) is paid once per K steps, and the
per-step loss history comes back as the scan's stacked output instead of K
host round trips. Semantics are bit-identical to K calls of the per-step
factory (asserted in tests/test_dp.py). Pattern references: weight-update
sharding (Xu et al., arxiv 2004.13336) and accumulate-while-you-communicate
overlap (ACCO, arxiv 2406.02613) — PAPERS.md.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.adam import apply_optimizer  # noqa: F401  (canonical home moved;
#                                         re-exported for existing callers)
from ..telemetry import comm
from ._compat import shard_map


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def init_state(params, optimizer: optax.GradientTransformation) -> TrainState:
    return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))


def sharded_opt_init(mesh: Mesh, params, optimizer: optax.GradientTransformation,
                     param_specs):
    """``optimizer.init`` with the optimizer state placed CORRECTLY on the
    mesh: moment subtrees (anything tree-isomorphic to params, e.g. adam's
    mu/nu) inherit the param PartitionSpecs; scalars (count) replicate.

    Plain ``jax.jit(optimizer.init)(params)`` does NOT do this — absent
    out_shardings it commits every output to one device, silently wasting
    HBM on what should be sharded moments.
    """
    pstruct = jax.tree.structure(params)

    def is_params_like(node):
        try:
            return jax.tree.structure(node) == pstruct
        except Exception:
            return False

    def shard_of(node):
        if is_params_like(node):
            return jax.tree.map(lambda s: NamedSharding(mesh, s), param_specs)
        return jax.tree.map(lambda _: NamedSharding(mesh, P()), node)

    abstract = jax.eval_shape(optimizer.init, params)
    out_shardings = jax.tree.map(shard_of, abstract, is_leaf=is_params_like)
    return jax.jit(optimizer.init, out_shardings=out_shardings)(params)


def _require_flat_data_mesh(mesh: Mesh, what: str) -> None:
    """The per-step dp factories reduce over the ``data`` axis only: on a
    hierarchical (dcn × data) mesh their pmean/scatter would aggregate
    within islands and silently never cross DCN. Hard error with the
    pointer to the composing path (compress.make_overlap_* with a per-axis
    wire dict) — the hierarchical collective layer is the one that knows
    the two-tier topology."""
    if mesh.shape.get("dcn", 1) > 1:
        raise ValueError(
            f"{what} reduces over the 'data' axis only and would silently "
            "aggregate per-island on a hierarchical (dcn x data) mesh; "
            "use the two-level ring driver (parallel/compress.py "
            "make_overlap_step / make_overlap_multi_step with "
            'wire={"ici": ..., "dcn": ...})')


def _make_local_grad_step(loss_fn: Callable, optimizer, accum_steps: int,
                          guard_nonfinite: bool, comm_scale: int = 1,
                          numerics=None) -> Callable:
    """The per-shard gradient-aggregation step body shared by the per-step
    factory (``make_grad_aggregation_step``) and the K-step scan driver
    (``make_multi_step``) — one implementation, so the two cannot drift.

    ``comm_scale`` is the telemetry execution multiplier: inside a
    ``lax.scan`` body the collectives trace once but run ``K`` times per
    dispatch, and the comm wrappers record that trip count so the static
    wire-byte profile stays exact (telemetry/comm.py ``scale``).

    ``numerics`` (telemetry.introspect.NumericsHandle) turns on the
    in-jit run-health summary: the step's second output becomes
    ``(loss, NumericsSummary)`` — per-layer-group grad/param/update norms
    plus the per-leaf gradient finite mask, computed from values the step
    already holds. Extra OUTPUTS never perturb the existing computation,
    so losses/params are bitwise identical with the summary on or off
    (pinned in tests/test_introspect.py). On THIS (replicated-gradient)
    path the summary reflects the ATTEMPTED update — under
    ``guard_nonfinite`` a skipped step still reports the norms/finite-mask
    of the update it refused (the zero1 body differs; see
    ``_make_zero1_local_step``)."""

    def local_step(state: TrainState, batch) -> Tuple[TrainState, jnp.ndarray]:
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        else:
            micro = batch.reshape((accum_steps, -1) + batch.shape[1:])

            def body(carry, mb):
                loss_sum, gsum = carry
                l, g = jax.value_and_grad(loss_fn)(state.params, mb)
                # Accumulate in fp32 regardless of param/grad dtype: a bf16
                # running sum would round away small microbatch
                # contributions (the vanishing-accumulation failure mode
                # ops/mixed_precision.py exists to fix).
                gsum = jax.tree.map(
                    lambda a, b: a + b.astype(jnp.float32), gsum, g)
                return (loss_sum + l.astype(jnp.float32), gsum), None

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
            (loss, gsum), _ = lax.scan(body, (jnp.zeros(()), zeros), micro)
            loss = loss / accum_steps
            grads = jax.tree.map(
                lambda g, p: (g / accum_steps).astype(p.dtype),
                gsum, state.params)
        # The one payload collective per iter (telemetry.comm wrappers are
        # lax pass-throughs that record bytes at trace time — see
        # telemetry/comm.py; compiled HLO is unchanged).
        grads = comm.pmean(grads, "data", label="grad_allreduce",
                           scale=comm_scale)
        loss = comm.pmean(loss, "data", label="loss_allreduce",
                          scale=comm_scale)
        params, opt_state = apply_optimizer(optimizer, grads,
                                            state.opt_state, state.params)
        summary = (numerics.summarize(state.params, grads, params)
                   if numerics is not None else None)
        if guard_nonfinite:
            ok = jnp.isfinite(loss)
            for g in jax.tree.leaves(grads):
                ok &= jnp.all(jnp.isfinite(g))
            # Select-back, not zeroed grads: a zero-grad optimizer update
            # still decays Adam moments and bumps count — only keeping the
            # incoming state makes the skip a true no-op.
            params = jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                                  params, state.params)
            opt_state = jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                                     opt_state, state.opt_state)
            new_state = TrainState(params, opt_state,
                                   state.step + ok.astype(state.step.dtype))
        else:
            new_state = TrainState(params, opt_state, state.step + 1)
        return new_state, ((loss, summary) if summary is not None else loss)

    return local_step


def make_grad_aggregation_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                               mesh: Mesh, accum_steps: int = 1,
                               guard_nonfinite: bool = False,
                               numerics=None) -> Callable:
    """jit-compiled SPMD step: local grads -> pmean over ``data`` -> update.

    ``loss_fn(params, batch) -> scalar``. The batch's leading axis is sharded
    over ``data``; params/opt state are replicated and stay bitwise-identical
    across shards because every shard applies the same averaged gradient.

    ``accum_steps > 1`` enables gradient accumulation: each shard's local
    batch is split into ``accum_steps`` microbatches scanned sequentially,
    their gradients averaged before the ONE pmean + update — an
    ``accum_steps``-times larger effective batch at one microbatch's
    activation memory, with unchanged collective traffic. The local batch's
    leading dim must divide evenly. Equivalent to the full-batch step up to
    float re-association (asserted in tests/test_dp.py).

    ``guard_nonfinite=True`` fuses a post-allreduce finiteness guard into
    the step (resilience layer): if the *averaged* gradient or loss carries
    a NaN/Inf — one poisoned shard poisons the pmean for everyone, which is
    exactly why the check sits after the collective — the update is a
    select-back to the incoming params/opt state and ``step`` does not
    advance. Zero host syncs and donation-safe (the select happens inside
    the jitted program), so it composes with compressed-wire and accum
    variants of the surrounding loop; the skipped step is visible to the
    host as the returned non-finite loss and the non-advancing ``step``.
    The host-side StepGuard (resilience/guard.py) layers EMA anomaly
    detection and checkpoint rollback on top when those are wanted.

    ``numerics`` (see ``_make_local_grad_step``) changes the second
    output to ``(loss, NumericsSummary)`` — replicated, computed from the
    post-pmean gradient, bitwise-free for losses/params.
    """
    _require_flat_data_mesh(mesh, "make_grad_aggregation_step")
    local_step = _make_local_grad_step(loss_fn, optimizer, accum_steps,
                                       guard_nonfinite, numerics=numerics)
    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=(P(), P()),
        check_vma=False,  # optax state carries non-vma-tracked leaves
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_multi_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                    mesh: Mesh, accum_steps: int = 1,
                    guard_nonfinite: bool = False, numerics=None) -> Callable:
    """Fused K-step driver: ``step(state, window) -> (state, losses)`` where
    ``window`` is a device-resident ``[K, n_shards·B, T]`` batch window
    (leading axis = consecutive training steps, second axis sharded over
    ``data`` — ``shard_batch_window``) and ``losses`` is the ``[K]``
    per-step loss sequence from the scan's stacked outputs.

    One compiled, donated dispatch runs all K steps: Python dispatch,
    donation bookkeeping and the host round trip are paid once per window
    instead of once per step. The scanned body IS
    ``make_grad_aggregation_step``'s body (shared ``_make_local_grad_step``),
    so the loss sequence and final state are bit-identical to K per-step
    calls (asserted in tests/test_dp.py at K∈{1,4}), and per-step wire
    bytes are unchanged — the comm profile records the same collectives at
    ``scale=K`` per dispatch.

    K is read from the window's static leading dim at trace time, so ONE
    returned callable serves every chunk size (a tail chunk of k < K steps
    just triggers one more compile for that shape).
    """

    _require_flat_data_mesh(mesh, "make_multi_step")

    def multi(state: TrainState, window):
        local_step = _make_local_grad_step(loss_fn, optimizer, accum_steps,
                                           guard_nonfinite,
                                           comm_scale=window.shape[0],
                                           numerics=numerics)
        return lax.scan(local_step, state, window)

    sharded = shard_map(
        multi,
        mesh=mesh,
        in_specs=(P(), P(None, "data")),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_weight_aggregation_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                                 mesh: Mesh) -> Callable:
    """Step locally on the local shard's gradient, then average the *weights*
    across shards — the reference's intro_DP_WA semantics, implemented as the
    intended average-in-place (not its no-op bug)."""
    _require_flat_data_mesh(mesh, "make_weight_aggregation_step")

    def local_step(state: TrainState, batch) -> Tuple[TrainState, jnp.ndarray]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        params = comm.pmean(params, "data", label="weight_allreduce")
        # Average the optimizer moments too: the reference keeps per-process
        # Adam state, but an SPMD TrainState declared replicated must BE
        # replicated — divergent per-shard moments would silently collapse to
        # shard 0's on any reshard/checkpoint. Documented deviation.
        opt_state = comm.pmean(opt_state, "data", label="optstate_allreduce")
        loss = comm.pmean(loss, "data", label="loss_allreduce")
        return TrainState(params, opt_state, state.step + 1), loss

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def data_axes(mesh: Mesh):
    """The mesh axes that together form the data-parallel world, outermost
    first: ``("dcn", "data")`` on a hierarchical mesh
    (parallel/distributed.py:hier_data_mesh — ICI islands bridged by DCN),
    ``("data",)`` otherwise. Every batch-sharding helper and the
    hierarchical collective layer (parallel/compress.py) read the topology
    through this one function, so flat and two-tier meshes cannot drift."""
    if mesh.shape.get("dcn", 1) > 1:
        return ("dcn", "data")
    return ("data",)


def data_partition(mesh: Mesh):
    """The PartitionSpec ENTRY for a dim sharded over the data world,
    normalized for jit-cache stability: a bare axis name when one axis
    carries the sharding, a tuple only when both hierarchical axes are
    real (size > 1). Sharding over a size-1 axis is a placement no-op,
    but the un-normalized spec survives into the state's sharding and
    differs from what shard_map's outputs report — the donated state
    would then miss the jit cache on its SECOND dispatch (one silent
    retrace per driver, caught by the comm_wire_smoke retrace gate)."""
    axes = data_axes(mesh)
    if len(axes) == 1:
        return axes[0]
    axes = tuple(a for a in axes if mesh.shape[a] > 1)
    return axes if len(axes) > 1 else axes[0]


def _flat_geometry(mesh: Mesh, params):
    """Padded flat-vector geometry shared by ZeRO-1 and the overlapped ring
    driver (parallel/compress.py): ``(n, pad, local, total)`` — n = the
    data-parallel world size (the ``data`` axis, × the ``dcn`` axis on a
    hierarchical mesh), total = the param count, pad brings it to a
    multiple of n, local = (total + pad) // n = one shard's slice (and one
    ring chunk). One implementation so the slice a ring chunk lands on is
    always the slice the ZeRO-1 update owns."""
    from ..utils import pytree as pt

    n = 1
    for a in data_axes(mesh):
        n *= mesh.shape[a]
    total = pt.param_count(params)
    pad = (-total) % n
    local = (total + pad) // n
    return n, pad, local, total


def hier_slice_index(n_dcn: int):
    """The hierarchical slice-ownership map, trace-time inside
    ``shard_map``: shard (d, s) owns flat slice ``s·D + d`` — the slice
    the two-level reduce-scatter's chunk lands on (phase 1 over the ICI
    ``data`` axis scatters superchunk s, phase 2 over ``dcn`` scatters
    chunk d within it; see compress.hier_reduce_scatter). THE one rule —
    the ZeRO-1 setup and the ring drivers both call it, so the reduced
    chunk always lands on the shard whose update owns it."""
    return lax.axis_index("data") * n_dcn + lax.axis_index("dcn")


def slice_index(mesh: Mesh):
    """This shard's slice of the padded flat param vector (trace-time,
    must run inside ``shard_map``): the ``data`` rank on a flat mesh,
    ``hier_slice_index`` on a hierarchical one. On a mesh that also
    carries a ``stage`` axis the same data-rank ownership map applies
    PER STAGE GROUP — the DP×PP drivers (parallel/pp.py
    ``_pp_overlap_setup``) read ``lax.axis_index("data")`` directly and
    shard their moments/residuals ``(data, stage)``, each stage's shard
    group owning its own stage slice's 1/n."""
    axes = data_axes(mesh)
    if len(axes) == 1:
        return lax.axis_index(axes[0])
    return hier_slice_index(mesh.shape["dcn"])


def _zero1_setup(optimizer, mesh: Mesh, params):
    """Shared ZeRO-1 initialization: the padded flat-vector geometry, the
    local-slice optimizer PartitionSpecs, and the initial TrainState with
    moments sharded over the data-parallel world (each shard owns the
    moments of its 1/n slice — the ``sharded_opt_init`` placement idea
    taken one step further, from "moments on the right devices" to "each
    device holds only its slice"; on a hierarchical mesh the slice is the
    one ``slice_index`` assigns). Returns ``(state, opt_specs, n, pad,
    local, total)``. The DP×PP generalization — the same geometry per
    STAGE slice, moments ``[n, S, local]`` sharded ``(data, stage)`` —
    lives in parallel/pp.py ``_pp_overlap_setup``."""
    from ..utils import pytree as pt

    dpart = data_partition(mesh)
    n, pad, local, total = _flat_geometry(mesh, params)

    # PartitionSpecs for the local-slice optimizer state: vector leaves
    # (mu/nu, [local]) shard over the data world; scalars (count)
    # replicate — every shard steps them identically.
    abstract_opt = jax.eval_shape(
        optimizer.init, jax.ShapeDtypeStruct((local,), jnp.float32))
    opt_specs = jax.tree.map(
        lambda x: P(dpart) if getattr(x, "ndim", 0) >= 1 else P(),
        abstract_opt)

    def local_init(params):
        # Each shard owns moments for its slice of the padded flat vector.
        shard = slice_index(mesh)
        flat = jnp.pad(pt.flatten(params)[0].astype(jnp.float32), (0, pad))
        mine = lax.dynamic_slice_in_dim(flat, shard * local, local)
        return optimizer.init(mine)

    opt_state = jax.jit(shard_map(
        local_init, mesh=mesh, in_specs=P(),
        out_specs=opt_specs, check_vma=False))(params)
    state = TrainState(replicate(mesh, params), opt_state,
                       jax.device_put(jnp.zeros((), jnp.int32),
                                      NamedSharding(mesh, P())))
    return state, opt_specs, n, pad, local, total


def _make_zero1_local_step(loss_fn: Callable, optimizer, n: int, pad: int,
                           local: int, total: int, *,
                           guard_nonfinite: bool = False,
                           comm_scale: int = 1, numerics=None) -> Callable:
    """The per-shard ZeRO-1 step body shared by ``make_zero1_step`` and
    ``make_zero1_multi_step``: local grads → reduce-scatter (each shard
    receives the averaged 1/n-th of the flat gradient) → optimizer update on
    the LOCAL slice only → all-gather of the fresh parameter slices.

    ``guard_nonfinite`` needs one extra (4-byte) collective here, unlike the
    replicated path: a NaN in shard j's gradient contribution lands only in
    the slice coordinates whose owner summed it, so the finiteness verdict
    is per-shard and must be psum-agreed before anyone applies an update —
    otherwise the replicas' "replicated" params would silently diverge."""

    def local_step(state: TrainState, batch):
        from ..utils import pytree as pt

        params = state.params
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        flat_g = jnp.pad(pt.flatten(grads)[0].astype(jnp.float32), (0, pad))
        # Averaged 1/n-th of the gradient lands on its owner shard.
        g_mine = comm.psum_scatter(flat_g, "data", scatter_dimension=0,
                                   tiled=True, label="zero1_grad_scatter",
                                   scale=comm_scale) / n
        raw_flat, unravel = pt.flatten(params)
        flat_p = jnp.pad(raw_flat.astype(jnp.float32), (0, pad))
        shard = lax.axis_index("data")
        p_mine = lax.dynamic_slice_in_dim(flat_p, shard * local, local)
        new_p_mine, opt_state = apply_optimizer(optimizer, g_mine,
                                                state.opt_state, p_mine)
        loss = comm.pmean(loss, "data", label="loss_allreduce",
                          scale=comm_scale)
        if guard_nonfinite:
            ok = jnp.all(jnp.isfinite(g_mine)) & jnp.isfinite(loss)
            ok = comm.psum(ok.astype(jnp.int32), "data",
                           label="zero1_guard_verdict",
                           scale=comm_scale) == n
            new_p_mine = jnp.where(ok, new_p_mine, p_mine)
            opt_state = jax.tree.map(lambda nw, o: jnp.where(ok, nw, o),
                                     opt_state, state.opt_state)
            step = state.step + ok.astype(state.step.dtype)
        else:
            step = state.step + 1
        flat_new = comm.all_gather(new_p_mine, "data", tiled=True,
                                   label="zero1_param_gather",
                                   scale=comm_scale)[:total]
        # Cast back before unravel: for single-dtype trees ravel_pytree's
        # unravel is dtype-polymorphic and would silently rebuild non-fp32
        # params (e.g. param_dtype="bfloat16") as fp32.
        new_params = unravel(flat_new.astype(raw_flat.dtype))
        if numerics is not None:
            # Built with psum_axis="data": the LOCAL grads differ per
            # shard, so the summarizer psum-agrees the grad stats + finite
            # mask inside this same dispatch (introspect.make_summarizer).
            # Under ``guard_nonfinite`` the summary here describes the
            # POST-guard state (a skipped step reports update ≈ 0) — the
            # attempted update's magnitude would cost a second all-gather
            # of the unselected slices; the grad norms and finite mask
            # still describe the FAULTED gradient, which is the
            # attribution a postmortem needs. The replicated-gradient
            # path reports the attempted update (no extra wire there).
            summary = numerics.summarize(params, grads, new_params)
            return TrainState(new_params, opt_state, step), (loss, summary)
        return TrainState(new_params, opt_state, step), loss

    return local_step


def make_zero1_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                    mesh: Mesh, params, *,
                    guard_nonfinite: bool = False,
                    numerics=None) -> Tuple[TrainState, Callable]:
    """ZeRO-1 data parallelism: optimizer state sharded across the ``data``
    axis (parity-plus — SURVEY.md §2.10 marks ZeRO/FSDP absent in the
    reference; pattern reference: "Automatic Cross-Replica Sharding of
    Weight Update in Data-Parallel Training", arxiv 2004.13336, PAPERS.md).

    Per step, on each shard: local grads → ``lax.psum_scatter`` (averaged
    1/n-th of the flattened gradient, half the allreduce's wire volume for
    this leg) → optimizer update on the LOCAL moment slice only →
    ``lax.all_gather`` of the updated parameter slice. Params stay
    replicated; Adam's mu/nu shrink to 1/n per device — the memory that
    caps model size under plain DP — and the update FLOPs drop n× with
    them. Ring wire bytes stay at allreduce parity: scatter ``(n−1)/n`` +
    gather ``(n−1)``·(1/n local shard) ≈ allreduce's ``2(n−1)/n`` —
    verified against the telemetry comm profile in tests/test_dp.py.

    Exact-equivalence caveat: valid for elementwise optimizers (sgd, adam,
    adamw, ...) whose update at coordinate i depends only on history at i —
    slicing commutes with the update rule (ops/adam.py), so the result is
    bit-comparable to ``make_grad_aggregation_step`` (asserted in
    tests/test_dp.py). The update goes through ``apply_optimizer``, so the
    fused-apply fast path (ops/pallas_adam.py) works on the slice too.

    ``guard_nonfinite`` fuses the in-jit skip guard, at the cost of one
    4-byte psum per step (see ``_make_zero1_local_step``).

    Returns ``(state, step_fn)`` — the initial TrainState with sharded
    moments, and ``step_fn(state, batch) -> (state, loss)``.

    Transient-memory note: each step ravels the replicated params/grads into
    one padded fp32 vector before the scatter — a ~2·|params| fp32 transient
    per device. The *persistent* saving (moments at 1/n, the 2/3 of Adam
    state that caps model size) is what ZeRO-1 is for; a fully flat-resident
    params layout would trade API simplicity for removing the transient.
    """
    _require_flat_data_mesh(mesh, "make_zero1_step")
    state, opt_specs, n, pad, local, total = _zero1_setup(optimizer, mesh,
                                                          params)
    local_step = _make_zero1_local_step(loss_fn, optimizer, n, pad, local,
                                        total,
                                        guard_nonfinite=guard_nonfinite,
                                        numerics=numerics)
    step = shard_map(
        local_step, mesh=mesh,
        in_specs=(TrainState(P(), opt_specs, P()), P("data")),
        out_specs=(TrainState(P(), opt_specs, P()), P()),
        check_vma=False)
    return state, jax.jit(step, donate_argnums=(0,))


def make_zero1_multi_step(loss_fn: Callable,
                          optimizer: optax.GradientTransformation,
                          mesh: Mesh, params, *,
                          guard_nonfinite: bool = False, numerics=None
                          ) -> Tuple[TrainState, Callable]:
    """The two hot-path levers composed: the ZeRO-1 sharded weight update
    *inside* the K-step scan driver. ``step(state, window) -> (state,
    losses)`` with ``window`` a ``[K, n_shards·B, T]`` batch window
    (``shard_batch_window``) — one donated dispatch runs K full
    reduce-scatter → sliced-update → all-gather steps, moments staying
    sharded in the scan carry throughout. Same equivalence contract as
    ``make_zero1_step`` (fp32-tolerance vs the replicated update), same
    per-step wire bytes (comm profile records ``scale=K``)."""
    _require_flat_data_mesh(mesh, "make_zero1_multi_step")
    state, opt_specs, n, pad, local, total = _zero1_setup(optimizer, mesh,
                                                          params)

    def multi(state: TrainState, window):
        local_step = _make_zero1_local_step(
            loss_fn, optimizer, n, pad, local, total,
            guard_nonfinite=guard_nonfinite, comm_scale=window.shape[0],
            numerics=numerics)
        return lax.scan(local_step, state, window)

    step = shard_map(
        multi, mesh=mesh,
        in_specs=(TrainState(P(), opt_specs, P()), P(None, "data")),
        out_specs=(TrainState(P(), opt_specs, P()), P()),
        check_vma=False)
    return state, jax.jit(step, donate_argnums=(0,))


def reshard_state(host_state, template_state):
    """Cross-topology state resharding: place a host-RAM TrainState snapshot
    (numpy leaves — e.g. an elastic controller's last-good mirror, or a
    checkpoint restored at its saved shapes) into ``template_state``'s
    layout, which may live on a DIFFERENT-SIZE mesh than the snapshot was
    taken on.

    Leaf rule: equal shapes re-place as-is into the template's sharding
    (replicated params land on every survivor; scalars replicate); a flat
    vector whose length differs is an N-way ZeRO-1 padded slice stack
    (params/mu/nu over the old ``data`` axis) and is resized to the M-way
    padded length via ``ops.adam.resize_zero_padded`` — the
    all-gather-then-rescatter: the host copy IS the gather, the resize
    swaps the pad, and the ``device_put`` against the template's
    ``P("data")`` sharding is the rescatter. Zero-pad-tail violations are
    a hard error there, not silent truncation.

    ``OverlapEFState`` snapshots (the int8-ring drivers) reshard too: the
    1-D ``gather_residual`` [Ppad] is pad-swapped by the flat-vector rule
    above (pad coordinates carry zero error — quantizing an exactly-zero
    pad is exact — so the zero-tail check holds), and the 2-D
    ``ring_residual`` [n, ring_len] goes through ``_resize_ring_residual``
    row-wise before the leaf pass. That is what lets elastic mode compose
    with compressed wire (ROADMAP 7c).

    Bucketed snapshots (``comm_buckets > 1``: both EF residual fields are
    per-bucket TUPLES) reshard bucket-by-bucket. Bucket counts must match
    between snapshot and template (rebucketing a live EF state is
    undefined — the residuals are per-coordinate pending corrections in
    bucket coordinate order). Every bucket except the last covers a FIXED
    span of flat coordinates (the global pad rides the last bucket), so a
    world resize is representable only when the new ``(world, buckets)``
    pair reproduces the interior bucket spans; otherwise the named
    "indivisible bucket×shard factorization" error fires — resize through
    ``comm_buckets=1``, or pick a divisible pair. Interior-span-preserving
    resizes run ``_resize_ring_residual`` per bucket (rows re-chunk, last
    bucket pad-swaps) and the per-bucket 1-D gather residuals fall through
    to the flat-vector leaf rule.

    Multi-axis templates route through dedicated pre-passes before the
    leaf rule:

    - a template living on a mesh WITH a ``stage`` axis is a DP×PP
      overlap state — ``pp.repartition_stage_state`` rewrites the
      ``(data, stage)`` stacks (ZeRO-1 moments, ring/gather EF residuals)
      through topology-invariant global coordinate ids, handling stage
      re-partition S→S′, data resize, or both at once. That pre-pass
      REPLACES the flat-ring pre-pass below (the PP residuals are 3-D
      ``[n, S, ·]`` stacks, not flat rings) and leaves every stack at the
      template's exact shape, so the leaf rule is placement-only.
    - a ``TPActState`` snapshot (the PSA activation-EF trainer) resizes
      its ``act_residual`` ``[n_data, tp, L, 2, B, T, D]`` across a
      data-axis resize by the row rule of ``_resize_ring_residual``:
      per-shard batch is fixed, so surviving data rows copy bitwise,
      new rows start at zero pending error, dropped rows die with their
      shards. Any non-``data`` dimension changing is a named error.

    Value-exact by construction: every surviving coordinate is a bitwise
    copy, so a trajectory continued from the resharded state is the
    trajectory of a fresh M-way run initialized from the same snapshot
    (asserted in tests/test_elastic.py)."""
    from ..ops.adam import resize_zero_padded

    t_arrays = [x for x in jax.tree.leaves(template_state)
                if isinstance(x, jax.Array)]
    t_mesh = t_arrays[0].sharding.mesh if t_arrays else None
    on_stage_mesh = (t_mesh is not None
                     and "stage" in getattr(t_mesh, "axis_names", ()))
    if on_stage_mesh:
        from . import pp as _pp
        host_state = _pp.repartition_stage_state(host_state, template_state)

    if hasattr(host_state, "act_residual") and hasattr(
            template_state, "act_residual"):
        host_state = host_state._replace(
            act_residual=_resize_act_residual(
                np.asarray(host_state.act_residual),
                tuple(template_state.act_residual.shape)))

    if (not on_stage_mesh
            and hasattr(host_state, "ring_residual")
            and hasattr(template_state, "ring_residual")):
        h_rr = host_state.ring_residual
        t_rr = template_state.ring_residual
        h_tup, t_tup = isinstance(h_rr, tuple), isinstance(t_rr, tuple)
        if h_tup != t_tup or (h_tup and len(h_rr) != len(t_rr)):
            raise ValueError(
                f"comm_buckets mismatch: the snapshot carries "
                f"{len(h_rr) if h_tup else 1} EF residual bucket(s), the "
                f"template {len(t_rr) if t_tup else 1} — rebucketing a "
                f"live EF state is not defined; rebuild the trainer with "
                f"the snapshot's comm_buckets")
        if h_tup:
            for b, (h, t) in enumerate(zip(h_rr[:-1], t_rr[:-1])):
                if int(np.asarray(h).shape[-1]) != int(t.shape[-1]):
                    raise ValueError(
                        f"indivisible bucket×shard factorization: "
                        f"interior bucket {b} covers "
                        f"{int(np.asarray(h).shape[-1])} coordinates in "
                        f"the snapshot but {int(t.shape[-1])} in the "
                        f"template — bucket boundaries move with the data "
                        f"world unless the per-shard slice divides "
                        f"evenly; resize via comm_buckets=1 or choose a "
                        f"(world, comm_buckets) pair that preserves the "
                        f"interior bucket spans")
            host_state = host_state._replace(ring_residual=tuple(
                _resize_ring_residual(np.asarray(h), tuple(t.shape))
                for h, t in zip(h_rr, t_rr)))
        else:
            host_state = host_state._replace(
                ring_residual=_resize_ring_residual(
                    np.asarray(h_rr), tuple(t_rr.shape)))

    def leaf(h, t):
        if not isinstance(t, jax.Array):
            return h
        h = np.asarray(h)
        if h.shape != t.shape:
            h = resize_zero_padded(h, t.shape[0] if t.ndim == 1 else -1)
        return jax.device_put(h, t.sharding)

    return jax.tree.map(leaf, host_state, template_state)


def _resize_ring_residual(h: np.ndarray, new_shape) -> np.ndarray:
    """Resize an int8-ring EF ``ring_residual`` [n_old, ring_len_old] to a
    new data-parallel world's [n_new, ring_len_new] — the per-(shard,chunk)
    generalization of ``resize_zero_padded``'s pad swap.

    Row r is shard r's per-coordinate pending quantization error over the
    flat padded vector, so each surviving row pad-swaps exactly like a
    ZeRO-1 moment slice stack (tail coordinates sit in the zero pad, where
    quantization error is exactly zero — nonzero tails hard-error, same
    contract). New rows (grow) start at zero error like a fresh shard's.
    Each row's OWN-chunk slice is re-zeroed in the NEW geometry: the owner
    never quantizes its own chunk (its contribution is added in fp32), so
    the slot is structurally zero — but the chunk boundaries moved with
    ``n``, and coordinates that used to belong to another shard's chunk may
    land in the own-chunk slot carrying old error the ring would never
    read or clear.

    Dropped rows (shrink) carry the dead shards' pending corrections —
    bounded by one int8 quantization step per coordinate — and are lost
    with the topology, exactly as the dead shards' unsent partials are.
    Both recovery paths (mirror and checkpoint) route through here, so the
    post-remesh trajectory still bitwise-matches a fresh run restored from
    the same snapshot."""
    from ..ops.adam import resize_zero_padded

    n_new, len_new = int(new_shape[0]), int(new_shape[1])
    n_old, _ = h.shape
    if len_new % n_new:
        raise ValueError(f"ring_len {len_new} is not a multiple of the "
                         f"data world {n_new} — not a flat-ring residual")
    local_new = len_new // n_new
    out = np.zeros((n_new, len_new), h.dtype)
    for r in range(min(n_old, n_new)):
        out[r] = resize_zero_padded(np.asarray(h[r]), len_new)
        out[r, r * local_new:(r + 1) * local_new] = 0.0
    return out


def _resize_act_residual(h: np.ndarray, new_shape) -> np.ndarray:
    """Resize a PSA ``act_residual`` [n_data, tp, L, 2, B, T, D] across a
    data-axis resize. Row r is data-shard r's per-sub-layer pending
    activation quantization error over its OWN fixed-size microbatch
    (per-shard batch is constant across worlds — the global batch scales
    with n), so the data dimension follows ``_resize_ring_residual``'s row
    rule: surviving rows copy bitwise, new rows (grow) start at zero
    pending error like a fresh shard's, dropped rows (shrink) die with
    their shards' in-flight data. Every non-``data`` dimension is
    topology-independent (tp layout, layer count, sub-layer pair, batch
    geometry) — a mismatch there is a reconfiguration, not a resize, and
    hard-errors by name."""
    if h.shape[1:] != tuple(new_shape[1:]):
        raise ValueError(
            f"act_residual resize only moves the data axis: snapshot "
            f"{h.shape} vs template {tuple(new_shape)} differ beyond "
            f"dimension 0 — changing tp/layers/batch geometry across a "
            f"re-mesh is not a resize")
    n_new = int(new_shape[0])
    out = np.zeros(tuple(new_shape), h.dtype)
    n_keep = min(h.shape[0], n_new)
    out[:n_keep] = h[:n_keep]
    return out


def host_snapshot(state):
    """Full host-RAM copy of a (possibly sharded) TrainState — the gather
    half of elastic recovery's fast path. ``np.asarray`` on a sharded
    global array materializes the whole array on host (single-process),
    so ZeRO-1 moment slices from EVERY replica land in the mirror — which
    is what makes recovery onto fewer replicas possible after some of
    those slices' owners die."""
    return jax.tree.map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, state)


def shard_batch(mesh: Mesh, batch) -> jax.Array:
    """Device-put a [n_shards·B, ...] host batch with leading axis sharded
    over the data-parallel world — ``data``, or ``("dcn", "data")``
    island-major on a hierarchical mesh (shard (d, s) reads batch rows
    [(d·S+s)·B, (d·S+s+1)·B), matching the device order)."""
    return jax.device_put(batch,
                          NamedSharding(mesh, P(data_partition(mesh))))


def shard_batch_window(mesh: Mesh, window) -> jax.Array:
    """Device-put a [K, n_shards·B, T] host batch window for the multi-step
    drivers: leading axis = K consecutive steps (replicated — every shard
    scans the same step sequence), second axis sharded over the
    data-parallel world (``data``, or ``("dcn", "data")`` hierarchically —
    same rule as ``shard_batch``)."""
    return jax.device_put(
        window, NamedSharding(mesh, P(None, data_partition(mesh))))


def replicate(mesh: Mesh, tree):
    """Replicate a host/device tree onto the mesh — via an explicit copy.

    A plain device_put keeps the caller's own buffer as one replica shard,
    and every step factory here donates its state: donating that aliased
    buffer silently deletes the caller's original ('Array has been deleted'
    when two states are built from one params tree — and ``may_alias=False``
    does NOT prevent the alias on this backend, verified empirically). The
    copy is init-time-only and insulates the caller's tree."""
    fresh = jax.tree.map(
        lambda x: jnp.array(x, copy=True) if isinstance(x, jax.Array) else x,
        tree)
    return jax.device_put(fresh, NamedSharding(mesh, P()))
