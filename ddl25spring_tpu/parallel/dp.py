"""Data parallelism: one SPMD train step over a ``data`` mesh axis.

Capability target: the reference's two DP variants —
- gradient aggregation: per-iter allreduce of flattened grads then avg+step
  (reference: lab/tutorial_1b/DP/gradient_aggr/intro_DP_GA.py:41-68);
- weight aggregation: step first, then allreduce and average the *weights*
  (intro_DP_WA.py:41-67; the reference script never writes the averaged
  weights back — a recorded bug. We implement the intended semantics.)

TPU-native shape: the barrier/flatten/all_reduce/unflatten/scale dance
(intro_DP_GA.py:53-66) collapses to ``lax.pmean(grads, "data")`` inside a
``shard_map`` — the collective lowers to one XLA all-reduce over ICI, fused
with the step. No CPU staging, no sockets, no tags.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def init_state(params, optimizer: optax.GradientTransformation) -> TrainState:
    return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))


def sharded_opt_init(mesh: Mesh, params, optimizer: optax.GradientTransformation,
                     param_specs):
    """``optimizer.init`` with the optimizer state placed CORRECTLY on the
    mesh: moment subtrees (anything tree-isomorphic to params, e.g. adam's
    mu/nu) inherit the param PartitionSpecs; scalars (count) replicate.

    Plain ``jax.jit(optimizer.init)(params)`` does NOT do this — absent
    out_shardings it commits every output to one device, silently wasting
    HBM on what should be sharded moments.
    """
    pstruct = jax.tree.structure(params)

    def is_params_like(node):
        try:
            return jax.tree.structure(node) == pstruct
        except Exception:
            return False

    def shard_of(node):
        if is_params_like(node):
            return jax.tree.map(lambda s: NamedSharding(mesh, s), param_specs)
        return jax.tree.map(lambda _: NamedSharding(mesh, P()), node)

    abstract = jax.eval_shape(optimizer.init, params)
    out_shardings = jax.tree.map(shard_of, abstract, is_leaf=is_params_like)
    return jax.jit(optimizer.init, out_shardings=out_shardings)(params)


def make_grad_aggregation_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                               mesh: Mesh) -> Callable:
    """jit-compiled SPMD step: local grads -> pmean over ``data`` -> update.

    ``loss_fn(params, batch) -> scalar``. The batch's leading axis is sharded
    over ``data``; params/opt state are replicated and stay bitwise-identical
    across shards because every shard applies the same averaged gradient.
    """

    def local_step(state: TrainState, batch) -> Tuple[TrainState, jnp.ndarray]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        grads = lax.pmean(grads, "data")          # the one collective per iter
        loss = lax.pmean(loss, "data")
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    sharded = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=(P(), P()),
        check_vma=False,  # optax state carries non-vma-tracked leaves
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_weight_aggregation_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                                 mesh: Mesh) -> Callable:
    """Step locally on the local shard's gradient, then average the *weights*
    across shards — the reference's intro_DP_WA semantics, implemented as the
    intended average-in-place (not its no-op bug)."""

    def local_step(state: TrainState, batch) -> Tuple[TrainState, jnp.ndarray]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        params = lax.pmean(params, "data")        # weight allreduce
        # Average the optimizer moments too: the reference keeps per-process
        # Adam state, but an SPMD TrainState declared replicated must BE
        # replicated — divergent per-shard moments would silently collapse to
        # shard 0's on any reshard/checkpoint. Documented deviation.
        opt_state = lax.pmean(opt_state, "data")
        loss = lax.pmean(loss, "data")
        return TrainState(params, opt_state, state.step + 1), loss

    sharded = jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def shard_batch(mesh: Mesh, batch) -> jax.Array:
    """Device-put a [n_shards·B, ...] host batch with leading axis sharded
    over ``data``."""
    return jax.device_put(batch, NamedSharding(mesh, P("data")))


def replicate(mesh: Mesh, tree):
    return jax.device_put(tree, NamedSharding(mesh, P()))
