"""Data parallelism: one SPMD train step over a ``data`` mesh axis.

Capability target: the reference's two DP variants —
- gradient aggregation: per-iter allreduce of flattened grads then avg+step
  (reference: lab/tutorial_1b/DP/gradient_aggr/intro_DP_GA.py:41-68);
- weight aggregation: step first, then allreduce and average the *weights*
  (intro_DP_WA.py:41-67; the reference script never writes the averaged
  weights back — a recorded bug. We implement the intended semantics.)

TPU-native shape: the barrier/flatten/all_reduce/unflatten/scale dance
(intro_DP_GA.py:53-66) collapses to ``lax.pmean(grads, "data")`` inside a
``shard_map`` — the collective lowers to one XLA all-reduce over ICI, fused
with the step. No CPU staging, no sockets, no tags.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry import comm
from ._compat import shard_map


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def init_state(params, optimizer: optax.GradientTransformation) -> TrainState:
    return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))


def sharded_opt_init(mesh: Mesh, params, optimizer: optax.GradientTransformation,
                     param_specs):
    """``optimizer.init`` with the optimizer state placed CORRECTLY on the
    mesh: moment subtrees (anything tree-isomorphic to params, e.g. adam's
    mu/nu) inherit the param PartitionSpecs; scalars (count) replicate.

    Plain ``jax.jit(optimizer.init)(params)`` does NOT do this — absent
    out_shardings it commits every output to one device, silently wasting
    HBM on what should be sharded moments.
    """
    pstruct = jax.tree.structure(params)

    def is_params_like(node):
        try:
            return jax.tree.structure(node) == pstruct
        except Exception:
            return False

    def shard_of(node):
        if is_params_like(node):
            return jax.tree.map(lambda s: NamedSharding(mesh, s), param_specs)
        return jax.tree.map(lambda _: NamedSharding(mesh, P()), node)

    abstract = jax.eval_shape(optimizer.init, params)
    out_shardings = jax.tree.map(shard_of, abstract, is_leaf=is_params_like)
    return jax.jit(optimizer.init, out_shardings=out_shardings)(params)


def apply_optimizer(optimizer, grads, opt_state, params):
    """One optimizer application: the duck-typed ``apply_gradients`` fast
    path when the optimizer provides it (ops.pallas_adam.FusedApplyAdam —
    one fused kernel pass over {p, m, v, g} instead of update + apply),
    else the plain optax update. Shared by every step factory that
    consumes averaged gradients (here and parallel/compress.py)."""
    if hasattr(optimizer, "apply_gradients"):
        return optimizer.apply_gradients(params, grads, opt_state)
    updates, opt_state = optimizer.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state


def make_grad_aggregation_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                               mesh: Mesh, accum_steps: int = 1,
                               guard_nonfinite: bool = False) -> Callable:
    """jit-compiled SPMD step: local grads -> pmean over ``data`` -> update.

    ``loss_fn(params, batch) -> scalar``. The batch's leading axis is sharded
    over ``data``; params/opt state are replicated and stay bitwise-identical
    across shards because every shard applies the same averaged gradient.

    ``accum_steps > 1`` enables gradient accumulation: each shard's local
    batch is split into ``accum_steps`` microbatches scanned sequentially,
    their gradients averaged before the ONE pmean + update — an
    ``accum_steps``-times larger effective batch at one microbatch's
    activation memory, with unchanged collective traffic. The local batch's
    leading dim must divide evenly. Equivalent to the full-batch step up to
    float re-association (asserted in tests/test_dp.py).

    ``guard_nonfinite=True`` fuses a post-allreduce finiteness guard into
    the step (resilience layer): if the *averaged* gradient or loss carries
    a NaN/Inf — one poisoned shard poisons the pmean for everyone, which is
    exactly why the check sits after the collective — the update is a
    select-back to the incoming params/opt state and ``step`` does not
    advance. Zero host syncs and donation-safe (the select happens inside
    the jitted program), so it composes with compressed-wire and accum
    variants of the surrounding loop; the skipped step is visible to the
    host as the returned non-finite loss and the non-advancing ``step``.
    The host-side StepGuard (resilience/guard.py) layers EMA anomaly
    detection and checkpoint rollback on top when those are wanted.
    """

    def local_step(state: TrainState, batch) -> Tuple[TrainState, jnp.ndarray]:
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        else:
            micro = batch.reshape((accum_steps, -1) + batch.shape[1:])

            def body(carry, mb):
                loss_sum, gsum = carry
                l, g = jax.value_and_grad(loss_fn)(state.params, mb)
                # Accumulate in fp32 regardless of param/grad dtype: a bf16
                # running sum would round away small microbatch
                # contributions (the vanishing-accumulation failure mode
                # ops/mixed_precision.py exists to fix).
                gsum = jax.tree.map(
                    lambda a, b: a + b.astype(jnp.float32), gsum, g)
                return (loss_sum + l.astype(jnp.float32), gsum), None

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
            (loss, gsum), _ = lax.scan(body, (jnp.zeros(()), zeros), micro)
            loss = loss / accum_steps
            grads = jax.tree.map(
                lambda g, p: (g / accum_steps).astype(p.dtype),
                gsum, state.params)
        # The one payload collective per iter (telemetry.comm wrappers are
        # lax pass-throughs that record bytes at trace time — see
        # telemetry/comm.py; compiled HLO is unchanged).
        grads = comm.pmean(grads, "data", label="grad_allreduce")
        loss = comm.pmean(loss, "data", label="loss_allreduce")
        params, opt_state = apply_optimizer(optimizer, grads,
                                            state.opt_state, state.params)
        if guard_nonfinite:
            ok = jnp.isfinite(loss)
            for g in jax.tree.leaves(grads):
                ok &= jnp.all(jnp.isfinite(g))
            # Select-back, not zeroed grads: a zero-grad optimizer update
            # still decays Adam moments and bumps count — only keeping the
            # incoming state makes the skip a true no-op.
            params = jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                                  params, state.params)
            opt_state = jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                                     opt_state, state.opt_state)
            return TrainState(params, opt_state,
                              state.step + ok.astype(state.step.dtype)), loss
        return TrainState(params, opt_state, state.step + 1), loss

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=(P(), P()),
        check_vma=False,  # optax state carries non-vma-tracked leaves
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_weight_aggregation_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                                 mesh: Mesh) -> Callable:
    """Step locally on the local shard's gradient, then average the *weights*
    across shards — the reference's intro_DP_WA semantics, implemented as the
    intended average-in-place (not its no-op bug)."""

    def local_step(state: TrainState, batch) -> Tuple[TrainState, jnp.ndarray]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        params = comm.pmean(params, "data", label="weight_allreduce")
        # Average the optimizer moments too: the reference keeps per-process
        # Adam state, but an SPMD TrainState declared replicated must BE
        # replicated — divergent per-shard moments would silently collapse to
        # shard 0's on any reshard/checkpoint. Documented deviation.
        opt_state = comm.pmean(opt_state, "data", label="optstate_allreduce")
        loss = comm.pmean(loss, "data", label="loss_allreduce")
        return TrainState(params, opt_state, state.step + 1), loss

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P("data")),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_zero1_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                    mesh: Mesh, params) -> Tuple[TrainState, Callable]:
    """ZeRO-1 data parallelism: optimizer state sharded across the ``data``
    axis (parity-plus — SURVEY.md §2.10 marks ZeRO/FSDP absent in the
    reference; pattern reference: "Automatic Cross-Replica Sharding of
    Weight Update in Data-Parallel Training", arxiv 2004.13336, PAPERS.md).

    Per step, on each shard: local grads → ``lax.psum_scatter`` (averaged
    1/n-th of the flattened gradient, half the allreduce's wire volume for
    this leg) → optimizer update on the LOCAL moment slice only →
    ``lax.all_gather`` of the updated parameter slice. Params stay
    replicated; Adam's mu/nu shrink to 1/n per device — the memory that
    caps model size under plain DP.

    Exact-equivalence caveat: valid for elementwise optimizers (sgd, adam,
    adamw, ...) whose update at coordinate i depends only on history at i —
    slicing commutes with the update rule, so the result is bit-comparable
    to ``make_grad_aggregation_step`` (asserted in tests/test_dp.py).

    Returns ``(state, step_fn)`` — the initial TrainState with sharded
    moments, and ``step_fn(state, batch) -> (state, loss)``.

    Transient-memory note: each step ravels the replicated params/grads into
    one padded fp32 vector before the scatter — a ~2·|params| fp32 transient
    per device. The *persistent* saving (moments at 1/n, the 2/3 of Adam
    state that caps model size) is what ZeRO-1 is for; a fully flat-resident
    params layout would trade API simplicity for removing the transient.
    """
    from ..utils import pytree as pt

    n = mesh.shape["data"]
    total = pt.param_count(params)
    pad = (-total) % n
    local = (total + pad) // n

    # PartitionSpecs for the local-slice optimizer state: vector leaves
    # (mu/nu, [local]) shard over ``data``; scalars (count) replicate —
    # every shard steps them identically.
    abstract_opt = jax.eval_shape(
        optimizer.init, jax.ShapeDtypeStruct((local,), jnp.float32))
    opt_specs = jax.tree.map(
        lambda x: P("data") if getattr(x, "ndim", 0) >= 1 else P(),
        abstract_opt)

    def local_init(params):
        # Each shard owns moments for its slice of the padded flat vector.
        shard = lax.axis_index("data")
        flat = jnp.pad(pt.flatten(params)[0].astype(jnp.float32), (0, pad))
        mine = lax.dynamic_slice_in_dim(flat, shard * local, local)
        return optimizer.init(mine)

    opt_state = jax.jit(shard_map(
        local_init, mesh=mesh, in_specs=P(),
        out_specs=opt_specs, check_vma=False))(params)
    state = TrainState(replicate(mesh, params), opt_state,
                       jax.device_put(jnp.zeros((), jnp.int32),
                                      NamedSharding(mesh, P())))

    def local_step(state: TrainState, batch):
        params = state.params
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        flat_g = jnp.pad(pt.flatten(grads)[0].astype(jnp.float32), (0, pad))
        # Averaged 1/n-th of the gradient lands on its owner shard.
        g_mine = comm.psum_scatter(flat_g, "data", scatter_dimension=0,
                                   tiled=True,
                                   label="zero1_grad_scatter") / n
        raw_flat, unravel = pt.flatten(params)
        flat_p = jnp.pad(raw_flat.astype(jnp.float32), (0, pad))
        shard = lax.axis_index("data")
        p_mine = lax.dynamic_slice_in_dim(flat_p, shard * local, local)
        updates, opt_state = optimizer.update(g_mine, state.opt_state, p_mine)
        p_new = optax.apply_updates(p_mine, updates)
        flat_new = comm.all_gather(p_new, "data", tiled=True,
                                   label="zero1_param_gather")[:total]
        # Cast back before unravel: for single-dtype trees ravel_pytree's
        # unravel is dtype-polymorphic and would silently rebuild non-fp32
        # params (e.g. param_dtype="bfloat16") as fp32.
        new_params = unravel(flat_new.astype(raw_flat.dtype))
        loss = comm.pmean(loss, "data", label="loss_allreduce")
        return TrainState(new_params, opt_state, state.step + 1), loss

    step = shard_map(
        local_step, mesh=mesh,
        in_specs=(TrainState(P(), opt_specs, P()), P("data")),
        out_specs=(TrainState(P(), opt_specs, P()), P()),
        check_vma=False)
    return state, jax.jit(step, donate_argnums=(0,))


def shard_batch(mesh: Mesh, batch) -> jax.Array:
    """Device-put a [n_shards·B, ...] host batch with leading axis sharded
    over ``data``."""
    return jax.device_put(batch, NamedSharding(mesh, P("data")))


def replicate(mesh: Mesh, tree):
    """Replicate a host/device tree onto the mesh — via an explicit copy.

    A plain device_put keeps the caller's own buffer as one replica shard,
    and every step factory here donates its state: donating that aliased
    buffer silently deletes the caller's original ('Array has been deleted'
    when two states are built from one params tree — and ``may_alias=False``
    does NOT prevent the alias on this backend, verified empirically). The
    copy is init-time-only and insulates the caller's tree."""
    fresh = jax.tree.map(
        lambda x: jnp.array(x, copy=True) if isinstance(x, jax.Array) else x,
        tree)
    return jax.device_put(fresh, NamedSharding(mesh, P()))
