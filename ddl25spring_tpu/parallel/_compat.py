"""JAX API-drift shims for the parallel layer.

``shard_map`` moved from ``jax.experimental.shard_map`` (where its
replication-check kwarg is ``check_rep``) to ``jax.shard_map`` (where it is
``check_vma``). This repo pins neither world: the container decides the jax
version, and the resilience posture is to degrade gracefully, not abort on
import. Every step factory in parallel/ routes through this one shim, so
the call sites keep the modern ``check_vma`` spelling and older jaxlibs
transparently get ``check_rep``.

``lax.axis_size`` is the same story: absent before jax 0.5, where the
static size of a mapped axis comes from ``core.axis_frame`` instead (which
itself drifted — older builds return a frame object with ``.size``, 0.4.37
returns the int directly).
"""

from __future__ import annotations

import jax
from jax import lax

if hasattr(jax, "shard_map"):
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
else:  # pre-move jax: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)


if hasattr(lax, "axis_size"):
    axis_size = lax.axis_size
else:
    def axis_size(axis_name) -> int:
        frame = jax.core.axis_frame(axis_name)
        return frame if isinstance(frame, int) else frame.size
