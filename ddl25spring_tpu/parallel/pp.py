"""Pipeline parallelism: GPipe microbatch schedule as one SPMD program.

Capability target (NOT a port): the reference's three pipeline variants —
- naive 3-stage PP: one batch flows stage0→1→2 forward then back with
  blocking send/recv (reference: lab/tutorial_1b/PP/1F1B/intro_PP_1F1B.py:27-99);
- microbatched GPipe: batch split into microbatches streamed with
  isend/irecv(tag=itr), grads accumulated across microbatches, one step per
  iteration (lab/tutorial_1a/homework_1_b1.py:50-144);
- joint DP×PP: two 3-stage pipelines + a cross-pipeline gradient allreduce
  (lab/hw01/homework 1 b/homework_1_b2.py:28-32,141-150).

TPU-native shape: ranks, tags, and point-to-point sockets disappear. Stages
are a named mesh axis; the per-iteration schedule is a ``lax.scan`` over
``n_microbatches + n_stages - 1`` ticks; the stage→stage activation hop is a
single ``lax.ppermute`` over the ICI ring. Crucially the *backward* pipeline
is not hand-written: ``jax.grad`` of the scanned forward transposes every
ppermute (hop direction reverses) and replays ticks in reverse — the reverse
schedule the reference codes by hand (homework_1_b1.py:111-139) falls out of
autodiff. Microbatch gradient semantics match the reference's accumulate-
then-step (one optimizer step per iteration, loss averaged over microbatches).

Two recorded reference quirks are deliberately NOT reproduced (documented
deviations, SURVEY.md §2.10/§3.3):
- homework_1_b1 retains only the *last* microbatch's activations, so stages
  0/1 only receive the last microbatch's backward. Here every microbatch
  back-propagates through every stage (faithful GPipe).
- homework_1_b2 allreduces gradients only in the first-stage DP group [0,3];
  replicas of other stages silently diverge. Here ALL stages pmean over the
  ``data`` axis.

DP×PP composes by construction: build the mesh with ``{"data": d, "stage": s}``
and the same step function pmean-s grads over ``data`` — the 2-pipeline ×
3-stage homework topology is ``make_mesh({"data": 2, "stage": 3})``.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import LlamaConfig
from ..models import llama
from ..ops import causal_lm_loss
from .dp import TrainState, sharded_opt_init


# ------------------------------------------------------------- param layout

from .tp import _COL as _TP_COL, _ROW as _TP_ROW  # one source of truth for
# which block leaves are column- vs row-sharded under tensor parallelism.


def param_specs(params: dict, tp: bool = False) -> dict:
    """PartitionSpecs for a stacked-block Llama param tree on a pipeline mesh.

    ``blocks`` (leading [n_layers] axis) shards over ``stage`` — each stage
    holds its contiguous slice of layers, the SPMD analog of simplellm's
    First/Stage/Last per-rank modules. With ``tp`` the block weight matrices
    additionally shard over ``model`` in the Megatron layout (parallel.tp).
    Embedding/head/final-norm stay replicated: only the first/last stage
    *reads* them, and their gradients are psum-ed back to all stages so the
    replicated update is identical.
    """
    def block_leaf_spec(name):
        if tp and name in _TP_COL:
            return P("stage", None, "model")
        if tp and name in _TP_ROW:
            return P("stage", "model", None)
        return P("stage")

    specs = {}
    for k, v in params.items():
        if k == "blocks":
            specs[k] = {name: jax.tree.map(lambda _, s=block_leaf_spec(name): s,
                                           leaf)
                        for name, leaf in v.items()}
        else:
            specs[k] = jax.tree.map(lambda _: P(), v)
    return specs


def shard_params(mesh: Mesh, params: dict) -> dict:
    specs = param_specs(params, tp=mesh.shape.get("model", 1) > 1)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def init_state(mesh: Mesh, params: dict, optimizer: optax.GradientTransformation) -> TrainState:
    """Shard params over the pipeline mesh; optimizer moments are explicitly
    placed with the param specs via dp.sharded_opt_init (a plain jitted
    optimizer.init would commit the whole opt state to one device)."""
    params = shard_params(mesh, params)
    opt_state = sharded_opt_init(mesh, params, optimizer,
                                 param_specs(params, tp=mesh.shape.get("model", 1) > 1))
    step = jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P()))
    return TrainState(params, opt_state, step)


# ------------------------------------------------------------- the schedule

def _pipeline_loss_and_grad(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
                            n_stages: int, n_microbatches: int,
                            has_data_axis: bool,
                            tp: int = 1) -> Tuple[jnp.ndarray, dict]:
    """Per-device body (runs under shard_map): GPipe forward over ticks,
    grads via autodiff, cross-stage/data reductions.

    ``params["blocks"]`` is the LOCAL stage slice [n_layers/n_stages, ...];
    ``tokens`` is the local data shard [B_local, T] with
    B_local = n_microbatches · microbatch_size. With ``tp > 1`` the block
    weights are additionally model-sharded (Megatron; see parallel.tp) and
    the loss is scaled by 1/tp under differentiation — every model shard
    seeds an identical loss replica, and the in-forward psums (transpose:
    psum) would otherwise count each weight path tp times.
    """
    stage = lax.axis_index("stage")
    is_first = stage == 0
    is_last = stage == n_stages - 1
    tp_axis = "model" if tp > 1 else None
    b, t = tokens.shape
    assert b % n_microbatches == 0, (b, n_microbatches)
    mb = b // n_microbatches
    tokens_mb = tokens.reshape(n_microbatches, mb, t)
    n_ticks = n_microbatches + n_stages - 1
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def loss_fn(p: dict) -> jnp.ndarray:
        def tick(carry, i):
            x_prev, loss_sum = carry
            # Stage 0 injects microbatch i (clipped: bubble ticks re-embed the
            # last microbatch and the result is masked out by the schedule).
            tok_in = tokens_mb[jnp.clip(i, 0, n_microbatches - 1)]
            x_in = jnp.where(is_first[..., None, None, None],
                             llama.embed(p, tok_in, cfg), x_prev)
            h = llama.blocks_apply(p["blocks"], x_in, cfg, tp_axis=tp_axis)
            # Last stage: microbatch (i - (n_stages-1)) exits the pipe here.
            out_i = i - (n_stages - 1)
            tok_out = tokens_mb[jnp.clip(out_i, 0, n_microbatches - 1)]
            valid = is_last & (out_i >= 0)
            mb_loss = lax.cond(
                valid,
                lambda: causal_lm_loss(llama.head(p, h, cfg), tok_out),
                lambda: jnp.zeros((), jnp.float32))
            # The hop: activations ride the ICI ring to the next stage. The
            # last→first edge carries bubble garbage that stage 0 discards.
            x_next = lax.ppermute(h, "stage", fwd)
            return (x_next, loss_sum + mb_loss), None

        x0 = jnp.zeros((mb, t, cfg.dmodel), jnp.dtype(cfg.dtype))
        (_, loss_sum), _ = lax.scan(
            tick, (x0, jnp.zeros((), jnp.float32)), jnp.arange(n_ticks))
        # LOCAL loss: nonzero only on the last stage. Do NOT psum over
        # ``stage`` here — the backward program is itself SPMD (ppermute
        # transposes hop the cotangent back up the ring), so every stage's
        # grads are reached from the last stage's seed alone; psum-ing the
        # loss first would seed all n_stages replicas and count each path
        # n_stages times. The 1/tp scaling is the model-axis counterpart.
        return loss_sum / n_microbatches / tp

    loss, grads = jax.value_and_grad(loss_fn)(params)
    loss = lax.psum(loss, "stage") * tp  # broadcast + undo 1/tp for reporting

    def reduce_grad(name, g):
        # Block weight matrices under TP are sharded over ``model`` — their
        # local grads are complete. Everything else replicated over ``model``
        # gets partial grads from each shard: psum. Leaves outside ``blocks``
        # (embed/head/final_norm) are also replicated over ``stage`` and got
        # grads only on the stage that read them: psum over ``stage`` too.
        if tp_axis is not None and name not in _TP_COL | _TP_ROW:
            g = jax.tree.map(lambda x: lax.psum(x, tp_axis), g)
        return g

    grads = {
        k: ({name: reduce_grad(name, g) for name, g in v.items()}
            if k == "blocks"
            else jax.tree.map(lambda g: lax.psum(g, "stage"),
                              reduce_grad(k, v)))
        for k, v in grads.items()
    }
    if has_data_axis:
        # The DP×PP cross-pipeline sync — for ALL stages, not just stage 0
        # (the reference's [0,3]-only allreduce is a recorded bug).
        grads = lax.pmean(grads, "data")
        loss = lax.pmean(loss, "data")
    return loss, grads


def make_pipeline_step(cfg: LlamaConfig, optimizer: optax.GradientTransformation,
                       mesh: Mesh, n_microbatches: int = 1) -> Callable:
    """jit-compiled GPipe train step over mesh axes (data, stage).

    ``n_microbatches=1`` degenerates to the reference's naive staged pipeline
    (intro_PP_1F1B.py); ``>1`` is the homework_1_b1 GPipe schedule; a mesh
    with ``data > 1`` is the homework_1_b2 DP×PP topology; adding a
    ``model`` axis gives the full 3-D DP×PP×TP layout.

    Returns ``step(state, tokens) -> (state, loss)`` where tokens is the
    global [B, T] batch, B divisible by data_size · n_microbatches.
    """
    n_stages = mesh.shape["stage"]
    has_data = mesh.shape.get("data", 1) > 1
    tp = mesh.shape.get("model", 1)

    def sharded_grads(params, tokens):
        return _pipeline_loss_and_grad(params, tokens, cfg, n_stages,
                                       n_microbatches, has_data, tp)

    def step(state: TrainState, tokens) -> Tuple[TrainState, jnp.ndarray]:
        specs = param_specs(state.params, tp=tp > 1)
        loss, grads = jax.shard_map(
            sharded_grads, mesh=mesh,
            in_specs=(specs, P("data") if has_data else P()),
            out_specs=(P(), specs),
            check_vma=False,
        )(state.params, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return jax.jit(step, donate_argnums=(0,))


from .mesh import shard_batch  # noqa: E402,F401  (shared batch placement)
