"""Pipeline parallelism: GPipe and 1F1B microbatch schedules as one SPMD program.

Capability target (NOT a port): the reference's three pipeline variants —
- naive 3-stage PP: one batch flows stage0→1→2 forward then back with
  blocking send/recv (reference: lab/tutorial_1b/PP/1F1B/intro_PP_1F1B.py:27-99
  — the file is *named* 1F1B but implements a naive schedule; here 1F1B is
  actually implemented, see `_pipeline_1f1b_loss_and_grad`);
- microbatched GPipe: batch split into microbatches streamed with
  isend/irecv(tag=itr), grads accumulated across microbatches, one step per
  iteration (lab/tutorial_1a/homework_1_b1.py:50-144);
- joint DP×PP: two 3-stage pipelines + a cross-pipeline gradient allreduce
  (lab/hw01/homework 1 b/homework_1_b2.py:28-32,141-150).

TPU-native shape: ranks, tags, and point-to-point sockets disappear. Stages
are a named mesh axis; the per-iteration schedule is a ``lax.scan`` over
``n_microbatches + n_stages - 1`` ticks; the stage→stage activation hop is a
single ``lax.ppermute`` over the ICI ring. Crucially the *backward* pipeline
is not hand-written: ``jax.grad`` of the scanned forward transposes every
ppermute (hop direction reverses) and replays ticks in reverse — the reverse
schedule the reference codes by hand (homework_1_b1.py:111-139) falls out of
autodiff. Microbatch gradient semantics match the reference's accumulate-
then-step (one optimizer step per iteration, loss averaged over microbatches).

Two recorded reference quirks are deliberately NOT reproduced (documented
deviations, SURVEY.md §2.10/§3.3):
- homework_1_b1 retains only the *last* microbatch's activations, so stages
  0/1 only receive the last microbatch's backward. Here every microbatch
  back-propagates through every stage (faithful GPipe).
- homework_1_b2 allreduces gradients only in the first-stage DP group [0,3];
  replicas of other stages silently diverge. Here ALL stages pmean over the
  ``data`` axis.

DP×PP composes by construction: build the mesh with ``{"data": d, "stage": s}``
and the same step function pmean-s grads over ``data`` — the 2-pipeline ×
3-stage homework topology is ``make_mesh({"data": 2, "stage": 3})``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry import comm
from ._compat import shard_map

from ..config import LlamaConfig
from ..models import llama
from .dp import TrainState, apply_optimizer, sharded_opt_init


# ------------------------------------------------------------- param layout

from .tp import _COL as _TP_COL, _ROW as _TP_ROW  # one source of truth for
# which block leaves are column- vs row-sharded under tensor parallelism.


def param_specs(params: dict, tp: bool = False) -> dict:
    """PartitionSpecs for a stacked-block Llama param tree on a pipeline mesh.

    ``blocks`` (leading [n_layers] axis) shards over ``stage`` — each stage
    holds its contiguous slice of layers, the SPMD analog of simplellm's
    First/Stage/Last per-rank modules. With ``tp`` the block weight matrices
    additionally shard over ``model`` in the Megatron layout (parallel.tp).
    Embedding/head/final-norm stay replicated: only the first/last stage
    *reads* them, and their gradients are psum-ed back to all stages so the
    replicated update is identical.
    """
    def block_leaf_spec(name):
        if tp and name in _TP_COL:
            return P("stage", None, "model")
        if tp and name in _TP_ROW:
            return P("stage", "model", None)
        return P("stage")

    specs = {}
    for k, v in params.items():
        if k == "blocks":
            specs[k] = {name: jax.tree.map(lambda _, s=block_leaf_spec(name): s,
                                           leaf)
                        for name, leaf in v.items()}
        else:
            specs[k] = jax.tree.map(lambda _: P(), v)
    return specs


def shard_params(mesh: Mesh, params: dict) -> dict:
    specs = param_specs(params, tp=mesh.shape.get("model", 1) > 1)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def init_state(mesh: Mesh, params: dict, optimizer: optax.GradientTransformation) -> TrainState:
    """Shard params over the pipeline mesh; optimizer moments are explicitly
    placed with the param specs via dp.sharded_opt_init (a plain jitted
    optimizer.init would commit the whole opt state to one device)."""
    params = shard_params(mesh, params)
    opt_state = sharded_opt_init(mesh, params, optimizer,
                                 param_specs(params, tp=mesh.shape.get("model", 1) > 1))
    step = jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P()))
    return TrainState(params, opt_state, step)


# ------------------------------------------------------------- the schedule

def _pipeline_loss_and_grad(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
                            n_stages: int, n_microbatches: int,
                            has_data_axis: bool,
                            tp: int = 1,
                            comm_scale: int = 1) -> Tuple[jnp.ndarray, dict]:
    """Per-device body (runs under shard_map): GPipe forward over ticks,
    grads via autodiff, cross-stage/data reductions.

    ``params["blocks"]`` is the LOCAL stage slice [n_layers/n_stages, ...];
    ``tokens`` is the local data shard [B_local, T] with
    B_local = n_microbatches · microbatch_size. With ``tp > 1`` the block
    weights are additionally model-sharded (Megatron; see parallel.tp) and
    the loss is scaled by 1/tp under differentiation — every model shard
    seeds an identical loss replica, and the in-forward psums (transpose:
    psum) would otherwise count each weight path tp times.

    ``comm_scale`` is the telemetry execution multiplier for the fused
    K-step scan driver (``make_pipeline_multi_step``): the body traces
    once per compilation but runs K times per dispatch, and the comm
    wrappers record that trip count so the static wire profile stays
    exact (the ``_make_local_grad_step`` convention, parallel/dp.py).
    """
    stage = lax.axis_index("stage")
    is_first = stage == 0
    is_last = stage == n_stages - 1
    tp_axis = "model" if tp > 1 else None
    b, t = tokens.shape
    assert b % n_microbatches == 0, (b, n_microbatches)
    mb = b // n_microbatches
    tokens_mb = tokens.reshape(n_microbatches, mb, t)
    n_ticks = n_microbatches + n_stages - 1
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def loss_fn(p: dict) -> jnp.ndarray:
        def tick(carry, i):
            x_prev, loss_sum = carry
            # Stage 0 injects microbatch i (clipped: bubble ticks re-embed the
            # last microbatch and the result is masked out by the schedule).
            tok_in = tokens_mb[jnp.clip(i, 0, n_microbatches - 1)]
            x_in = jnp.where(is_first[..., None, None, None],
                             llama.embed(p, tok_in, cfg), x_prev)
            h = llama.blocks_apply(p["blocks"], x_in, cfg, tp_axis=tp_axis)
            # Last stage: microbatch (i - (n_stages-1)) exits the pipe here.
            out_i = i - (n_stages - 1)
            tok_out = tokens_mb[jnp.clip(out_i, 0, n_microbatches - 1)]
            valid = is_last & (out_i >= 0)
            mb_loss = lax.cond(
                valid,
                lambda: llama.head_loss(p, h, tok_out, cfg),
                lambda: jnp.zeros((), jnp.float32))
            # The hop: activations ride the ICI ring to the next stage. The
            # last→first edge carries bubble garbage that stage 0 discards.
            # (scale=n_ticks: the scan body traces once, hops n_ticks times;
            # the backward hops autodiff adds are telemetry/comm.py's
            # documented under-count.)
            x_next = comm.ppermute(h, "stage", fwd, label="pp_activation_hop",
                                   scale=n_ticks * comm_scale)
            return (x_next, loss_sum + mb_loss), None

        x0 = jnp.zeros((mb, t, cfg.dmodel), jnp.dtype(cfg.dtype))
        (_, loss_sum), _ = lax.scan(
            tick, (x0, jnp.zeros((), jnp.float32)), jnp.arange(n_ticks))
        # LOCAL loss: nonzero only on the last stage. Do NOT psum over
        # ``stage`` here — the backward program is itself SPMD (ppermute
        # transposes hop the cotangent back up the ring), so every stage's
        # grads are reached from the last stage's seed alone; psum-ing the
        # loss first would seed all n_stages replicas and count each path
        # n_stages times. The 1/tp scaling is the model-axis counterpart.
        return loss_sum / n_microbatches / tp

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return _reduce_loss_and_grads(loss, grads, tp_axis, has_data_axis, tp,
                                  comm_scale)


def _reduce_loss_and_grads(loss, grads, tp_axis, has_data_axis, tp,
                           comm_scale: int = 1):
    """Cross-stage/model/data reductions shared by all three schedules.

    ``has_data_axis=False`` with a real ``data`` axis present is the
    composed DP×PP path (``make_pipeline_overlap_*``): the cross-STAGE
    reductions still run, but the data-axis sync is left to the caller's
    ring driver — the seam where zero1/wire-compression attach."""
    loss = comm.psum(loss, "stage",  # broadcast + undo 1/tp for reporting
                     label="pp_loss_allreduce", scale=comm_scale) * tp

    def reduce_grad(name, g):
        # Block weight matrices under TP are sharded over ``model`` — their
        # local grads are complete. Everything else replicated over ``model``
        # gets partial grads from each shard: psum. Leaves outside ``blocks``
        # (embed/head/final_norm) are also replicated over ``stage`` and got
        # grads only on the stage that read them: psum over ``stage`` too.
        if tp_axis is not None and name not in _TP_COL | _TP_ROW:
            g = jax.tree.map(
                lambda x: comm.psum(x, tp_axis,
                                    label="tp_replicated_grads",
                                    scale=comm_scale), g)
        return g

    grads = {
        k: ({name: reduce_grad(name, g) for name, g in v.items()}
            if k == "blocks"
            else jax.tree.map(
                lambda g: comm.psum(g, "stage",
                                    label="pp_replicated_grads",
                                    scale=comm_scale),
                reduce_grad(k, v)))
        for k, v in grads.items()
    }
    if has_data_axis:
        # The DP×PP cross-pipeline sync — for ALL stages, not just stage 0
        # (the reference's [0,3]-only allreduce is a recorded bug).
        grads = comm.pmean(grads, "data", label="grad_allreduce",
                           scale=comm_scale)
        loss = comm.pmean(loss, "data", label="loss_allreduce",
                          scale=comm_scale)
    return loss, grads


# ------------------------------------------------------- interleaved layout

def interleave_blocks(blocks, n_stages: int, n_chunks: int):
    """Permute the stacked [L] block axis into the interleaved-schedule layout.

    The interleaved schedule assigns stage ``s`` the *non-contiguous* virtual
    stages ``c·S + s`` (chunk c ∈ [0, v)); mesh sharding over ``stage`` always
    hands each device a *contiguous* slice of the leading axis. Rather than
    reshard every step, permute once so that the contiguous local slice
    [s·L/S, (s+1)·L/S) holds exactly stage s's chunks, ordered by c:
    position ``s·(L/S) + c·per + l`` ← layer ``(c·S + s)·per + l`` with
    ``per = L/(S·v)``. `deinterleave_blocks` inverts (e.g. before comparing
    with a GPipe run or exporting a checkpoint in natural layer order).
    """
    return jax.tree.map(
        lambda x: x[_interleave_order(x.shape[0], n_stages, n_chunks)], blocks)


def deinterleave_blocks(blocks, n_stages: int, n_chunks: int):
    """Inverse of `interleave_blocks`."""
    def inv(x):
        order = _interleave_order(x.shape[0], n_stages, n_chunks)
        inverse = jnp.zeros_like(order).at[order].set(jnp.arange(order.size))
        return x[inverse]
    return jax.tree.map(inv, blocks)


# The interleaved layout is shape-identical to the natural one, so a layout
# mistake cannot be caught from the arrays. interleave_params tags the tree
# with a scalar sentinel (value encodes S and v) that make_pipeline_step
# verifies on the first call — natural-layout params under
# schedule="interleaved" (or vice versa) fail loudly instead of silently
# running layers in the wrong order. The sentinel is a float32 leaf; its
# grad is identically zero so plain Adam/SGD leave it alone, and
# make_pipeline_step additionally re-pins it after every optimizer update so
# params-coupled transforms (adamw weight decay, EMA) cannot drift it.
_LAYOUT_KEY = "blocks_layout"


def _layout_tag(n_stages: int, n_chunks: int) -> float:
    return float(n_stages * 1000 + n_chunks)


def interleave_params(params: dict, n_stages: int, n_chunks: int) -> dict:
    """`interleave_blocks` over the full param tree, plus the layout tag.

    Use this (not a bare ``dict(params, blocks=interleave_blocks(...))``)
    before ``init_state`` when training with ``schedule="interleaved"``.
    """
    out = dict(params, blocks=interleave_blocks(params["blocks"],
                                                n_stages, n_chunks))
    out[_LAYOUT_KEY] = jnp.float32(_layout_tag(n_stages, n_chunks))
    return out


def deinterleave_params(params: dict, n_stages: int, n_chunks: int) -> dict:
    """Inverse of `interleave_params` (natural layer order, tag stripped)."""
    out = dict(params, blocks=deinterleave_blocks(params["blocks"],
                                                  n_stages, n_chunks))
    out.pop(_LAYOUT_KEY, None)
    return out


def _interleave_order(n_layers: int, n_stages: int, n_chunks: int) -> jnp.ndarray:
    assert n_layers % (n_stages * n_chunks) == 0, (n_layers, n_stages, n_chunks)
    per = n_layers // (n_stages * n_chunks)
    return jnp.asarray([(c * n_stages + s) * per + l
                        for s in range(n_stages)
                        for c in range(n_chunks)
                        for l in range(per)])


def _pipeline_interleaved_loss_and_grad(params: dict, tokens: jnp.ndarray,
                                        cfg: LlamaConfig, n_stages: int,
                                        n_microbatches: int, has_data_axis: bool,
                                        tp: int = 1, comm_scale: int = 1,
                                        n_chunks: int = 2
                                        ) -> Tuple[jnp.ndarray, dict]:
    """Interleaved virtual-stage schedule (Megatron-LM's "virtual pipeline"):
    each stage holds ``v = n_chunks`` non-contiguous layer chunks and every
    microbatch rides the ICI ring v times, visiting virtual stage c·S+s on
    its c-th lap. A stage is busy v·M of the v·M + S − 1 ticks, so the
    bubble fraction drops from GPipe's (S−1)/(M+S−1) to (S−1)/(v·M+S−1) —
    the fill/drain cost is amortized over v× more (smaller) stage visits.

    Injection is grouped: microbatches enter in waves of S (ticks where
    (j − s) mod v·S < S present stage 0 with a fresh microbatch; on all other
    ticks its input is the wrap-around of an in-flight lap), so M must be a
    multiple of S. At tick j, stage s works on relative tick r = j − s:
    group g = r // (v·S), chunk c = (r mod v·S) // S, microbatch
    g·S + (r mod S); valid iff 0 ≤ r < v·M. The loss exits at stage S−1 on
    chunk v−1. Backward is the autodiff transpose of the whole scan (GPipe
    semantics): simple and exact, at the cost of stashing O(v·M) microbatch
    activations — combine with ``cfg.remat`` when memory matters; the 1F1B
    O(S) stash bound does not apply to this schedule.

    ``params["blocks"]`` must be in `interleave_blocks` layout (the local
    [L/S] slice is [v, per] chunk-major): permute with
    ``interleave_params(params, S, v)`` BEFORE ``init_state`` places the
    tree on the mesh (a later permute across the sharded stage axis would
    be an all-to-all). The layout is shape-identical to the natural one so
    it cannot be asserted from the arrays; `make_pipeline_step` checks the
    `interleave_params` layout tag on the first call instead.
    """
    stage = lax.axis_index("stage")
    is_first = stage == 0
    is_last = stage == n_stages - 1
    tp_axis = "model" if tp > 1 else None
    v = n_chunks
    b, t = tokens.shape
    assert b % n_microbatches == 0, (b, n_microbatches)
    assert n_microbatches % n_stages == 0, (n_microbatches, n_stages)
    mb = b // n_microbatches
    tokens_mb = tokens.reshape(n_microbatches, mb, t)
    n_ticks = v * n_microbatches + n_stages - 1
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def loss_fn(p: dict) -> jnp.ndarray:
        # Local blocks [L/S, ...] → [v, per, ...], chunk-major by layout.
        n_local = jax.tree.leaves(p["blocks"])[0].shape[0]
        per = n_local // v
        chunks = jax.tree.map(
            lambda x: x.reshape((v, per) + x.shape[1:]), p["blocks"])

        def tick(carry, j):
            x_prev, loss_sum = carry
            r = j - stage
            valid = (r >= 0) & (r < v * n_microbatches)
            cyc = jnp.mod(r, v * n_stages)
            c = jnp.clip(cyc // n_stages, 0, v - 1)
            mb_idx = jnp.clip(r // (v * n_stages) * n_stages
                              + jnp.mod(cyc, n_stages),
                              0, n_microbatches - 1)
            tok = tokens_mb[mb_idx]
            inject = is_first & (cyc < n_stages)
            x_in = jnp.where(inject[..., None, None, None],
                             llama.embed(p, tok, cfg), x_prev)
            chunk_c = jax.tree.map(
                lambda x: lax.dynamic_index_in_dim(x, c, keepdims=False),
                chunks)
            h = llama.blocks_apply(chunk_c, x_in, cfg, tp_axis=tp_axis)
            exit_here = is_last & (c == v - 1) & valid
            mb_loss = lax.cond(
                exit_here,
                lambda: llama.head_loss(p, h, tok, cfg),
                lambda: jnp.zeros((), jnp.float32))
            x_next = comm.ppermute(h, "stage", fwd, label="pp_activation_hop",
                                   scale=n_ticks * comm_scale)
            return (x_next, loss_sum + mb_loss), None

        x0 = jnp.zeros((mb, t, cfg.dmodel), jnp.dtype(cfg.dtype))
        (_, loss_sum), _ = lax.scan(
            tick, (x0, jnp.zeros((), jnp.float32)), jnp.arange(n_ticks))
        return loss_sum / n_microbatches / tp   # same seeding rule as GPipe

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return _reduce_loss_and_grads(loss, grads, tp_axis, has_data_axis, tp,
                                  comm_scale)


def _pipeline_1f1b_loss_and_grad(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
                                 n_stages: int, n_microbatches: int,
                                 has_data_axis: bool,
                                 tp: int = 1,
                                 comm_scale: int = 1) -> Tuple[jnp.ndarray, dict]:
    """1F1B (one-forward-one-backward) schedule, hand-written backward.

    GPipe (above) lets autodiff transpose the whole forward scan, which means
    every tick's stage input — n_microbatches + n_stages − 1 activations —
    is saved for the backward replay: activation memory grows linearly with
    the microbatch count. 1F1B interleaves each microbatch's backward as soon
    as its forward clears the last stage, so at most ``2·n_stages − 1``
    microbatch inputs are ever in flight per stage (Megatron-LM's memory
    argument; the bubble fraction itself matches GPipe). Because a ``vjp``
    closure cannot ride a ``lax.scan`` carry, the backward recomputes the
    stage forward from the stashed *input* — the standard full-recompute
    (remat) variant, so the fair time comparison is GPipe with
    ``cfg.remat=True`` (see experiments/pp_schedules.py for measurements).

    Schedule (SPMD lockstep; iteration j does one F then one B sub-tick):
    - F: stage s runs microbatch ``i_f = j − s``            (valid if 0≤i_f<M)
    - B: stage s runs microbatch ``i_b = j − 2(S−1) + s``   (valid if 0≤i_b<M)
    so the last stage backs up microbatch i immediately after forwarding it
    (same j), and the cotangent hops one stage down the ring per iteration.
    Gradient semantics are identical to GPipe: mean loss over microbatches,
    grads accumulated across B sub-ticks, one optimizer step per call.
    """
    stage = lax.axis_index("stage")
    is_first = stage == 0
    is_last = stage == n_stages - 1
    tp_axis = "model" if tp > 1 else None
    b, t = tokens.shape
    assert b % n_microbatches == 0, (b, n_microbatches)
    mb = b // n_microbatches
    tokens_mb = tokens.reshape(n_microbatches, mb, t)
    n_iters = n_microbatches + 2 * (n_stages - 1)
    n_slots = min(2 * n_stages - 1, n_microbatches)
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    dt = jnp.dtype(cfg.dtype)

    def stage_fn(p: dict, act_in: jnp.ndarray, i: jnp.ndarray):
        """One stage application for microbatch index i (clipped): embeds on
        the first stage, computes the (masked) loss on the last."""
        tok = tokens_mb[jnp.clip(i, 0, n_microbatches - 1)]
        x_in = jnp.where(is_first[..., None, None, None],
                         llama.embed(p, tok, cfg), act_in)
        h = llama.blocks_apply(p["blocks"], x_in, cfg, tp_axis=tp_axis)
        mb_loss = lax.cond(
            is_last,
            lambda: llama.head_loss(p, h, tok, cfg),
            lambda: jnp.zeros((), jnp.float32))
        return h, mb_loss

    def iteration(carry, j):
        stash, grads, loss_sum, x_fwd, g_bwd = carry

        # --- F sub-tick: forward microbatch i_f, stash its input ----------
        i_f = j - stage
        valid_f = (i_f >= 0) & (i_f < n_microbatches)
        act_in = x_fwd
        h, _ = stage_fn(params, act_in, i_f)
        slot_f = jnp.clip(i_f, 0, n_microbatches - 1) % n_slots
        old = lax.dynamic_index_in_dim(stash, slot_f, keepdims=False)
        stash = lax.dynamic_update_index_in_dim(
            stash, jnp.where(valid_f, act_in, old), slot_f, axis=0)
        x_fwd = comm.ppermute(h, "stage", fwd_perm,
                              label="pp_activation_hop",
                              scale=n_iters * comm_scale)

        # --- B sub-tick: vjp-recompute microbatch i_b from its stash ------
        i_b = j - 2 * (n_stages - 1) + stage
        valid_b = (i_b >= 0) & (i_b < n_microbatches)
        slot_b = jnp.clip(i_b, 0, n_microbatches - 1) % n_slots
        act_b = lax.dynamic_index_in_dim(stash, slot_b, keepdims=False)
        (_, mb_loss), pull = jax.vjp(
            lambda p, a: stage_fn(p, a, i_b), params, act_b)
        # Cotangent seeds: the last stage seeds from its own loss (scaled for
        # the microbatch mean and the TP loss-replica double count, as in
        # GPipe's loss_fn); every other stage seeds from the cotangent that
        # arrived down the ring. Invalid sub-ticks seed zero, which makes
        # their (finite) recomputed grads exactly zero — no masking needed.
        g_h = jnp.where((is_last | ~valid_b)[..., None, None, None],
                        jnp.zeros_like(g_bwd), g_bwd)
        g_loss = jnp.where(is_last & valid_b, 1.0 / (n_microbatches * tp), 0.0)
        dp, da = pull((g_h, g_loss.astype(jnp.float32)))
        grads = jax.tree.map(jnp.add, grads, dp)
        loss_sum = loss_sum + jnp.where(is_last & valid_b, mb_loss, 0.0)
        g_bwd = comm.ppermute(da.astype(dt), "stage", bwd_perm,
                              label="pp_cotangent_hop",
                              scale=n_iters * comm_scale)

        return (stash, grads, loss_sum, x_fwd, g_bwd), None

    stash0 = jnp.zeros((n_slots, mb, t, cfg.dmodel), dt)
    grads0 = jax.tree.map(jnp.zeros_like, params)
    act0 = jnp.zeros((mb, t, cfg.dmodel), dt)
    (_, grads, loss_sum, _, _), _ = lax.scan(
        iteration,
        (stash0, grads0, jnp.zeros((), jnp.float32), act0, act0),
        jnp.arange(n_iters))
    return _reduce_loss_and_grads(loss_sum / n_microbatches / tp, grads,
                                  tp_axis, has_data_axis, tp, comm_scale)


def _schedule_body(schedule: str, n_chunks: int) -> Callable:
    """The per-shard loss+grad body for a schedule name — the ONE lookup
    every pipeline step factory routes through, so a new factory cannot
    support a different schedule set by accident."""
    if schedule == "interleaved":
        return functools.partial(_pipeline_interleaved_loss_and_grad,
                                 n_chunks=n_chunks)
    try:
        return {"gpipe": _pipeline_loss_and_grad,
                "1f1b": _pipeline_1f1b_loss_and_grad}[schedule]
    except KeyError:
        raise ValueError(f"unknown schedule {schedule!r}: expected 'gpipe', "
                         "'1f1b' or 'interleaved'") from None


def _opt_specs(opt_state, params, specs):
    """PartitionSpecs for a pipeline optimizer state: moment subtrees
    (anything tree-isomorphic to params — adam's mu/nu) inherit the param
    specs, scalars (count) replicate — ``sharded_opt_init``'s placement
    rule as SPECS, computable from a traced state inside a jitted step
    (only tree structure is read, never values)."""
    pstruct = jax.tree.structure(params)

    def is_params_like(node):
        try:
            return jax.tree.structure(node) == pstruct
        except Exception:
            return False

    return jax.tree.map(
        lambda node: specs if is_params_like(node)
        else jax.tree.map(lambda _: P(), node),
        opt_state, is_leaf=is_params_like)


def _check_layout(params_tag, schedule: str, n_stages: int,
                  n_chunks: int) -> None:
    """The interleaved-layout sanity check shared by every factory:
    schedule="interleaved" demands the interleave_params tag for exactly
    this (S, v); any other schedule demands its absence."""
    if schedule == "interleaved":
        want = _layout_tag(n_stages, n_chunks)
        if params_tag is None:
            raise ValueError(
                "schedule='interleaved' requires params permuted with "
                "interleave_params(params, n_stages, n_chunks) before "
                "init_state — natural-layout blocks would run layers "
                "in the wrong order")
        if float(params_tag) != want:
            raise ValueError(
                f"params were interleaved for a different topology "
                f"(tag {float(params_tag):.0f}, expected {want:.0f} = "
                f"stages*1000+chunks)")
    elif params_tag is not None:
        raise ValueError(
            f"params carry the interleaved layout tag but "
            f"schedule={schedule!r} expects natural layer order — "
            f"undo with deinterleave_params first")


def _layout_guarded(jitted: Callable, schedule: str, n_stages: int,
                    n_chunks: int) -> Callable:
    """First-call layout guard around a jitted pipeline step (params are
    concrete at the Python call boundary, and reading the scalar here
    avoids a per-step host sync)."""
    checked = []

    def guarded(state: TrainState, tokens):
        if not checked:
            _check_layout(state.params.get(_LAYOUT_KEY), schedule,
                          n_stages, n_chunks)
            checked.append(True)
        return jitted(state, tokens)

    guarded.lower = jitted.lower   # AOT inspection (experiments/pp_schedules)
    if hasattr(jitted, "_cache_size"):
        # CompileWatch's compile/retrace detection reads the jit cache
        # size through whatever it wraps (introspect.CompileWatch._size);
        # without this passthrough the guard wrapper silently disables
        # compile accounting for every pipeline step factory (pinned by
        # experiments/pp_fusion_smoke.py's retrace + compile-meta gates).
        guarded._cache_size = jitted._cache_size
    return guarded


def _make_pp_local_step(cfg: LlamaConfig, optimizer, body: Callable, *,
                        n_stages: int, n_microbatches: int, has_data: bool,
                        tp: int, comm_scale: int = 1,
                        numerics=None) -> Callable:
    """The per-shard pipeline train-step body shared by the per-step
    factory (``make_pipeline_step``) and the K-step scan driver
    (``make_pipeline_multi_step``) — the ``_make_local_grad_step`` pattern
    (parallel/dp.py): one implementation, so per-step and fused dispatch
    cannot drift, and their bitwise equality at any K is a structural
    property, not a numerical accident (pinned in tests/test_pp.py for
    all three schedules).

    Runs under shard_map over (data, stage[, model]). The optimizer is
    applied to each shard's LOCAL param slice — valid for elementwise
    optimizers (sgd/adam/adamw/..., the same slice-commuting argument as
    ZeRO-1, ops/adam.py), which is every optimizer this repo ships. The
    interleaved layout tag is re-pinned exactly after the update.

    ``numerics`` (a ``make_pp_numerics`` handle): the second output
    becomes ``(loss, NumericsSummary)`` with stage-stacked group stats —
    extra OUTPUTS only, so losses/params are bitwise identical on vs off.
    """

    def local_step(state: TrainState, tokens):
        loss, grads = body(state.params, tokens, cfg, n_stages,
                           n_microbatches, has_data, tp,
                           comm_scale=comm_scale)
        params, opt_state = apply_optimizer(optimizer, grads,
                                            state.opt_state, state.params)
        if _LAYOUT_KEY in params:
            # Keep the layout tag exactly invariant under ANY optimizer —
            # zero grad does not protect it from params-coupled transforms
            # like decoupled weight decay.
            params = dict(params, **{_LAYOUT_KEY: state.params[_LAYOUT_KEY]})
        new_state = TrainState(params, opt_state, state.step + 1)
        if numerics is not None:
            summary = numerics.summarize(state.params, grads, params)
            return new_state, (loss, summary)
        return new_state, loss

    return local_step


def make_pipeline_step(cfg: LlamaConfig, optimizer: optax.GradientTransformation,
                       mesh: Mesh, n_microbatches: int = 1,
                       schedule: str = "gpipe", n_chunks: int = 2,
                       numerics=None) -> Callable:
    """jit-compiled pipeline train step over mesh axes (data, stage).

    ``n_microbatches=1`` degenerates to the reference's naive staged pipeline
    (intro_PP_1F1B.py); ``>1`` is the homework_1_b1 GPipe schedule; a mesh
    with ``data > 1`` is the homework_1_b2 DP×PP topology; adding a
    ``model`` axis gives the full 3-D DP×PP×TP layout.

    ``schedule`` selects "gpipe" (autodiff-transposed forward scan, O(M)
    activation memory), "1f1b" (interleaved hand-written backward, O(S)
    activation memory), or "interleaved" (virtual-stage schedule with
    ``n_chunks`` chunks per stage — smallest bubble, O(v·M) memory;
    requires params permuted via `interleave_params` — checked loudly on
    the first step — and n_microbatches divisible by n_stages) — all
    compute the identical gradient.

    ``numerics`` (``make_pp_numerics``) arms the in-jit run-health summary;
    the step then returns ``(state, (loss, NumericsSummary))``.

    ``optimizer`` must be ELEMENTWISE (sgd / adam / adamw / the ops/
    fused variants — everything this repo ships): the update runs inside
    shard_map on each shard's local stage slice (so the per-step and
    fused K-step drivers share one body bitwise), which is only
    equivalent to a full-tree update for transforms that commute with
    slicing. A globally-coupled transform (e.g.
    ``optax.clip_by_global_norm``) would clip per stage slice — wrong
    silently; keep such chains on the DP trainer.

    Returns ``step(state, tokens) -> (state, loss)`` where tokens is the
    global [B, T] batch, B divisible by data_size · n_microbatches.
    """
    n_stages = mesh.shape["stage"]
    has_data = mesh.shape.get("data", 1) > 1
    tp = mesh.shape.get("model", 1)
    body = _schedule_body(schedule, n_chunks)
    local_step = _make_pp_local_step(cfg, optimizer, body, n_stages=n_stages,
                                     n_microbatches=n_microbatches,
                                     has_data=has_data, tp=tp,
                                     numerics=numerics)

    def step(state: TrainState, tokens):
        specs = param_specs(state.params, tp=tp > 1)
        state_specs = TrainState(specs,
                                 _opt_specs(state.opt_state, state.params,
                                            specs), P())
        out_specs = (state_specs,
                     ((P(), numerics.summary_specs()) if numerics is not None
                      else P()))
        return shard_map(
            local_step, mesh=mesh,
            in_specs=(state_specs, P("data") if has_data else P()),
            out_specs=out_specs,
            check_vma=False,
        )(state, tokens)

    jitted = jax.jit(step, donate_argnums=(0,))
    return _layout_guarded(jitted, schedule, n_stages, n_chunks)


def make_pipeline_multi_step(cfg: LlamaConfig,
                             optimizer: optax.GradientTransformation,
                             mesh: Mesh, n_microbatches: int = 1,
                             schedule: str = "gpipe", n_chunks: int = 2,
                             numerics=None) -> Callable:
    """Fused K-step pipeline driver: ``step(state, window) -> (state,
    losses)`` where ``window`` is a device-resident ``[K, B, T]`` token
    window (leading axis = K consecutive training steps, second axis
    sharded over ``data`` on a DP×PP mesh — ``shard_batch_window``) and
    ``losses`` is the ``[K]`` per-step loss sequence from the scan's
    stacked outputs.

    One compiled, donated dispatch runs all K steps of ANY schedule
    (gpipe / 1f1b / interleaved): the per-step Python dispatch, donation
    bookkeeping and host round trip — the ~1.6× per-step tax on
    dispatch-bound hosts (PR 4 bench) that the PP schedules kept paying
    after DP stopped — are paid once per window. The scanned body IS
    ``make_pipeline_step``'s body (shared ``_make_pp_local_step``), so the
    loss sequence and final params are BITWISE identical to K per-step
    calls at K∈{1,4} for every schedule (tests/test_pp.py), and per-step
    wire bytes are unchanged — the comm profile records the same
    collectives at ``scale=K`` per dispatch
    (``CommProfile.as_dict(steps_per_dispatch=K)`` normalizes).

    K is read from the window's static leading dim at trace time, so ONE
    returned callable serves every chunk size (a tail chunk of k < K
    steps just triggers one more compile for that shape — the trainer's
    CompileWatch stamps each compile event with its actual window size).

    ``optimizer`` must be elementwise — same rule and reason as
    ``make_pipeline_step`` (the shared per-shard body applies it to the
    local stage slice).
    """
    n_stages = mesh.shape["stage"]
    has_data = mesh.shape.get("data", 1) > 1
    tp = mesh.shape.get("model", 1)
    body = _schedule_body(schedule, n_chunks)

    def step(state: TrainState, window):
        specs = param_specs(state.params, tp=tp > 1)
        state_specs = TrainState(specs,
                                 _opt_specs(state.opt_state, state.params,
                                            specs), P())

        def multi(st, win):
            local_step = _make_pp_local_step(
                cfg, optimizer, body, n_stages=n_stages,
                n_microbatches=n_microbatches, has_data=has_data, tp=tp,
                comm_scale=win.shape[0], numerics=numerics)
            return lax.scan(local_step, st, win)

        out_specs = (state_specs,
                     ((P(), numerics.summary_specs(stacked=True))
                      if numerics is not None else P()))
        return shard_map(
            multi, mesh=mesh,
            in_specs=(state_specs, P(None, "data") if has_data else P()),
            out_specs=out_specs,
            check_vma=False,
        )(state, window)

    jitted = jax.jit(step, donate_argnums=(0,))
    return _layout_guarded(jitted, schedule, n_stages, n_chunks)


# ------------------------------------------- DP×PP data-axis ring drivers
#
# The fused hot path built for DP (PRs 3/10/12) stops at the data mesh:
# ZeRO-1 sliced updates, wire-compressed ring reduce-scatter and ACCO-style
# microbatch overlap all assume the step sees the FULL params tree. Under
# DP×PP each (data, stage) shard holds one stage's slice, but the data-axis
# sync of the CROSS-STAGE-REDUCED gradient has exactly the same shape as
# flat DP's: flatten the LOCAL stage tree, ring it over ``data``, update
# the owned 1/n slice, gather the fresh slices back. The drivers below
# compose the existing machinery (compress.ring_reduce_scatter, the int8
# encode + EF-residual discipline, dp.slice_index's data-rank ownership)
# with the pipeline schedule bodies — the one new piece is the residual /
# moment layout, which gains a ``stage`` axis ([n_data, n_stages, ...],
# sharded P("data", "stage")) because each stage's shard group compensates
# its own stage's quantization error. With a real ``model`` axis in the
# mesh (DP×PP×TP) the layout gains one more trailing shard axis and the
# schedule bodies run their Megatron-TP partial forms — the composition
# rule that replaced the old model=1 hard error (see parallel/tp.py's
# DP×TP section for the TP-mesh counterpart and the int8 cross-model
# scale caveat, which applies to the stage/model-replicated leaves here
# identically).


def _pp_flat_geometry(mesh: Mesh, params):
    """Padded flat-vector geometry of the LOCAL per-(stage[, model])-shard
    param tree — the unit the DP×PP data-axis zero1/ring sync operates on.
    Every stage's local tree has the same flat length (equal [L/S] block
    slices + the stage-replicated embed/head/final_norm), and on a
    DP×PP×TP mesh the column/row-sharded block leaves additionally
    contribute 1/tp of their elements, identically on every model shard —
    so the geometry is SPMD-consistent across both non-data axes. Returns
    ``(n, pad, local, total)`` with n = the ``data`` axis size and total =
    the per-shard param count."""
    n = mesh.shape.get("data", 1)
    n_stages = mesh.shape["stage"]
    tp = mesh.shape.get("model", 1)
    total = 0
    for k, v in params.items():
        if k == "blocks":
            for name, leaf in v.items():
                size = sum(int(x.size) for x in jax.tree.leaves(leaf))
                size //= n_stages
                if name in _TP_COL or name in _TP_ROW:
                    size //= tp
                total += size
        else:
            total += sum(int(x.size) for x in jax.tree.leaves(v))
    pad = (-total) % n
    local = (total + pad) // n
    return n, pad, local, total


def _pp_bucket_map(mesh: Mesh, params, comm_buckets: int):
    """The DP×PP ``BucketMap``: ``compress.make_bucket_map`` over the
    PER-CELL leaf geometry — each (stage[, model]) cell's local tree
    (stage block slices of [L/S] layers, col/row leaves at 1/tp, the
    stage-replicated embed/head/final-norm in full), which is the tree
    the shard_map body actually flattens. Returns None at
    ``comm_buckets == 1`` (the legacy single-vector path)."""
    from .compress import make_bucket_map

    if int(comm_buckets) < 1:
        raise ValueError(
            f"comm_buckets must be >= 1 (got {comm_buckets})")
    if int(comm_buckets) == 1:
        return None
    n = mesh.shape.get("data", 1)
    n_stages = mesh.shape["stage"]
    tp = mesh.shape.get("model", 1)

    def leaf_local(path, leaf):
        key = getattr(path[0], "key", None) if path else None
        if key == "blocks":
            name = getattr(path[1], "key", None) if len(path) > 1 else None
            size = int(leaf.size) // n_stages
            if name in _TP_COL or name in _TP_ROW:
                size //= tp
            return size, int(leaf.shape[0]) // n_stages
        return int(leaf.size), None

    return make_bucket_map(params, n, comm_buckets, leaf_local=leaf_local)


def _pp_overlap_setup(optimizer, mesh: Mesh, params, wire: str,
                      aggregation: str, schedule: str, n_chunks: int,
                      comm_buckets: int = 1):
    """State + shard specs + flat geometry for the DP×PP overlap drivers.

    ZeRO-1 moments live as ``[n_data, n_stages, local]`` global arrays
    sharded ``P("data", "stage")`` — each (d, s) shard owns the moments of
    stage s's d-th flat slice (the ``dp.slice_index`` data-rank ownership
    map applied per stage group); int8 EF residuals get the same layout
    (ring: ``[n, S, n·local]``; gather: ``[n, S, local]``), because each
    (data, stage) shard compensates its OWN quantization error.

    On a DP×PP×TP mesh (``model > 1`` — the composition rule the TP PSA
    work lifted the old model=1 hard error into, see parallel/tp.py's
    DP×TP section) every per-shard layout gains a trailing ``model``
    axis: moments ``[n, S, tp, local]``, residuals
    ``[n, S, tp, n·local | local]``, sharded ``P("data", "stage",
    "model")`` — each (d, s, m) shard rings its OWN per-model-shard flat
    slice over ``data``, so the rings on different model coordinates are
    independent. The tp == 1 layouts stay byte-identical to the classic
    DP×PP ones (checkpoint compatibility).

    ``comm_buckets > 1`` (the bucketed backward, ``compress.BucketMap``
    over the PER-CELL geometry — ``_pp_bucket_map``) turns the ZeRO-1
    moments and both EF residuals into per-bucket tuples, mirroring the
    DP driver's layout rule with the (stage[, model]) shard axes kept."""
    if aggregation not in ("gradient", "zero1"):
        raise ValueError("the DP×PP overlap driver supports gradient/zero1 "
                         f"aggregation only (got {aggregation!r})")
    if wire not in ("fp32", "bf16", "int8_ef"):
        raise ValueError(f"unknown wire format {wire!r}")
    if "data" not in mesh.axis_names:
        raise ValueError("the DP×PP overlap driver needs a mesh with a "
                         "'data' axis (size 1 is fine) — build it with "
                         'make_mesh({"data": d, "stage": s})')
    if mesh.shape.get("dcn", 1) > 1:
        raise ValueError("the DP×PP overlap driver runs the flat data ring "
                         "only; the hierarchical (dcn x data) tier is the "
                         "DP trainer's (parallel/compress.py)")
    tp = mesh.shape.get("model", 1)
    n_stages = mesh.shape["stage"]
    # Leading shard axes the per-shard [local] views are wrapped in:
    # (data, stage) on the classic DP×PP mesh, (data, stage, model) once
    # a real model axis joins. tp == 1 keeps the old 2-axis layout so
    # existing checkpoints round-trip byte-identically.
    lead = 3 if tp > 1 else 2
    dshard = (P("data", "stage", "model") if tp > 1
              else P("data", "stage"))
    _check_layout(params.get(_LAYOUT_KEY), schedule, n_stages, n_chunks)
    n, pad, local, total = _pp_flat_geometry(mesh, params)
    bm = _pp_bucket_map(mesh, params, comm_buckets)
    specs = param_specs(params, tp=tp > 1)
    sharded = shard_params(mesh, params)
    step0 = jax.device_put(jnp.zeros((), jnp.int32),
                           NamedSharding(mesh, P()))
    if aggregation == "zero1":
        sizes = bm.sizes if bm is not None else (local,)

        def _specs_for(sz):
            abstract = jax.eval_shape(
                optimizer.init, jax.ShapeDtypeStruct((sz,), jnp.float32))
            return jax.tree.map(
                lambda x: dshard if getattr(x, "ndim", 0) >= 1 else P(),
                abstract)

        opt_specs = (_specs_for(local) if bm is None
                     else tuple(_specs_for(sz) for sz in sizes))

        def local_init(p):
            from ..utils import pytree as pt
            from .compress import _bucket_vectors
            shard = lax.axis_index("data")
            if bm is None:
                flat = jnp.pad(pt.flatten(p)[0].astype(jnp.float32),
                               (0, pad))
                mine = [lax.dynamic_slice_in_dim(flat, shard * local,
                                                 local)]
            else:
                vecs = _bucket_vectors(bm, p)
                mine = [lax.dynamic_slice_in_dim(
                    vecs[b], shard * bm.sizes[b], bm.sizes[b])
                    for b in range(bm.nbuckets)]
            # Vector leaves gain the (data, stage[, model]) shard axes;
            # scalars (count) replicate — every shard steps them
            # identically.
            opts = [jax.tree.map(
                lambda x: (x[(None,) * lead]
                           if getattr(x, "ndim", 0) >= 1 else x),
                optimizer.init(m)) for m in mine]
            return opts[0] if bm is None else tuple(opts)

        opt_state = jax.jit(shard_map(
            local_init, mesh=mesh, in_specs=(specs,),
            out_specs=opt_specs, check_vma=False))(sharded)
        state = TrainState(sharded, opt_state, step0)
    else:
        opt_state = sharded_opt_init(mesh, sharded, optimizer, specs)
        opt_specs = _opt_specs(opt_state, sharded, specs)
        state = TrainState(sharded, opt_state, step0)
    if wire == "int8_ef":
        from .compress import OverlapEFState
        mid = (n_stages, tp) if tp > 1 else (n_stages,)

        def _zeros(shape):
            return jax.device_put(jnp.zeros(shape, jnp.float32),
                                  NamedSharding(mesh, dshard))

        if bm is None:
            ring_res = _zeros((n,) + mid + (n * local,))
            gather_res = _zeros((n,) + mid + (local,))
            ring_specs = gather_specs = dshard
        else:
            ring_res = tuple(_zeros((n,) + mid + (n * sz,))
                             for sz in bm.sizes)
            gather_res = tuple(_zeros((n,) + mid + (sz,))
                               for sz in bm.sizes)
            ring_specs = gather_specs = (dshard,) * bm.nbuckets
        state = OverlapEFState(state.params, state.opt_state, state.step,
                               ring_res, gather_res)
        state_specs = OverlapEFState(specs, opt_specs, P(), ring_specs,
                                     gather_specs)
    else:
        state_specs = TrainState(specs, opt_specs, P())
    return state, state_specs, n, pad, local, total, bm


def _stage_coord_ids(params, n: int, n_stages: int, comm_buckets: int):
    """Global-coordinate id layout of the DP×PP flat state space: for each
    stage ``s`` and ring bucket ``b``, the int64 array mapping every slot of
    the ``[n·sizes[b]]`` bucket vector (data-row-major: row ``r`` owns slots
    ``[r·sizes[b], (r+1)·sizes[b])``) to a unique id over the GLOBAL param
    coordinates, with ``-1`` marking pad slots. Ids are assigned in tree
    order over the global leaves; a stage's block slice maps to the
    contiguous ``[s·gsz/S, (s+1)·gsz/S)`` range of its leaf's ravel (the
    blocked layer layout), and stage-replicated leaves (embed/head/
    final-norm) share one id range across stages.

    This is the coordinate system ``repartition_stage_state`` reshards
    through: a value's id is topology-invariant, so gathering an old
    ``(n, S)`` stack by id and re-reading it at ``(n', S')`` is a bitwise
    per-coordinate copy whatever moved — the data world, the stage count,
    or both. Returns ``(ids, sizes, total_coords)`` with ``ids[s][b]`` the
    per-(stage, bucket) map and ``sizes`` the per-shard bucket sizes."""
    from .compress import make_bucket_map

    entries = jax.tree_util.tree_flatten_with_path(params)[0]
    bases, metas = [], []
    off = 0
    for path, leaf in entries:
        key = getattr(path[0], "key", None) if path else None
        gsz = int(np.prod(np.shape(leaf), dtype=int))
        is_block = key == "blocks"
        if is_block and gsz % n_stages:
            raise ValueError(f"blocks leaf of {gsz} elements does not "
                             f"split over {n_stages} stages")
        bases.append(off)
        metas.append((is_block, gsz, gsz // n_stages if is_block else gsz))
        off += gsz
    total_coords = off

    def local_ids(s):
        out = []
        for base, (is_block, gsz, lsz) in zip(bases, metas):
            start = base + s * lsz if is_block else base
            out.append(np.arange(start, start + lsz, dtype=np.int64))
        return out

    B = int(comm_buckets)
    if B == 1:
        total = sum(lsz for _, _, lsz in metas)
        pad = (-total) % n
        sizes = ((total + pad) // n,)
        ids = [[np.concatenate(local_ids(s)
                               + [np.full((pad,), -1, np.int64)])]
               for s in range(n_stages)]
        return ids, sizes, total_coords

    def leaf_local(path, leaf):
        key = getattr(path[0], "key", None) if path else None
        if key == "blocks":
            return (int(np.prod(np.shape(leaf), dtype=int)) // n_stages,
                    int(np.shape(leaf)[0]) // n_stages)
        return int(np.prod(np.shape(leaf), dtype=int)), None

    bm = make_bucket_map(params, n, B, leaf_local=leaf_local)
    ids = []
    for s in range(n_stages):
        lids = local_ids(s)
        per_bucket = []
        for b, pieces in enumerate(bm.pieces):
            parts = [lids[li][st:st + sz] for li, st, sz in pieces]
            if b == bm.nbuckets - 1 and bm.pad:
                parts.append(np.full((bm.pad,), -1, np.int64))
            per_bucket.append(np.concatenate(parts))
        ids.append(per_bucket)
    return ids, bm.sizes, total_coords


def repartition_stage_state(host_state, template_state):
    """Stage re-partition / data reshard of a DP×PP overlap-state host
    snapshot: rewrite the ``(data, stage)``-stacked ZeRO-1 moments
    (``[n, S, local]``, per-bucket tuples under ``comm_buckets > 1``), ring
    EF residuals (``[n, S, n·local]``) and gather residuals
    (``[n, S, local]``) from the snapshot's ``(n, S)`` topology to the
    template's ``(n', S')`` — S may change (layer re-partition after a
    stage loss), n may change (data-axis shrink/grow on the DP×PP mesh),
    or both. Equal-shape leaves — global params (``blocks`` keeps its
    ``[n_layers, ...]`` shape at ANY stage count), per-leaf moments of the
    gradient-aggregation path, scalars — pass through untouched for
    ``reshard_state``'s placement rule.

    Mechanism: every state coordinate gets a topology-invariant global id
    (``_stage_coord_ids``); the old stacks scatter by id into one global
    vector per (row, bucket) and the new stacks gather back — a bitwise
    per-coordinate copy, the stage-axis generalization of
    ``dp._resize_ring_residual``'s pad swap. Conventions carried over from
    the data-only path: values in pad slots must be exactly zero (hard
    error, never silent truncation); ring rows beyond the new data world
    are dropped with their shards, new rows start at zero error, and each
    surviving row's own-chunk slot re-zeros in the new geometry.
    Stage-replicated leaves (embed/head/final-norm) carry identical
    moments on every stage (their gradients are stage-psum'd), so the
    by-id overwrite is value-stable; ring residuals there keep the
    highest surviving stage's pending error (deterministic — both
    recovery paths and the fresh-run comparison all route through here).

    Named errors: bucket-count mismatches (rebucketing a live EF state is
    undefined), an interleaved layout across a stage-count change (the
    chunked layer order breaks the blocked-slice id map), a model axis in
    the template mesh (DP×PP×TP elastic is out of scope), and an ``S'``
    that does not divide ``n_layers``."""
    t_arrays = [x for x in jax.tree.leaves(template_state)
                if isinstance(x, jax.Array)]
    if not t_arrays:
        return host_state
    mesh = t_arrays[0].sharding.mesh
    if mesh.shape.get("model", 1) > 1:
        raise ValueError(
            "elastic re-mesh of the DP×PP×TP overlap state is unsupported "
            "— the (data, stage, model) stacks have no reshard rule; run "
            "elastic DP×PP at model=1")
    n_new = int(mesh.shape.get("data", 1))
    s_new = int(mesh.shape["stage"])

    def _stacks(state):
        """(opt vector stacks, ring tuple, gather tuple) — tuples
        normalized to per-bucket lists; None where the field is absent."""
        ring = getattr(state, "ring_residual", None)
        gather = getattr(state, "gather_residual", None)
        as_list = (lambda x: None if x is None
                   else (list(x) if isinstance(x, tuple) else [x]))
        return as_list(ring), as_list(gather)

    h_ring, h_gather = _stacks(host_state)
    t_ring, t_gather = _stacks(template_state)
    if (h_ring is None) != (t_ring is None) or (
            h_ring is not None and len(h_ring) != len(t_ring)):
        raise ValueError(
            f"comm_buckets mismatch: the snapshot carries "
            f"{len(h_ring) if h_ring else 0} EF residual bucket(s), the "
            f"template {len(t_ring) if t_ring else 0} — rebucketing a "
            "live EF state is not defined; rebuild the trainer with the "
            "snapshot's comm_buckets")

    # The snapshot's (n, S) topology, read off the stacked leaves whose
    # shapes DIFFER from the template's. Shape-equal 3-D leaves must pass
    # through untouched — the gradient-aggregation path's param-shaped
    # moments (blocks [L, d, d]) are global arrays, not stacks — so only
    # mismatched pairs identify the (data, stage) stacks to rewrite.
    pairs = set()

    def _note(h, t):
        hs, ts = tuple(np.shape(h)), tuple(np.shape(t))
        if len(hs) == 3 and len(ts) == 3 and hs != ts:
            pairs.add((hs[:2], ts[:2]))

    jax.tree.map(_note, host_state.opt_state, template_state.opt_state)
    for h, t in zip(h_ring or [], t_ring or []):
        _note(h, t)
    for h, t in zip(h_gather or [], t_gather or []):
        _note(h, t)
    if not pairs:
        return host_state       # same topology: placement-only reshard
    olds = {o for o, _ in pairs}
    news = {t for _, t in pairs}
    if len(olds) != 1 or len(news) != 1:
        raise ValueError(
            f"inconsistent (data, stage) stack topologies across the "
            f"snapshot/template state: {sorted(olds)} -> {sorted(news)} — "
            "the stacks of one overlap state must share one layout")
    (n_old, s_old), = olds
    n_old, s_old = int(n_old), int(s_old)
    if next(iter(news)) != (n_new, s_new):
        raise ValueError(
            f"template stacks are laid out {next(iter(news))} but its "
            f"mesh is (data={n_new}, stage={s_new}) — not a DP×PP "
            "overlap template")

    params = host_state.params
    blocks = params.get("blocks", {})
    n_layers = int(np.shape(jax.tree.leaves(blocks)[0])[0]) if blocks else 0
    for s, tag in ((s_old, "snapshot"), (s_new, "template")):
        if n_layers and n_layers % s:
            raise ValueError(
                f"stage re-partition: the {tag}'s stage count {s} does "
                f"not divide n_layers={n_layers} — layers shard as equal "
                "[n_layers/S] blocks, so S' must divide n_layers")
    if s_old != s_new and _LAYOUT_KEY in params:
        raise ValueError(
            "stage re-partition of an interleaved layout is unsupported: "
            "the chunk-major layer order breaks the blocked [L/S] stage "
            "slices the re-partition re-slices — run elastic PP with "
            "schedule='gpipe' or '1f1b'")

    # Bucket structure. The ring-bucket count splits every per-shard flat
    # slice into per-bucket stacks, and the ZeRO-1 opt tree mirrors it as
    # a TOP-LEVEL tuple of per-bucket optax states. With EF residuals the
    # count is the residual tuple's; without them a bucketed opt tuple
    # must be told apart from a single optax state (which is itself a
    # tuple) — done by checking which bucket geometry actually explains
    # the mismatched stack sizes.
    def _mismatch_dims(opt, t_opt):
        dims = []

        def leaf(x, t):
            hs, ts = tuple(np.shape(x)), tuple(np.shape(t))
            if len(hs) == 3 and hs != ts:
                dims.append(int(hs[2]))

        jax.tree.map(leaf, opt, t_opt)
        return dims

    def _explains(nb_try):
        try:
            sizes = _stage_coord_ids(params, n_old, s_old, nb_try)[1]
        except ValueError:
            return False
        if nb_try == 1:
            return all(d == sizes[0]
                       for d in _mismatch_dims(host_state.opt_state,
                                               template_state.opt_state))
        if not (isinstance(host_state.opt_state, tuple)
                and len(host_state.opt_state) == nb_try):
            # nb buckets but no per-bucket opt tuple: legal only when the
            # opt tree has no stacks at all (gradient aggregation keeps
            # param-shaped global moments).
            return not _mismatch_dims(host_state.opt_state,
                                      template_state.opt_state)
        return all(d == sizes[b]
                   for b in range(nb_try)
                   for d in _mismatch_dims(host_state.opt_state[b],
                                           template_state.opt_state[b]))

    if h_ring is not None:
        nb = len(h_ring)
        if not _explains(nb):
            raise ValueError(
                f"DP×PP overlap snapshot does not match its own bucket "
                f"geometry ({nb} bucket(s) at data={n_old}, "
                f"stage={s_old}) — refusing to re-partition")
    else:
        cands = [1] + ([len(host_state.opt_state)]
                       if isinstance(host_state.opt_state, tuple) else [])
        nb = next((c for c in cands if _explains(c)), None)
        if nb is None:
            raise ValueError(
                "cannot infer the bucket structure of the DP×PP ZeRO-1 "
                "stacks — the mismatched stack sizes fit neither a "
                "single-bucket nor a per-bucket tuple layout")
    opt_bucketed = (nb > 1 and isinstance(host_state.opt_state, tuple)
                    and len(host_state.opt_state) == nb
                    and bool(_mismatch_dims(host_state.opt_state,
                                            template_state.opt_state)))
    ids_old, sizes_old, total_coords = _stage_coord_ids(
        params, n_old, s_old, nb)
    ids_new, sizes_new, _ = _stage_coord_ids(params, n_new, s_new, nb)

    def _scatter(g, vals, ids, what):
        pad = ids < 0
        if np.any(vals[pad] != 0):
            raise ValueError(
                f"nonzero {what} values in the flat pad tail — the "
                "snapshot does not look like a zero-padded DP×PP stack")
        g[ids[~pad]] = vals[~pad]

    # A coordinate's bucket changes with the topology (bucket boundaries
    # are carved out of the per-stage LOCAL geometry), so a field's
    # buckets pool into ONE global id-indexed vector before the new
    # layout gathers back — a per-bucket-independent remap would drop
    # every coordinate that migrated buckets.
    def _stacks_to_global(stacks, what):
        g = None
        for b, h in enumerate(stacks):
            h = np.asarray(h)
            if h.shape != (n_old, s_old, sizes_old[b]):
                raise ValueError(
                    f"{what} stack has shape {h.shape}, expected "
                    f"{(n_old, s_old, sizes_old[b])}")
            if g is None:
                g = np.zeros((total_coords,), h.dtype)
            for s in range(s_old):
                _scatter(g, np.ascontiguousarray(h[:, s]).reshape(-1),
                         ids_old[s][b], what)
        return g

    def _global_to_stacks(g, dtype):
        out = []
        for b in range(nb):
            ob = np.zeros((n_new, s_new, sizes_new[b]), dtype)
            for s2 in range(s_new):
                ids = ids_new[s2][b]
                vals = np.where(ids >= 0, g[np.clip(ids, 0, None)], 0)
                ob[:, s2] = vals.reshape(n_new, sizes_new[b]).astype(dtype)
            out.append(ob)
        return out

    def _remap_field(stacks, what):
        g = _stacks_to_global(stacks, what)
        return _global_to_stacks(g, np.asarray(stacks[0]).dtype)

    def _remap_ring_field(rings):
        dtype = np.asarray(rings[0]).dtype
        outs = [np.zeros((n_new, s_new, n_new * sizes_new[b]), dtype)
                for b in range(nb)]
        for r in range(min(n_old, n_new)):
            g = np.zeros((total_coords,), dtype)
            for b, h in enumerate(rings):
                h = np.asarray(h)
                if h.shape != (n_old, s_old, n_old * sizes_old[b]):
                    raise ValueError(
                        f"ring_residual stack has shape {h.shape}, "
                        f"expected "
                        f"{(n_old, s_old, n_old * sizes_old[b])}")
                for s in range(s_old):
                    _scatter(g, h[r, s], ids_old[s][b], "ring_residual")
            for b in range(nb):
                for s2 in range(s_new):
                    ids = ids_new[s2][b]
                    outs[b][r, s2] = np.where(ids >= 0,
                                              g[np.clip(ids, 0, None)], 0)
                # The owner never quantizes its own chunk — structurally
                # zero, but the chunk boundaries moved with (n', S').
                outs[b][r, :,
                        r * sizes_new[b]:(r + 1) * sizes_new[b]] = 0.0
        return outs

    def _remap_opt_tree(opts, t_opts):
        """Remap the stacked leaves of per-bucket same-treedef opt states
        jointly (leaf j of bucket b is one field's bucket-b stack)."""
        flat = [jax.tree_util.tree_flatten(o) for o in opts]
        t_flat = [jax.tree_util.tree_flatten(o)[0] for o in t_opts]
        leaves = [list(f[0]) for f in flat]
        for j in range(len(leaves[0])):
            hs = tuple(np.shape(leaves[0][j]))
            ts = tuple(np.shape(t_flat[0][j]))
            if len(hs) == 3 and hs != ts:
                outs = _remap_field([leaves[b][j] for b in range(nb)],
                                    "opt_state")
                for b in range(nb):
                    leaves[b][j] = outs[b]
        return [jax.tree_util.tree_unflatten(flat[b][1], leaves[b])
                for b in range(nb)]

    if opt_bucketed:
        new_opt = tuple(_remap_opt_tree(list(host_state.opt_state),
                                        list(template_state.opt_state)))
    else:
        new_opt = _remap_opt_tree([host_state.opt_state],
                                  [template_state.opt_state])[0]
    host_state = host_state._replace(opt_state=new_opt)
    if h_ring is not None:
        ring = _remap_ring_field(h_ring)
        gather = _remap_field(h_gather, "gather_residual")
        host_state = host_state._replace(
            ring_residual=tuple(ring) if len(ring) > 1 else ring[0],
            gather_residual=tuple(gather) if len(gather) > 1 else gather[0])
    return host_state


def _make_pp_overlap_local_step(cfg: LlamaConfig, optimizer, body: Callable,
                                *, n_stages: int, n_microbatches: int,
                                tp: int, n: int, pad: int, local: int,
                                total: int, microbatches: int, wire: str,
                                aggregation: str, comm_scale: int = 1,
                                bucket_map=None,
                                numerics=None) -> Callable:
    """The per-shard DP×PP overlapped step body shared by
    ``make_pipeline_overlap_step`` and ``make_pipeline_overlap_multi_step``.

    Structure per step (under shard_map over (data, stage)): the local
    batch splits into M sync-microbatches; each runs the FULL pipeline
    schedule (with its own n_microbatches pipeline microbatches) via the
    shared schedule body called with ``has_data_axis=False`` — the
    cross-STAGE reductions still run, but the data-axis pmean is replaced
    by the ring: microbatch m−1's flat cross-stage-reduced gradient rides
    the ppermute ring (``compress.ring_reduce_scatter`` over ``data``, in
    the ``wire`` format with per-(shard, chunk) error feedback) in the same
    trace positions as microbatch m's schedule — the ACCO overlap, now
    under the pipeline. Reduced chunks accumulate in fp32 on the owner;
    zero1 updates the owned slice and gathers fresh params (int8 delta
    gather under ``wire="int8_ef"`` — everyone applies the same quantized
    deltas, so replicas stay bitwise in sync), gradient aggregation
    gathers the reduced gradient (in the wire format) and applies the
    replicated update.

    Numerics contract mirrors the flat driver's
    (``compress._make_overlap_local_step``): M>1 re-associates (reduce-
    then-accumulate vs the pmean path's accumulate-then-reduce), so
    equivalence vs ``make_pipeline_step`` is fp32-tolerance; M=1 fp32
    differs only by ring-vs-XLA reduction order. The interleaved layout
    tag re-pins exactly after the flat update round-trip.

    ``bucket_map`` (``compress.BucketMap`` over the per-cell geometry,
    None for the legacy single-vector path) selects the bucketed
    backward: per-bucket rings in VJP emission order under
    ``pp_ring_grad_b{b}`` labels, single-collective gather legs, and
    per-bucket moment/residual tuples — the DP driver's rules
    (``compress._make_overlap_local_step``) under the pipeline."""
    from ..utils import pytree as pt
    from .compress import (_bucket_slices, _bucket_vectors, _int8_encode,
                           _scatter_buckets, ring_reduce_scatter)

    M = microbatches
    bm = bucket_map
    B = bm.nbuckets if bm is not None else 1
    ef = wire == "int8_ef"
    # Leading shard axes wrapping the per-shard [local] state views:
    # (data, stage) classically, (data, stage, model) on a DP×PP×TP mesh
    # (layout rule in _pp_overlap_setup).
    lead = 3 if tp > 1 else 2
    # Cell-agreed int8 scales (compress._int8_encode docstring): each
    # (stage[, model]) cell's flat vector mixes cell-SPECIFIC leaves (the
    # stage's block slice, its col/row shards) with leaves REPLICATED
    # across those axes (embed/head/final-norm over stage, norm scales
    # over model), so per-cell scales would decode the replicated entries
    # differently per cell and silently drift the replicas apart — the
    # stage axis always needs the agreement, the model axis joins on the
    # composed DP×PP×TP mesh. Pinned by the replica-sync tests in
    # tests/test_pp.py.
    ssync = ("stage", "model") if tp > 1 else ("stage",)

    def _ring_all(pending, ring_res):
        # pending: the flat vector (bm None) or the per-bucket vector
        # list; ring_res mirrors it. Returns the owned [local] slice
        # (concat of per-bucket chunks when bucketed).
        if bm is None:
            return ring_reduce_scatter(
                pending, "data", wire=wire, residual=ring_res,
                label="pp_ring_grad", comm_scale=comm_scale,
                scale_sync_axis=ssync)
        reds, news = [], []
        for b in range(B):
            red_b, r_b = ring_reduce_scatter(
                pending[b], "data", wire=wire,
                residual=ring_res[b] if ef else None,
                label=f"pp_ring_grad_b{b}", comm_scale=comm_scale,
                scale_sync_axis=ssync)
            reds.append(red_b)
            news.append(r_b)
        return jnp.concatenate(reds), news

    def local_step(state, tokens):
        params = state.params
        if tokens.shape[0] % M:
            raise ValueError(f"local batch {tokens.shape[0]} not divisible "
                             f"by overlap_microbatches={M}")
        micro = tokens.reshape((M, -1) + tokens.shape[1:])
        if not ef:
            ring_res = None
        elif bm is None:
            ring_res = state.ring_residual[(0,) * lead]
        else:
            ring_res = [r[(0,) * lead] for r in state.ring_residual]
        acc = jnp.zeros((local,), jnp.float32)
        loss_sum = jnp.zeros((), jnp.float32)
        gacc = None
        pending = None
        for m in range(M):
            l, g = body(params, micro[m], cfg, n_stages, n_microbatches,
                        False, tp, comm_scale=comm_scale)
            loss_sum = loss_sum + l.astype(jnp.float32)
            if numerics is not None:
                # Extra OUTPUT only: the fp32 grad accumulator feeds the
                # summary, never the ring — losses/params bitwise on/off.
                gacc = (jax.tree.map(lambda x: x.astype(jnp.float32), g)
                        if gacc is None else
                        jax.tree.map(lambda a, b: a + b.astype(jnp.float32),
                                     gacc, g))
            if pending is not None:
                # Microbatch m−1's ring rides alongside microbatch m's
                # schedule (the body call above): independent dataflow.
                red, ring_res = _ring_all(pending, ring_res)
                acc = acc + red
            pending = (_bucket_vectors(bm, g) if bm is not None else
                       jnp.pad(pt.flatten(g)[0].astype(jnp.float32),
                               (0, pad)))
        red, ring_res = _ring_all(pending, ring_res)
        acc = acc + red
        g_mine = acc / (n * M)      # mean over data shards and microbatches
        loss = comm.pmean(loss_sum / M, "data", label="loss_allreduce",
                          scale=comm_scale)

        raw_flat, unravel = pt.flatten(params)
        if bm is None:
            flat_p = jnp.pad(raw_flat.astype(jnp.float32), (0, pad))
            pvecs = None
        else:
            flat_p = None
            pvecs = _bucket_vectors(bm, params)
        gather_res = None
        shard = lax.axis_index("data")
        if aggregation == "zero1":
            if bm is None:
                p_mine = lax.dynamic_slice_in_dim(flat_p, shard * local,
                                                  local)
                # Local moment view: (data, stage[, model])-sharded vector
                # leaves squeeze to the flat slice; scalars pass.
                opt_local = jax.tree.map(
                    lambda x: (x[(0,) * lead]
                               if getattr(x, "ndim", 0) >= lead + 1 else x),
                    state.opt_state)
                new_p_mine, opt_local = apply_optimizer(optimizer, g_mine,
                                                        opt_local, p_mine)
                opt_state = jax.tree.map(
                    lambda x: (x[(None,) * lead]
                               if getattr(x, "ndim", 0) >= 1 else x),
                    opt_local)
            else:
                # One optimizer apply per bucket against the per-bucket
                # moment tuple (layout rule in _pp_overlap_setup).
                p_chunks = [lax.dynamic_slice_in_dim(
                    pvecs[b], shard * bm.sizes[b], bm.sizes[b])
                    for b in range(B)]
                new_chunks, opts = [], []
                for b in range(B):
                    opt_local = jax.tree.map(
                        lambda x: (x[(0,) * lead]
                                   if getattr(x, "ndim", 0) >= lead + 1
                                   else x),
                        state.opt_state[b])
                    np_b, opt_local = apply_optimizer(
                        optimizer,
                        g_mine[bm.offsets[b]:bm.offsets[b] + bm.sizes[b]],
                        opt_local, p_chunks[b])
                    new_chunks.append(np_b)
                    opts.append(jax.tree.map(
                        lambda x: (x[(None,) * lead]
                                   if getattr(x, "ndim", 0) >= 1 else x),
                        opt_local))
                p_mine = jnp.concatenate(p_chunks)
                new_p_mine = jnp.concatenate(new_chunks)
                opt_state = tuple(opts)
            vec_new = None
            if wire == "int8_ef":
                # Compressed second leg: broadcast the param DELTA int8
                # with its own EF residual (the compress.py zero1 rule —
                # fp32 moments stay exact, replicas stay bitwise in sync).
                gres = (jnp.concatenate(
                    [r[(0,) * lead] for r in state.gather_residual])
                    if bm is not None
                    else state.gather_residual[(0,) * lead])
                q, s, gather_res = _int8_encode(
                    (new_p_mine - p_mine) + gres,
                    scale_sync_axis=ssync)
                q_all = comm.all_gather(q, "data", tiled=True,
                                        label="pp_delta_gather_int8",
                                        scale=comm_scale)
                s_all = comm.all_gather(s[None], "data", tiled=True,
                                        label="pp_delta_scale_gather",
                                        scale=comm_scale)
                if bm is None:
                    flat_new = flat_p + (jnp.repeat(s_all, local)
                                         * q_all.astype(jnp.float32))
                else:
                    q_slc = _bucket_slices(bm, q_all.astype(jnp.float32))
                    vec_new = [pvecs[b]
                               + jnp.repeat(s_all, bm.sizes[b]) * q_slc[b]
                               for b in range(B)]
            else:
                # bf16 wire compresses the RING leg only — the param
                # gather stays fp32 (params stay exact, compress.py rule).
                flat_new = comm.all_gather(new_p_mine, "data", tiled=True,
                                           label="pp_param_gather",
                                           scale=comm_scale)
                if bm is not None:
                    vec_new = _bucket_slices(bm, flat_new)
            if bm is None:
                new_params = unravel(
                    flat_new[:total].astype(raw_flat.dtype))
            else:
                new_params = _scatter_buckets(bm, vec_new, params)
        else:                       # replicated gradient update
            if wire == "int8_ef":
                gres = (jnp.concatenate(
                    [r[(0,) * lead] for r in state.gather_residual])
                    if bm is not None
                    else state.gather_residual[(0,) * lead])
                q, s, gather_res = _int8_encode(
                    g_mine + gres, scale_sync_axis=ssync)
                q_all = comm.all_gather(q, "data", tiled=True,
                                        label="pp_grad_gather_int8",
                                        scale=comm_scale)
                s_all = comm.all_gather(s[None], "data", tiled=True,
                                        label="pp_grad_scale_gather",
                                        scale=comm_scale)
                flat_g = (jnp.repeat(s_all, local)
                          * q_all.astype(jnp.float32))
            elif wire == "bf16":
                flat_g = comm.all_gather(
                    g_mine.astype(jnp.bfloat16), "data", tiled=True,
                    label="pp_grad_gather_bf16",
                    scale=comm_scale).astype(jnp.float32)
            else:
                flat_g = comm.all_gather(g_mine, "data", tiled=True,
                                         label="pp_grad_gather",
                                         scale=comm_scale)
            if bm is None:
                grads = unravel(flat_g[:total].astype(raw_flat.dtype))
            else:
                grads = _scatter_buckets(bm, _bucket_slices(bm, flat_g),
                                         params)
            new_params, opt_state = apply_optimizer(optimizer, grads,
                                                    state.opt_state, params)
        if _LAYOUT_KEY in new_params:
            new_params = dict(new_params,
                              **{_LAYOUT_KEY: params[_LAYOUT_KEY]})
        step = state.step + 1
        if ef:
            from .compress import OverlapEFState
            if bm is not None:
                ring_out = tuple(r[(None,) * lead] for r in ring_res)
                gather_out = tuple(
                    gather_res[bm.offsets[b]:bm.offsets[b] + bm.sizes[b]]
                    [(None,) * lead]
                    for b in range(B))
            else:
                ring_out = ring_res[(None,) * lead]
                gather_out = gather_res[(None,) * lead]
            new_state = OverlapEFState(new_params, opt_state, step,
                                       ring_out, gather_out)
        else:
            new_state = TrainState(new_params, opt_state, step)
        if numerics is not None:
            summary = numerics.summarize(
                params, jax.tree.map(lambda x: x / M, gacc), new_params)
            return new_state, (loss, summary)
        return new_state, loss

    return local_step


def make_pipeline_overlap_step(cfg: LlamaConfig,
                               optimizer: optax.GradientTransformation,
                               mesh: Mesh, params, *,
                               n_microbatches: int = 1,
                               schedule: str = "gpipe", n_chunks: int = 2,
                               aggregation: str = "zero1",
                               wire: str = "fp32",
                               overlap_microbatches: int = 1,
                               comm_buckets: int = 1,
                               numerics=None):
    """Per-step DP×PP composition driver: ``step(state, tokens) -> (state,
    loss)`` over a ``[n_data·B, T]`` batch sharded over ``data``, with the
    data-axis gradient sync routed through the compressed/overlapped ring
    (semantics in ``_make_pp_overlap_local_step``; ``comm_buckets > 1``
    selects the bucketed backward). Returns ``(state,
    step_fn)`` — an ``OverlapEFState`` under ``wire="int8_ef"`` (EF
    residuals in the checkpointed tree, per (data, stage) shard), a plain
    TrainState otherwise, with ZeRO-1 moments sharded over
    ``(data, stage)`` when ``aggregation="zero1"``."""
    n_stages = mesh.shape["stage"]
    body = _schedule_body(schedule, n_chunks)
    state, state_specs, n, pad, local, total, bm = _pp_overlap_setup(
        optimizer, mesh, params, wire, aggregation, schedule, n_chunks,
        comm_buckets)
    has_data = mesh.shape.get("data", 1) > 1
    local_step = _make_pp_overlap_local_step(
        cfg, optimizer, body, n_stages=n_stages,
        n_microbatches=n_microbatches, tp=mesh.shape.get("model", 1), n=n,
        pad=pad, local=local, total=total,
        microbatches=overlap_microbatches, wire=wire,
        aggregation=aggregation, bucket_map=bm, numerics=numerics)
    out_specs = (state_specs,
                 ((P(), numerics.summary_specs()) if numerics is not None
                  else P()))
    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(state_specs, P("data") if has_data else P()),
        out_specs=out_specs, check_vma=False)
    return state, jax.jit(sharded, donate_argnums=(0,))


def make_pipeline_overlap_multi_step(cfg: LlamaConfig,
                                     optimizer: optax.GradientTransformation,
                                     mesh: Mesh, params, *,
                                     n_microbatches: int = 1,
                                     schedule: str = "gpipe",
                                     n_chunks: int = 2,
                                     aggregation: str = "zero1",
                                     wire: str = "fp32",
                                     overlap_microbatches: int = 1,
                                     comm_buckets: int = 1,
                                     numerics=None):
    """The DP×PP composition driver inside the K-step scan: ``step(state,
    window) -> (state, losses)`` with ``window`` a ``[K, n_data·B, T]``
    batch window (``shard_batch_window``) run in ONE compiled, donated
    dispatch — ZeRO-1 moments AND int8 EF residuals ride the scan carry,
    so error feedback is exact across fused steps, chunk-edge checkpoints
    and a preempt/resume cycle (pinned in tests/test_pp.py). The scanned
    body IS ``make_pipeline_overlap_step``'s, so the loss sequence and
    final state are bitwise-identical to K per-step calls at any K."""
    n_stages = mesh.shape["stage"]
    body = _schedule_body(schedule, n_chunks)
    state, state_specs, n, pad, local, total, bm = _pp_overlap_setup(
        optimizer, mesh, params, wire, aggregation, schedule, n_chunks,
        comm_buckets)
    has_data = mesh.shape.get("data", 1) > 1

    def multi(st, window):
        local_step = _make_pp_overlap_local_step(
            cfg, optimizer, body, n_stages=n_stages,
            n_microbatches=n_microbatches, tp=mesh.shape.get("model", 1),
            n=n, pad=pad, local=local, total=total,
            microbatches=overlap_microbatches, wire=wire,
            aggregation=aggregation, comm_scale=window.shape[0],
            bucket_map=bm, numerics=numerics)
        return lax.scan(local_step, st, window)

    out_specs = (state_specs,
                 ((P(), numerics.summary_specs(stacked=True))
                  if numerics is not None else P()))
    sharded = shard_map(
        multi, mesh=mesh,
        in_specs=(state_specs, P(None, "data") if has_data else P()),
        out_specs=out_specs, check_vma=False)
    return state, jax.jit(sharded, donate_argnums=(0,))


# --------------------------------------------------- stage-stacked numerics

def make_pp_numerics(params, mesh: Mesh, *, psum_data: bool = False):
    """In-jit numerics for the pipeline step bodies (the
    ``TrainConfig.numerics_every`` lever, telemetry/introspect.py).

    The DP summarizer assumes the step sees the FULL params tree; under PP
    each shard holds only its stage's block slice, so the per-layer-group
    geometry is built on the LOCAL stage template and the per-stage group
    stats come back STACKED over the ``stage`` axis (shard_map out-spec
    ``P("stage")``). Host-side, block groups are stage-qualified
    ("stage1/blocks/0" = the second stage's first LOCAL layer; under the
    interleaved layout, local indices follow ``interleave_params``'s
    chunk-major order) and the stage-replicated groups (embed / head /
    final norm — their grads are psum'd across stages by
    ``_reduce_loss_and_grads``) are kept once, from stage 0's copy.

    ``psum_data=True`` additionally psum-agrees grad stats and the finite
    mask over ``data`` (the overlap/ring path, where local gradients
    differ per data shard — compress.py's rule); the plain gradient path's
    grads are already data-pmean'd, so it passes False and pays nothing.
    Same bitwise contract as DP's: extra OUTPUTS only — losses/params are
    identical with the summary on or off (pinned in tests/test_pp.py)."""
    import numpy as np

    from ..telemetry import introspect

    if mesh.shape.get("model", 1) > 1:
        raise ValueError(
            "make_pp_numerics supports model=1 meshes: its per-group "
            "summaries are not model-axis psum-agreed, so stats would "
            "differ per TP shard. The overlap/ring drivers themselves DO "
            "compose with model>1 now (DP×PP×TP, see _pp_overlap_setup); "
            "for model-axis-agreed numerics use a TP mesh with "
            "tp.make_tp_numerics.")
    n_stages = mesh.shape["stage"]
    local_template = {
        k: (jax.tree.map(lambda x: x[: x.shape[0] // n_stages], v)
            if k == "blocks" else v)
        for k, v in params.items()}
    base = introspect.make_summarizer(
        local_template, psum_axis="data" if psum_data else None)

    def stage_expand(names, block_flags):
        rows, cols, out = [], [], []
        for s in range(n_stages):
            for i, name in enumerate(names):
                if block_flags[i]:
                    rows.append(s)
                    cols.append(i)
                    out.append(f"stage{s}/{name}")
        for i, name in enumerate(names):
            if not block_flags[i]:
                rows.append(0)
                cols.append(i)
                out.append(name)
        return (np.asarray(rows), np.asarray(cols)), out

    g_idx, groups = stage_expand(
        base.groups, [g.startswith("blocks/") for g in base.groups])
    l_idx, paths = stage_expand(
        base.paths, [p.startswith("blocks/") for p in base.paths])

    def summarize(params_local, grads_local, new_params_local):
        s = base.summarize(params_local, grads_local, new_params_local)
        # [1, G]/[1, L]: the leading axis becomes ``stage`` through the
        # shard_map out-spec.
        return introspect.NumericsSummary(*(x[None] for x in s))

    class _PPHandle(introspect.NumericsHandle):
        def summary_specs(self, stacked: bool = False):
            """shard_map out-specs for the stage-stacked summary leaves:
            ``[S, ·]`` per-step, ``[K, S, ·]`` under the K-step scan."""
            spec = P(None, "stage") if stacked else P("stage")
            return introspect.NumericsSummary(spec, spec, spec, spec)

        def event_fields(self, summary, *, index=None, top=4):
            def host(x):
                a = np.asarray(x)
                return a[index] if index is not None else a

            flat = introspect.NumericsSummary(
                grad_sq=host(summary.grad_sq)[g_idx],
                param_sq=host(summary.param_sq)[g_idx],
                update_sq=host(summary.update_sq)[g_idx],
                grad_finite=host(summary.grad_finite)[l_idx])
            return introspect.NumericsHandle.event_fields(
                self, flat, index=None, top=top)

    return _PPHandle(groups, paths, summarize)


def shard_batch_window(mesh: Mesh, window) -> jax.Array:
    """Device-put a [K, B, T] host batch window for the fused pipeline
    drivers: leading axis = K consecutive steps (replicated — every shard
    scans the same step sequence), second axis sharded over ``data`` when
    the mesh carries a real data axis (a size-1 axis normalizes to the
    replicated spec — the dp.data_partition jit-cache-stability rule)."""
    spec = P(None, "data") if mesh.shape.get("data", 1) > 1 else P()
    return jax.device_put(window, NamedSharding(mesh, spec))


from .mesh import shard_batch  # noqa: E402,F401  (shared batch placement)
