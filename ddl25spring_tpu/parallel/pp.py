"""Pipeline parallelism: GPipe and 1F1B microbatch schedules as one SPMD program.

Capability target (NOT a port): the reference's three pipeline variants —
- naive 3-stage PP: one batch flows stage0→1→2 forward then back with
  blocking send/recv (reference: lab/tutorial_1b/PP/1F1B/intro_PP_1F1B.py:27-99
  — the file is *named* 1F1B but implements a naive schedule; here 1F1B is
  actually implemented, see `_pipeline_1f1b_loss_and_grad`);
- microbatched GPipe: batch split into microbatches streamed with
  isend/irecv(tag=itr), grads accumulated across microbatches, one step per
  iteration (lab/tutorial_1a/homework_1_b1.py:50-144);
- joint DP×PP: two 3-stage pipelines + a cross-pipeline gradient allreduce
  (lab/hw01/homework 1 b/homework_1_b2.py:28-32,141-150).

TPU-native shape: ranks, tags, and point-to-point sockets disappear. Stages
are a named mesh axis; the per-iteration schedule is a ``lax.scan`` over
``n_microbatches + n_stages - 1`` ticks; the stage→stage activation hop is a
single ``lax.ppermute`` over the ICI ring. Crucially the *backward* pipeline
is not hand-written: ``jax.grad`` of the scanned forward transposes every
ppermute (hop direction reverses) and replays ticks in reverse — the reverse
schedule the reference codes by hand (homework_1_b1.py:111-139) falls out of
autodiff. Microbatch gradient semantics match the reference's accumulate-
then-step (one optimizer step per iteration, loss averaged over microbatches).

Two recorded reference quirks are deliberately NOT reproduced (documented
deviations, SURVEY.md §2.10/§3.3):
- homework_1_b1 retains only the *last* microbatch's activations, so stages
  0/1 only receive the last microbatch's backward. Here every microbatch
  back-propagates through every stage (faithful GPipe).
- homework_1_b2 allreduces gradients only in the first-stage DP group [0,3];
  replicas of other stages silently diverge. Here ALL stages pmean over the
  ``data`` axis.

DP×PP composes by construction: build the mesh with ``{"data": d, "stage": s}``
and the same step function pmean-s grads over ``data`` — the 2-pipeline ×
3-stage homework topology is ``make_mesh({"data": 2, "stage": 3})``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry import comm
from ._compat import shard_map

from ..config import LlamaConfig
from ..models import llama
from .dp import TrainState, apply_optimizer, sharded_opt_init


# ------------------------------------------------------------- param layout

from .tp import _COL as _TP_COL, _ROW as _TP_ROW  # one source of truth for
# which block leaves are column- vs row-sharded under tensor parallelism.


def param_specs(params: dict, tp: bool = False) -> dict:
    """PartitionSpecs for a stacked-block Llama param tree on a pipeline mesh.

    ``blocks`` (leading [n_layers] axis) shards over ``stage`` — each stage
    holds its contiguous slice of layers, the SPMD analog of simplellm's
    First/Stage/Last per-rank modules. With ``tp`` the block weight matrices
    additionally shard over ``model`` in the Megatron layout (parallel.tp).
    Embedding/head/final-norm stay replicated: only the first/last stage
    *reads* them, and their gradients are psum-ed back to all stages so the
    replicated update is identical.
    """
    def block_leaf_spec(name):
        if tp and name in _TP_COL:
            return P("stage", None, "model")
        if tp and name in _TP_ROW:
            return P("stage", "model", None)
        return P("stage")

    specs = {}
    for k, v in params.items():
        if k == "blocks":
            specs[k] = {name: jax.tree.map(lambda _, s=block_leaf_spec(name): s,
                                           leaf)
                        for name, leaf in v.items()}
        else:
            specs[k] = jax.tree.map(lambda _: P(), v)
    return specs


def shard_params(mesh: Mesh, params: dict) -> dict:
    specs = param_specs(params, tp=mesh.shape.get("model", 1) > 1)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def init_state(mesh: Mesh, params: dict, optimizer: optax.GradientTransformation) -> TrainState:
    """Shard params over the pipeline mesh; optimizer moments are explicitly
    placed with the param specs via dp.sharded_opt_init (a plain jitted
    optimizer.init would commit the whole opt state to one device)."""
    params = shard_params(mesh, params)
    opt_state = sharded_opt_init(mesh, params, optimizer,
                                 param_specs(params, tp=mesh.shape.get("model", 1) > 1))
    step = jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P()))
    return TrainState(params, opt_state, step)


# ------------------------------------------------------------- the schedule

def _pipeline_loss_and_grad(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
                            n_stages: int, n_microbatches: int,
                            has_data_axis: bool,
                            tp: int = 1) -> Tuple[jnp.ndarray, dict]:
    """Per-device body (runs under shard_map): GPipe forward over ticks,
    grads via autodiff, cross-stage/data reductions.

    ``params["blocks"]`` is the LOCAL stage slice [n_layers/n_stages, ...];
    ``tokens`` is the local data shard [B_local, T] with
    B_local = n_microbatches · microbatch_size. With ``tp > 1`` the block
    weights are additionally model-sharded (Megatron; see parallel.tp) and
    the loss is scaled by 1/tp under differentiation — every model shard
    seeds an identical loss replica, and the in-forward psums (transpose:
    psum) would otherwise count each weight path tp times.
    """
    stage = lax.axis_index("stage")
    is_first = stage == 0
    is_last = stage == n_stages - 1
    tp_axis = "model" if tp > 1 else None
    b, t = tokens.shape
    assert b % n_microbatches == 0, (b, n_microbatches)
    mb = b // n_microbatches
    tokens_mb = tokens.reshape(n_microbatches, mb, t)
    n_ticks = n_microbatches + n_stages - 1
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def loss_fn(p: dict) -> jnp.ndarray:
        def tick(carry, i):
            x_prev, loss_sum = carry
            # Stage 0 injects microbatch i (clipped: bubble ticks re-embed the
            # last microbatch and the result is masked out by the schedule).
            tok_in = tokens_mb[jnp.clip(i, 0, n_microbatches - 1)]
            x_in = jnp.where(is_first[..., None, None, None],
                             llama.embed(p, tok_in, cfg), x_prev)
            h = llama.blocks_apply(p["blocks"], x_in, cfg, tp_axis=tp_axis)
            # Last stage: microbatch (i - (n_stages-1)) exits the pipe here.
            out_i = i - (n_stages - 1)
            tok_out = tokens_mb[jnp.clip(out_i, 0, n_microbatches - 1)]
            valid = is_last & (out_i >= 0)
            mb_loss = lax.cond(
                valid,
                lambda: llama.head_loss(p, h, tok_out, cfg),
                lambda: jnp.zeros((), jnp.float32))
            # The hop: activations ride the ICI ring to the next stage. The
            # last→first edge carries bubble garbage that stage 0 discards.
            # (scale=n_ticks: the scan body traces once, hops n_ticks times;
            # the backward hops autodiff adds are telemetry/comm.py's
            # documented under-count.)
            x_next = comm.ppermute(h, "stage", fwd, label="pp_activation_hop",
                                   scale=n_ticks)
            return (x_next, loss_sum + mb_loss), None

        x0 = jnp.zeros((mb, t, cfg.dmodel), jnp.dtype(cfg.dtype))
        (_, loss_sum), _ = lax.scan(
            tick, (x0, jnp.zeros((), jnp.float32)), jnp.arange(n_ticks))
        # LOCAL loss: nonzero only on the last stage. Do NOT psum over
        # ``stage`` here — the backward program is itself SPMD (ppermute
        # transposes hop the cotangent back up the ring), so every stage's
        # grads are reached from the last stage's seed alone; psum-ing the
        # loss first would seed all n_stages replicas and count each path
        # n_stages times. The 1/tp scaling is the model-axis counterpart.
        return loss_sum / n_microbatches / tp

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return _reduce_loss_and_grads(loss, grads, tp_axis, has_data_axis, tp)


def _reduce_loss_and_grads(loss, grads, tp_axis, has_data_axis, tp):
    """Cross-stage/model/data reductions shared by both schedules."""
    loss = comm.psum(loss, "stage",  # broadcast + undo 1/tp for reporting
                     label="pp_loss_allreduce") * tp

    def reduce_grad(name, g):
        # Block weight matrices under TP are sharded over ``model`` — their
        # local grads are complete. Everything else replicated over ``model``
        # gets partial grads from each shard: psum. Leaves outside ``blocks``
        # (embed/head/final_norm) are also replicated over ``stage`` and got
        # grads only on the stage that read them: psum over ``stage`` too.
        if tp_axis is not None and name not in _TP_COL | _TP_ROW:
            g = jax.tree.map(
                lambda x: comm.psum(x, tp_axis,
                                    label="tp_replicated_grads"), g)
        return g

    grads = {
        k: ({name: reduce_grad(name, g) for name, g in v.items()}
            if k == "blocks"
            else jax.tree.map(
                lambda g: comm.psum(g, "stage",
                                    label="pp_replicated_grads"),
                reduce_grad(k, v)))
        for k, v in grads.items()
    }
    if has_data_axis:
        # The DP×PP cross-pipeline sync — for ALL stages, not just stage 0
        # (the reference's [0,3]-only allreduce is a recorded bug).
        grads = comm.pmean(grads, "data", label="grad_allreduce")
        loss = comm.pmean(loss, "data", label="loss_allreduce")
    return loss, grads


# ------------------------------------------------------- interleaved layout

def interleave_blocks(blocks, n_stages: int, n_chunks: int):
    """Permute the stacked [L] block axis into the interleaved-schedule layout.

    The interleaved schedule assigns stage ``s`` the *non-contiguous* virtual
    stages ``c·S + s`` (chunk c ∈ [0, v)); mesh sharding over ``stage`` always
    hands each device a *contiguous* slice of the leading axis. Rather than
    reshard every step, permute once so that the contiguous local slice
    [s·L/S, (s+1)·L/S) holds exactly stage s's chunks, ordered by c:
    position ``s·(L/S) + c·per + l`` ← layer ``(c·S + s)·per + l`` with
    ``per = L/(S·v)``. `deinterleave_blocks` inverts (e.g. before comparing
    with a GPipe run or exporting a checkpoint in natural layer order).
    """
    return jax.tree.map(
        lambda x: x[_interleave_order(x.shape[0], n_stages, n_chunks)], blocks)


def deinterleave_blocks(blocks, n_stages: int, n_chunks: int):
    """Inverse of `interleave_blocks`."""
    def inv(x):
        order = _interleave_order(x.shape[0], n_stages, n_chunks)
        inverse = jnp.zeros_like(order).at[order].set(jnp.arange(order.size))
        return x[inverse]
    return jax.tree.map(inv, blocks)


# The interleaved layout is shape-identical to the natural one, so a layout
# mistake cannot be caught from the arrays. interleave_params tags the tree
# with a scalar sentinel (value encodes S and v) that make_pipeline_step
# verifies on the first call — natural-layout params under
# schedule="interleaved" (or vice versa) fail loudly instead of silently
# running layers in the wrong order. The sentinel is a float32 leaf; its
# grad is identically zero so plain Adam/SGD leave it alone, and
# make_pipeline_step additionally re-pins it after every optimizer update so
# params-coupled transforms (adamw weight decay, EMA) cannot drift it.
_LAYOUT_KEY = "blocks_layout"


def _layout_tag(n_stages: int, n_chunks: int) -> float:
    return float(n_stages * 1000 + n_chunks)


def interleave_params(params: dict, n_stages: int, n_chunks: int) -> dict:
    """`interleave_blocks` over the full param tree, plus the layout tag.

    Use this (not a bare ``dict(params, blocks=interleave_blocks(...))``)
    before ``init_state`` when training with ``schedule="interleaved"``.
    """
    out = dict(params, blocks=interleave_blocks(params["blocks"],
                                                n_stages, n_chunks))
    out[_LAYOUT_KEY] = jnp.float32(_layout_tag(n_stages, n_chunks))
    return out


def deinterleave_params(params: dict, n_stages: int, n_chunks: int) -> dict:
    """Inverse of `interleave_params` (natural layer order, tag stripped)."""
    out = dict(params, blocks=deinterleave_blocks(params["blocks"],
                                                  n_stages, n_chunks))
    out.pop(_LAYOUT_KEY, None)
    return out


def _interleave_order(n_layers: int, n_stages: int, n_chunks: int) -> jnp.ndarray:
    assert n_layers % (n_stages * n_chunks) == 0, (n_layers, n_stages, n_chunks)
    per = n_layers // (n_stages * n_chunks)
    return jnp.asarray([(c * n_stages + s) * per + l
                        for s in range(n_stages)
                        for c in range(n_chunks)
                        for l in range(per)])


def _pipeline_interleaved_loss_and_grad(params: dict, tokens: jnp.ndarray,
                                        cfg: LlamaConfig, n_stages: int,
                                        n_microbatches: int, has_data_axis: bool,
                                        tp: int = 1, n_chunks: int = 2
                                        ) -> Tuple[jnp.ndarray, dict]:
    """Interleaved virtual-stage schedule (Megatron-LM's "virtual pipeline"):
    each stage holds ``v = n_chunks`` non-contiguous layer chunks and every
    microbatch rides the ICI ring v times, visiting virtual stage c·S+s on
    its c-th lap. A stage is busy v·M of the v·M + S − 1 ticks, so the
    bubble fraction drops from GPipe's (S−1)/(M+S−1) to (S−1)/(v·M+S−1) —
    the fill/drain cost is amortized over v× more (smaller) stage visits.

    Injection is grouped: microbatches enter in waves of S (ticks where
    (j − s) mod v·S < S present stage 0 with a fresh microbatch; on all other
    ticks its input is the wrap-around of an in-flight lap), so M must be a
    multiple of S. At tick j, stage s works on relative tick r = j − s:
    group g = r // (v·S), chunk c = (r mod v·S) // S, microbatch
    g·S + (r mod S); valid iff 0 ≤ r < v·M. The loss exits at stage S−1 on
    chunk v−1. Backward is the autodiff transpose of the whole scan (GPipe
    semantics): simple and exact, at the cost of stashing O(v·M) microbatch
    activations — combine with ``cfg.remat`` when memory matters; the 1F1B
    O(S) stash bound does not apply to this schedule.

    ``params["blocks"]`` must be in `interleave_blocks` layout (the local
    [L/S] slice is [v, per] chunk-major): permute with
    ``interleave_params(params, S, v)`` BEFORE ``init_state`` places the
    tree on the mesh (a later permute across the sharded stage axis would
    be an all-to-all). The layout is shape-identical to the natural one so
    it cannot be asserted from the arrays; `make_pipeline_step` checks the
    `interleave_params` layout tag on the first call instead.
    """
    stage = lax.axis_index("stage")
    is_first = stage == 0
    is_last = stage == n_stages - 1
    tp_axis = "model" if tp > 1 else None
    v = n_chunks
    b, t = tokens.shape
    assert b % n_microbatches == 0, (b, n_microbatches)
    assert n_microbatches % n_stages == 0, (n_microbatches, n_stages)
    mb = b // n_microbatches
    tokens_mb = tokens.reshape(n_microbatches, mb, t)
    n_ticks = v * n_microbatches + n_stages - 1
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def loss_fn(p: dict) -> jnp.ndarray:
        # Local blocks [L/S, ...] → [v, per, ...], chunk-major by layout.
        n_local = jax.tree.leaves(p["blocks"])[0].shape[0]
        per = n_local // v
        chunks = jax.tree.map(
            lambda x: x.reshape((v, per) + x.shape[1:]), p["blocks"])

        def tick(carry, j):
            x_prev, loss_sum = carry
            r = j - stage
            valid = (r >= 0) & (r < v * n_microbatches)
            cyc = jnp.mod(r, v * n_stages)
            c = jnp.clip(cyc // n_stages, 0, v - 1)
            mb_idx = jnp.clip(r // (v * n_stages) * n_stages
                              + jnp.mod(cyc, n_stages),
                              0, n_microbatches - 1)
            tok = tokens_mb[mb_idx]
            inject = is_first & (cyc < n_stages)
            x_in = jnp.where(inject[..., None, None, None],
                             llama.embed(p, tok, cfg), x_prev)
            chunk_c = jax.tree.map(
                lambda x: lax.dynamic_index_in_dim(x, c, keepdims=False),
                chunks)
            h = llama.blocks_apply(chunk_c, x_in, cfg, tp_axis=tp_axis)
            exit_here = is_last & (c == v - 1) & valid
            mb_loss = lax.cond(
                exit_here,
                lambda: llama.head_loss(p, h, tok, cfg),
                lambda: jnp.zeros((), jnp.float32))
            x_next = comm.ppermute(h, "stage", fwd, label="pp_activation_hop",
                                   scale=n_ticks)
            return (x_next, loss_sum + mb_loss), None

        x0 = jnp.zeros((mb, t, cfg.dmodel), jnp.dtype(cfg.dtype))
        (_, loss_sum), _ = lax.scan(
            tick, (x0, jnp.zeros((), jnp.float32)), jnp.arange(n_ticks))
        return loss_sum / n_microbatches / tp   # same seeding rule as GPipe

    loss, grads = jax.value_and_grad(loss_fn)(params)
    return _reduce_loss_and_grads(loss, grads, tp_axis, has_data_axis, tp)


def _pipeline_1f1b_loss_and_grad(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
                                 n_stages: int, n_microbatches: int,
                                 has_data_axis: bool,
                                 tp: int = 1) -> Tuple[jnp.ndarray, dict]:
    """1F1B (one-forward-one-backward) schedule, hand-written backward.

    GPipe (above) lets autodiff transpose the whole forward scan, which means
    every tick's stage input — n_microbatches + n_stages − 1 activations —
    is saved for the backward replay: activation memory grows linearly with
    the microbatch count. 1F1B interleaves each microbatch's backward as soon
    as its forward clears the last stage, so at most ``2·n_stages − 1``
    microbatch inputs are ever in flight per stage (Megatron-LM's memory
    argument; the bubble fraction itself matches GPipe). Because a ``vjp``
    closure cannot ride a ``lax.scan`` carry, the backward recomputes the
    stage forward from the stashed *input* — the standard full-recompute
    (remat) variant, so the fair time comparison is GPipe with
    ``cfg.remat=True`` (see experiments/pp_schedules.py for measurements).

    Schedule (SPMD lockstep; iteration j does one F then one B sub-tick):
    - F: stage s runs microbatch ``i_f = j − s``            (valid if 0≤i_f<M)
    - B: stage s runs microbatch ``i_b = j − 2(S−1) + s``   (valid if 0≤i_b<M)
    so the last stage backs up microbatch i immediately after forwarding it
    (same j), and the cotangent hops one stage down the ring per iteration.
    Gradient semantics are identical to GPipe: mean loss over microbatches,
    grads accumulated across B sub-ticks, one optimizer step per call.
    """
    stage = lax.axis_index("stage")
    is_first = stage == 0
    is_last = stage == n_stages - 1
    tp_axis = "model" if tp > 1 else None
    b, t = tokens.shape
    assert b % n_microbatches == 0, (b, n_microbatches)
    mb = b // n_microbatches
    tokens_mb = tokens.reshape(n_microbatches, mb, t)
    n_iters = n_microbatches + 2 * (n_stages - 1)
    n_slots = min(2 * n_stages - 1, n_microbatches)
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    bwd_perm = [(i, (i - 1) % n_stages) for i in range(n_stages)]
    dt = jnp.dtype(cfg.dtype)

    def stage_fn(p: dict, act_in: jnp.ndarray, i: jnp.ndarray):
        """One stage application for microbatch index i (clipped): embeds on
        the first stage, computes the (masked) loss on the last."""
        tok = tokens_mb[jnp.clip(i, 0, n_microbatches - 1)]
        x_in = jnp.where(is_first[..., None, None, None],
                         llama.embed(p, tok, cfg), act_in)
        h = llama.blocks_apply(p["blocks"], x_in, cfg, tp_axis=tp_axis)
        mb_loss = lax.cond(
            is_last,
            lambda: llama.head_loss(p, h, tok, cfg),
            lambda: jnp.zeros((), jnp.float32))
        return h, mb_loss

    def iteration(carry, j):
        stash, grads, loss_sum, x_fwd, g_bwd = carry

        # --- F sub-tick: forward microbatch i_f, stash its input ----------
        i_f = j - stage
        valid_f = (i_f >= 0) & (i_f < n_microbatches)
        act_in = x_fwd
        h, _ = stage_fn(params, act_in, i_f)
        slot_f = jnp.clip(i_f, 0, n_microbatches - 1) % n_slots
        old = lax.dynamic_index_in_dim(stash, slot_f, keepdims=False)
        stash = lax.dynamic_update_index_in_dim(
            stash, jnp.where(valid_f, act_in, old), slot_f, axis=0)
        x_fwd = comm.ppermute(h, "stage", fwd_perm,
                              label="pp_activation_hop", scale=n_iters)

        # --- B sub-tick: vjp-recompute microbatch i_b from its stash ------
        i_b = j - 2 * (n_stages - 1) + stage
        valid_b = (i_b >= 0) & (i_b < n_microbatches)
        slot_b = jnp.clip(i_b, 0, n_microbatches - 1) % n_slots
        act_b = lax.dynamic_index_in_dim(stash, slot_b, keepdims=False)
        (_, mb_loss), pull = jax.vjp(
            lambda p, a: stage_fn(p, a, i_b), params, act_b)
        # Cotangent seeds: the last stage seeds from its own loss (scaled for
        # the microbatch mean and the TP loss-replica double count, as in
        # GPipe's loss_fn); every other stage seeds from the cotangent that
        # arrived down the ring. Invalid sub-ticks seed zero, which makes
        # their (finite) recomputed grads exactly zero — no masking needed.
        g_h = jnp.where((is_last | ~valid_b)[..., None, None, None],
                        jnp.zeros_like(g_bwd), g_bwd)
        g_loss = jnp.where(is_last & valid_b, 1.0 / (n_microbatches * tp), 0.0)
        dp, da = pull((g_h, g_loss.astype(jnp.float32)))
        grads = jax.tree.map(jnp.add, grads, dp)
        loss_sum = loss_sum + jnp.where(is_last & valid_b, mb_loss, 0.0)
        g_bwd = comm.ppermute(da.astype(dt), "stage", bwd_perm,
                              label="pp_cotangent_hop", scale=n_iters)

        return (stash, grads, loss_sum, x_fwd, g_bwd), None

    stash0 = jnp.zeros((n_slots, mb, t, cfg.dmodel), dt)
    grads0 = jax.tree.map(jnp.zeros_like, params)
    act0 = jnp.zeros((mb, t, cfg.dmodel), dt)
    (_, grads, loss_sum, _, _), _ = lax.scan(
        iteration,
        (stash0, grads0, jnp.zeros((), jnp.float32), act0, act0),
        jnp.arange(n_iters))
    return _reduce_loss_and_grads(loss_sum / n_microbatches / tp, grads,
                                  tp_axis, has_data_axis, tp)


def make_pipeline_step(cfg: LlamaConfig, optimizer: optax.GradientTransformation,
                       mesh: Mesh, n_microbatches: int = 1,
                       schedule: str = "gpipe", n_chunks: int = 2) -> Callable:
    """jit-compiled pipeline train step over mesh axes (data, stage).

    ``n_microbatches=1`` degenerates to the reference's naive staged pipeline
    (intro_PP_1F1B.py); ``>1`` is the homework_1_b1 GPipe schedule; a mesh
    with ``data > 1`` is the homework_1_b2 DP×PP topology; adding a
    ``model`` axis gives the full 3-D DP×PP×TP layout.

    ``schedule`` selects "gpipe" (autodiff-transposed forward scan, O(M)
    activation memory), "1f1b" (interleaved hand-written backward, O(S)
    activation memory), or "interleaved" (virtual-stage schedule with
    ``n_chunks`` chunks per stage — smallest bubble, O(v·M) memory;
    requires params permuted via `interleave_params` — checked loudly on
    the first step — and n_microbatches divisible by n_stages) — all
    compute the identical gradient.

    Returns ``step(state, tokens) -> (state, loss)`` where tokens is the
    global [B, T] batch, B divisible by data_size · n_microbatches.
    """
    n_stages = mesh.shape["stage"]
    has_data = mesh.shape.get("data", 1) > 1
    tp = mesh.shape.get("model", 1)
    body = {"gpipe": _pipeline_loss_and_grad,
            "1f1b": _pipeline_1f1b_loss_and_grad,
            "interleaved": functools.partial(
                _pipeline_interleaved_loss_and_grad, n_chunks=n_chunks),
            }[schedule]

    def sharded_grads(params, tokens):
        return body(params, tokens, cfg, n_stages,
                    n_microbatches, has_data, tp)

    def step(state: TrainState, tokens) -> Tuple[TrainState, jnp.ndarray]:
        specs = param_specs(state.params, tp=tp > 1)
        loss, grads = shard_map(
            sharded_grads, mesh=mesh,
            in_specs=(specs, P("data") if has_data else P()),
            out_specs=(P(), specs),
            check_vma=False,
        )(state.params, tokens)
        params, opt_state = apply_optimizer(optimizer, grads,
                                            state.opt_state, state.params)
        if _LAYOUT_KEY in params:
            # Keep the layout tag exactly invariant under ANY optimizer —
            # zero grad does not protect it from params-coupled transforms
            # like decoupled weight decay.
            params = dict(params, **{_LAYOUT_KEY: state.params[_LAYOUT_KEY]})
        return TrainState(params, opt_state, state.step + 1), loss

    jitted = jax.jit(step, donate_argnums=(0,))

    # Layout guard (first call only — params are concrete at the Python call
    # boundary, and reading the scalar here avoids a per-step host sync):
    # schedule="interleaved" demands the interleave_params tag for exactly
    # this (S, v); any other schedule demands its absence.
    checked = []

    def guarded(state: TrainState, tokens) -> Tuple[TrainState, jnp.ndarray]:
        if not checked:
            tag = state.params.get(_LAYOUT_KEY)
            if schedule == "interleaved":
                want = _layout_tag(n_stages, n_chunks)
                if tag is None:
                    raise ValueError(
                        "schedule='interleaved' requires params permuted with "
                        "interleave_params(params, n_stages, n_chunks) before "
                        "init_state — natural-layout blocks would run layers "
                        "in the wrong order")
                if float(tag) != want:
                    raise ValueError(
                        f"params were interleaved for a different topology "
                        f"(tag {float(tag):.0f}, expected {want:.0f} = "
                        f"stages*1000+chunks)")
            elif tag is not None:
                raise ValueError(
                    f"params carry the interleaved layout tag but "
                    f"schedule={schedule!r} expects natural layer order — "
                    f"undo with deinterleave_params first")
            checked.append(True)
        return jitted(state, tokens)

    guarded.lower = jitted.lower   # AOT inspection (experiments/pp_schedules)
    return guarded


from .mesh import shard_batch  # noqa: E402,F401  (shared batch placement)
