"""Compressed gradient all-reduce for data parallelism.

Capability/pattern target: the reference's DP loop all-reduces full-precision
fp32 gradients every iteration (lab/tutorial_1b/DP/gradient_aggr/
intro_DP_GA.py:53-66 — flatten, allreduce, scale); at multi-host scale the
wire bytes of that allreduce are the step's bandwidth bill. Public pattern
references for shrinking it inside an XLA program: EQuARX (quantized
all-reduce in XLA, arxiv 2506.17615) and DynamiQ (compressed all-reduce,
arxiv 2602.08923) — see PAPERS.md. This module implements the two standard
operating points, TPU-first (the compression is elementwise work XLA fuses
around one collective; no custom comm code):

- **bf16 wire format** (``make_bf16_grad_step``): cast grads to bf16, pmean,
  upcast. Halves the wire bytes; stateless; the mantissa loss per step is
  ~1e-3 relative and unbiased enough in practice that it is the default
  "free" lever on DCN-bound topologies.

- **int8 + error feedback** (``make_int8_ef_grad_step``): per-leaf symmetric
  quantization to int8 around the shard-group max (one pmax of the stacked
  per-leaf maxima keeps every shard on the same fixed-point grid), then ONE
  **int8 all-gather of the whole concatenated gradient** — a single
  collective launch whose wire operand is the 1-byte payload — followed by
  an exact local int32 sum and per-leaf dequantization. (A psum of the
  quantized values would be mathematically identical but moves int32 on the
  wire — zero savings; gathering the int8 payload keeps the wire at
  1 byte/element, ~8× fewer bytes than the fp32 allreduce's ≈2×4
  bytes/element, at the cost of an n_shards× int8 transient.) The local
  quantization residual is fed back into the next step's gradient (error
  feedback — the standard fix that restores convergence for biased
  compressors).

Both factories return ``(state, step_fn)`` with the same TrainState the
plain step uses; the int8 variant carries its residual tree inside an
extended state tuple. Equivalence/convergence pinned in
tests/test_compress.py.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry import comm
from ._compat import shard_map

from .dp import TrainState, apply_optimizer, init_state, replicate


def _pmean_bf16(grads, axis: str):
    """pmean with a bf16 wire format: the collective moves half the bytes;
    accumulation happens in the reduction's native precision."""
    down = jax.tree.map(lambda g: g.astype(jnp.bfloat16), grads)
    # Recorded on the bf16 operand: telemetry.comm credits this collective
    # with HALF the fp32 allreduce's payload — the whole point of the wire
    # format, now visible in the comm profile.
    summed = comm.pmean(down, axis, label="grad_allreduce_bf16")
    return jax.tree.map(lambda g, ref: g.astype(ref.dtype), summed, grads)


def make_bf16_grad_step(loss_fn: Callable,
                        optimizer: optax.GradientTransformation,
                        mesh: Mesh) -> Callable:
    """The plain DP gradient-aggregation step with a bf16 collective.

    Drop-in for ``dp.make_grad_aggregation_step`` — same TrainState, same
    loss semantics; only the gradient allreduce's wire format changes."""

    def local_step(state: TrainState, batch) -> Tuple[TrainState, jnp.ndarray]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        grads = _pmean_bf16(grads, "data")
        loss = comm.pmean(loss, "data", label="loss_allreduce")
        params, opt_state = apply_optimizer(optimizer, grads,
                                            state.opt_state, state.params)
        return TrainState(params, opt_state, state.step + 1), loss

    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P("data")), out_specs=(P(), P()),
        check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,))


class EFTrainState(NamedTuple):
    """TrainState + the per-shard error-feedback residual tree."""
    params: Any
    opt_state: Any
    step: jnp.ndarray
    residual: Any


def init_ef_state(mesh: Mesh, params,
                  optimizer: optax.GradientTransformation) -> EFTrainState:
    """The residual is PER-SHARD state (each shard compensates its own
    quantization error): materialized as a ``[n_data, ...]``-stacked tree
    sharded over ``data``, so each shard owns one zero-initialized slice."""
    base = replicate(mesh, init_state(params, optimizer))
    n = mesh.shape["data"]
    stacked = jax.tree.map(
        lambda p: jnp.zeros((n,) + p.shape, p.dtype), params)
    stacked = jax.device_put(stacked, NamedSharding(mesh, P("data")))
    return EFTrainState(base.params, base.opt_state, base.step, stacked)


def make_int8_ef_grad_step(loss_fn: Callable,
                           optimizer: optax.GradientTransformation,
                           mesh: Mesh) -> Callable:
    """DP step with int8-quantized gradient allreduce + error feedback.

    Per step, on each shard: ``c = g_local + residual`` per leaf → ONE pmax
    of the stacked per-leaf maxima (shared fixed-point grids, [n_leaves]
    scalars on the wire) → per-leaf ``q = round(c/s)`` (int8 range) → ONE
    **int8 all-gather of the concatenated payload** (the wire leg: 1
    byte/element, and one collective launch regardless of tree size — the
    per-leaf formulation would pay ~2·n_leaves collective latencies, which
    is what per-collective-latency-bound DCN topologies cannot afford) →
    exact local int32 sum → ``g_avg = s·Σq/n`` per leaf → new residual
    ``c − s·q``. The optimizer consumes ``g_avg``; the un-transmitted
    remainder re-enters next step, so the compressor's bias does not
    accumulate.
    """
    n = mesh.shape["data"]

    def local_step(state: EFTrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        loss = comm.pmean(loss, "data", label="loss_allreduce")

        flat_g, treedef = jax.tree.flatten(grads)
        res = jax.tree.leaves(state.residual)
        c_leaves = [g + r[0] for g, r in zip(flat_g, res)]

        # One collective for all scales: pmax of the [n_leaves] maxima.
        local_max = jnp.stack(
            [jnp.max(jnp.abs(c)).astype(jnp.float32) for c in c_leaves])
        scales = jnp.maximum(
            comm.pmax(local_max, "data", label="int8_scale_pmax") / 127.0,
            jnp.finfo(jnp.float32).tiny)

        q_leaves = [
            jnp.clip(jnp.round(c / scales[i].astype(c.dtype)),
                     -127, 127).astype(jnp.int8)
            for i, c in enumerate(c_leaves)]
        # One collective for all payload bytes: gather the concatenated
        # int8 vector (1 byte/element on the wire; a psum of quantized
        # values would up-cast the operand to int32 and save nothing).
        payload = jnp.concatenate([q.reshape(-1) for q in q_leaves])
        gathered = comm.all_gather(payload, "data",
                                   label="int8_grad_gather")  # [n, N] int8
        totals = jnp.sum(gathered.astype(jnp.int32), axis=0)

        g_avg_leaves, res_leaves = [], []
        off = 0
        for i, (g, c, q) in enumerate(zip(flat_g, c_leaves, q_leaves)):
            s = scales[i].astype(c.dtype)
            tot = totals[off:off + g.size].reshape(g.shape)
            off += g.size
            g_avg_leaves.append((s * tot.astype(c.dtype) / n).astype(g.dtype))
            res_leaves.append((c - s * q.astype(c.dtype))[None])
        g_avg = jax.tree.unflatten(treedef, g_avg_leaves)
        residual = jax.tree.unflatten(treedef, res_leaves)
        params, opt_state = apply_optimizer(optimizer, g_avg,
                                            state.opt_state, state.params)
        return EFTrainState(params, opt_state, state.step + 1, residual), loss

    state_specs = EFTrainState(P(), P(), P(), P("data"))
    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(state_specs, P("data")),
        out_specs=(state_specs, P()),
        check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,))
