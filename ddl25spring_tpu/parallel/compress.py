"""Compressed gradient all-reduce for data parallelism.

Capability/pattern target: the reference's DP loop all-reduces full-precision
fp32 gradients every iteration (lab/tutorial_1b/DP/gradient_aggr/
intro_DP_GA.py:53-66 — flatten, allreduce, scale); at multi-host scale the
wire bytes of that allreduce are the step's bandwidth bill. Public pattern
references for shrinking it inside an XLA program: EQuARX (quantized
all-reduce in XLA, arxiv 2506.17615) and DynamiQ (compressed all-reduce,
arxiv 2602.08923) — see PAPERS.md. This module implements the two standard
operating points, TPU-first (the compression is elementwise work XLA fuses
around one collective; no custom comm code):

- **bf16 wire format** (``make_bf16_grad_step``): cast grads to bf16, pmean,
  upcast. Halves the wire bytes; stateless; the mantissa loss per step is
  ~1e-3 relative and unbiased enough in practice that it is the default
  "free" lever on DCN-bound topologies.

- **int8 + error feedback** (``make_int8_ef_grad_step``): per-leaf symmetric
  quantization to int8 around the shard-group max (pmax-ed so every shard
  uses the same fixed-point grid), then an **int8 all-gather** — the only
  collective whose wire operand is the 1-byte tensor — followed by an exact
  local int32 sum and dequantization. (A psum of the quantized values would
  be mathematically identical but moves int32 on the wire — zero savings;
  gathering the int8 shards keeps the wire at 1 byte/element, ~8× fewer
  bytes than the fp32 allreduce's ≈2×4 bytes/element, at the cost of an
  n_shards× int8 transient per leaf.) The local quantization residual is
  fed back into the next step's gradient (error feedback — the standard fix
  that restores convergence for biased compressors).

Both factories return ``(state, step_fn)`` with the same TrainState the
plain step uses; the int8 variant carries its residual tree inside an
extended state tuple. Equivalence/convergence pinned in
tests/test_compress.py.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .dp import TrainState, init_state, replicate


def _pmean_bf16(grads, axis: str):
    """pmean with a bf16 wire format: the collective moves half the bytes;
    accumulation happens in the reduction's native precision."""
    down = jax.tree.map(lambda g: g.astype(jnp.bfloat16), grads)
    summed = lax.pmean(down, axis)
    return jax.tree.map(lambda g, ref: g.astype(ref.dtype), summed, grads)


def make_bf16_grad_step(loss_fn: Callable,
                        optimizer: optax.GradientTransformation,
                        mesh: Mesh) -> Callable:
    """The plain DP gradient-aggregation step with a bf16 collective.

    Drop-in for ``dp.make_grad_aggregation_step`` — same TrainState, same
    loss semantics; only the gradient allreduce's wire format changes."""

    def local_step(state: TrainState, batch) -> Tuple[TrainState, jnp.ndarray]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        grads = _pmean_bf16(grads, "data")
        loss = lax.pmean(loss, "data")
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    sharded = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P("data")), out_specs=(P(), P()),
        check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,))


class EFTrainState(NamedTuple):
    """TrainState + the per-shard error-feedback residual tree."""
    params: Any
    opt_state: Any
    step: jnp.ndarray
    residual: Any


def init_ef_state(mesh: Mesh, params,
                  optimizer: optax.GradientTransformation) -> EFTrainState:
    """The residual is PER-SHARD state (each shard compensates its own
    quantization error): materialized as a ``[n_data, ...]``-stacked tree
    sharded over ``data``, so each shard owns one zero-initialized slice."""
    base = replicate(mesh, init_state(params, optimizer))
    n = mesh.shape["data"]
    stacked = jax.tree.map(
        lambda p: jnp.zeros((n,) + p.shape, p.dtype), params)
    stacked = jax.device_put(stacked, NamedSharding(mesh, P("data")))
    return EFTrainState(base.params, base.opt_state, base.step, stacked)


def make_int8_ef_grad_step(loss_fn: Callable,
                           optimizer: optax.GradientTransformation,
                           mesh: Mesh) -> Callable:
    """DP step with int8-quantized gradient allreduce + error feedback.

    Per leaf and per step, on each shard: ``c = g_local + residual`` →
    shared scale ``s = pmax(max|c|)/127`` → ``q = round(c/s)`` (int8 range)
    → **int8 all-gather** (the wire leg) → exact local int32 sum →
    ``g_avg = s·Σq/n`` → new residual ``c − s·q``. The optimizer consumes
    ``g_avg``; the un-transmitted remainder re-enters next step, so the
    compressor's bias does not accumulate.
    """
    n = mesh.shape["data"]

    def local_step(state: EFTrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        loss = lax.pmean(loss, "data")

        def leaf(g, r_stacked):
            r = r_stacked[0]          # this shard's [1, ...] slice of the
            c = g + r                 # stacked residual tree
            # Shared symmetric scale: pmax keeps every shard's quantizer
            # identical, so the int32 sum is a faithful fixed-point sum.
            s = lax.pmax(jnp.max(jnp.abs(c)).astype(jnp.float32),
                         "data") / 127.0
            s = jnp.maximum(s, jnp.finfo(jnp.float32).tiny).astype(c.dtype)
            q = jnp.clip(jnp.round(c / s), -127, 127).astype(jnp.int8)
            # Wire leg: gather the int8 shards (1 byte/element on the
            # collective), then sum locally in int32 — exact, and the only
            # formulation where the moved bytes are actually compressed (a
            # psum would up-cast the operand to int32 on the wire).
            gathered = lax.all_gather(q, "data")          # [n, ...] int8
            total = jnp.sum(gathered.astype(jnp.int32), axis=0)
            g_avg = (s * total.astype(c.dtype) / n).astype(g.dtype)
            return g_avg, (c - s * q.astype(c.dtype))[None]

        flat_g, treedef = jax.tree.flatten(grads)
        pairs = [leaf(g, r) for g, r in
                 zip(flat_g, jax.tree.leaves(state.residual))]
        g_avg = jax.tree.unflatten(treedef, [p[0] for p in pairs])
        residual = jax.tree.unflatten(treedef, [p[1] for p in pairs])
        updates, opt_state = optimizer.update(g_avg, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return EFTrainState(params, opt_state, state.step + 1, residual), loss

    state_specs = EFTrainState(P(), P(), P(), P("data"))
    sharded = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(state_specs, P("data")),
        out_specs=(state_specs, P()),
        check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,))
