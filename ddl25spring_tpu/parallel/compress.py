"""Compressed gradient all-reduce for data parallelism.

Capability/pattern target: the reference's DP loop all-reduces full-precision
fp32 gradients every iteration (lab/tutorial_1b/DP/gradient_aggr/
intro_DP_GA.py:53-66 — flatten, allreduce, scale); at multi-host scale the
wire bytes of that allreduce are the step's bandwidth bill. Public pattern
references for shrinking it inside an XLA program: EQuARX (quantized
all-reduce in XLA, arxiv 2506.17615) and DynamiQ (compressed all-reduce,
arxiv 2602.08923) — see PAPERS.md. This module implements the two standard
operating points, TPU-first (the compression is elementwise work XLA fuses
around one collective; no custom comm code):

- **bf16 wire format** (``make_bf16_grad_step``): cast grads to bf16, pmean,
  upcast. Halves the wire bytes; stateless; the mantissa loss per step is
  ~1e-3 relative and unbiased enough in practice that it is the default
  "free" lever on DCN-bound topologies.

- **int8 + error feedback** (``make_int8_ef_grad_step``): per-leaf symmetric
  quantization to int8 around the shard-group max (one pmax of the stacked
  per-leaf maxima keeps every shard on the same fixed-point grid), then ONE
  **int8 all-gather of the whole concatenated gradient** — a single
  collective launch whose wire operand is the 1-byte payload — followed by
  an exact local int32 sum and per-leaf dequantization. (A psum of the
  quantized values would be mathematically identical but moves int32 on the
  wire — zero savings; gathering the int8 payload keeps the wire at
  1 byte/element, ~8× fewer bytes than the fp32 allreduce's ≈2×4
  bytes/element, at the cost of an n_shards× int8 transient.) The local
  quantization residual is fed back into the next step's gradient (error
  feedback — the standard fix that restores convergence for biased
  compressors).

Both factories return ``(state, step_fn)`` with the same TrainState the
plain step uses; the int8 variant carries its residual tree inside an
extended state tuple. Equivalence/convergence pinned in
tests/test_compress.py.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry import comm
from ._compat import axis_size, shard_map

from .dp import TrainState, apply_optimizer, init_state, replicate


def _pmean_bf16(grads, axis: str):
    """pmean with a bf16 wire format: the collective moves half the bytes;
    accumulation happens in the reduction's native precision."""
    down = jax.tree.map(lambda g: g.astype(jnp.bfloat16), grads)
    # Recorded on the bf16 operand: telemetry.comm credits this collective
    # with HALF the fp32 allreduce's payload — the whole point of the wire
    # format, now visible in the comm profile.
    summed = comm.pmean(down, axis, label="grad_allreduce_bf16")
    return jax.tree.map(lambda g, ref: g.astype(ref.dtype), summed, grads)


def make_bf16_grad_step(loss_fn: Callable,
                        optimizer: optax.GradientTransformation,
                        mesh: Mesh) -> Callable:
    """The plain DP gradient-aggregation step with a bf16 collective.

    Drop-in for ``dp.make_grad_aggregation_step`` — same TrainState, same
    loss semantics; only the gradient allreduce's wire format changes."""

    def local_step(state: TrainState, batch) -> Tuple[TrainState, jnp.ndarray]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        grads = _pmean_bf16(grads, "data")
        loss = comm.pmean(loss, "data", label="loss_allreduce")
        params, opt_state = apply_optimizer(optimizer, grads,
                                            state.opt_state, state.params)
        return TrainState(params, opt_state, state.step + 1), loss

    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P("data")), out_specs=(P(), P()),
        check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,))


class EFTrainState(NamedTuple):
    """TrainState + the per-shard error-feedback residual tree."""
    params: Any
    opt_state: Any
    step: jnp.ndarray
    residual: Any


def init_ef_state(mesh: Mesh, params,
                  optimizer: optax.GradientTransformation) -> EFTrainState:
    """The residual is PER-SHARD state (each shard compensates its own
    quantization error): materialized as a ``[n_data, ...]``-stacked tree
    sharded over ``data``, so each shard owns one zero-initialized slice."""
    base = replicate(mesh, init_state(params, optimizer))
    n = mesh.shape["data"]
    stacked = jax.tree.map(
        lambda p: jnp.zeros((n,) + p.shape, p.dtype), params)
    stacked = jax.device_put(stacked, NamedSharding(mesh, P("data")))
    return EFTrainState(base.params, base.opt_state, base.step, stacked)


def make_int8_ef_grad_step(loss_fn: Callable,
                           optimizer: optax.GradientTransformation,
                           mesh: Mesh) -> Callable:
    """DP step with int8-quantized gradient allreduce + error feedback.

    Per step, on each shard: ``c = g_local + residual`` per leaf → ONE pmax
    of the stacked per-leaf maxima (shared fixed-point grids, [n_leaves]
    scalars on the wire) → per-leaf ``q = round(c/s)`` (int8 range) → ONE
    **int8 all-gather of the concatenated payload** (the wire leg: 1
    byte/element, and one collective launch regardless of tree size — the
    per-leaf formulation would pay ~2·n_leaves collective latencies, which
    is what per-collective-latency-bound DCN topologies cannot afford) →
    exact local int32 sum → ``g_avg = s·Σq/n`` per leaf → new residual
    ``c − s·q``. The optimizer consumes ``g_avg``; the un-transmitted
    remainder re-enters next step, so the compressor's bias does not
    accumulate.
    """
    n = mesh.shape["data"]

    def local_step(state: EFTrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        loss = comm.pmean(loss, "data", label="loss_allreduce")

        flat_g, treedef = jax.tree.flatten(grads)
        res = jax.tree.leaves(state.residual)
        c_leaves = [g + r[0] for g, r in zip(flat_g, res)]

        # One collective for all scales: pmax of the [n_leaves] maxima.
        local_max = jnp.stack(
            [jnp.max(jnp.abs(c)).astype(jnp.float32) for c in c_leaves])
        scales = jnp.maximum(
            comm.pmax(local_max, "data", label="int8_scale_pmax") / 127.0,
            jnp.finfo(jnp.float32).tiny)

        q_leaves = [
            jnp.clip(jnp.round(c / scales[i].astype(c.dtype)),
                     -127, 127).astype(jnp.int8)
            for i, c in enumerate(c_leaves)]
        # One collective for all payload bytes: gather the concatenated
        # int8 vector (1 byte/element on the wire; a psum of quantized
        # values would up-cast the operand to int32 and save nothing).
        payload = jnp.concatenate([q.reshape(-1) for q in q_leaves])
        gathered = comm.all_gather(payload, "data",
                                   label="int8_grad_gather")  # [n, N] int8
        totals = jnp.sum(gathered.astype(jnp.int32), axis=0)

        g_avg_leaves, res_leaves = [], []
        off = 0
        for i, (g, c, q) in enumerate(zip(flat_g, c_leaves, q_leaves)):
            s = scales[i].astype(c.dtype)
            tot = totals[off:off + g.size].reshape(g.shape)
            off += g.size
            g_avg_leaves.append((s * tot.astype(c.dtype) / n).astype(g.dtype))
            res_leaves.append((c - s * q.astype(c.dtype))[None])
        g_avg = jax.tree.unflatten(treedef, g_avg_leaves)
        residual = jax.tree.unflatten(treedef, res_leaves)
        params, opt_state = apply_optimizer(optimizer, g_avg,
                                            state.opt_state, state.params)
        return EFTrainState(params, opt_state, state.step + 1, residual), loss

    state_specs = EFTrainState(P(), P(), P(), P("data"))
    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(state_specs, P("data")),
        out_specs=(state_specs, P()),
        check_vma=False)
    return jax.jit(sharded, donate_argnums=(0,))


# --------------------------------------------------------------------------
# Overlapped, compressed gradient sync (the ACCO-style microbatch ring).
#
# The factories above compose with neither ``make_multi_step`` nor ZeRO-1 —
# the fastest correctness path and the cheapest wire path were mutually
# exclusive. The machinery below closes that: a ppermute-pipelined ring
# reduce-scatter whose in-flight chunks can ride the wire in fp32, bf16 or
# int8+error-feedback, driven by a microbatch software pipeline in which
# microbatch k+1's gradient compute is dataflow-independent of microbatch
# k's ring hops — the compute/comm overlap is explicit in the HLO, not
# hoped-for from the XLA scheduler. Pattern references (PAPERS.md):
# accumulate-while-you-communicate (ACCO, arxiv 2406.02613) and quantized
# in-flight collectives (EQuARX, arxiv 2506.17615; DynamiQ, 2602.08923).
#
# On a HIERARCHICAL mesh (parallel/distributed.py:hier_data_mesh — fast
# ICI islands bridged by slow DCN) the same drivers take a PER-AXIS wire
# format (wire={"ici": ..., "dcn": ...}) and run the TWO-LEVEL reduction
# (``hier_reduce_scatter``): full-precision ring within each island, the
# compressed ring across the DCN axis only, compressed DCN broadcast +
# intra-island gather on the way back — wire compression spent exactly
# where bandwidth is scarce (the EQuARX/DynamiQ topology-aware shape),
# with every hop's bytes attributed to its mesh axis in the telemetry
# comm profile (CommProfile.by_axis — the CI-gated DCN budget).


def _int8_encode(c, scale_sync_axis=None):
    """Symmetric per-vector int8 quantization around max|c|: returns
    ``(q, s, residual)`` with ``c ≈ s·q`` and ``residual = c − s·q`` (the
    error-feedback remainder, |residual| ≤ s/2 elementwise).

    ``scale_sync_axis``: mesh axis (or tuple of axes) to ``pmax`` the
    scale over before quantizing (must run inside ``shard_map`` over
    those axes). The composed drivers set this to every axis their flat
    vector is PARTIALLY replicated over — ``"model"`` for DP×TP,
    ``("stage"[, "model"])`` for DP×PP[×TP]: each cell's flat vector
    mixes cell-SPECIFIC leaves (col/row shards, the stage's block slice)
    with cell-REPLICATED leaves (norm scales, embed/head), and a per-cell
    scale would decode the replicated entries differently per cell —
    replicas drift apart and ``device_get``-based checkpoints silently
    lose the divergence. A cell-agreed scale keeps every replicated
    entry's quantize/decode (and its EF residual) bitwise identical
    across cells; cell-specific entries just see the more conservative
    max. Scale agreement costs one scalar pmax (raw ``lax.pmax`` — not a
    wire-accounted collective; the scale that rides the wire is unchanged
    in size)."""
    m = jnp.max(jnp.abs(c))
    if scale_sync_axis is not None:
        m = lax.pmax(m, scale_sync_axis)
    s = jnp.maximum(m / 127.0, jnp.finfo(jnp.float32).tiny)
    q = jnp.clip(jnp.round(c / s), -127, 127).astype(jnp.int8)
    return q, s, c - s * q.astype(jnp.float32)


def ring_reduce_scatter(x, axis_name: str, *, wire: str = "fp32",
                        residual=None, label: str = "ring_grad",
                        comm_scale: int = 1, scale_sync_axis=None):
    """Pipelined ring reduce-scatter of a padded flat vector over
    ``lax.ppermute`` hops, with a selectable wire format for the in-flight
    chunk partials. Must run inside ``shard_map``.

    ``x``: ``[n·chunk]`` fp32 local contribution (n = the axis size).
    Returns ``(owned, residual')`` where ``owned`` is this shard's chunk of
    the cross-shard SUM — chunk r lands on shard r, the ``lax.psum_scatter``
    ownership convention — and ``residual'`` threads the int8
    error-feedback state (flat ``[n·chunk]``, slot c = this shard's error
    for chunk c's partial; pass ``None`` for fp32/bf16, where it is
    returned unchanged).

    Summation order (the documented ring spec, pinned bitwise against a
    host-side reference in tests/test_compress.py): the partial for chunk c
    starts at rank (c+1) % n and travels c+1 → c+2 → ... → c, each rank
    adding its own contribution on receipt, so chunk c associates as
    (((g_{c+1} + g_{c+2}) + ...) + g_c) with the OWNER's contribution added
    last — in fp32, never quantized. XLA CPU's ``psum_scatter`` associates
    rank-linearly (((g_0 + g_1) + g_2) + ...) instead, so the two are
    bitwise-equal exactly when the addition is exact (pinned on
    integer-valued gradients) and re-association-close otherwise; a ring
    cannot reproduce the rank-linear order for every chunk without
    serializing all partials through rank 0, which would forfeit the
    balanced (n−1)·chunk_bytes wire profile this exists for.

    Wire formats, applied to each hop's in-flight partial:
    - ``"fp32"``: sent as-is — exact math at allreduce-parity wire.
    - ``"bf16"``: cast to bf16 on the wire (half the bytes), upcast and
      accumulated in fp32 on receipt; stateless — each hop's rounding is
      dropped, like the bf16 pmean path above.
    - ``"int8_ef"``: quantized to int8 around a per-hop scale that rides
      alongside as one fp32 scalar per chunk per hop; the SENDER's
      quantization error is fed back into its next send of the same chunk
      slot (the residual — per (shard, chunk), so the static ring schedule
      makes the feedback loop consistent across calls), restoring
      convergence for the biased compressor exactly as error feedback does
      for the all-gather path above.

    Telemetry: every hop is a ``comm.ppermute`` record — (n−1) trips of
    chunk-payload bytes per call (plus (n−1) 4-byte scale trips for int8),
    so the comm profile's ring accounting reproduces the analytic
    (n−1)·chunk_bytes wire formula exactly (pinned in
    tests/test_telemetry.py).

    ``scale_sync_axis`` threads through to ``_int8_encode`` (see its
    docstring): the composed DP×TP / DP×PP×TP drivers sync each hop's
    int8 scale over the ORTHOGONAL ``model`` axis so model-replicated
    entries of the flat vector decode identically in every model cell.
    No effect on fp32/bf16 wire, and no change to the ring's wire bytes.
    """
    if residual is not None and wire != "int8_ef":
        # Fail loudly: the fp32/bf16 hops never touch the residual, and
        # threading one through them would silently return garbage in
        # place of accumulated EF state (the write-back below only covers
        # the int8 schedule).
        raise ValueError(f"residual is int8_ef-only (got wire={wire!r})")
    n = axis_size(axis_name)
    if n == 1:
        return x, residual
    chunk = x.shape[0] // n
    chunks = x.reshape(n, chunk)
    r = lax.axis_index(axis_name)
    # Rank-relative schedule: rolled[t] = chunks[(r − 1 − t) % n] is the
    # chunk this rank initiates/forwards at hop t, rolled[n−1] its own
    # (received-last) chunk. The index map is an involution, so the same
    # gather restores the residual's chunk-indexed layout on write-back.
    idx = (r - 1 - jnp.arange(n)) % n
    rolled = chunks[idx]
    res_rolled = (residual.reshape(n, chunk)[idx]
                  if residual is not None else None)
    perm = [(i, (i + 1) % n) for i in range(n)]
    new_res = []
    partial = rolled[0]
    for t in range(n - 1):
        if wire == "int8_ef":
            c = partial + res_rolled[t]
            q, s, err = _int8_encode(c, scale_sync_axis=scale_sync_axis)
            new_res.append(err)
            q = comm.ppermute(q, axis_name, perm, label=f"{label}_int8",
                              scale=comm_scale)
            s = comm.ppermute(s, axis_name, perm, label=f"{label}_scale",
                              scale=comm_scale)
            got = s * q.astype(jnp.float32)
        elif wire == "bf16":
            got = comm.ppermute(partial.astype(jnp.bfloat16), axis_name,
                                perm, label=f"{label}_bf16",
                                scale=comm_scale).astype(jnp.float32)
        elif wire == "fp32":
            got = comm.ppermute(partial, axis_name, perm,
                                label=f"{label}_f32", scale=comm_scale)
        else:
            raise ValueError(f"unknown ring wire format {wire!r}")
        partial = got + rolled[t + 1]
    if residual is not None:
        # Own-chunk slot (never quantized by this rank) passes through.
        new_res.append(res_rolled[n - 1])
        # Involution: the same gather restores chunk-indexed flat layout.
        residual = jnp.stack(new_res)[idx].reshape(-1)
    return partial, residual


def hier_reduce_scatter(x, *, wire_ici: str = "fp32",
                        wire_dcn: str = "int8_ef", residual=None,
                        ici_axis: str = "data", dcn_axis: str = "dcn",
                        label: str = "ring_grad", comm_scale: int = 1):
    """Two-level reduce-scatter on the hierarchical (dcn × data) mesh
    (parallel/distributed.py:hier_data_mesh): a full-precision ring
    reduce-scatter WITHIN each ICI island (the fast tier — ``wire_ici`` ∈
    {fp32, bf16}), then a second ring across the ``dcn`` axis only (the
    scarce tier — ``wire_dcn`` ∈ {fp32, bf16, int8_ef}), so compressed
    wire formats are spent exactly on the hops where bandwidth is scarce
    (EQuARX / DynamiQ, PAPERS.md). Must run inside ``shard_map`` over both
    axes.

    ``x``: ``[n·chunk]`` fp32 local contribution with n = D·S (D =
    islands, S = island size). Phase 1 scatters S superchunks of D·chunk
    over the island (each a contiguous ``(S−1)``-hop ICI ring of
    ``ring_reduce_scatter``'s documented order); phase 2 scatters each
    superchunk's D chunks across islands ((D−1) DCN hops of chunk bytes —
    1/S of the vector ever crosses DCN, and S parallel DCN rings carry
    it). Shard (d, s) ends up owning chunk ``s·D + d`` of the cross-shard
    SUM — the ``dp.slice_index`` ownership map, shared with the ZeRO-1
    update so the reduced chunk lands on the shard that owns its slice.

    ``residual`` threads the DCN ring's int8 error-feedback state (flat
    ``[D·chunk]``, per (shard, dcn-chunk) — the ICI tier is full
    precision and carries none); pass None for fp32/bf16 DCN wire.

    Summation-order spec (pinned in tests/test_hier_collectives.py):
    chunk ``s·D + d`` associates as the DCN-ring-order chain over island
    partials, each island partial itself the ICI-ring-order chain of its
    members — a chain of chains. At D = 1 or S = 1 this IS the flat
    ring's single chain (bitwise — one of the two rings degenerates to
    the identity); at other factorizations it re-associates the same sum,
    so flat-vs-two-level equality is bitwise exactly where the addition
    is exact (integer-valued gradients — the ``ring_reduce_scatter`` vs
    ``psum_scatter`` contract) and re-association-close on general
    floats.

    Telemetry: every hop records through ``comm.ppermute`` with its OWN
    axis name, so the comm profile attributes ICI and DCN bytes
    separately (``CommProfile.by_axis``) — per device: (S−1)·(D·chunk)
    bytes on the ICI axis, (D−1)·chunk bytes (in the DCN wire format) on
    the DCN axis, per call.
    """
    if wire_ici not in ("fp32", "bf16"):
        raise ValueError(
            "the ICI tier is the full-precision tier: wire_ici must be "
            f"'fp32' or 'bf16' (got {wire_ici!r}) — int8+EF belongs on "
            "the scarce DCN axis")
    superchunk, _ = ring_reduce_scatter(
        x, ici_axis, wire=wire_ici, residual=None,
        label=f"{label}_ici", comm_scale=comm_scale)
    return ring_reduce_scatter(
        superchunk, dcn_axis, wire=wire_dcn, residual=residual,
        label=f"{label}_dcn", comm_scale=comm_scale)


# ------------------------------------------------- bucketed backward sync
#
# Everything below `_make_overlap_local_step` historically flattened the
# WHOLE microbatch gradient (pt.flatten, tree order) before the first ring
# hop — the overlap was across microbatches only, and the first hop always
# waited on the last layer's VJP. The bucket map splits the flat geometry
# into an ORDERED list of buckets matching reverse-mode emission order
# (lm_head first, final_norm, the stacked `blocks` layer groups top-down,
# the embedding last), so bucket b's ring vector is built from ONLY the
# leaf slices it covers: its quantize/EF/ring is dataflow-independent of
# every later bucket's grad compute, and the overlap is visible in the
# jaxpr (``ring_overlap_evidence`` — the PR 10 evidence standard, asserted
# in experiments/comm_wire_smoke.py). The ACCO shape ROADMAP 7b names,
# composed with DynamiQ-style chunking (PAPERS.md).


class BucketMap(NamedTuple):
    """Ordered bucket decomposition of the padded flat gradient space —
    ``dp._flat_geometry`` split into ``comm_buckets`` contiguous ranges of
    a VJP-emission-ordered coordinate space (NOT tree order: top-of-network
    leaves first, embedding last, see ``_ordered_pieces``).

    Geometry: ``local`` (one shard's slice of the padded flat vector)
    splits into per-bucket chunk ``sizes`` (``local//B`` each, the
    remainder spread over the leading buckets, so ``sum(sizes) == local``
    EXACTLY — no per-bucket padding, which is what keeps total ring wire
    bytes invariant in the bucket count). Bucket b covers the ordered
    coordinates ``[n·offsets[b], n·offsets[b] + n·sizes[b])``; the global
    ``pad`` rides the tail of the LAST bucket (``pad < n ≤ n·sizes[-1]``
    always fits). ``pieces[b]`` lists the ``(leaf_idx, start, size)``
    slices of the tree-order leaf ravels that bucket b concatenates —
    the static map both ``_bucket_vectors`` (grads → ring vectors) and
    ``_scatter_buckets`` (gathered vectors → param tree) drive.

    Ring ownership at B > 1 is bucket-major: shard r owns chunk r of
    EVERY bucket, and its ZeRO-1 slice is the concat of those per-bucket
    chunks — which is why the per-bucket EF residuals, gather residuals
    and ZeRO-1 moments are all stored per bucket (each bucket's stack is
    a contiguous ordered-coordinate range, the property ``reshard_state``
    needs to pad-swap them across elastic world changes)."""
    n: int
    pad: int
    local: int
    total: int
    sizes: Tuple[int, ...]
    offsets: Tuple[int, ...]
    pieces: Tuple[Tuple[Tuple[int, int, int], ...], ...]

    @property
    def nbuckets(self) -> int:
        return len(self.sizes)


def _ordered_pieces(params, leaf_local=None):
    """VJP-emission-ordered coverage of the local flat param space: a list
    of ``(leaf_idx, start, size)`` pieces over the tree-order leaf ravels,
    ordered ``lm_head`` → ``final_norm`` → the stacked ``blocks`` layer
    groups from the TOP layer down (each layer group = that layer's slice
    of every stacked block leaf, contiguous in the leaf's own ravel) → any
    remaining leaves (tree order) → ``embed`` last. That is the order
    reverse-mode autodiff produces gradients in, so cutting buckets along
    it puts the gradients that materialize FIRST into the buckets that
    ring FIRST. Trees without the llama top-level keys (the quadratic test
    trees, generic models) degrade to plain tree order — the bucketing
    still reshapes the ring, it just stops tracking emission order.

    ``leaf_local``: optional ``(path, leaf) -> (local_size, local_layers)``
    override for the composed drivers whose per-cell leaf sizes differ
    from the global shapes (DP×PP stage slices, DP×TP col/row shards);
    ``local_layers`` is the stacked leading dim of a per-cell blocks leaf
    (None for unstacked leaves). Defaults to the DP identity."""
    entries = jax.tree_util.tree_flatten_with_path(params)[0]
    head, norm, embed, other = [], [], [], []
    blocks = []                          # (leaf_idx, per_layer_size, layers)
    for li, (path, leaf) in enumerate(entries):
        key = getattr(path[0], "key", None) if path else None
        if leaf_local is not None:
            size, layers = leaf_local(path, leaf)
        else:
            size = int(leaf.size)
            layers = (int(leaf.shape[0])
                      if key == "blocks" and getattr(leaf, "ndim", 0) >= 1
                      else None)
        if size == 0:
            continue
        whole = (li, 0, size)
        if key == "lm_head":
            head.append(whole)
        elif key == "final_norm":
            norm.append(whole)
        elif key == "embed":
            embed.append(whole)
        elif key == "blocks" and layers and size % layers == 0:
            blocks.append((li, size // layers, layers))
        else:
            other.append(whole)
    pieces = head + norm
    if blocks:
        n_layers = max(layers for _, _, layers in blocks)
        for layer in range(n_layers - 1, -1, -1):
            for li, per_layer, layers in blocks:
                if layer < layers:
                    pieces.append((li, layer * per_layer, per_layer))
    return pieces + other + embed


def make_bucket_map(params, n: int, comm_buckets: int,
                    *, leaf_local=None) -> BucketMap:
    """Build the ``BucketMap`` for ``params`` over an ``n``-shard data
    world: ``_ordered_pieces``'s emission-ordered coverage, cut at the
    ``n·sizes[b]`` bucket boundaries (a piece straddling a boundary splits
    — buckets are exact coordinate ranges, never rounded to leaf edges).
    Raises for non-positive or oversubscribed bucket counts (every bucket
    needs ≥ 1 coordinate per shard)."""
    B = int(comm_buckets)
    if B < 1:
        raise ValueError(f"comm_buckets must be >= 1 (got {comm_buckets})")
    pieces = _ordered_pieces(params, leaf_local)
    total = sum(sz for _, _, sz in pieces)
    pad = (-total) % n
    local = (total + pad) // n
    if B > local:
        raise ValueError(
            f"comm_buckets={B} exceeds the per-shard slice ({local} "
            f"coordinates at data world {n}) — every bucket needs at "
            "least one coordinate per shard")
    base, rem = divmod(local, B)
    sizes = tuple(base + (1 if b < rem else 0) for b in range(B))
    offsets = tuple(sum(sizes[:b]) for b in range(B))
    buckets, cur = [], []
    need = n * sizes[0]
    for li, st, sz in pieces:
        while sz:
            if need == 0:
                buckets.append(tuple(cur))
                cur = []
                need = n * sizes[len(buckets)]
            take = min(sz, need)
            cur.append((li, st, take))
            st += take
            sz -= take
            need -= take
    buckets.append(tuple(cur))           # last bucket; the pad fills `need`
    return BucketMap(n, pad, local, total, sizes, offsets, tuple(buckets))


def _bucket_vectors(bm: BucketMap, tree):
    """Per-bucket fp32 ring vectors ``[n·sizes[b]]`` from a tree's leaves.
    Each bucket's vector concatenates ONLY the leaf slices its pieces
    cover, so bucket b's vector — and everything downstream of it
    (quantize, EF, ring hops) — carries no data dependence on any leaf
    outside bucket b: the jaxpr-visible overlap. The global pad is
    appended to the last bucket's tail (its coordinates are the tail of
    the ordered space)."""
    leaves = jax.tree.leaves(tree)
    vecs = []
    for b, pieces in enumerate(bm.pieces):
        parts = [leaves[li].reshape(-1)[st:st + sz].astype(jnp.float32)
                 for li, st, sz in pieces]
        if b == bm.nbuckets - 1 and bm.pad:
            parts.append(jnp.zeros((bm.pad,), jnp.float32))
        vecs.append(parts[0] if len(parts) == 1
                    else jnp.concatenate(parts))
    return vecs


def _scatter_buckets(bm: BucketMap, vecs, ref_tree):
    """Inverse of ``_bucket_vectors``: reassemble a tree from per-bucket
    FULL vectors ``[n·sizes[b]]`` (every shard's chunk present — the
    post-all-gather layout), casting each leaf back to its reference
    dtype. The last bucket's pad tail is simply never referenced."""
    ref_leaves, treedef = jax.tree.flatten(ref_tree)
    per_leaf = {}
    for b, pieces in enumerate(bm.pieces):
        pos = 0
        for li, st, sz in pieces:
            per_leaf.setdefault(li, []).append((st, b, pos, sz))
            pos += sz
    out = []
    for li, ref in enumerate(ref_leaves):
        segs = sorted(per_leaf[li])
        parts = [vecs[b][pos:pos + sz] for _, b, pos, sz in segs]
        flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        out.append(flat.reshape(ref.shape).astype(ref.dtype))
    return jax.tree.unflatten(treedef, out)


def _bucket_slices(bm: BucketMap, gathered, lead: int = 1):
    """Split a rank-major gathered stack back into per-bucket full
    vectors: ``gathered`` is ``[ranks·local]`` (each rank's slot its
    owned concat-of-bucket-chunks slice — the flat all-gather, the DCN
    q_all, or the two-leg hierarchical gather, whose S·D rows compose in
    exactly the s·D + d ownership order), so bucket b's full vector is
    the ``[:, offsets[b]:offsets[b]+sizes[b]]`` stripe re-flattened.
    ``lead = D`` handles the one layout where each rank's slot is itself
    a concat of ``[D·sizes[b]]`` superchunk blocks (the ICI gather of
    per-bucket DCN-decoded superchunks in the hierarchical int8 path)."""
    g = gathered.reshape(-1, lead * bm.local)
    return [g[:, lead * bm.offsets[b]:
              lead * (bm.offsets[b] + bm.sizes[b])].reshape(-1)
            for b in range(bm.nbuckets)]


def _find_ppermute_jaxpr(jaxpr):
    """Depth-first search for the (sub)jaxpr whose equation list directly
    contains ``ppermute`` equations — the shard_map body the ring hops
    live in. Returns None when the program has no ring."""
    if any(e.primitive.name == "ppermute" for e in jaxpr.eqns):
        return jaxpr
    for eqn in jaxpr.eqns:
        subs = []
        for v in eqn.params.values():
            cand = v if isinstance(v, (tuple, list)) else (v,)
            for c in cand:
                inner = getattr(c, "jaxpr", c)
                if hasattr(inner, "eqns"):
                    subs.append(inner)
        for sub in subs:
            found = _find_ppermute_jaxpr(sub)
            if found is not None:
                return found
    return None


def ring_overlap_evidence(fn, *args):
    """Structural (jaxpr-level) proof of the bucketed-backward overlap —
    the PR 10 evidence standard applied to ISSUE 19's sub-gradient
    chunking. Traces ``fn(*args)`` (no execution), finds the shard_map
    body carrying the ring's ``ppermute`` hops, and classifies each hop
    against the TEXTUALLY LAST ``scan`` equation — the final microbatch's
    backward scan, i.e. the point where the full gradient has
    materialized. Returns::

        {"n_ring_hops":        total ppermute equations,
         "waited_hops":        hops data-dependent on the last scan,
         "independent_hops":   hops with NO such dependence,
         "overlap_fraction":   independent / total,
         "first_hop_independent": bucket 0's first hop carries no data
                                  dependence on the last backward scan}

    Unbucketed (B = 1, M = 1) the single ring vector includes the
    embedding gradient, every hop descends from the backward scan, and
    ``overlap_fraction`` is 0.0 — the sanity negative. Bucketed, the
    top-of-network buckets' hops (and at M > 1 every non-final
    microbatch's hops) are independent: ``first_hop_independent`` is the
    acceptance predicate comm_wire_smoke asserts, and
    ``overlap_fraction`` is the higher-is-better row it emits for
    bench_compare."""
    closed = jax.make_jaxpr(fn)(*args)
    inner = _find_ppermute_jaxpr(closed.jaxpr)
    if inner is None:
        return {"n_ring_hops": 0, "waited_hops": 0, "independent_hops": 0,
                "overlap_fraction": 0.0, "first_hop_independent": False}
    eqns = list(inner.eqns)
    hops = [e for e in eqns if e.primitive.name == "ppermute"]
    scans = [e for e in eqns if e.primitive.name == "scan"]
    if not scans:
        # No scanned layer stack in the loss — every hop trivially
        # "independent"; report zero evidence rather than free credit.
        return {"n_ring_hops": len(hops), "waited_hops": 0,
                "independent_hops": 0, "overlap_fraction": 0.0,
                "first_hop_independent": False}
    anchor = scans[-1]
    consumers = {}
    for e in eqns:
        for v in e.invars:
            if v.__class__.__name__ == "Literal":
                continue
            consumers.setdefault(v, []).append(e)
    # Transitive descendants of the anchor scan, equations treated
    # atomically (any invar produced downstream taints the whole eqn).
    desc, stack = set(), [anchor]
    while stack:
        e = stack.pop()
        if id(e) in desc:
            continue
        desc.add(id(e))
        for v in e.outvars:
            stack.extend(consumers.get(v, ()))
    waited = sum(1 for h in hops if id(h) in desc)
    independent = len(hops) - waited
    return {"n_ring_hops": len(hops), "waited_hops": waited,
            "independent_hops": independent,
            "overlap_fraction": (independent / len(hops)) if hops else 0.0,
            "first_hop_independent": bool(hops)
            and id(hops[0]) not in desc}


class OverlapEFState(NamedTuple):
    """TrainState + the two error-feedback residual trees of the int8 ring
    driver, both sharded over the data-parallel world and zero at init:

    - ``ring_residual`` [n, ring_len] (per-shard slice [1, ring_len]):
      chunk-indexed per-hop quantization error of the int8 gradient ring —
      shard r's slot c is the error of the partial r last sent for chunk c
      (r's own chunk slot stays 0: the owner's contribution is added in
      fp32). Flat driver: ring_len = Ppad (the n-chunk data ring).
      Hierarchical driver: ring_len = D·local (only the DCN ring carries
      EF state — the ICI tier is full precision).
    - ``gather_residual`` [Ppad] (per-shard slice [local]): error of the
      second-leg quantization — the param-delta broadcast (zero1) or the
      reduced-grad-slice broadcast (gradient aggregation); hierarchically,
      the broadcast's DCN leg.

    Both ride the scan carry of the K-step driver and the checkpointed
    state tree, so the accumulated quantization error survives
    ``make_overlap_multi_step`` composition, chunk-edge checkpoints and a
    preempt/resume cycle exactly (pinned in tests/test_compress.py and
    tests/test_hier_collectives.py).

    The DP×PP drivers (parallel/pp.py ``_pp_overlap_setup``) reuse this
    tuple with a ``stage`` axis spliced in — ring ``[n, S, n·local]``,
    gather ``[n, S, local]``, sharded ``P("data", "stage")`` — because
    each (data, stage) shard compensates its OWN stage slice's
    quantization error (same bars, pinned in tests/test_pp.py).

    At ``comm_buckets > 1`` both fields are TUPLES of per-bucket arrays
    (ring ``[n, ring_n·sizes[b]]``, gather ``[n·sizes[b]]``) — same
    semantics per bucket, stored per bucket so each stack is a contiguous
    ordered-coordinate range ``dp.reshard_state`` can pad-swap across
    elastic world changes (see ``BucketMap``)."""
    params: Any
    opt_state: Any
    step: jnp.ndarray
    ring_residual: Any
    gather_residual: Any


def _zero1_bucket_setup(optimizer, mesh: Mesh, params, bm: BucketMap,
                        dpart):
    """ZeRO-1 initialization at ``comm_buckets > 1``: one optimizer state
    PER BUCKET, each over that bucket's per-shard chunk (``[sizes[b]]``
    locally, ``[n·sizes[b]]`` globally). Elementwise optimizers make the
    split value-identical to ``dp._zero1_setup``'s single ``[local]``
    slice — the tuple exists for the STORAGE layout: each bucket's moment
    stack is a contiguous ordered-coordinate range, which is what lets
    ``reshard_state`` pad-swap it across elastic world changes (a single
    ``[n·local]`` stack at B > 1 would interleave buckets rank-major and
    scramble under a world resize)."""
    from .dp import slice_index

    specs = []
    for sz in bm.sizes:
        abstract = jax.eval_shape(
            optimizer.init, jax.ShapeDtypeStruct((sz,), jnp.float32))
        specs.append(jax.tree.map(
            lambda x: P(dpart) if getattr(x, "ndim", 0) >= 1 else P(),
            abstract))
    opt_specs = tuple(specs)

    def local_init(params):
        shard = slice_index(mesh)
        vecs = _bucket_vectors(bm, params)
        return tuple(
            optimizer.init(lax.dynamic_slice_in_dim(
                vecs[b], shard * bm.sizes[b], bm.sizes[b]))
            for b in range(bm.nbuckets))

    opt_state = jax.jit(shard_map(
        local_init, mesh=mesh, in_specs=P(),
        out_specs=opt_specs, check_vma=False))(params)
    state = TrainState(replicate(mesh, params), opt_state,
                       jax.device_put(jnp.zeros((), jnp.int32),
                                      NamedSharding(mesh, P())))
    return state, opt_specs


def _overlap_setup(mesh: Mesh, params, optimizer, wire, aggregation: str,
                   comm_buckets: int = 1):
    """State + shard specs + flat geometry for the overlap driver. The
    zero1 variant reuses ``dp._zero1_setup`` wholesale, so the slice the
    ring chunk lands on IS the slice the sharded update owns (including
    the hierarchical ``dp.slice_index`` map).

    ``wire``: a format string for the flat data ring, or the per-axis dict
    ``{"ici": ..., "dcn": ...}`` selecting the two-level path on a
    hierarchical mesh. ``comm_buckets > 1`` selects the bucketed backward
    (``BucketMap``): the EF residuals, gather residuals and ZeRO-1 moments
    all become per-bucket tuples, and the returned ``bm`` drives the
    bucketed local step. Returns ``(state, specs, dpart, n, pad, local,
    total, hier_shape, bm)`` — ``dpart`` the normalized data PartitionSpec
    entry (dp.data_partition), ``hier_shape`` = ``(D, S)`` for the
    two-level path, None for the flat ring, ``bm`` None at
    ``comm_buckets == 1`` (the exact legacy path)."""
    from .dp import _flat_geometry, _zero1_setup, data_partition

    if aggregation not in ("gradient", "zero1"):
        raise ValueError("overlap driver supports gradient/zero1 "
                         f"aggregation only (got {aggregation!r})")
    if isinstance(wire, dict):
        if set(wire) != {"ici", "dcn"}:
            raise ValueError("per-axis wire must be "
                             '{"ici": fmt, "dcn": fmt} '
                             f"(got keys {sorted(wire)})")
        if "dcn" not in mesh.shape:
            raise ValueError(
                "per-axis wire formats need a hierarchical mesh with a "
                "'dcn' axis (parallel/distributed.py:hier_data_mesh)")
        if wire["ici"] not in ("fp32", "bf16"):
            raise ValueError(
                "the ICI tier is the full-precision tier: wire['ici'] "
                f"must be 'fp32' or 'bf16' (got {wire['ici']!r}) — "
                "int8+EF belongs on the scarce DCN axis")
        if wire["dcn"] not in ("fp32", "bf16", "int8_ef"):
            raise ValueError(f"unknown DCN wire format {wire['dcn']!r}")
        hier_shape = (mesh.shape["dcn"], mesh.shape["data"])
        ef = wire["dcn"] == "int8_ef"
    else:
        if wire not in ("fp32", "bf16", "int8_ef"):
            raise ValueError(f"unknown wire format {wire!r}")
        if mesh.shape.get("dcn", 1) > 1:
            raise ValueError(
                "a hierarchical (dcn x data) mesh needs the per-axis wire "
                'dict ({"ici": ..., "dcn": ...}) — a flat wire string '
                "would run the ring over the 'data' axis only and never "
                "cross DCN")
        hier_shape = None
        ef = wire == "int8_ef"
    dpart = data_partition(mesh)
    n, pad, local, total = _flat_geometry(mesh, params)
    if int(comm_buckets) < 1:
        raise ValueError(
            f"comm_buckets must be >= 1 (got {comm_buckets})")
    bm = (make_bucket_map(params, n, comm_buckets)
          if int(comm_buckets) > 1 else None)
    if aggregation == "zero1":
        if bm is not None:
            base, opt_specs = _zero1_bucket_setup(
                optimizer, mesh, params, bm, dpart)
        else:
            base, opt_specs, *_ = _zero1_setup(optimizer, mesh, params)
    else:
        base = replicate(mesh, init_state(params, optimizer))
        opt_specs = P()
    if ef:
        ring_n = hier_shape[0] if hier_shape is not None else n
        dshard = P(dpart)
        if bm is not None:
            ring_res = tuple(
                jax.device_put(jnp.zeros((n, ring_n * sz), jnp.float32),
                               NamedSharding(mesh, dshard))
                for sz in bm.sizes)
            gather_res = tuple(
                jax.device_put(jnp.zeros((n * sz,), jnp.float32),
                               NamedSharding(mesh, dshard))
                for sz in bm.sizes)
            specs = OverlapEFState(P(), opt_specs, P(),
                                   (dshard,) * bm.nbuckets,
                                   (dshard,) * bm.nbuckets)
        else:
            ring_res = jax.device_put(
                jnp.zeros((n, ring_n * local), jnp.float32),
                NamedSharding(mesh, dshard))
            gather_res = jax.device_put(
                jnp.zeros((n * local,), jnp.float32),
                NamedSharding(mesh, dshard))
            specs = OverlapEFState(P(), opt_specs, P(), dshard, dshard)
        state = OverlapEFState(base.params, base.opt_state, base.step,
                               ring_res, gather_res)
    else:
        state = base
        specs = TrainState(P(), opt_specs, P())
    return state, specs, dpart, n, pad, local, total, hier_shape, bm


def _make_overlap_local_step(loss_fn: Callable, optimizer, n: int, pad: int,
                             local: int, total: int, *, microbatches: int,
                             wire, aggregation: str,
                             comm_scale: int = 1, hier_shape=None,
                             bucket_map=None,
                             guard_nonfinite: bool = False,
                             numerics=None) -> Callable:
    """The per-shard overlapped step body shared by ``make_overlap_step``
    and ``make_overlap_multi_step`` — one implementation, so per-step and
    K-scanned dispatch cannot drift (their bitwise equality at any K is the
    same contract ``make_multi_step`` pins).

    Structure per step: the local batch splits into M microbatches; the
    ring reduce-scatter of microbatch m−1's flat gradient is issued in the
    same trace position as microbatch m's forward+backward, with no data
    dependence between them — the explicit overlap. Reduced chunks
    accumulate in fp32 on the owner; the result is averaged over n·M and
    fed to the ZeRO-1 sliced update + (compressed) param gather, or
    all-gathered (in the wire format) for the replicated update.

    ``hier_shape`` = (D, S) selects the two-level topology: the reduce is
    ``hier_reduce_scatter`` (full-precision ICI ring within each island,
    ``wire["dcn"]`` ring across islands), slice ownership is
    ``dp.slice_index``'s s·D + d map, and the broadcast leg runs its DCN
    hop first (compressed when ``wire["dcn"] = "int8_ef"``: the quantized
    delta/grad payload crosses DCN once at one byte/element) and the
    intra-island gather second — only 1/S of the vector ever crosses the
    DCN axis, the telemetry-visible budget the smoke gates. bf16 on the
    ICI tier compresses the ring's in-flight partials (and the replicated
    path's grad gather); the zero1 param gather stays fp32 on both legs
    except the int8 DCN delta, mirroring the flat driver's
    params-stay-exact rule.

    ``guard_nonfinite`` fuses the in-jit skip: the finiteness verdict on
    (loss, owned gradient slice) is psum-agreed across every data axis —
    per-shard slices can disagree, and replicas applying different
    verdicts would silently diverge — and a bad step select-backs the
    WHOLE incoming state (params, moments, both EF residual trees) without
    leaving jit; ``step`` does not advance, which is how the host counts
    skips into ResilienceStats (train/llm.py). The returned loss stays the
    non-finite one, so host-side guards/telemetry still see the fault.

    ``numerics`` (telemetry.introspect.NumericsHandle, built with
    ``psum_axis`` = the data axes): the step's second output becomes
    ``(loss, NumericsSummary)`` — grad stats over the local microbatch-mean
    gradient (psum-agreed by the summarizer), update stats over the
    ATTEMPTED update — computed from values the step already holds, so
    losses/params are bitwise identical on vs off (pinned).

    Numerics contract: microbatch gradients are REDUCED per microbatch and
    summed on the owner (reduce-then-accumulate), whereas ``accum_steps``
    accumulates locally then reduces once — same math, different float
    association, so M>1 matches the monolithic paths to fp32 tolerance,
    not bitwise (M=1 differs from them only by the ring-vs-linear
    reduction order; see ``ring_reduce_scatter``). The compressed gather
    legs broadcast one payload that every shard applies identically, so
    replicas stay bitwise in sync in every mode and topology.

    ``bucket_map`` (a ``BucketMap``, None for the legacy single-vector
    path) selects the bucketed backward: each microbatch gradient is
    produced as per-bucket ring vectors (``_bucket_vectors`` — bucket b
    built from ONLY the leaf slices it covers, in VJP emission order), and
    each bucket rings independently under its own label
    (``ring_grad_b{b}``), so bucket b's quantize/EF/ring carries no data
    dependence on bucket b+1..'s grad compute — the within-backward
    overlap (``ring_overlap_evidence``), on top of the across-microbatch
    overlap above. A shard's owned slice becomes the concat of its
    per-bucket chunks; the gather legs stay ONE collective of ``local``
    elements in every mode (buckets are extracted from the gathered stack
    with static slices), so gather-leg bytes and collective counts — and,
    in fp32/bf16, total wire bytes — are exactly invariant in the bucket
    count (the int8 ring adds one 4-byte scale sideband per extra bucket
    per hop, pinned analytically in the smoke). EF residuals, gather
    residuals and ZeRO-1 moments are per-bucket tuples (see
    ``_zero1_bucket_setup`` for why)."""
    M = microbatches
    bm = bucket_map
    B = bm.nbuckets if bm is not None else 1
    hier = hier_shape is not None
    if hier:
        D, S = hier_shape
        wire_ici, wire_dcn = wire["ici"], wire["dcn"]
        ef = wire_dcn == "int8_ef"
    else:
        ef = wire == "int8_ef"

    def _reduce(pending, ring_res, bucket=None):
        label = "ring_grad" if bucket is None else f"ring_grad_b{bucket}"
        if hier:
            return hier_reduce_scatter(
                pending, wire_ici=wire_ici, wire_dcn=wire_dcn,
                residual=ring_res, comm_scale=comm_scale, label=label)
        return ring_reduce_scatter(pending, "data", wire=wire,
                                   residual=ring_res,
                                   comm_scale=comm_scale, label=label)

    def _reduce_all(pending, ring_res):
        # pending: the flat vector (bm None) or the per-bucket vector
        # list; ring_res mirrors it. Returns this shard's owned [local]
        # slice (concat of per-bucket chunks when bucketed).
        if bm is None:
            return _reduce(pending, ring_res)
        reds, new_res = [], []
        for b in range(B):
            red_b, r_b = _reduce(pending[b],
                                 ring_res[b] if ef else None, b)
            reds.append(red_b)
            new_res.append(r_b)
        return jnp.concatenate(reds), new_res

    def local_step(state, batch):
        from ..utils import pytree as pt

        if batch.shape[0] % M:
            raise ValueError(f"local batch {batch.shape[0]} not divisible "
                             f"by overlap_microbatches={M}")
        params = state.params
        if not ef:
            ring_res = None
        elif bm is None:
            ring_res = state.ring_residual[0]
        else:
            ring_res = [r[0] for r in state.ring_residual]
        micro = batch.reshape((M, -1) + batch.shape[1:])
        acc = jnp.zeros((local,), jnp.float32)
        loss_sum = jnp.zeros((), jnp.float32)
        gacc = None
        pending = None
        for m in range(M):
            l, g = jax.value_and_grad(loss_fn)(params, micro[m])
            loss_sum = loss_sum + l.astype(jnp.float32)
            if numerics is not None:
                # Extra OUTPUT only: the fp32 grad accumulator feeds the
                # summary, never the ring — losses/params bitwise on/off.
                gacc = (jax.tree.map(lambda x: x.astype(jnp.float32), g)
                        if gacc is None else
                        jax.tree.map(lambda a, b: a + b.astype(jnp.float32),
                                     gacc, g))
            if pending is not None:
                # Microbatch m−1's ring rides alongside microbatch m's
                # grad compute (the lines above): independent dataflow.
                red, ring_res = _reduce_all(pending, ring_res)
                acc = acc + red
            pending = (_bucket_vectors(bm, g) if bm is not None else
                       jnp.pad(pt.flatten(g)[0].astype(jnp.float32),
                               (0, pad)))
        red, ring_res = _reduce_all(pending, ring_res)
        acc = acc + red
        g_mine = acc / (n * M)      # mean over shards and microbatches
        loss = comm.pmean(loss_sum / M, "data", label="loss_allreduce",
                          scale=comm_scale)
        if hier:
            # Mean of equal-size island means == the global mean; the DCN
            # leg of the loss reduction is 4 bytes, attributed to its axis.
            loss = comm.pmean(loss, "dcn", label="loss_allreduce_dcn",
                              scale=comm_scale)

        raw_flat, unravel = pt.flatten(params)
        if bm is None:
            flat_p = jnp.pad(raw_flat.astype(jnp.float32), (0, pad))
            pvecs = None
        else:
            # Bucketed: the param-side flat views are per-bucket too, so
            # the owned slice is the concat of per-bucket chunks — the
            # same coordinate order the per-bucket rings reduce into.
            flat_p = None
            pvecs = _bucket_vectors(bm, params)
        gather_res = None
        if aggregation == "zero1":
            if hier:
                from .dp import hier_slice_index
                shard = hier_slice_index(D)
            else:
                shard = lax.axis_index("data")
            if bm is None:
                p_mine = lax.dynamic_slice_in_dim(flat_p, shard * local,
                                                  local)
                new_p_mine, opt_state = apply_optimizer(
                    optimizer, g_mine, state.opt_state, p_mine)
            else:
                # One optimizer apply per bucket against the per-bucket
                # moment state; elementwise updates make the concat
                # value-identical to the single-slice apply.
                p_chunks = [lax.dynamic_slice_in_dim(
                    pvecs[b], shard * bm.sizes[b], bm.sizes[b])
                    for b in range(B)]
                new_chunks, opts = [], []
                for b in range(B):
                    np_b, opt_b = apply_optimizer(
                        optimizer,
                        g_mine[bm.offsets[b]:bm.offsets[b] + bm.sizes[b]],
                        state.opt_state[b], p_chunks[b])
                    new_chunks.append(np_b)
                    opts.append(opt_b)
                p_mine = jnp.concatenate(p_chunks)
                new_p_mine = jnp.concatenate(new_chunks)
                opt_state = tuple(opts)
            vec_new = None
            if hier:
                # Two-level broadcast, DCN leg first: islands exchange
                # their superchunk's D slices (compressed when the DCN
                # wire says so), then the island gathers S superchunks
                # over ICI in fp32 — params stay exact on the fast tier.
                if wire_dcn == "int8_ef":
                    gres = (jnp.concatenate(state.gather_residual)
                            if bm is not None else state.gather_residual)
                    q, s, gather_res = _int8_encode(
                        (new_p_mine - p_mine) + gres)
                    q_all = comm.all_gather(
                        q, "dcn", tiled=True,
                        label="overlap_delta_gather_int8",
                        scale=comm_scale)
                    s_all = comm.all_gather(
                        s[None], "dcn", tiled=True,
                        label="overlap_delta_scale_gather",
                        scale=comm_scale)
                    if bm is None:
                        p_super = lax.dynamic_slice_in_dim(
                            flat_p, lax.axis_index("data") * (D * local),
                            D * local)
                        super_new = p_super + (jnp.repeat(s_all, local)
                                               * q_all.astype(jnp.float32))
                    else:
                        q_slc = _bucket_slices(bm,
                                               q_all.astype(jnp.float32))
                        super_new = jnp.concatenate([
                            lax.dynamic_slice_in_dim(
                                pvecs[b],
                                lax.axis_index("data") * (D * bm.sizes[b]),
                                D * bm.sizes[b])
                            + jnp.repeat(s_all, bm.sizes[b]) * q_slc[b]
                            for b in range(B)])
                else:
                    super_new = comm.all_gather(
                        new_p_mine, "dcn", tiled=True,
                        label="overlap_param_gather_dcn",
                        scale=comm_scale)
                flat_new = comm.all_gather(
                    super_new, "data", tiled=True,
                    label="overlap_param_gather_ici", scale=comm_scale)
                if bm is not None:
                    # int8 DCN builds per-rank superchunk CONCATS (lead=D
                    # blocks); the fp32/bf16 two-leg gather stacks plain
                    # [local] slots in s·D + d order (lead=1).
                    vec_new = _bucket_slices(
                        bm, flat_new,
                        lead=(D if wire_dcn == "int8_ef" else 1))
            elif wire == "int8_ef":
                # Compressed second leg: broadcast the param DELTA int8
                # (one byte/element + one scale/shard) with its own EF
                # residual at the owner. Every shard — the owner included —
                # applies the same dequantized deltas, so replicas stay
                # bitwise identical; the fp32 moments stay exact; the
                # quantization drift is compensated next step.
                gres = (jnp.concatenate(state.gather_residual)
                        if bm is not None else state.gather_residual)
                q, s, gather_res = _int8_encode(
                    (new_p_mine - p_mine) + gres)
                q_all = comm.all_gather(q, "data", tiled=True,
                                        label="overlap_delta_gather_int8",
                                        scale=comm_scale)
                s_all = comm.all_gather(s[None], "data", tiled=True,
                                        label="overlap_delta_scale_gather",
                                        scale=comm_scale)
                if bm is None:
                    flat_new = flat_p + (jnp.repeat(s_all, local)
                                         * q_all.astype(jnp.float32))
                else:
                    q_slc = _bucket_slices(bm, q_all.astype(jnp.float32))
                    vec_new = [pvecs[b]
                               + jnp.repeat(s_all, bm.sizes[b]) * q_slc[b]
                               for b in range(B)]
            else:
                flat_new = comm.all_gather(new_p_mine, "data", tiled=True,
                                           label="overlap_param_gather",
                                           scale=comm_scale)
                if bm is not None:
                    vec_new = _bucket_slices(bm, flat_new)
            if bm is None:
                new_params = unravel(
                    flat_new[:total].astype(raw_flat.dtype))
            else:
                new_params = _scatter_buckets(bm, vec_new, params)
        else:                       # replicated update
            gres = (jnp.concatenate(state.gather_residual)
                    if ef and bm is not None else state.gather_residual
                    if ef else None)
            if hier:
                if wire_dcn == "int8_ef":
                    q, s, gather_res = _int8_encode(g_mine + gres)
                    q_all = comm.all_gather(
                        q, "dcn", tiled=True,
                        label="overlap_grad_gather_int8",
                        scale=comm_scale)
                    s_all = comm.all_gather(
                        s[None], "dcn", tiled=True,
                        label="overlap_grad_scale_gather",
                        scale=comm_scale)
                    super_g = (jnp.repeat(s_all, local)
                               * q_all.astype(jnp.float32))
                elif wire_dcn == "bf16":
                    super_g = comm.all_gather(
                        g_mine.astype(jnp.bfloat16), "dcn", tiled=True,
                        label="overlap_grad_gather_dcn_bf16",
                        scale=comm_scale).astype(jnp.float32)
                else:
                    super_g = comm.all_gather(
                        g_mine, "dcn", tiled=True,
                        label="overlap_grad_gather_dcn",
                        scale=comm_scale)
                if wire_ici == "bf16":
                    flat_g = comm.all_gather(
                        super_g.astype(jnp.bfloat16), "data", tiled=True,
                        label="overlap_grad_gather_ici_bf16",
                        scale=comm_scale).astype(jnp.float32)
                else:
                    flat_g = comm.all_gather(
                        super_g, "data", tiled=True,
                        label="overlap_grad_gather_ici",
                        scale=comm_scale)
            elif wire == "int8_ef":
                q, s, gather_res = _int8_encode(g_mine + gres)
                q_all = comm.all_gather(q, "data", tiled=True,
                                        label="overlap_grad_gather_int8",
                                        scale=comm_scale)
                s_all = comm.all_gather(s[None], "data", tiled=True,
                                        label="overlap_grad_scale_gather",
                                        scale=comm_scale)
                flat_g = (jnp.repeat(s_all, local)
                          * q_all.astype(jnp.float32))
            elif wire == "bf16":
                flat_g = comm.all_gather(
                    g_mine.astype(jnp.bfloat16), "data", tiled=True,
                    label="overlap_grad_gather_bf16",
                    scale=comm_scale).astype(jnp.float32)
            else:
                flat_g = comm.all_gather(g_mine, "data", tiled=True,
                                         label="overlap_grad_gather",
                                         scale=comm_scale)
            if bm is None:
                grads = unravel(flat_g[:total].astype(raw_flat.dtype))
            else:
                # Every gathered stack in this branch is rank-major
                # [ranks, local] in ownership order — lead=1 extraction.
                grads = _scatter_buckets(bm, _bucket_slices(bm, flat_g),
                                         params)
            new_params, opt_state = apply_optimizer(
                optimizer, grads, state.opt_state, params)
        summary = None
        if numerics is not None:
            # Grad stats: local microbatch-mean gradient (the summarizer
            # psum-agrees them over the data axes); update stats: the
            # ATTEMPTED update — under guard_nonfinite a skipped step
            # still reports the norms of the update it refused, the
            # attribution a postmortem needs.
            summary = numerics.summarize(
                params, jax.tree.map(lambda x: x / M, gacc), new_params)
        step = state.step + 1
        if ef:
            if bm is not None:
                # Per-bucket storage: each bucket's stack is a contiguous
                # ordered-coordinate range (the reshard_state contract).
                ring_res = tuple(r[None] for r in ring_res)
                gather_res = tuple(
                    gather_res[bm.offsets[b]:bm.offsets[b] + bm.sizes[b]]
                    for b in range(B))
            else:
                ring_res = ring_res[None]
            new_state = OverlapEFState(new_params, opt_state, step,
                                       ring_res, gather_res)
        else:
            new_state = TrainState(new_params, opt_state, step)
        if guard_nonfinite:
            # Per-shard verdicts CAN disagree (each shard owns a different
            # slice of the reduced gradient), so the skip must be
            # psum-agreed before anyone applies state — the zero1 guard's
            # rule, extended over both axes of the hierarchical mesh.
            ok = jnp.isfinite(loss) & jnp.all(jnp.isfinite(g_mine))
            oki = comm.psum(ok.astype(jnp.int32), "data",
                            label="overlap_guard_verdict",
                            scale=comm_scale)
            if hier:
                oki = comm.psum(oki, "dcn",
                                label="overlap_guard_verdict_dcn",
                                scale=comm_scale)
            ok = oki == n
            # Select-back the WHOLE state (EF residuals included): a
            # skipped step is a true no-op, and the residuals must not
            # absorb a rejected step's quantization error.
            new_state = jax.tree.map(lambda a, b: jnp.where(ok, a, b),
                                     new_state, state)
            new_state = new_state._replace(
                step=state.step + ok.astype(state.step.dtype))
        return new_state, ((loss, summary) if summary is not None
                           else loss)

    return local_step


def make_overlap_step(loss_fn: Callable,
                      optimizer: optax.GradientTransformation,
                      mesh: Mesh, params, *, microbatches: int = 1,
                      wire="fp32", aggregation: str = "gradient",
                      comm_buckets: int = 1,
                      guard_nonfinite: bool = False, numerics=None):
    """Per-step overlapped+compressed gradient-sync driver: ``step(state,
    batch) -> (state, loss)`` over a ``[B, T]`` batch sharded over the
    data-parallel world. Returns ``(state, step_fn)``; the state is an
    ``OverlapEFState`` when any tier runs ``int8_ef`` (EF residuals in the
    tree), a plain TrainState otherwise — with ZeRO-1-sharded moments when
    ``aggregation="zero1"``.

    ``wire``: a format string runs the flat data-axis ring (PR 10); the
    per-axis dict ``{"ici": "fp32"|"bf16", "dcn":
    "fp32"|"bf16"|"int8_ef"}`` runs the TWO-LEVEL reduction on a
    hierarchical mesh (``hier_data_mesh``): full-precision reduce-scatter
    within each ICI island, the compressed exchange across the DCN axis
    only, then the intra-island gather. ``comm_buckets > 1`` turns on the
    bucketed backward — per-bucket ring dispatch in VJP emission order,
    so the first hop starts before the full gradient materializes (the
    semantics and invariants in ``_make_overlap_local_step``; structural
    proof via ``ring_overlap_evidence``). ``guard_nonfinite`` fuses the
    psum-agreed in-jit skip; ``numerics`` turns on the in-jit run-health
    summary."""
    (state, specs, dpart, n, pad, local, total, hier_shape,
     bm) = _overlap_setup(mesh, params, optimizer, wire, aggregation,
                          comm_buckets)
    local_step = _make_overlap_local_step(
        loss_fn, optimizer, n, pad, local, total, microbatches=microbatches,
        wire=wire, aggregation=aggregation, hier_shape=hier_shape,
        bucket_map=bm, guard_nonfinite=guard_nonfinite, numerics=numerics)
    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(specs, P(dpart)), out_specs=(specs, P()),
        check_vma=False)
    return state, jax.jit(sharded, donate_argnums=(0,))


def make_overlap_multi_step(loss_fn: Callable,
                            optimizer: optax.GradientTransformation,
                            mesh: Mesh, params, *, microbatches: int = 1,
                            wire="fp32", aggregation: str = "gradient",
                            comm_buckets: int = 1,
                            guard_nonfinite: bool = False, numerics=None):
    """The overlapped+compressed driver inside the K-step scan:
    ``step(state, window) -> (state, losses)`` with ``window`` a
    ``[K, n_shards·B, T]`` batch window (``dp.shard_batch_window``) run in
    ONE compiled, donated dispatch. The scanned body IS
    ``make_overlap_step``'s body, so the loss sequence and final state are
    bitwise-identical to K per-step calls at any K and M (pinned in
    tests/test_compress.py) — and the int8 EF residuals ride the scan
    carry, so error feedback is exact across fused steps and chunk-edge
    checkpoints. ``wire`` accepts the same per-axis dict as
    ``make_overlap_step`` for the two-level hierarchical path, and
    ``guard_nonfinite``/``numerics`` ride the scanned body unchanged (the
    numerics summary comes back stacked [K], exactly like
    ``dp.make_multi_step``'s). ``comm_buckets`` composes: the per-bucket
    EF residual tuples ride the scan carry like the legacy arrays, so
    K-scanned bucketed dispatch stays bitwise-equal to K per-step calls
    at any K, M and bucket count."""
    (state, specs, dpart, n, pad, local, total, hier_shape,
     bm) = _overlap_setup(mesh, params, optimizer, wire, aggregation,
                          comm_buckets)

    def multi(state, window):
        local_step = _make_overlap_local_step(
            loss_fn, optimizer, n, pad, local, total,
            microbatches=microbatches, wire=wire, aggregation=aggregation,
            comm_scale=window.shape[0], hier_shape=hier_shape,
            bucket_map=bm, guard_nonfinite=guard_nonfinite,
            numerics=numerics)
        return lax.scan(local_step, state, window)

    sharded = shard_map(
        multi, mesh=mesh,
        in_specs=(specs, P(None, dpart)), out_specs=(specs, P()),
        check_vma=False)
    return state, jax.jit(sharded, donate_argnums=(0,))
