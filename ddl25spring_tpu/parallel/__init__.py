from .mesh import make_mesh, axis_size  # noqa: F401
