"""Device-mesh construction — the framework's replacement for process groups.

The reference wires N OS processes with `init_process_group("gloo", rank, N)`,
ranks, MASTER_ADDR/PORT, and `dist.new_group` sub-groups (reference:
lab/tutorial_1b/DP/gradient_aggr/intro_DP_GA.py:11-15, lab/hw01/homework 1 b/
homework_1_b2.py:28-32). Here the whole layer is one named
`jax.sharding.Mesh`: axes replace groups, SPMD program order replaces tags,
and collective lowering to XLA HLO over ICI/DCN replaces gloo's TCP.

Multi-host: call `jax.distributed.initialize()` before building the mesh and
`jax.devices()` spans hosts; nothing else changes (DCN between hosts, ICI
within a slice).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# Canonical axis order, outermost first. ``dcn`` is the cross-host tier of
# a hierarchical data-parallel mesh (parallel/distributed.py:hier_data_mesh)
# — islands of fast ICI bridged by slow DCN — and sits outermost so the
# device order is island-major: replica (d, s) = device d·island_size + s.
# Meshes without a ``dcn`` axis are laid out exactly as before.
AXES = ("dcn", "data", "stage", "model", "seq", "expert")


def make_mesh(axis_sizes: Optional[Dict[str, int]] = None, *,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a named mesh.

    ``axis_sizes`` maps axis name -> size; omitted axes get size 1. The mesh
    uses the first prod(sizes) devices (a size of -1 is inferred from the
    device count); a warning is emitted if that leaves devices idle. With no
    arguments, all devices land on the ``data`` axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axis_sizes or {})
    if not sizes:
        sizes = {"data": n}
    names = [a for a in AXES if a in sizes] + [a for a in sizes if a not in AXES]
    shape = [sizes[a] for a in names]
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = n // known
    need = int(np.prod(shape))
    assert need <= n, f"axis sizes {dict(zip(names, shape))} need {need} > {n} devices"
    if need < n:
        import warnings
        warnings.warn(f"mesh {dict(zip(names, shape))} uses {need} of {n} devices; "
                      f"the rest stay idle", stacklevel=2)
    dev_array = np.asarray(devices[:need]).reshape(shape)
    return Mesh(dev_array, tuple(names))


def _elastic_second_axis(mesh: Mesh, who: str) -> Optional[str]:
    """The one non-``data`` axis an elastic re-mesh may carry along —
    ``stage`` (DPxPP) or ``model`` (DPxTP) — or None for the classic
    data-only mesh. Every other axis must be size 1, and composing BOTH a
    real stage and a real model axis with elasticity is out of scope (one
    non-data axis at a time)."""
    names = mesh.axis_names
    for name in names:
        if name not in ("data", "stage", "model") and mesh.shape[name] > 1:
            raise ValueError(
                f"{who} supports data/stage/model mesh axes only; "
                f"axis {name!r} has size {mesh.shape[name]}")
    if mesh.shape.get("stage", 1) > 1 and mesh.shape.get("model", 1) > 1:
        raise ValueError(
            f"{who}: a 3-axis (data x stage x model) mesh has no "
            "supported survivor topology — elastic recovery composes "
            "over one non-data axis at a time")
    if "stage" in names:
        return "stage"
    if "model" in names:
        return "model"
    return None


def _mesh_from_flat(mesh: Mesh, devices, n_data: int, second: Optional[str],
                    second_size: int) -> Mesh:
    """Rebuild a mesh with ``mesh``'s axis names from a flat (data-major)
    device list, resizing ``data`` to ``n_data`` and the second axis to
    ``second_size`` (every other axis stays at size 1)."""
    if second is None:
        return Mesh(np.asarray(devices), ("data",))
    shape = tuple(n_data if a == "data"
                  else (second_size if a == second else 1)
                  for a in mesh.axis_names)
    return Mesh(np.asarray(devices).reshape(shape), mesh.axis_names)


def _largest_stage_divisor(n_layers: int, cap: int) -> int:
    """The largest stage count ``S' <= cap`` with ``S' | n_layers`` — the
    factorization choice of a layer re-partition. ``S' = 1`` always
    qualifies, so this only fails on a non-positive cap."""
    for s in range(min(int(cap), int(n_layers)), 0, -1):
        if n_layers % s == 0:
            return s
    raise ValueError(f"no stage count <= {cap} divides n_layers={n_layers}")


def survivor_submesh(mesh: Mesh, lost: Sequence[int],
                     *, layer_divisor: Optional[int] = None) -> Mesh:
    """The mesh that remains after losing devices ``lost`` — the elastic
    re-mesh step (resilience/elastic.py). Surviving devices keep their
    relative order, so replica ``i`` of the new mesh is the ``i``-th
    survivor of the old one.

    On a data-only mesh ``lost`` indexes replicas, exactly as before. On a
    2-axis mesh — ``(data, stage)`` DPxPP or ``(data, model)`` DPxTP —
    ``lost`` indexes the FLAT (data-major) device grid, and the survivor
    topology is chosen per axis:

    - **data shrink** (preferred): every victim's data row is dropped
      whole; the victims' stage/model column partners in the surviving
      rows are intact replicas of the same shards, so the recovery is a
      pure reshard at the same stage/model count.
    - **stage re-partition**: when NO complete data row survives, a
      ``stage`` mesh falls back to re-partitioning layers over the
      survivors — the new stage count is the largest ``S'`` that divides
      ``layer_divisor`` (the model's ``n_layers``, required here — a
      named error otherwise) and fits the surviving device count; the
      remaining survivors fill ``S'``-wide data rows. A ``model`` mesh
      has no such fallback (re-partitioning the Megatron column/row
      layout is unsupported) and errors instead."""
    second = _elastic_second_axis(mesh, "survivor_submesh")
    n_data = mesh.shape.get("data", 1)
    s2 = int(np.prod([s for a, s in mesh.shape.items() if a != "data"],
                     dtype=int)) if second is not None else 1
    total = n_data * s2
    lost = sorted(set(int(i) for i in lost))
    if any(i < 0 or i >= total for i in lost):
        noun = "replicas" if second is None else "devices"
        raise ValueError(f"lost {noun} {lost} out of range for "
                         f"{dict(mesh.shape)}")
    if len(lost) >= total:
        raise ValueError(f"losing {len(lost)} of {total} devices leaves no "
                         "survivors — nothing to re-mesh onto")
    flat = list(mesh.devices.flatten())
    if second is None:
        devices = [d for i, d in enumerate(flat) if i not in lost]
        return Mesh(np.asarray(devices), ("data",))
    victim_rows = {i // s2 for i in lost}
    surviving_rows = [r for r in range(n_data) if r not in victim_rows]
    if surviving_rows:
        devices = [flat[r * s2 + c] for r in surviving_rows
                   for c in range(s2)]
        return _mesh_from_flat(mesh, devices, len(surviving_rows),
                               second, s2)
    survivors = [d for i, d in enumerate(flat) if i not in lost]
    if second == "model":
        raise ValueError(
            f"device loss left no complete data row of the "
            f"{dict(mesh.shape)} mesh intact, and the model axis cannot "
            "re-partition (the Megatron column/row layout is not "
            "layer-sliced) — a model-axis loss is unrecoverable")
    if layer_divisor is None:
        raise ValueError(
            "stage re-partition needs layer_divisor (the model's "
            "n_layers) to choose a stage count S' with S' | n_layers — "
            "pass it through ElasticController(layer_divisor=...)")
    new_s = _largest_stage_divisor(int(layer_divisor),
                                   min(len(survivors), s2))
    new_d = len(survivors) // new_s
    return _mesh_from_flat(mesh, survivors[:new_d * new_s], new_d,
                           second, new_s)


def rejoin_mesh(mesh: Mesh, returned: Sequence, *,
                pool: Optional[Sequence] = None,
                pool_shape: Optional[Sequence[int]] = None,
                layer_divisor: Optional[int] = None) -> Mesh:
    """The mesh after previously-lost devices come back — the scale-UP
    inverse of ``survivor_submesh`` (resilience/elastic.py's grow path).

    ``returned`` is the device objects rejoining. ``pool`` is the run's
    original full device list: when given, the merged devices are ordered
    by their pool positions, so a shrink followed by a full rejoin
    reconstructs the original device order exactly — which is what makes a
    4→3→4 trajectory comparable to a fresh 4-replica run on
    ``jax.devices()[:4]`` (the bitwise bar in tests/test_elastic.py).
    Without ``pool`` the returned devices append at the end.

    On a 2-axis mesh ``pool_shape`` is the run's ORIGINAL device-grid
    shape: a full rejoin reshapes the pool-ordered devices straight back
    into it, restoring the original ``(data, stage)`` factorization
    device-for-device (a stage re-partition grows back to the original
    stage count, the multi-axis pool-order bar). A PARTIAL rejoin on a
    ``stage`` mesh re-runs the factorization choice (largest
    ``S' | layer_divisor`` that fits, capped by the original stage
    count); on a ``model`` mesh the model degree is fixed and the data
    axis takes whole rows.

    Rejoining a device already in the mesh is a hard error (a duplicate
    device would alias two replicas onto one chip and silently halve real
    throughput)."""
    second = _elastic_second_axis(mesh, "rejoin_mesh")
    returned = list(returned)
    if not returned:
        raise ValueError("rejoin_mesh needs at least one returned device")
    if len(set(returned)) != len(returned):
        raise ValueError(f"returned devices contain duplicates: {returned}")
    current = list(mesh.devices.flatten())
    for d in returned:
        if d in current:
            raise ValueError(f"device {d} is already in the mesh — "
                             "rejoining it would alias two replicas")
    devices = current + returned
    if pool is not None:
        index = {d: i for i, d in enumerate(pool)}
        missing = [d for d in devices if d not in index]
        if missing:
            raise ValueError(f"devices {missing} are not in the original "
                             "pool — rejoin_mesh can only restore capacity "
                             "the run started with")
        devices = sorted(devices, key=lambda d: index[d])
    if second is None:
        return Mesh(np.asarray(devices), ("data",))
    if pool_shape is not None and len(devices) == int(np.prod(pool_shape)):
        return Mesh(np.asarray(devices).reshape(tuple(pool_shape)),
                    mesh.axis_names)
    s2 = int(np.prod([s for a, s in mesh.shape.items() if a != "data"],
                     dtype=int))
    if second == "model":
        new_s = s2                  # the Megatron degree never changes
    else:
        cap = s2
        if pool_shape is not None:
            # Partial rejoins never exceed the run's original stage count
            # — the full-pool reshape above is the only path back to it.
            axis_pos = mesh.axis_names.index("stage")
            cap = int(pool_shape[axis_pos])
        if layer_divisor is None:
            raise ValueError(
                "a partial rejoin onto a stage mesh re-runs the "
                "factorization choice and needs layer_divisor (the "
                "model's n_layers)")
        new_s = _largest_stage_divisor(int(layer_divisor),
                                       min(len(devices), cap))
    new_d = len(devices) // new_s
    if new_d < 1:
        raise ValueError(f"{len(devices)} devices cannot host a "
                         f"{second}={new_s} mesh")
    return _mesh_from_flat(mesh, devices[:new_d * new_s], new_d,
                           second, new_s)


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape.get(name, 1)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch) -> jax.Array:
    """Place a host batch with its leading axis sharded over ``data`` (when
    the mesh has a data axis) and replicated over every other axis — the one
    batch layout all parallelism modes here share (PP stages, TP/EP shards
    and SP windows each read the full local batch)."""
    spec = P("data") if mesh.shape.get("data", 1) > 1 else P()
    return jax.device_put(batch, NamedSharding(mesh, spec))


def sharded(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))
