"""Device-mesh construction — the framework's replacement for process groups.

The reference wires N OS processes with `init_process_group("gloo", rank, N)`,
ranks, MASTER_ADDR/PORT, and `dist.new_group` sub-groups (reference:
lab/tutorial_1b/DP/gradient_aggr/intro_DP_GA.py:11-15, lab/hw01/homework 1 b/
homework_1_b2.py:28-32). Here the whole layer is one named
`jax.sharding.Mesh`: axes replace groups, SPMD program order replaces tags,
and collective lowering to XLA HLO over ICI/DCN replaces gloo's TCP.

Multi-host: call `jax.distributed.initialize()` before building the mesh and
`jax.devices()` spans hosts; nothing else changes (DCN between hosts, ICI
within a slice).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# Canonical axis order, outermost first. ``dcn`` is the cross-host tier of
# a hierarchical data-parallel mesh (parallel/distributed.py:hier_data_mesh)
# — islands of fast ICI bridged by slow DCN — and sits outermost so the
# device order is island-major: replica (d, s) = device d·island_size + s.
# Meshes without a ``dcn`` axis are laid out exactly as before.
AXES = ("dcn", "data", "stage", "model", "seq", "expert")


def make_mesh(axis_sizes: Optional[Dict[str, int]] = None, *,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a named mesh.

    ``axis_sizes`` maps axis name -> size; omitted axes get size 1. The mesh
    uses the first prod(sizes) devices (a size of -1 is inferred from the
    device count); a warning is emitted if that leaves devices idle. With no
    arguments, all devices land on the ``data`` axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axis_sizes or {})
    if not sizes:
        sizes = {"data": n}
    names = [a for a in AXES if a in sizes] + [a for a in sizes if a not in AXES]
    shape = [sizes[a] for a in names]
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        shape[shape.index(-1)] = n // known
    need = int(np.prod(shape))
    assert need <= n, f"axis sizes {dict(zip(names, shape))} need {need} > {n} devices"
    if need < n:
        import warnings
        warnings.warn(f"mesh {dict(zip(names, shape))} uses {need} of {n} devices; "
                      f"the rest stay idle", stacklevel=2)
    dev_array = np.asarray(devices[:need]).reshape(shape)
    return Mesh(dev_array, tuple(names))


def survivor_submesh(mesh: Mesh, lost: Sequence[int]) -> Mesh:
    """The mesh that remains after losing data-axis replicas ``lost`` —
    elastic DP's re-mesh step (resilience/elastic.py). Surviving devices
    keep their relative order, so replica ``i`` of the new mesh is the
    ``i``-th survivor of the old one.

    Data-axis-only meshes for now: dropping a replica from a multi-axis
    mesh (DPxPP, DPxTP) would orphan the lost replica's stage/model
    partners, a genuinely different recovery problem (their shards are
    intact and must be re-wired, not resharded)."""
    for name, size in mesh.shape.items():
        if name != "data" and size > 1:
            raise ValueError(
                f"survivor_submesh supports data-axis-only meshes; "
                f"axis {name!r} has size {size}")
    n = mesh.shape.get("data", 1)
    lost = sorted(set(int(i) for i in lost))
    if any(i < 0 or i >= n for i in lost):
        raise ValueError(f"lost replicas {lost} out of range for data={n}")
    if len(lost) >= n:
        raise ValueError(f"losing {len(lost)} of {n} replicas leaves no "
                         "survivors — nothing to re-mesh onto")
    devices = [d for i, d in enumerate(mesh.devices.flatten())
               if i not in lost]
    return Mesh(np.asarray(devices), ("data",))


def rejoin_mesh(mesh: Mesh, returned: Sequence, *,
                pool: Optional[Sequence] = None) -> Mesh:
    """The mesh after previously-lost devices come back — the scale-UP
    inverse of ``survivor_submesh`` (resilience/elastic.py's grow path).

    ``returned`` is the device objects rejoining. ``pool`` is the run's
    original full device list: when given, the merged devices are ordered
    by their pool positions, so a shrink followed by a full rejoin
    reconstructs the original device order exactly — which is what makes a
    4→3→4 trajectory comparable to a fresh 4-replica run on
    ``jax.devices()[:4]`` (the bitwise bar in tests/test_elastic.py).
    Without ``pool`` the returned devices append at the end.

    Same data-axis-only restriction as ``survivor_submesh``, and rejoining
    a device already in the mesh is a hard error (a duplicate device would
    alias two replicas onto one chip and silently halve real throughput)."""
    for name, size in mesh.shape.items():
        if name != "data" and size > 1:
            raise ValueError(
                f"rejoin_mesh supports data-axis-only meshes; "
                f"axis {name!r} has size {size}")
    returned = list(returned)
    if not returned:
        raise ValueError("rejoin_mesh needs at least one returned device")
    if len(set(returned)) != len(returned):
        raise ValueError(f"returned devices contain duplicates: {returned}")
    current = list(mesh.devices.flatten())
    for d in returned:
        if d in current:
            raise ValueError(f"device {d} is already in the mesh — "
                             "rejoining it would alias two replicas")
    devices = current + returned
    if pool is not None:
        index = {d: i for i, d in enumerate(pool)}
        missing = [d for d in devices if d not in index]
        if missing:
            raise ValueError(f"devices {missing} are not in the original "
                             "pool — rejoin_mesh can only restore capacity "
                             "the run started with")
        devices = sorted(devices, key=lambda d: index[d])
    return Mesh(np.asarray(devices), ("data",))


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape.get(name, 1)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch) -> jax.Array:
    """Place a host batch with its leading axis sharded over ``data`` (when
    the mesh has a data axis) and replicated over every other axis — the one
    batch layout all parallelism modes here share (PP stages, TP/EP shards
    and SP windows each read the full local batch)."""
    spec = P("data") if mesh.shape.get("data", 1) > 1 else P()
    return jax.device_put(batch, NamedSharding(mesh, spec))


def sharded(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))
