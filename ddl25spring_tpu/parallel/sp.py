"""Sequence/context parallelism: ring attention over a ``seq`` mesh axis.

The reference has NO long-context support — sequence length is fixed at 256
on a single device (reference: lab/tutorial_1b/primer/intro.py:10; SURVEY.md
§5.7). This module is the capability the TPU build adds as first-class: the
sequence axis becomes a mesh axis, each device holds a contiguous window of
the sequence, and attention runs as a **ring**: K/V shards rotate around the
ICI ring via ``lax.ppermute`` while each device's queries accumulate the
online-softmax statistics (the blockwise-parallel/RingAttention recurrence).
Peak activation memory per device drops from O(T) to O(T / n_seq), so context
scales linearly with the ring size.

Design notes:
- The rotation direction is the ICI ring: device s sends its current K/V
  chunk to s+1, so after t hops device s holds the chunk owned by s−t.
- Causality is positional: the owner of the incoming chunk determines its
  global key offsets; masked entries get zero softmax mass exactly (the
  `p = where(visible, ...)` guard, not just a −inf logit, so fully-masked
  future chunks contribute nothing to the running sums).
- The backward pass is jax.grad through the scanned ppermute — the cotangent
  rotates the opposite way around the ring automatically; no hand-written
  reverse schedule.
- RoPE stays correct because models/llama.rope_angles takes *absolute*
  positions; each shard passes its global window offsets.
- Composes with data parallelism on a ``(data, seq)`` mesh: batch sharded
  over ``data``, sequence over ``seq``, grads psum over both.
- The per-hop inner attention stays the XLA einsum + online-softmax, NOT the
  Pallas flash kernel, deliberately: each hop sees a [T/n_seq, T/n_seq]
  block, and at this model's head_dim=48 the flash kernel only beats XLA
  from seq ≈4096 up (lane padding 48→128 wastes ~62% of each MXU pass —
  measured, experiments/attn_bench.py). A ring large enough to make hops
  flash-profitable (T/n_seq ≥ 4096) is exactly the regime where plain
  single-device flash would already fit; the ring exists to shard memory,
  and its chunks sit below the crossover.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry import comm
from ._compat import axis_size, shard_map

from ..config import LlamaConfig
from ..models import llama
from .dp import TrainState, sharded_opt_init

_NEG_INF = -1e30


# --------------------------------------------------------------- the kernel

def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, *, causal: bool = True,
                   comm_scale: int = 1) -> jnp.ndarray:
    """Ring attention over sequence shards. Must run inside shard_map.

    q, k, v: local shards [B, T_local, H, Dh] whose global positions are
    ``axis_index * T_local + arange(T_local)``. Returns [B, T_local, H, Dh] —
    each query attends over the FULL global sequence (causally masked).

    ``comm_scale``: executions of this call per step beyond what tracing
    sees — callers inside a scanned layer stack pass their layer count so
    telemetry.comm's per-step byte accounting stays truthful (the K/V hop
    ppermutes below already self-scale by the ring length; the backward
    ring autodiff synthesizes is the documented under-count).
    """
    n = axis_size(axis_name)
    s = lax.axis_index(axis_name)
    b, tl, h, dh = q.shape
    scale = 1.0 / (dh ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]
    qpos = jnp.arange(tl)[:, None] + s * tl                     # [tl, 1]

    def step(carry, t):
        k_c, v_c, m, l, acc = carry
        owner = (s - t) % n                                     # chunk origin
        scores = (jnp.einsum("bthd,bshd->bhts", q, k_c)
                  .astype(jnp.float32) * scale)                 # [b,h,tl,tl]
        kpos = jnp.arange(tl)[None, :] + owner * tl
        visible = (qpos >= kpos) if causal else jnp.ones_like(qpos >= kpos)
        scores = jnp.where(visible[None, None], scores, _NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        # Explicit zeroing (not just −inf logits): a fully-masked chunk has
        # m_new == m == _NEG_INF, where exp(scores − m_new) would be exp(0)=1.
        p = jnp.where(visible[None, None], jnp.exp(scores - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhts,bshd->bhtd", p.astype(v_c.dtype), v_c).astype(jnp.float32)
        # scale = n·comm_scale: the scan body traces ONCE but hops n times
        # per attention call, comm_scale attention calls per step.
        k_n = comm.ppermute(k_c, axis_name, perm, label="ring_kv_hop",
                            scale=n * comm_scale)
        v_n = comm.ppermute(v_c, axis_name, perm, label="ring_kv_hop",
                            scale=n * comm_scale)
        return (k_n, v_n, m_new, l, acc), None

    init = (k, v,
            jnp.full((b, h, tl, 1), _NEG_INF, jnp.float32),
            jnp.zeros((b, h, tl, 1), jnp.float32),
            jnp.zeros((b, h, tl, dh), jnp.float32))
    (_, _, _, l, acc), _ = lax.scan(step, init, jnp.arange(n))
    out = acc / jnp.where(l == 0.0, 1.0, l)
    return jnp.moveaxis(out, 1, 2).astype(q.dtype)              # [b,tl,h,dh]


# ------------------------------------------------------- sequence-parallel LM

def _local_window(tokens: jnp.ndarray, s, tl: int) -> jnp.ndarray:
    """Slice shard s's [B, tl] window out of the replicated [B, T] batch."""
    return lax.dynamic_slice_in_dim(tokens, s * tl, tl, axis=1)


def _sp_logits(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
               n_seq: int) -> jnp.ndarray:
    """Per-shard body: local logits [B, T/n_seq, V] for this shard's window."""
    s = lax.axis_index("seq")
    t = tokens.shape[1]
    assert t % n_seq == 0, (t, n_seq)
    tl = t // n_seq
    local_tok = _local_window(tokens, s, tl)
    positions = jnp.arange(tl) + s * tl                         # global RoPE
    h = llama.embed(params, local_tok, cfg)
    # comm_scale=n_layers: blocks_apply scans the layer stack, so the ring
    # traces once for L executions per step.
    attn = functools.partial(ring_attention, axis_name="seq", causal=True,
                             comm_scale=cfg.n_layers)
    h = llama.blocks_apply(params["blocks"], h, cfg, positions, attn_fn=attn)
    return llama.head(params, h, cfg)


def _sp_loss(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
             n_seq: int) -> jnp.ndarray:
    """LOCAL share of the causal LM loss under sequence sharding; psum over
    ``seq`` of this equals single-device ops.causal_lm_loss (mean NLL over
    the B·(T−1) next-token positions).

    The shift crosses shard boundaries: shard s's last position is predicted
    against shard s+1's first token, so targets come from the *replicated*
    token batch rolled left by one; the global final position is masked.

    Deliberately NO psum inside: this function sits under value_and_grad, and
    psum's transpose is psum — reducing the loss before differentiation would
    seed every replica and scale gradients by n_seq (same pitfall documented
    in parallel.pp._pipeline_loss_and_grad). Callers psum loss and grads
    AFTER the grad computation.
    """
    s = lax.axis_index("seq")
    b, t = tokens.shape
    tl = t // n_seq
    logits = _sp_logits(params, tokens, cfg, n_seq)
    rolled = jnp.roll(tokens, -1, axis=1)
    targets = _local_window(rolled, s, tl)
    gpos = jnp.arange(tl) + s * tl
    valid = (gpos < t - 1)[None, :]                             # [1, tl]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return (nll * valid).sum() / (b * (t - 1))


@functools.cache
def _sp_forward_fn(cfg: LlamaConfig, mesh: Mesh, n_seq: int) -> Callable:
    fn = shard_map(
        lambda p, tok: _sp_logits(p, tok, cfg, n_seq),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(None, "seq"),
        check_vma=False,
    )
    return jax.jit(fn)


def sp_forward(params: dict, tokens: jnp.ndarray, cfg: LlamaConfig,
               mesh: Mesh) -> jnp.ndarray:
    """Full logits [B, T, V] computed sequence-parallel (for tests/eval).
    The jitted program is cached on (cfg, mesh) so eval loops don't retrace."""
    return _sp_forward_fn(cfg, mesh, mesh.shape["seq"])(params, tokens)


def init_state(mesh: Mesh, params: dict,
               optimizer: optax.GradientTransformation) -> TrainState:
    """Params replicated (sequence parallelism shards activations, not
    weights); see parallel.tp for weight sharding."""
    params = jax.device_put(params, NamedSharding(mesh, P()))
    opt_state = sharded_opt_init(mesh, params, optimizer,
                                 jax.tree.map(lambda _: P(), params))
    step = jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P()))
    return TrainState(params, opt_state, step)


def make_sp_train_step(cfg: LlamaConfig, optimizer: optax.GradientTransformation,
                       mesh: Mesh) -> Callable:
    """jit-compiled train step on a ``(data?, seq)`` mesh.

    ``step(state, tokens)`` with tokens [B_global, T]: batch axis sharded over
    ``data`` (if present), tokens replicated over ``seq`` (each shard slices
    its own window — int tokens are tiny; activations are what SP shards).
    """
    n_seq = mesh.shape["seq"]
    has_data = mesh.shape.get("data", 1) > 1

    def local_step(state: TrainState, tokens):
        loss, grads = jax.value_and_grad(_sp_loss)(
            state.params, tokens, cfg, n_seq)
        # Each shard computed grads from its local loss slice; the total
        # gradient is the sum over shards (loss was already globally scaled).
        grads = comm.psum(grads, "seq", label="sp_grad_allreduce")
        loss = comm.psum(loss, "seq", label="sp_loss_allreduce")
        if has_data:
            grads = comm.pmean(grads, "data", label="grad_allreduce")
            loss = comm.pmean(loss, "data", label="loss_allreduce")
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P("data") if has_data else P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


from .mesh import shard_batch  # noqa: E402,F401  (shared batch placement)
