"""Expert parallelism: the MoE expert bank sharded over an ``expert`` axis.

Parity-plus (SURVEY.md §2.10: EP "Absent" in the reference). Each device
holds ``n_experts / ep`` experts' weights and runs ONLY its local experts'
matmuls; the tiny router runs replicated on every shard (its [D, E] matrix
is negligible next to the expert FFNs) and the combine is one psum over the
``expert`` axis — dispatch stays dense/static-shaped, so the per-expert
matmuls land on the MXU and the collective rides ICI.

Gradient accounting mirrors parallel.tp: per-shard loss is scaled by 1/ep
before differentiation (each shard's replicated loss copy sees every shard's
expert weights through the psum), making sharded-leaf grads exact locally
and replicated-leaf grads exact after a psum over ``expert``. Composes with
data parallelism on a ``(data, expert)`` mesh.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..telemetry import comm
from ._compat import shard_map

from ..config import MoEConfig
from ..models import moe
from ..ops import causal_lm_loss
from .dp import TrainState, apply_optimizer, sharded_opt_init

_EXPERT_LEAVES = {"w_gate", "w_up", "w_down"}   # leading [L, E, ...] axis


def param_specs(params: dict) -> dict:
    """PartitionSpecs: expert banks sharded on their [E] axis (dim 1 after
    the stacked-layer dim), everything else replicated."""
    def block_spec(name, leaf):
        if name in _EXPERT_LEAVES:
            return jax.tree.map(lambda _: P(None, "expert", None, None), leaf)
        return jax.tree.map(lambda _: P(), leaf)

    return {
        k: ({name: block_spec(name, leaf) for name, leaf in v.items()}
            if k == "blocks" else jax.tree.map(lambda _: P(), v))
        for k, v in params.items()
    }


def shard_params(mesh: Mesh, params: dict) -> dict:
    specs = param_specs(params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def init_state(mesh: Mesh, params: dict,
               optimizer: optax.GradientTransformation) -> TrainState:
    params = shard_params(mesh, params)
    opt_state = sharded_opt_init(mesh, params, optimizer, param_specs(params))
    step = jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P()))
    return TrainState(params, opt_state, step)


def _ep_loss(params: dict, tokens: jnp.ndarray, cfg: MoEConfig,
             ep: int) -> jnp.ndarray:
    logits, aux = moe.forward(params, tokens, cfg, expert_axis="expert")
    loss = causal_lm_loss(logits, tokens) + cfg.aux_loss_coef * aux
    return loss / ep


def make_ep_train_step(cfg: MoEConfig, optimizer: optax.GradientTransformation,
                       mesh: Mesh) -> Callable:
    """jit-compiled MoE train step on a ``(data?, expert)`` mesh."""
    ep = mesh.shape["expert"]
    has_data = mesh.shape.get("data", 1) > 1

    def sharded_grads(params: dict, tokens):
        loss, grads = jax.value_and_grad(_ep_loss)(params, tokens, cfg, ep)
        def _replicated_psum(x):
            return comm.psum(x, "expert", label="ep_replicated_grads")

        grads = {
            k: ({name: (g if name in _EXPERT_LEAVES else
                        jax.tree.map(_replicated_psum, g))
                 for name, g in v.items()} if k == "blocks"
                else jax.tree.map(_replicated_psum, v))
            for k, v in grads.items()
        }
        loss = loss * ep
        if has_data:
            grads = comm.pmean(grads, "data", label="grad_allreduce")
            loss = comm.pmean(loss, "data", label="loss_allreduce")
        return loss, grads

    def step(state: TrainState, tokens):
        pspecs = param_specs(state.params)
        loss, grads = shard_map(
            sharded_grads, mesh=mesh,
            in_specs=(pspecs, P("data") if has_data else P()),
            out_specs=(P(), pspecs),
            check_vma=False,
        )(state.params, tokens)
        params, opt_state = apply_optimizer(optimizer, grads,
                                            state.opt_state, state.params)
        return TrainState(params, opt_state, state.step + 1), loss

    return jax.jit(step, donate_argnums=(0,))


@functools.cache
def _ep_forward_fn(cfg: MoEConfig, mesh: Mesh) -> Callable:
    def body(params, tokens):
        logits, aux = moe.forward(params, tokens, cfg, expert_axis="expert")
        return logits, aux

    def fn(params, tokens):
        return shard_map(
            body, mesh=mesh,
            in_specs=(param_specs(params), P()),
            out_specs=(P(), P()),
            check_vma=False,
        )(params, tokens)

    return jax.jit(fn)


def ep_forward(params: dict, tokens: jnp.ndarray, cfg: MoEConfig,
               mesh: Mesh):
    """(logits, aux) via expert-parallel forward; cached on (cfg, mesh)."""
    return _ep_forward_fn(cfg, mesh)(params, tokens)


from .mesh import shard_batch  # noqa: E402,F401  (shared batch placement)
