"""RNG discipline.

The reference pins determinism with global seeds plus a per-(client, round)
seed formula ``seed + ind + 1 + round * clients_per_round`` so client work is
reproducible regardless of sampling order (reference:
lab/tutorial_1a/hfl_complete.py:285,364 and :323 ``torch.manual_seed(seed)``).

Here the same contract is expressed with JAX's splittable keys: a single base
key per experiment, and *observable* per-(client, round) derivation via
``fold_in``. We also expose the reference's integer formula itself
(`per_client_seed`) so tests can assert the exact derivation the reference
used, and FL servers can log it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def base_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def per_client_seed(seed: int, round_idx: int, client_ind: int, clients_per_round: int) -> int:
    """The reference's exact integer seed formula (hfl_complete.py:364):
    ``seed + ind + 1 + round * nr_clients_per_round``."""
    return seed + client_ind + 1 + round_idx * clients_per_round


def client_round_key(seed: int, round_idx: int, client_ind: int, clients_per_round: int) -> jax.Array:
    """Key for one client's local work in one round.

    Folds the reference's integer formula into a JAX key so that (a) the
    derivation is order-independent exactly like the reference's, and (b) two
    different (round, client) pairs that collide under the reference's additive
    formula also collide here — preserving its observable semantics.
    """
    return jax.random.key(per_client_seed(seed, round_idx, client_ind, clients_per_round))


def epochs_keys(key: jax.Array, epochs: int) -> jax.Array:
    """Per-epoch shuffle keys for local training."""
    return jax.random.split(key, epochs)


def sample_clients(seed: int, round_idx: int, nr_clients: int, nr_per_round: int) -> jnp.ndarray:
    """Client sampling for a round — without-replacement choice of
    ``nr_per_round`` of ``nr_clients`` (reference: hfl_complete.py:353
    ``rng.choice(nr_clients, nr_per_round, replace=False)`` with a
    ``npr.default_rng(seed)`` advanced per round).

    We derive a fresh key per round by folding the round index, which gives
    the same distributional semantics with order-independent reproducibility.
    """
    k = jax.random.fold_in(jax.random.key(seed), round_idx)
    perm = jax.random.permutation(k, nr_clients)
    return perm[:nr_per_round]
