from .spm import SentencePieceTokenizer, ByteTokenizer, load_tokenizer  # noqa: F401
