"""Self-contained SentencePiece tokenizer.

Capability target: simplellm's `SPTokenizer` surface — ``.vocab_size``,
``.pad_id``, encode/decode — backed by the vendored Llama SentencePiece model
(reference: lab/requirements.txt:9, lab/llama-tokenizer.model; log evidence
lab/out_b1_0.txt:1-4). The `sentencepiece` wheel is not available in this
image, so this module reads the ``.model`` file directly: it is a protobuf
(ModelProto) whose field 1 is the repeated (piece, score, type) vocabulary,
and unigram segmentation is a Viterbi pass over those scores.

No external deps: a ~60-line protobuf wire-format reader + Viterbi encoder +
byte-fallback. A `ByteTokenizer` stands in when no model file is present
(zero-egress containers), keeping every downstream pipeline runnable.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, List, Optional, Tuple

# SentencePiece piece types (ModelProto.SentencePiece.Type)
_NORMAL, _UNKNOWN, _CONTROL, _USER_DEFINED, _BYTE, _UNUSED = 1, 2, 3, 4, 6, 5
_WS = "▁"  # the ▁ whitespace marker


# ------------------------------------------------------------ protobuf reader

def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a protobuf message body."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:      # varint
            val, pos = _read_varint(buf, pos)
        elif wire == 1:    # 64-bit
            val = buf[pos:pos + 8]; pos += 8
        elif wire == 2:    # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]; pos += ln
        elif wire == 5:    # 32-bit
            val = buf[pos:pos + 4]; pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def parse_model_proto(data: bytes) -> Tuple[List[Tuple[str, float, int]], int]:
    """Extract ([(piece, score, type), ...], model_type) from a SentencePiece
    ModelProto. model_type: 1=unigram, 2=bpe (TrainerSpec.model_type)."""
    pieces = []
    model_type = 1
    for field, wire, val in _iter_fields(data):
        if field == 1 and wire == 2:  # repeated SentencePiece pieces
            piece, score, ptype = "", 0.0, _NORMAL
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1:
                    piece = v2.decode("utf-8")
                elif f2 == 2:
                    score = struct.unpack("<f", v2)[0]
                elif f2 == 3:
                    ptype = v2
            pieces.append((piece, score, ptype))
        elif field == 2 and wire == 2:  # TrainerSpec
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 3 and w2 == 0:  # model_type enum
                    model_type = v2
    return pieces, model_type


# ------------------------------------------------------------ tokenizers

class SentencePieceTokenizer:
    """Unigram-model tokenizer with byte fallback (Llama convention)."""

    def __init__(self, model_path: str):
        with open(model_path, "rb") as f:
            pieces, model_type = parse_model_proto(f.read())
        self._setup(pieces, model_type == 2)

    @classmethod
    def from_pieces(cls, pieces: List[Tuple[str, float, int]], *,
                    is_bpe: bool = False) -> "SentencePieceTokenizer":
        """Build from an in-memory (piece, score, type) table — used by tests
        and by the native-pipeline parity harness."""
        self = cls.__new__(cls)
        self._setup(pieces, is_bpe)
        return self

    def _setup(self, pieces: List[Tuple[str, float, int]], is_bpe: bool) -> None:
        self.pieces = pieces
        self.is_bpe = is_bpe
        self.vocab_size = len(pieces)
        self._piece_to_id: Dict[str, int] = {}
        self._byte_to_id: Dict[int, int] = {}
        self.unk_id = 0
        self.bos_id = -1
        self.eos_id = -1
        for i, (piece, score, ptype) in enumerate(pieces):
            if ptype == _BYTE:
                # pieces look like "<0x0A>"
                self._byte_to_id[int(piece[1:-1], 16)] = i
            elif ptype == _UNKNOWN:
                self.unk_id = i
            elif ptype == _CONTROL:
                if piece == "<s>":
                    self.bos_id = i
                elif piece == "</s>":
                    self.eos_id = i
            else:
                self._piece_to_id[piece] = i
        # Llama's SP model has no pad piece; simplellm uses unk as pad. Keep
        # pad_id distinct-but-valid: eos if present else unk.
        self.pad_id = self.eos_id if self.eos_id >= 0 else self.unk_id
        self._scores = [score for _, score, _ in pieces]
        self._max_piece_len = max((len(p) for p, _, t in pieces if t == _NORMAL), default=1)

    def encode(self, text: str, *, add_bos: bool = False) -> List[int]:
        """Segment text: BPE greedy-merge for BPE models (the Llama tokenizer
        stores score = -merge_rank), Viterbi max-score for unigram models."""
        s = _WS + text.replace(" ", _WS)
        if self.is_bpe:
            ids = self._encode_bpe(s)
        else:
            ids = self._encode_unigram(s)
        if add_bos and self.bos_id >= 0:
            ids.insert(0, self.bos_id)
        return ids

    def _fallback_ids(self, piece: str) -> List[int]:
        """Byte-fallback for a substring not in the vocab."""
        bs = piece.encode("utf-8")
        if all(b in self._byte_to_id for b in bs):
            return [self._byte_to_id[b] for b in bs]
        return [self.unk_id]

    def _encode_bpe(self, s: str) -> List[int]:
        """SentencePiece-BPE: start from characters, repeatedly merge the
        adjacent pair whose concatenation is the best-scored vocab piece."""
        import heapq

        parts: List[str] = list(s)
        if not parts:
            return []
        # Doubly-linked list over parts; heap of candidate merges.
        nxt = list(range(1, len(parts))) + [-1]
        prv = [-1] + list(range(len(parts) - 1))
        alive = [True] * len(parts)
        heap: List[Tuple[float, int, int]] = []

        def push(i: int):
            j = nxt[i]
            if j == -1:
                return
            pid = self._piece_to_id.get(parts[i] + parts[j])
            if pid is not None:
                heapq.heappush(heap, (-self._scores[pid], i, j))

        for i in range(len(parts) - 1):
            push(i)
        while heap:
            negscore, i, j = heapq.heappop(heap)
            if not (alive[i] and alive[j]) or nxt[i] != j:
                continue  # stale entry
            parts[i] = parts[i] + parts[j]
            alive[j] = False
            nxt[i] = nxt[j]
            if nxt[j] != -1:
                prv[nxt[j]] = i
            if prv[i] != -1:
                push(prv[i])
            push(i)
        ids: List[int] = []
        i = 0
        while i != -1:
            if alive[i]:
                pid = self._piece_to_id.get(parts[i])
                ids.extend([pid] if pid is not None else self._fallback_ids(parts[i]))
            i = nxt[i]
        return ids

    def _encode_unigram(self, s: str) -> List[int]:
        """Viterbi segmentation maximizing total piece score (unigram LM)."""
        n = len(s)
        NEG = -1e18
        best = [NEG] * (n + 1)
        back: List[Optional[Tuple[int, int]]] = [None] * (n + 1)  # (start, id)
        best[0] = 0.0
        unk_penalty = min(self._scores) - 10.0 if self._scores else -20.0
        for end in range(1, n + 1):
            lo = max(0, end - self._max_piece_len)
            for start in range(lo, end):
                if best[start] <= NEG / 2:
                    continue
                pid = self._piece_to_id.get(s[start:end])
                if pid is not None:
                    sc = best[start] + self._scores[pid]
                    if sc > best[end]:
                        best[end], back[end] = sc, (start, pid)
            # unk/byte fallback: single char from best[end-1]
            if back[end] is None and best[end - 1] > NEG / 2:
                best[end], back[end] = best[end - 1] + unk_penalty, (end - 1, -1)
        ids: List[int] = []
        pos = n
        while pos > 0:
            start, pid = back[pos]
            if pid >= 0:
                ids.append(pid)
            else:
                ch = s[start:pos]
                bs = ch.encode("utf-8")
                if all(b in self._byte_to_id for b in bs):
                    ids.extend(self._byte_to_id[b] for b in reversed(bs))
                else:
                    ids.append(self.unk_id)
            pos = start
        ids.reverse()
        return ids

    def decode(self, ids: List[int]) -> str:
        out: List[str] = []
        byte_buf: List[int] = []
        inv_bytes = {v: k for k, v in self._byte_to_id.items()}

        def flush():
            if byte_buf:
                out.append(bytes(byte_buf).decode("utf-8", errors="replace"))
                byte_buf.clear()

        for i in ids:
            piece, _, ptype = self.pieces[i]
            if ptype == _BYTE:
                byte_buf.append(inv_bytes[i])
                continue
            flush()
            if ptype in (_CONTROL, _UNKNOWN):
                continue
            out.append(piece)
        flush()
        text = "".join(out).replace(_WS, " ")
        # Remove exactly the one dummy-prefix space encode() added — real
        # SentencePiece semantics; lstrip would eat genuine leading spaces.
        return text[1:] if text.startswith(" ") else text


class ByteTokenizer:
    """Offline fallback: UTF-8 bytes + specials; same interface."""

    def __init__(self):
        self.vocab_size = 259
        self.pad_id = 256
        self.bos_id = 257
        self.eos_id = 258
        self.unk_id = 256

    def encode(self, text: str, *, add_bos: bool = False) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: List[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")


_DEFAULT_PATHS = (
    "data/llama-tokenizer.model",
    "/root/reference/lab/llama-tokenizer.model",
)


def load_tokenizer(model_path: Optional[str] = None):
    """Load the SentencePiece model if one can be found, else ByteTokenizer.

    Search order: explicit arg, $DDL_TOKENIZER_MODEL, ./data/, the reference
    checkout. Falls back to bytes so zero-asset environments still run.
    """
    candidates = [model_path, os.environ.get("DDL_TOKENIZER_MODEL"), *_DEFAULT_PATHS]
    for c in candidates:
        if c and os.path.exists(c):
            return SentencePieceTokenizer(c)
    return ByteTokenizer()
