"""Minimal functional NN primitives.

The reference leans on ``torch.nn`` for its layer zoo; this module is the
framework's own equivalent: pure init/apply function pairs over plain pytrees.
Everything composes with jit/vmap/shard_map with no module magic, which is
what the FL client axis (vmap over clients) and the parallelism strategies
(shard_map over mesh axes) need.

Conventions:
- ``*_init(key, ...) -> params`` returns a dict pytree of arrays.
- apply functions are pure; layers with running state (BatchNorm) take and
  return an explicit ``state`` pytree; stochastic layers (Dropout) take a key.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


# ---------------------------------------------------------------- dense

def dense_init(key, in_dim: int, out_dim: int, *, scale: Optional[float] = None,
               bias: bool = True, dtype=jnp.float32) -> dict:
    """Kaiming-uniform by default (the torch.nn.Linear convention the
    reference models implicitly rely on for their accuracy baselines)."""
    kw, kb = jax.random.split(key)
    bound = scale if scale is not None else (1.0 / jnp.sqrt(in_dim))
    p = {"w": jax.random.uniform(kw, (in_dim, out_dim), dtype, -bound, bound)}
    if bias:
        p["b"] = jax.random.uniform(kb, (out_dim,), dtype, -bound, bound)
    return p


def dense(params: dict, x: jnp.ndarray) -> jnp.ndarray:
    y = x @ params["w"]
    if "b" in params:
        y = y + params["b"]
    return y


# ---------------------------------------------------------------- conv2d

def conv2d_init(key, in_ch: int, out_ch: int, kernel: int, *, dtype=jnp.float32) -> dict:
    kw, kb = jax.random.split(key)
    fan_in = in_ch * kernel * kernel
    bound = 1.0 / jnp.sqrt(fan_in)
    return {
        "w": jax.random.uniform(kw, (out_ch, in_ch, kernel, kernel), dtype, -bound, bound),
        "b": jax.random.uniform(kb, (out_ch,), dtype, -bound, bound),
    }


def conv2d(params: dict, x: jnp.ndarray, *, stride: int = 1, padding: str = "VALID") -> jnp.ndarray:
    """x: [N, C, H, W] (NCHW, matching the reference's tensor layout)."""
    y = lax.conv_general_dilated(
        x, params["w"],
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return y + params["b"][None, :, None, None]


def max_pool2d(x: jnp.ndarray, window: int = 2, stride: Optional[int] = None) -> jnp.ndarray:
    stride = stride or window
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 1, window, window),
        window_strides=(1, 1, stride, stride),
        padding="VALID",
    )


# ---------------------------------------------------------------- norm layers

def batchnorm_init(dim: int, dtype=jnp.float32) -> Tuple[dict, dict]:
    """Returns (params, state). State carries running mean/var like
    torch.nn.BatchNorm1d (used throughout the reference VAE,
    lab/tutorial_2a/generative-modeling.py:17-38)."""
    params = {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,), dtype)}
    state = {"mean": jnp.zeros((dim,), dtype), "var": jnp.ones((dim,), dtype)}
    return params, state


def batchnorm(params: dict, state: dict, x: jnp.ndarray, *, train: bool,
              momentum: float = 0.1, eps: float = 1e-5) -> Tuple[jnp.ndarray, dict]:
    """BatchNorm over the leading (batch) axis for 2-D inputs [N, D]."""
    if train:
        mean = x.mean(axis=0)
        var = x.var(axis=0)
        n = x.shape[0]
        unbiased = var * (n / max(n - 1, 1))
        new_state = {
            "mean": (1 - momentum) * state["mean"] + momentum * mean,
            "var": (1 - momentum) * state["var"] + momentum * unbiased,
        }
    else:
        mean, var = state["mean"], state["var"]
        new_state = state
    y = (x - mean) / jnp.sqrt(var + eps)
    return y * params["scale"] + params["bias"], new_state


def rmsnorm_init(dim: int, dtype=jnp.float32) -> dict:
    return {"scale": jnp.ones((dim,), dtype)}


def rmsnorm(params: dict, x: jnp.ndarray, *, eps: float = 1e-5) -> jnp.ndarray:
    # Compute the reduction in fp32 for stability under bf16 activations.
    x32 = x.astype(jnp.float32)
    rms = jnp.sqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + eps)
    return (x32 / rms).astype(x.dtype) * params["scale"].astype(x.dtype)


# ---------------------------------------------------------------- dropout

def dropout(key, x: jnp.ndarray, rate: float, *, train: bool) -> jnp.ndarray:
    if not train or rate == 0.0:
        return x
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0)


# ---------------------------------------------------------------- activations

relu = jax.nn.relu
leaky_relu = jax.nn.leaky_relu   # default slope 0.01 == torch.nn.LeakyReLU
silu = jax.nn.silu
gelu = jax.nn.gelu
softmax = jax.nn.softmax
log_softmax = jax.nn.log_softmax


def mlp_init(key, dims: Sequence[int], *, dtype=jnp.float32) -> list:
    """Stack of dense layers: dims = [in, h1, ..., out]."""
    keys = jax.random.split(key, len(dims) - 1)
    return [dense_init(k, dims[i], dims[i + 1], dtype=dtype) for i, k in enumerate(keys)]


def mlp(params: list, x: jnp.ndarray, *, activation=relu, final_activation=None) -> jnp.ndarray:
    for i, layer in enumerate(params):
        x = dense(layer, x)
        if i < len(params) - 1:
            x = activation(x)
        elif final_activation is not None:
            x = final_activation(x)
    return x
