"""ddl25spring_tpu — a TPU-native distributed deep learning framework.

A from-scratch JAX/XLA re-design of the capability surface of the DDL25Spring
course stack (see SURVEY.md at the repo root). Instead of rank-conditional
Python processes wired with gloo sockets (reference: lab/tutorial_1b/**), every
workload here is a single SPMD program over a named `jax.sharding.Mesh`:

- data parallelism      -> `shard_map` over a ``data`` axis + ``lax.psum``
- pipeline parallelism  -> a ``stage`` axis with ``lax.ppermute`` hops
- tensor parallelism    -> sharded matmuls over a ``model`` axis
- sequence parallelism  -> ring attention over a ``seq`` axis
- federated learning    -> a vmapped/sharded ``client`` axis; aggregation rules
  (FedAvg, Krum, median, ...) are pure functions over that axis.

Subpackages:
  config    — dataclass configs carrying the reference's default hyperparameters
  rng       — seed discipline (per-(client, round) determinism)
  metrics   — RunResult records and evaluation metrics
  data      — MNIST / tabular / token-stream pipelines (offline-capable)
  tokenizers— self-contained SentencePiece unigram model reader/encoder
  models    — functional model zoo (tiny-Llama, MnistCnn, MLPs, VAE, VFL nets)
  ops       — losses, attention, collective helpers, Pallas kernels
  parallel  — DP / PP / TP / SP strategies and the FL client/server suite
  resilience— fault injection (FaultPlan) + self-healing (StepGuard, retry,
              preemption handling) for every training path
  serving   — production inference: paged KV cache + continuous-batching
              scheduler + Poisson load front end (bitwise-parity with
              models.generate)
  telemetry — schema-versioned JSONL event stream, span tracing
              (trace/span contexts, Perfetto export), comm accounting,
              heartbeat liveness, metrics registry
  utils     — pytree helpers, timing, checkpointing, logging
"""

__version__ = "0.1.0"
