"""Speculative decoding on the paged serving engine: draft-propose, verify.

On dispatch-bound hosts each generated token costs one full engine
dispatch — ~70 matVecs streaming every weight byte at batch 1
(experiments/ROOFLINE.md, decode table) — so tokens-per-dispatch, not
FLOPs, is the decode lever. Speculative decoding buys tokens per dispatch
(ROADMAP item 2b): a cheap DRAFT model proposes ``k`` tokens with ``k``
single-token decode steps over its OWN paged pool, then the target model
scores all ``k + 1`` window positions in ONE donated dispatch over the
block-table cache (``make_verify_step``) and accepts a prefix:

- **greedy** (``temperature == 0``): accept while ``argmax(target) ==
  draft``. Every accepted token IS the target's own argmax at that
  position, and the one correction/bonus token beyond the accepted prefix
  is too — so greedy speculative streams are BITWISE the streams
  ``generate()`` emits alone, at any ``k``, any acceptance rate, any
  draft (the house bar, pinned in tests/test_generate.py).
- **stochastic** (``temperature > 0``): standard rejection sampling —
  draft token ``d ~ q`` is accepted with probability ``min(1, p(d)/q(d))``
  and the first rejection resamples from the normalized residual
  ``max(p - q, 0)`` — which preserves the target distribution ``p``
  exactly (the classic speculative-sampling identity:
  ``Σ_x q(x)·min(1, p(x)/q(x)) + P[reject]·residual(x) = p(x)``), though
  NOT the same sample path as ``generate()``: rejection sampling consumes
  randomness differently, so the stochastic bar is distributional, not
  bitwise. Per-slot RNG discipline keeps the PR 6 invariant: INACTIVE
  slots' keys are untouched (``where``-select), and an active slot's key
  advances exactly once per verify dispatch.

Cache discipline (the part that makes paged speculation correct):

- The verify dispatch writes K/V for all ``k + 1`` window positions
  ``pos .. pos + k``. After accepting ``a`` draft tokens, positions
  ``pos .. pos + a`` hold K/V of accepted stream tokens (valid); positions
  beyond hold K/V of rejected drafts (garbage). The next window starts at
  ``pos + a + 1`` and rewrites every garbage position BEFORE any query can
  attend to it — in-window positions are scattered before the gather
  (engine._block_paged), and positions beyond a row's absolute position
  are masked, the same invariant that makes the trash block safe.
- The draft runs ``k + 1`` single-token dispatches per round: ``k``
  proposals plus one CACHE-FILL consuming its own last proposal, so the
  draft pool is valid through ``pos + k`` even on full acceptance (without
  the fill, an all-accepted round leaves a one-position hole the next
  round's attention would read). Rejected-draft K/V in the draft pool is
  overwritten by the next round exactly like the target's.
- Near the horizon, per-slot ``live = min(k + 1, remaining)`` masks window
  rows whose writes would spill past the slot's reservation to the trash
  block (a ``max_seq_len`` request's block table has no slack — an
  unmasked clamp would wrap onto its own last block).

The compile contract grows from two programs per engine to THREE (prefill
+ decode_step + verify_step; decode_step idles while speculation is on
but remains the non-speculative path) plus the draft's TWO (its own
prefill + decode) — all compiled once, zero retraces across any workload
and any ``k`` (CompileWatch-gated in experiments/serving_bench.py
``--speculate``). A weight hot-swap lands between ``step()`` calls, i.e.
at a VERIFY boundary: a round's draft proposals and its verification
always run under one generation of weights (the draft keeps its own
weights across target swaps — acceptance may drop, correctness cannot:
greedy verification re-derives every token from the target).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from ..config import LlamaConfig
from ..models import generate, llama
from .kvcache import TRASH_BLOCK, PagedKVConfig, init_pool


@dataclass(frozen=True)
class SpecConfig:
    """Speculative-decoding knob for one engine: propose ``k`` tokens per
    round with a draft model holding ``draft_params`` (a separately
    weighted tiny-llama — smaller via ``draft_cfg``, or same-arch; a
    SAME-WEIGHTS draft makes greedy acceptance deterministically 1, the
    CPU bench's trick for a deterministic tokens-per-dispatch bar).
    ``draft_cfg=None`` means the target's config (same shapes, its own
    weights). The draft must share the target's vocabulary — proposals
    are token ids the target scores."""

    k: int
    draft_params: dict
    draft_cfg: Optional[LlamaConfig] = None

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"SpecConfig.k={self.k}: propose at least one "
                             "token per round")


# ------------------------------------------------------------ draft engine

class DraftEngine:
    """The draft half of speculation: its own block pool (same geometry as
    the target's, so the TARGET's block tables index it unchanged — one
    allocator serves both), its own prefill/decode programs, its own
    per-slot RNG keys. The parent Engine drives it with the same host-side
    slot state (tables / pos / temps) it feeds the target programs."""

    # Salt folded into a sampling request's key to derive the draft's
    # independent proposal stream (the target's own key must advance
    # exactly as generate()'s does, so the draft cannot share it).
    KEY_SALT = 0x5bec

    def __init__(self, spec: SpecConfig, target_cfg: LlamaConfig,
                 paged: PagedKVConfig, num_slots: int, *,
                 prefill_chunk: int, top_k: Optional[int],
                 top_p: Optional[float], engine_id: Optional[int] = None,
                 decode_shapes: int = 1):
        from . import engine as _engine
        from ..telemetry import introspect

        self.cfg = spec.draft_cfg or target_cfg
        if self.cfg.vocab_size != target_cfg.vocab_size:
            raise ValueError(
                f"draft vocab {self.cfg.vocab_size} != target vocab "
                f"{target_cfg.vocab_size}: proposals are token ids the "
                "target must be able to score")
        self.k = spec.k
        self.params = spec.draft_params
        self.fused = generate._fuse_blocks(self.params["blocks"])
        self.pool = init_pool(self.cfg, paged)
        self.keys = jnp.zeros((num_slots, 2), jnp.uint32)
        tag = "" if engine_id is None else f"[{engine_id}]"
        self._prefill = introspect.watch(
            _engine.make_prefill_chunk(self.cfg, paged, prefill_chunk,
                                       top_k, top_p),
            name=f"serving/draft_prefill{tag}", max_caches=1)
        # The TARGET's decode factory in its return_probs variant — one
        # paged-cache body serves both models, so cache-indexing fixes
        # can never drift between them (the bitwise bar depends on the
        # two pools agreeing op-for-op). ``decode_shapes`` is the parent's
        # gather-narrowing bucket count: propose() runs over the SAME
        # narrowed table slice as the verify dispatch, so the draft decode
        # legitimately compiles once per bucket width too.
        self._decode = introspect.watch(
            _engine.make_decode_step(self.cfg, paged, num_slots, top_k,
                                     top_p, return_probs=True),
            name=f"serving/draft_decode{tag}", max_caches=decode_shapes)

    def admit_key(self, s: int, temperature: float, key) -> None:
        """Seed slot ``s``'s draft proposal stream: an independent child of
        the request key for sampling requests (KEY_SALT), the placeholder
        for greedy ones (argmax never reads it)."""
        if temperature > 0 and key is not None:
            dkey = jax.random.fold_in(key, self.KEY_SALT)
        else:
            dkey = jax.random.PRNGKey(0)
        self.keys = self.keys.at[s].set(dkey)

    def prefill_chunk(self, table_row, chunk, off, n_valid, write_from,
                      temperature) -> None:
        """Mirror one prompt chunk into the draft pool. The sampled token
        and split key are ALWAYS discarded — the draft's first proposal
        comes from its decode program consuming the target's first emitted
        token, so prefill is purely a cache write here."""
        self.pool, _, _ = self._prefill(
            self.pool, self.params, self.fused, table_row, chunk,
            off, n_valid, write_from, self.keys[0],
            jnp.float32(temperature))

    def propose(self, tables, last_tok, pos, temps, active, live):
        """One proposal round: k single-token decode dispatches from the
        target's last emitted tokens, plus the cache-fill dispatch
        consuming the final proposal (module docstring). Rows beyond a
        slot's ``live`` window are masked inactive — their writes go to
        trash and their proposals are never accepted. Returns
        (draft_tokens [S, k], draft_probs [S, k, V])."""
        cur = last_tok
        toks, probs = [], []
        for j in range(self.k + 1):
            step_active = jnp.logical_and(active, j < live)
            self.pool, cur, q, self.keys = self._decode(
                self.pool, self.params, self.fused, tables, cur,
                pos + j, self.keys, temps, step_active)
            if j < self.k:             # the last dispatch is cache-fill
                toks.append(cur)
                probs.append(q)
        return jnp.stack(toks, axis=1), jnp.stack(probs, axis=1)


# ------------------------------------------------------------- verify step

def rejection_accept(sub: jnp.ndarray, p: jnp.ndarray, q: jnp.ndarray,
                     drafts: jnp.ndarray):
    """One slot's stochastic acceptance: standard speculative rejection
    sampling. ``p`` [k+1, V] is the target's sampling distribution at each
    window row, ``q`` [k, V] the draft's at each proposal, ``drafts`` [k]
    the proposals (each sampled from its ``q`` row). Accept proposal ``i``
    while ``u_i < min(1, p_i(d_i)/q_i(d_i))``; the first rejection
    resamples from the normalized residual ``max(p_i - q_i, 0)`` and full
    acceptance draws the bonus token from ``p_k``. Returns
    ``(accepted_count, correction_token)`` — the emitted window is the
    accepted drafts then the correction.

    This is the speculative-sampling identity — emitted tokens are
    distributed EXACTLY as ``p`` row by row
    (``q(x)·min(1, p(x)/q(x)) + (1 - Σ_y min(p, q)(y))·residual(x) =
    p(x)``) — kept standalone so the math is unit-testable against the
    analytic acceptance rate ``Σ_x min(p(x), q(x))`` without a model in
    the loop (tests/test_speculate.py). Randomness discipline: decision
    draws fold ``sub`` per position (2i accept, 2i+1 resample, 2k+1
    bonus) so consumption is fixed no matter where rejection lands —
    the verify program splits a slot's key exactly once per dispatch."""
    k = q.shape[0]
    idx = jnp.arange(k)
    p_tok = jnp.take_along_axis(p[:k], drafts[:, None], axis=-1)[:, 0]
    q_tok = jnp.take_along_axis(q, drafts[:, None], axis=-1)[:, 0]
    u = jax.vmap(lambda i: jax.random.uniform(
        jax.random.fold_in(sub, 2 * i)))(idx)
    accept = u * jnp.maximum(q_tok, 1e-30) < p_tok
    s_acc = jnp.cumprod(accept.astype(jnp.int32)).sum()
    # Residual resample at every candidate rejection row (only the row at
    # s_acc is ever emitted); an all-zero residual (p <= q everywhere,
    # numerically) falls back to p — there rejection has probability ~0,
    # so the fallback only guards against a -inf-everywhere categorical.
    resid = jnp.maximum(p[:k] - q, 0.0)                        # [k, V]
    ok = resid.sum(axis=-1, keepdims=True) > 0
    resid = jnp.where(ok, resid, p[:k])
    logr = jnp.where(resid > 0, jnp.log(jnp.maximum(resid, 1e-30)),
                     -jnp.inf)
    resampled = jax.vmap(
        lambda i: jax.random.categorical(
            jax.random.fold_in(sub, 2 * i + 1), logr[i]))(idx)
    bonus = jax.random.categorical(
        jax.random.fold_in(sub, 2 * k + 1),
        jnp.where(p[k] > 0, jnp.log(jnp.maximum(p[k], 1e-30)), -jnp.inf))
    corr = jnp.where(s_acc < k, resampled[jnp.minimum(s_acc, k - 1)], bonus)
    return s_acc, corr


def make_verify_step(cfg: LlamaConfig, paged: PagedKVConfig,
                     num_slots: int, k: int, top_k: Optional[int],
                     top_p: Optional[float]):
    """ONE compiled program scoring ``k + 1`` positions per slot over the
    block-table cache: the decode step widened to a multi-position window
    (the chunked-prefill scatter/gather machinery with per-slot live
    lengths), with a sampling head at EVERY position and the acceptance
    rule computed in-dispatch — so a speculative round costs exactly one
    target dispatch regardless of how many tokens it lands.

    Inputs: ``window`` [S, k+1] = (last emitted token, then the k draft
    proposals); ``draft_probs`` [S, k, V] = the draft's sampling
    distribution at each proposal (the ``q`` of the rejection test);
    ``live`` [S] masks window rows past a slot's remaining horizon.
    Returns (pool, out_tokens [S, k+1], accepted [S], new_keys): the host
    emits ``out_tokens[s, :min(accepted[s] + 1, remaining)]`` — accepted
    draft tokens re-derived by the target, then one correction (on
    rejection) or bonus (on full acceptance) token."""
    from .engine import _forward_paged  # import here to avoid a cycle

    bl = paged.block_len
    kp1 = k + 1

    @partial(jax.jit, donate_argnums=(0,))
    def verify_step(pool: dict, params: dict, fused: dict,
                    tables: jnp.ndarray, window: jnp.ndarray,
                    draft_probs: jnp.ndarray, pos: jnp.ndarray,
                    live: jnp.ndarray, keys: jnp.ndarray,
                    temps: jnp.ndarray, active: jnp.ndarray):
        mb = tables.shape[1]
        rows = jnp.arange(kp1, dtype=jnp.int32)
        positions = pos[:, None] + rows[None, :]               # [S, k+1]
        writable = jnp.logical_and(active[:, None], rows[None, :] < live[:, None])
        blk_idx = jnp.minimum(positions // bl, mb - 1)
        own = jnp.take_along_axis(tables, blk_idx, axis=1)     # [S, k+1]
        wblk = jnp.where(writable, own, TRASH_BLOCK)
        woff = positions % bl
        h, pool = _forward_paged(params, fused, window, pool, tables,
                                 positions, wblk, woff, cfg)
        logits = llama.head(params, h, cfg)                    # [S, k+1, V]

        # Greedy: the target's argmax at every window position; accept the
        # longest prefix where it re-derives the draft. Each accepted
        # token — and the correction/bonus beyond it — is the token
        # generate() would have emitted, which is the bitwise bar.
        greedy_toks = jnp.argmax(logits, axis=-1)              # [S, k+1]
        drafts = window[:, 1:]                                 # [S, k]
        g_match = greedy_toks[:, :k] == drafts
        g_acc = jnp.cumprod(g_match.astype(jnp.int32), axis=1).sum(axis=1)

        # Stochastic: rejection sampling against the draft's q
        # (``rejection_accept`` — the unit-tested identity). One key
        # split per dispatch per active slot; per-position decision keys
        # fold from the sub-key, so randomness consumption is fixed at
        # one split regardless of where the rejection lands.
        safe_t = jnp.where(temps > 0, temps, 1.0)[:, None, None]
        p = jax.nn.softmax(
            generate.filter_logits(logits / safe_t, top_k, top_p), axis=-1)
        split = jax.vmap(jax.random.split)(keys)
        subs = split[:, 1]
        new_keys = jnp.where(active[:, None], split[:, 0], keys)
        s_acc, s_corr = jax.vmap(rejection_accept)(subs, p, draft_probs,
                                                   drafts)
        # Stochastic out tokens: accepted drafts verbatim, the
        # correction/bonus at row s_acc, bonus at row k on full accept.
        base = jnp.concatenate(
            [drafts, jnp.zeros((num_slots, 1), drafts.dtype)], axis=1)
        st_toks = jnp.where(rows[None, :] == s_acc[:, None],
                            s_corr[:, None], base)

        sampled = temps > 0
        out = jnp.where(sampled[:, None], st_toks, greedy_toks)
        accepted = jnp.where(sampled, s_acc, g_acc).astype(jnp.int32)
        return pool, out, accepted, new_keys

    return verify_step

