"""Front end: synthetic heavy-traffic workloads + the serving run driver.

The load half of the serving subsystem: a seeded Poisson arrival process
over mixed prompt/output length distributions (`synthetic_workload` — the
"millions of users" stand-in the north star asks to be measured against),
its multi-tenant generalization (`TrafficClass`/`multi_tenant_workload`:
one Poisson stream per class with its own rates, admission priority and
per-class SLO targets, merged arrival-ordered — the fleet's traffic,
serving/fleet.py), and `run_serving`, the driver that replays a workload
through the continuous-batching scheduler in (fast-forwarded) real time
and aggregates per-request latency into the serving headline: sustained
tok/s + p50/p95/p99 queue wait and TTFT at N concurrent streams.

Determinism contract: the workload is fully determined by its seed (one
`np.random.default_rng` drives arrivals, lengths, temperatures, prompt
tokens and per-request sampling seeds), and request CONTENT determines
request TOKENS (scheduler.py's admission-order invariant) — so latency
numbers are load-dependent but every token stream is reproducible and
checkable against `generate()` one request at a time
(experiments/serving_bench.py does exactly that).

The clock is wall time with idle fast-forward: while requests are in
flight the engine does real work and latencies are honest measurements;
when the engine and queue are BOTH empty, the clock jumps to the next
arrival instead of sleeping, so a light workload doesn't stretch CI
wall time. Fast-forward never runs while anything is queued or in flight,
so it cannot shrink a queue wait or a TTFT.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import LlamaConfig
from ..telemetry.events import EventLog
from ..telemetry.registry import percentile
from .engine import Engine
from .kvcache import PagedKVConfig, naive_cache_bytes, pool_bytes
from .scheduler import Request, RequestRecord, Scheduler


def synthetic_workload(*, seed: int, n_requests: int, rate_rps: float,
                       vocab_size: int,
                       prompt_lens: Sequence[int] = (8, 16, 48),
                       prompt_weights: Optional[Sequence[float]] = None,
                       max_news: Sequence[int] = (8, 16, 32),
                       max_new_weights: Optional[Sequence[float]] = None,
                       temperatures: Sequence[float] = (0.0, 0.8),
                       temperature_weights: Optional[Sequence[float]] = None,
                       tenant: str = "default", priority: int = 0,
                       rid_prefix: str = "req",
                       ) -> List[Request]:
    """Seeded Poisson arrivals (exponential inter-arrival at ``rate_rps``)
    over mixed prompt/output length and temperature mixtures.

    Lengths draw from small DISCRETE sets rather than continuous
    distributions on purpose: the paged engine is shape-oblivious, but the
    per-request `generate()` parity reference compiles once per distinct
    (prompt_len, max_new, temperature) combination — a discrete mixture
    keeps the verification sweep to a handful of compiles while still
    exercising raggedness. Widen the sets (or pass weights) to skew the
    mix; the engine itself never recompiles."""
    rng = np.random.default_rng(seed)
    t = 0.0
    reqs: List[Request] = []
    for i in range(n_requests):
        t += float(rng.exponential(1.0 / rate_rps))
        tp = int(rng.choice(np.asarray(prompt_lens), p=prompt_weights))
        mx = int(rng.choice(np.asarray(max_news), p=max_new_weights))
        temp = float(rng.choice(np.asarray(temperatures, np.float64),
                                p=temperature_weights))
        prompt = tuple(int(x) for x in rng.integers(0, vocab_size, tp))
        reqs.append(Request(rid=f"{rid_prefix}-{i:04d}", prompt=prompt,
                            max_new=mx, temperature=temp,
                            seed=int(rng.integers(0, 2 ** 31 - 1)),
                            arrival=t, tenant=tenant, priority=priority))
    return reqs


@dataclass(frozen=True)
class TrafficClass:
    """One tenant class of a multi-tenant workload: its own Poisson rate,
    length/temperature mixture, admission ``priority`` (higher admits
    first at a contended boundary — scheduler.py), and optional per-class
    SLO targets (consumed by ``experiments/slo_monitor.py``'s per-class
    verdicts and the fleet smoke). A class is a traffic SHAPE: counts
    belong to the ``multi_tenant_workload`` call."""
    name: str
    rate_rps: float
    prompt_lens: Sequence[int] = (8, 16, 48)
    max_news: Sequence[int] = (8, 16, 32)
    temperatures: Sequence[float] = (0.0, 0.8)
    priority: int = 0
    ttft_p99_s: Optional[float] = None
    queue_p99_s: Optional[float] = None


def multi_tenant_workload(*, seed: int, classes: Sequence[TrafficClass],
                          n_per_class, vocab_size: int) -> List[Request]:
    """Merge one seeded Poisson stream per traffic class into a single
    arrival-ordered workload. Each class draws from its own child seed
    (derived from ``seed`` and the class position), so adding a class
    never perturbs another's stream; request ids are ``<class>-<i>`` and
    every request carries its class name as ``tenant`` plus the class
    ``priority``. ``n_per_class`` is an int (same count for every class)
    or a ``{name: count}`` mapping."""
    reqs: List[Request] = []
    for idx, cls in enumerate(classes):
        n = (n_per_class[cls.name] if isinstance(n_per_class, dict)
             else int(n_per_class))
        reqs.extend(synthetic_workload(
            seed=seed + 7919 * (idx + 1), n_requests=n,
            rate_rps=cls.rate_rps, vocab_size=vocab_size,
            prompt_lens=cls.prompt_lens, max_news=cls.max_news,
            temperatures=cls.temperatures, tenant=cls.name,
            priority=cls.priority, rid_prefix=cls.name))
    return sorted(reqs, key=lambda r: (r.arrival, r.rid))


def class_slos(classes: Sequence[TrafficClass]) -> Dict[str, Dict[str, float]]:
    """The per-class SLO table in ``experiments/slo_monitor.py``'s
    ``SLOConfig.per_class`` shape: {class: {objective: threshold}},
    classes with no targets omitted."""
    out: Dict[str, Dict[str, float]] = {}
    for cls in classes:
        limits = {}
        if cls.ttft_p99_s is not None:
            limits["ttft_p99_s"] = cls.ttft_p99_s
        if cls.queue_p99_s is not None:
            limits["queue_p99_s"] = cls.queue_p99_s
        if limits:
            out[cls.name] = limits
    return out


def reference_stream(params: dict, cfg: LlamaConfig, paged: PagedKVConfig,
                     req: Request, *, top_k: Optional[int] = None,
                     top_p: Optional[float] = None) -> List[int]:
    """The bitwise-parity reference: ``generate()`` run ALONE on one
    request. One implementation for every consumer of the parity bar
    (tests + serving_bench), because the construction rules are load-
    bearing and easy to get silently wrong: ``max_len`` must pin to
    ``paged.max_seq_len`` (so both sides reduce over identically-shaped
    score rows), ``kv_dtype`` must match the pool's storage dtype, and
    key/temperature are passed only for sampling requests (greedy
    ``generate`` forbids a key-less temperature, and its greedy path
    ignores the key exactly like the engine's where-select)."""
    import jax
    import jax.numpy as jnp

    from ..models import generate

    kw = dict(max_len=paged.max_seq_len, kv_dtype=paged.kv_dtype,
              top_k=top_k, top_p=top_p)
    if req.temperature > 0:
        kw.update(key=jax.random.PRNGKey(req.seed),
                  temperature=req.temperature)
    toks = generate.generate(params, jnp.asarray(req.prompt)[None], cfg,
                             req.max_new, **kw)[0].tolist()
    if req.eos_id is not None and req.eos_id in toks:
        # generate() has no early stop (one compiled scan to the max_new
        # horizon); a request with an EOS id is served its stream
        # truncated at the first EOS INCLUSIVE — the scheduler retires the
        # slot at that boundary, so nothing after it was ever emitted.
        toks = toks[:toks.index(req.eos_id) + 1]
    return toks


class _Clock:
    """Monotonic seconds since start, with idle fast-forward (module
    docstring): `now` advances with wall time; `fast_forward` adds the gap
    to the next arrival without sleeping through it."""

    def __init__(self):
        self._t0 = time.monotonic()
        self._skew = 0.0

    def now(self) -> float:
        return time.monotonic() - self._t0 + self._skew

    def fast_forward(self, to: float) -> None:
        self._skew += max(0.0, to - self.now())


@dataclass
class ServingReport:
    """One serving run's outcome: per-request records + the aggregate row."""
    records: Dict[str, RequestRecord]
    aggregates: dict
    wall_s: float
    peak_blocks_in_use: int
    pool_blocks: int
    pool_bytes: int = 0
    naive_bytes_at_peak: int = 0
    peak_concurrency: int = 0
    requests: List[Request] = field(default_factory=list)
    # Compile/retrace accounting (telemetry/introspect.py CompileWatch on
    # the engine's program set): the contract is compiles == the
    # documented set (2 plain; 4 with speculation — prefill + verify +
    # the draft's two, decode_step idling; gather narrowing adds one per
    # extra bucket width actually hit) and retraces == 0 for ANY
    # workload — raggedness is data, not shapes.
    compiles: int = 0
    retraces: int = 0
    # Speculative decoding accounting (serving/speculate.py): target
    # decode dispatches (verify dispatches when speculating), tokens they
    # emitted, and the draft's (cheap) dispatch count. tokens_per_dispatch
    # = decode_tokens / decode_dispatches — the dispatch-bound hosts'
    # headline (ROOFLINE.md "speculative decode" row); ≈1×avg-batch
    # without speculation, ×(accepted+1) with it.
    decode_dispatches: int = 0
    decode_tokens: int = 0
    draft_dispatches: int = 0
    tokens_per_dispatch: Optional[float] = None
    spec_proposed: int = 0
    spec_accepted: int = 0
    acceptance_rate: Optional[float] = None
    # Gather-narrowing accounting (Engine(gather_buckets=True)): KV bytes
    # the decode/verify gathers walked, and the bytes the full
    # max_blocks_per_seq walk would have added on top.
    gather_bytes: int = 0
    gather_bytes_saved: int = 0


def aggregate_latency(records: Dict[str, RequestRecord],
                      busy_span_s: Optional[float] = None) -> dict:
    """p50/p95/p99 queue wait + TTFT, per-request tok/s, and the sustained
    throughput — the serving row's numbers, shared by bench.py,
    serving_bench and the tests so no consumer re-derives them
    differently. ``busy_span_s`` (run_serving supplies it) is the
    engine's accumulated working time; without it the fallback span is
    first admission → last completion, which is only honest when the
    clock contains no fast-forwarded idle gaps (record timestamps come
    from the skewed clock, so under sparse load the fallback would count
    jumped idle time as serving time and deflate the figure).

    Always returns the FULL record shape: an empty (or all-in-flight)
    window yields ``completed: 0`` with ``None`` percentiles and rates,
    and a single-request window yields its degenerate percentiles —
    never a key-missing dict callers must special-case. The fleet's
    per-class/per-engine slices make empty windows a legitimate steady
    state (a quiet tenant, an engine mid-rollout), so the shape contract
    is pinned (tests/test_fleet_serving.py)."""
    pct = lambda vals: {f"p{q:g}": (percentile(vals, q) if vals else None)
                        for q in (50, 95, 99)}
    done = [r for r in records.values() if r.done_t is not None]
    if not done:
        return {"completed": 0, "total_tokens": 0,
                "sustained_tokens_per_sec": None,
                "busy_span_s": busy_span_s,
                "queue_wait_s": pct([]), "ttft_s": pct([]),
                "request_tokens_per_sec": pct([])}
    waits = [r.queue_wait_s for r in done if r.queue_wait_s is not None]
    ttfts = [r.ttft_s for r in done if r.ttft_s is not None]
    rates = [r.tokens_per_sec for r in done if r.tokens_per_sec is not None]
    total_tokens = sum(len(r.tokens) for r in done)
    span = busy_span_s if busy_span_s is not None else (
        max(r.done_t for r in done)
        - min(r.admit_t for r in done if r.admit_t is not None))
    return {
        "completed": len(done),
        "total_tokens": total_tokens,
        "sustained_tokens_per_sec": (total_tokens / span if span > 0
                                     else None),
        "busy_span_s": span,
        "queue_wait_s": pct(waits),
        "ttft_s": pct(ttfts),
        "request_tokens_per_sec": pct(rates),
    }


def run_serving(params: dict, cfg: LlamaConfig, paged: PagedKVConfig,
                workload: Sequence[Request], *, num_slots: int,
                prefill_chunk: int = 16, top_k: Optional[int] = None,
                top_p: Optional[float] = None,
                events: Optional[EventLog] = None,
                token_events: bool = True,
                speculate=None, prefix_share: bool = False,
                gather_buckets: bool = False) -> ServingReport:
    """Replay ``workload`` (arrival offsets in seconds) through a fresh
    engine + scheduler; returns per-request records and the aggregate row.
    Every request is guaranteed retired on return — reservation-based
    admission cannot deadlock (scheduler.py), so the loop's only exit is
    completion. ``speculate`` (a ``SpecConfig``) turns on draft-propose /
    one-dispatch-verify decoding; ``prefix_share`` maps identical
    full-block prompt prefixes copy-on-write; ``gather_buckets`` narrows
    the decode gather to bucketed live-block counts."""
    engine = Engine(params, cfg, paged, num_slots,
                    prefill_chunk=prefill_chunk, top_k=top_k, top_p=top_p,
                    speculate=speculate, prefix_share=prefix_share,
                    gather_buckets=gather_buckets)
    clock = _Clock()
    sched = Scheduler(engine, events=events, token_events=token_events,
                      clock=clock.now)
    pending = sorted(workload, key=lambda r: r.arrival)
    busy_s = 0.0       # real working time, fast-forwarded idle excluded —
    i = 0              # the denominator of sustained tok/s
    while i < len(pending) or sched.outstanding:
        now = clock.now()
        while i < len(pending) and pending[i].arrival <= now:
            sched.submit(pending[i], now=now)
            i += 1
        if sched.outstanding == 0:
            clock.fast_forward(pending[i].arrival)   # idle: jump, don't sleep
            continue
        sched.tick()
        busy_s += clock.now() - now
    peak_conc = sched.peak_in_flight   # recorded at admission (scheduler.py)
    spec_prop = sum(e.get("proposed", 0) for e in sched.spec_rounds)
    spec_acc = sum(e.get("accepted", 0) for e in sched.spec_rounds)
    report = ServingReport(
        records=sched.records,
        aggregates=aggregate_latency(sched.records, busy_span_s=busy_s),
        wall_s=clock.now(),
        peak_blocks_in_use=engine.allocator.peak_in_use,
        pool_blocks=engine.allocator.capacity,
        compiles=sum(len(w.compiles) for w in engine.watches()),
        retraces=sum(w.retraces for w in engine.watches()),
        pool_bytes=pool_bytes(cfg, paged),
        naive_bytes_at_peak=naive_cache_bytes(
            cfg, max(1, peak_conc), paged.max_seq_len, paged.kv_dtype),
        peak_concurrency=peak_conc,
        requests=list(workload),
        decode_dispatches=engine.decode_dispatches,
        decode_tokens=engine.decode_tokens,
        draft_dispatches=engine.draft_dispatches,
        tokens_per_dispatch=(engine.decode_tokens / engine.decode_dispatches
                             if engine.decode_dispatches else None),
        spec_proposed=spec_prop,
        spec_accepted=spec_acc,
        acceptance_rate=(spec_acc / spec_prop if spec_prop else None),
        gather_bytes=engine.gather_bytes,
        gather_bytes_saved=engine.gather_bytes_saved)
    return report
