"""Serving fleet: SLO-aware multi-engine router + live weight hot-swap.

The scale-out half of the serving subsystem (ROADMAP item 1): PR 6's slot
engine serves one mesh; a production front end is MANY engines behind a
router. This module replicates the engine N ways — each replica keeps the
single-engine contract intact (two compiled programs, zero retraces,
streams bitwise ``generate()``'s) — and fronts them with:

- ``Router`` — per-request dispatch under a policy seam:
  * ``least_loaded``: fewest outstanding requests (queued + in flight),
    ties to the lowest engine id — deterministic given identical state.
  * ``predicted_ttft``: the same rolling-window shape
    ``experiments/slo_monitor.py`` evaluates SLOs over, fed per engine
    from completed-request TTFTs (``Scheduler.recent_done``): predicted
    TTFT on engine e = median TTFT over e's window × (1 + outstanding_e /
    num_slots) — a queue-depth-scaled service-time estimate. Engines with
    an empty window fall back to the fleet-wide window, then to
    least-loaded ordering, so cold starts still spread.
  Routing is a LATENCY decision only: per-slot state and row-independent
  engine math mean WHICH engine (like which slot) a request lands on can
  never change its tokens — the bitwise bar holds at any engine count
  (tests/test_fleet_serving.py pins N ∈ {1, 3} against ``generate()``).

- **Live weight hot-swap** — ``publish()`` hands the fleet a new
  (equal-shape) weight tree and rolls it out ONE ENGINE PER TICK: each
  engine swaps at its own token boundary (``Scheduler.swap_weights`` →
  ``Engine.swap_params``) without dropping queued or in-flight streams,
  and because the rollout staggers, the fleet is never globally paused —
  at most one engine is swapping at any boundary while the rest serve.
  The "drain" of the elastic discipline (resilience/elastic.py) is the
  token boundary itself: the host drives every compiled call, so between
  ticks an engine has nothing in flight by construction. Publication
  provenance (watching the trainer's checkpoint stream) lives in
  serving/deploy.py; this module only applies an already-loaded tree.

- **Active capacity** — ``set_active(k)`` restricts NEW routes to engines
  ``[0, k)`` while deactivated engines drain their outstanding work to
  completion. This is the serving half of the elasticity control plane:
  resilience/autoscale.py moves capacity between the training mesh and
  this fleet by pairing ``set_active`` with the trainer's elastic
  ``resize`` at a chunk edge (experiments/autoscale_smoke.py).

Telemetry (schema v6): one ``route`` event per dispatch decision, one
``deploy`` event + span per engine swap, and every ``request_*`` event
tagged with its ``engine`` — ``experiments/obs_report.py`` groups the
serving section per engine, ``experiments/slo_monitor.py`` issues
per-class/per-engine verdicts, and the ``deploy`` spans land on the
Perfetto timeline via ``experiments/trace_export.py``.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..config import LlamaConfig
from ..telemetry.events import EventLog
from ..telemetry.registry import percentile
from .engine import Engine
from .frontend import _Clock, aggregate_latency
from .kvcache import PagedKVConfig, pool_bytes
from .scheduler import Request, RequestRecord, Scheduler

POLICIES = ("least_loaded", "predicted_ttft")


class Router:
    """SLO-aware dispatch over a set of schedulers (module docstring).

    Holds one rolling TTFT window per engine — the slo_monitor window
    shape: a deque of (t, value) pruned to ``window_s`` behind the
    scheduler clock — fed by ``harvest()`` from each scheduler's
    ``recent_done``. ``pick`` never mutates engine state; the decision
    inputs it used land in the ``route`` event for the stream to audit.
    """

    def __init__(self, scheds: Sequence[Scheduler], *,
                 policy: str = "least_loaded", window_s: float = 30.0,
                 events: Optional[EventLog] = None):
        if policy not in POLICIES:
            raise ValueError(f"policy must be one of {POLICIES} "
                             f"(got {policy!r})")
        self.scheds = list(scheds)
        self.policy = policy
        self.window_s = window_s
        self.events = events
        self._ttft: List[deque] = [deque() for _ in self.scheds]

    def harvest(self, now: float) -> None:
        """Pull new completions into the per-engine windows; prune."""
        horizon = now - self.window_s
        for dq, sched in zip(self._ttft, self.scheds):
            for t, ttft in sched.recent_done:
                if ttft is not None:
                    dq.append((t, ttft))
            sched.recent_done.clear()
            while dq and dq[0][0] < horizon:
                dq.popleft()

    def predicted_ttft(self, eid: int) -> Optional[float]:
        """Queue-depth-scaled TTFT estimate for a request dispatched to
        ``eid`` now; None while no window (anywhere) has a sample."""
        vals = [v for _, v in self._ttft[eid]]
        if not vals:       # cold engine: borrow the fleet-wide window
            vals = [v for dq in self._ttft for _, v in dq]
        if not vals:
            return None
        sched = self.scheds[eid]
        return percentile(vals, 50) * (
            1.0 + sched.outstanding / max(1, sched.engine.num_slots))

    def pick(self, req: Request, now: float,
             eligible: Optional[Sequence[int]] = None) -> int:
        """Choose the engine for ``req`` and emit the ``route`` event.
        ``eligible`` restricts the choice (the fleet's active-capacity
        seam: a drained-but-not-yet-reactivated engine must not receive
        new work); default is every engine."""
        self.harvest(now)
        ids = (list(eligible) if eligible is not None
               else list(range(len(self.scheds))))
        if not ids:
            raise ValueError("Router.pick: no eligible engines")
        loads = [s.outstanding for s in self.scheds]
        if self.policy == "least_loaded":
            eid = min(ids, key=lambda i: (loads[i], i))
            predicted = None
        else:
            predictions = {i: self.predicted_ttft(i) for i in ids}
            # No samples yet anywhere → identical (None) predictions:
            # the load/id tie-break below IS least-loaded, so a cold
            # fleet still spreads deterministically.
            eid = min(ids,
                      key=lambda i: (predictions[i]
                                     if predictions[i] is not None else 0.0,
                                     loads[i], i))
            predicted = predictions[eid]
        if self.events is not None:
            self.events.route(req=req.rid, engine=eid, policy=self.policy,
                              tenant=req.tenant, outstanding=loads,
                              predicted_ttft_s=predicted)
        return eid


class ServingFleet:
    """N slot engines behind one router, with staggered weight hot-swap.

    >>> fleet = ServingFleet(params, cfg, paged, num_engines=3,
    ...                      num_slots=8, events=telemetry.events)
    >>> fleet.submit(req)                       # router picks the engine
    >>> while fleet.outstanding:
    ...     fleet.tick()
    >>> fleet.publish(new_params, version=1200)  # rolls out over N ticks

    Every engine is a full PR 6 engine (own pool, own two compiled
    programs); the fleet adds routing, the publish rollout, and merged
    accounting. ``admission`` passes through to every scheduler
    (scheduler.py's policy seam)."""

    def __init__(self, params: dict, cfg: LlamaConfig, paged: PagedKVConfig,
                 *, num_engines: int, num_slots: int,
                 prefill_chunk: int = 16, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 events: Optional[EventLog] = None,
                 token_events: bool = True,
                 policy: str = "least_loaded", window_s: float = 30.0,
                 admission: str = "fcfs", speculate=None,
                 prefix_share: bool = False,
                 memory_every: int = 0,
                 clock: Callable[[], float] = time.monotonic):
        if num_engines < 1:
            raise ValueError(f"num_engines={num_engines}")
        self.cfg = cfg
        self.paged = paged
        self.clock = clock
        # ``speculate`` (serving/speculate.py SpecConfig) arms EVERY
        # engine with the draft + verify programs — per-engine draft
        # pools, like per-engine block pools. ``prefix_share`` likewise
        # (prefix caches are per engine: blocks are physical pool
        # indices, so sharing cannot cross engines — the routing seam
        # ROADMAP 1b's prefix-affinity policy will exploit).
        self.engines = [Engine(params, cfg, paged, num_slots,
                               prefill_chunk=prefill_chunk, top_k=top_k,
                               top_p=top_p, engine_id=i,
                               speculate=speculate,
                               prefix_share=prefix_share)
                        for i in range(num_engines)]
        # ``memory_every`` arms each scheduler's per-engine memory meter
        # (scheduler.py; schema v9) — every census event carries its
        # ``engine`` tag, so the fleet's N pools stay distinguishable.
        self.scheds = [Scheduler(eng, events=events,
                                 token_events=token_events, clock=clock,
                                 engine_id=i, admission=admission,
                                 memory_every=memory_every)
                       for i, eng in enumerate(self.engines)]
        self.router = Router(self.scheds, policy=policy, window_s=window_s,
                             events=events)
        self.engine_of: Dict[str, int] = {}     # rid -> routed engine
        self._swap = None       # pending publish: rolls out one engine/tick
        self._active = num_engines  # engines [0, _active) accept new work
        self.deploys: List[dict] = []

    # ------------------------------------------------------------- capacity
    @property
    def active_engines(self) -> int:
        """How many engines currently accept NEW requests."""
        return self._active

    def set_active(self, k: int) -> None:
        """Serve new requests on engines ``[0, k)`` only — the autoscaler's
        capacity seam (resilience/autoscale.py). Shrinking DRAINS rather
        than drops: a deactivated engine stops receiving routes immediately
        but ``tick()`` keeps advancing any engine with outstanding work, so
        its queued and in-flight streams finish on the engine they started
        on (per-slot state cannot migrate) — same chunk-edge discipline as
        the trainer's elastic drain. Growing is instant: a reactivated
        engine holds no state a request could miss (weights roll out to
        every engine regardless of active status, see ``publish``)."""
        k = int(k)
        if not 1 <= k <= len(self.engines):
            raise ValueError(f"set_active({k}): fleet has "
                             f"{len(self.engines)} engines; need 1 <= k <= "
                             f"{len(self.engines)}")
        self._active = k

    # ------------------------------------------------------------- dispatch
    def submit(self, req: Request, now: Optional[float] = None) -> int:
        now = self.clock() if now is None else now
        eid = self.router.pick(req, now, eligible=range(self._active))
        self.scheds[eid].submit(req, now=now)
        self.engine_of[req.rid] = eid
        return eid

    @property
    def outstanding(self) -> int:
        return sum(s.outstanding for s in self.scheds)

    @property
    def swap_pending(self) -> bool:
        return self._swap is not None

    def tick(self) -> List[tuple]:
        """One fleet boundary: advance the publish rollout by AT MOST one
        engine (the stagger that keeps the fleet serving through a
        deploy), then tick every engine with work. Returns the merged
        (rid, token) pairs."""
        if self._swap is not None:
            # Peek-then-pop: the engine leaves the rollout only AFTER its
            # swap succeeded, so an unexpected per-engine failure neither
            # drops the engine from the rollout nor wedges the fleet with
            # a half-applied publish (publish() already validated the
            # tree, so the expected failure mode here is none).
            eid = self._swap["remaining"][0]
            self.scheds[eid].swap_weights(self._swap["params"],
                                          self._swap["version"],
                                          fused=self._swap["fused"])
            self._swap["remaining"].popleft()
            self.deploys.append({"version": self._swap["version"],
                                 "engine": eid, "t": self.clock()})
            if not self._swap["remaining"]:
                self._swap = None
        emitted: List[tuple] = []
        for sched in self.scheds:
            if sched.outstanding:
                emitted.extend(sched.tick())
        return emitted

    # -------------------------------------------------------------- publish
    def publish(self, params: dict, *, version) -> None:
        """Queue a fleet-wide weight swap: engine i swaps at the i-th
        subsequent ``tick()``'s boundary. Validates the equal-tree
        contract HERE, against the current weights, so a bad publish
        fails atomically with the fleet untouched and fully serviceable
        (every engine holds the same tree, so one verdict is every
        engine's); fuses the block stack ONCE for all engines."""
        if self._swap is not None:
            raise RuntimeError(
                f"publish({version!r}): previous publish "
                f"{self._swap['version']!r} is still rolling out "
                f"({len(self._swap['remaining'])} engines to go)")
        from ..models import generate
        from .engine import _match_placement, check_swappable
        check_swappable(self.engines[0].params, params)
        # Normalize placement ONCE against the fleet's boot params (every
        # engine was built from the same tree, so one reference serves
        # all): each engine's swap then re-validates but never re-copies,
        # and the fused view is computed from the already-normalized tree.
        params = _match_placement(params, self.engines[0].params)
        self._swap = {"version": version, "params": params,
                      "fused": generate._fuse_blocks(params["blocks"]),
                      "remaining": deque(range(len(self.engines)))}

    # ----------------------------------------------------------- accounting
    @property
    def records(self) -> Dict[str, RequestRecord]:
        merged: Dict[str, RequestRecord] = {}
        for sched in self.scheds:
            merged.update(sched.records)
        return merged

    @property
    def completed(self) -> int:
        return sum(s.completed for s in self.scheds)

    def pool_headroom(self, k: Optional[int] = None) -> float:
        """Min free-block fraction across the first ``k`` engines (default:
        the currently active set) — the autoscaler's guard-rail feed
        (resilience/autoscale.py ``min_headroom_frac``): scaling serving
        UP is only safe if the pools it lands on have room. Host list
        arithmetic only; pass a prospective ``k`` to ask "would k active
        engines have headroom?" before committing the scale."""
        k = self._active if k is None else max(1, min(int(k),
                                                      len(self.engines)))
        return min(e.allocator.free_blocks / max(1, e.allocator.capacity)
                   for e in self.engines[:k])

    def compiles(self) -> List[int]:
        return [sum(len(w.compiles) for w in e.watches())
                for e in self.engines]

    def retraces(self) -> List[int]:
        return [sum(w.retraces for w in e.watches())
                for e in self.engines]


@dataclass
class FleetReport:
    """One fleet run's outcome: merged records, fleet-wide + per-class +
    per-engine aggregates, per-engine compile/retrace budgets (each engine
    promises exactly two programs, zero retraces — across any number of
    hot-swaps), and the deploy rollout log."""
    records: Dict[str, RequestRecord]
    aggregates: dict
    per_class: Dict[str, dict]
    per_engine: Dict[int, dict]
    engine_of: Dict[str, int]
    wall_s: float
    num_engines: int
    pool_blocks: int
    pool_bytes_per_engine: int
    peak_blocks_per_engine: List[int] = field(default_factory=list)
    compiles: List[int] = field(default_factory=list)
    retraces: List[int] = field(default_factory=list)
    deploys: List[dict] = field(default_factory=list)
    requests: List[Request] = field(default_factory=list)


def run_serving_fleet(params: dict, cfg: LlamaConfig, paged: PagedKVConfig,
                      workload: Sequence[Request], *, num_engines: int,
                      num_slots: int, prefill_chunk: int = 16,
                      top_k: Optional[int] = None,
                      top_p: Optional[float] = None,
                      events: Optional[EventLog] = None,
                      token_events: bool = True,
                      policy: str = "least_loaded", window_s: float = 30.0,
                      admission: str = "fcfs", speculate=None,
                      prefix_share: bool = False,
                      memory_every: int = 0,
                      publish_after: Optional[int] = None,
                      publish_params: Optional[dict] = None,
                      publish_version=None) -> FleetReport:
    """``frontend.run_serving`` generalized to N engines: replay the
    workload through a fresh fleet in (fast-forwarded) real time. With
    ``publish_after`` set, one live publish of ``publish_params`` fires
    at the first boundary where that many requests have completed —
    the mid-run hot-swap the fleet smoke drives (same-weights there, so
    the bitwise bar holds across it). The loop's only exits are
    completion + a drained rollout: reservation-based admission cannot
    deadlock, and a pending swap applies within ``num_engines`` ticks."""
    clock = _Clock()
    fleet = ServingFleet(params, cfg, paged, num_engines=num_engines,
                         num_slots=num_slots, prefill_chunk=prefill_chunk,
                         top_k=top_k, top_p=top_p, events=events,
                         token_events=token_events, policy=policy,
                         window_s=window_s, admission=admission,
                         speculate=speculate, prefix_share=prefix_share,
                         memory_every=memory_every, clock=clock.now)
    pending = sorted(workload, key=lambda r: (r.arrival, r.rid))
    published = publish_after is None
    busy_s = 0.0
    i = 0
    while i < len(pending) or fleet.outstanding or fleet.swap_pending:
        now = clock.now()
        while i < len(pending) and pending[i].arrival <= now:
            fleet.submit(pending[i], now=now)
            i += 1
        if not published and fleet.completed >= publish_after:
            fleet.publish(publish_params, version=publish_version)
            published = True
        if (fleet.outstanding == 0 and not fleet.swap_pending
                and i < len(pending)):
            clock.fast_forward(pending[i].arrival)   # idle: jump, not sleep
            continue
        fleet.tick()
        busy_s += clock.now() - now
    records = fleet.records
    classes = sorted({r.tenant for r in records.values()})
    per_class = {c: aggregate_latency({k: r for k, r in records.items()
                                       if r.tenant == c})
                 for c in classes}
    per_engine = {}
    for eid in range(num_engines):
        agg = aggregate_latency({k: r for k, r in records.items()
                                 if r.engine == eid})
        agg["peak_blocks_in_use"] = fleet.engines[eid].allocator.peak_in_use
        per_engine[eid] = agg
    return FleetReport(
        records=records,
        aggregates=aggregate_latency(records, busy_span_s=busy_s),
        per_class=per_class, per_engine=per_engine,
        engine_of=dict(fleet.engine_of), wall_s=clock.now(),
        num_engines=num_engines,
        pool_blocks=fleet.engines[0].allocator.capacity,
        pool_bytes_per_engine=pool_bytes(cfg, paged),
        peak_blocks_per_engine=[e.allocator.peak_in_use
                                for e in fleet.engines],
        compiles=fleet.compiles(), retraces=fleet.retraces(),
        deploys=list(fleet.deploys), requests=list(workload))
