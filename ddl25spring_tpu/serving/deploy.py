"""Live train→deploy weight publication: checkpoint stream → serving fleet.

The loop-closing half of the fleet subsystem (serving/fleet.py): the
trainer keeps checkpointing as it always has, and the serving fleet keeps
serving — this module is the conveyor between them, built entirely from
checkpoint.py's existing machinery so a publication inherits every
robustness property checkpoints already have (atomic orbax commits,
SHA-256 digest verification before restore, corrupt-step fallback,
restore-at-saved-shapes cross-topology resharding).

Two halves, one directory:

- ``CheckpointPublisher`` (trainer side) — the ``on_checkpoint`` hook
  ``train_llm_dp`` calls after every periodic/final save: extracts the
  PARAMS from the train state and saves them as a params-only checkpoint
  step in the publish directory. Params-only on purpose: the serving
  side must never need the trainer's optimizer-state template (whose
  ZeRO-1 moments are sharded to a world size serving doesn't have), and
  a params tree is what ``Engine.swap_params`` takes. Never raises into
  the trainer — a failed publication is logged and dropped, the same
  never-sink-the-run posture as telemetry.

- ``WeightPublisher`` (serving side) — watches the publish directory:
  ``poll()`` returns ``(step, params)`` when a step newer than the last
  publication restores cleanly (digest-verified; a corrupt newest step
  falls back to the next, exactly like a trainer resume), restored
  through ``Checkpointer.restore`` against the serving engine's own
  params template — the restore-at-saved-shapes path, so a tree saved
  under a different topology reshards instead of truncating.
  ``publish_to(fleet)`` hands a fresh tree to ``ServingFleet.publish``,
  which rolls it out one engine per token boundary (fleet.py) — the
  fleet is never globally idle across a publish, and no stream drops.

The smoke (`experiments/serving_bench.py --engines N --hot-swap`) drives
the full loop: params → publish dir → digest-verified restore →
staggered per-engine swap mid-traffic, with the bitwise bar held (same
weights) and the ``deploy`` events/spans in the stream as evidence.
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, Optional, Tuple


def _default_params_of(state: Any):
    """TrainState-shaped trees carry ``.params``; a bare params tree (or
    anything unshaped) publishes as-is."""
    return getattr(state, "params", state)


class CheckpointPublisher:
    """Trainer-side publication hook (``train_llm_dp(on_checkpoint=...)``).

    >>> pub = CheckpointPublisher(publish_dir)
    >>> train_llm_dp(cfg, tcfg, checkpoint_dir=ckpt_dir,
    ...              checkpoint_every=200, on_checkpoint=pub)

    Each call saves ``params_of(state)`` at the checkpoint's step index
    and WAITS for the write to land (publications are off the hot path —
    checkpoint cadence — and a landed step is digest-manifested, so the
    watching ``WeightPublisher`` only ever sees verifiable bytes).
    ``max_to_keep=2`` keeps the dir O(1): the newest publication plus one
    fallback for a corrupt-newest restore."""

    def __init__(self, publish_dir: str, *,
                 params_of: Callable[[Any], Any] = _default_params_of,
                 max_to_keep: int = 2,
                 log_fn: Callable[[str], None] = print):
        from ..checkpoint import Checkpointer
        self.publish_dir = publish_dir
        self._params_of = params_of
        self._log = log_fn
        self._ckpt = Checkpointer(publish_dir, max_to_keep=max_to_keep)
        self.published: List[int] = []

    def __call__(self, step: int, state: Any) -> None:
        try:
            self._ckpt.save(int(step), self._params_of(state), force=True,
                            overwrite=True)
            self._ckpt.wait()      # land + digest-manifest before visible
            self.published.append(int(step))
        except Exception as e:     # publication must never sink the trainer
            self._log(f"weight publication at step {step} failed "
                      f"({type(e).__name__}: {e}); training continues")

    def close(self) -> None:
        try:
            self._ckpt.close()
        except Exception:
            pass

    def __enter__(self) -> "CheckpointPublisher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class WeightPublisher:
    """Serving-side watcher over a publish directory (class docstring
    above): ``poll()`` → newest fresh ``(step, params)`` or None;
    ``publish_to(fleet)`` → poll and hand off as a staggered hot-swap.

    A FRESH ``Checkpointer`` is opened per poll and closed after: the
    writer is another process, and orbax's step listing is snapshotted
    per manager — reopening is what makes newly landed steps visible.
    Poll cadence is the caller's (publications arrive at checkpoint
    cadence, so per-token polling would be absurd; the smoke polls once,
    a sidecar would poll on the order of seconds)."""

    def __init__(self, publish_dir: str, template_params: Any):
        self.publish_dir = publish_dir
        self.template = template_params
        self.last_step: Optional[int] = None

    def poll(self) -> Optional[Tuple[int, Any]]:
        from ..checkpoint import Checkpointer
        if not os.path.isdir(self.publish_dir):
            return None               # nothing published yet
        ckpt = Checkpointer(self.publish_dir)
        try:
            latest = ckpt.latest_step()
            if latest is None or (self.last_step is not None
                                  and latest <= self.last_step):
                return None
            # Digest-verify + restore-at-saved-shapes + corrupt-newest
            # fallback, all checkpoint.py's: the step that actually
            # restored is ``restored_step`` (≤ latest), and a fallback
            # onto something already published is NOT a new publication.
            params = ckpt.restore(self.template)
            step = int(ckpt.restored_step)
        finally:
            ckpt.close()
        if self.last_step is not None and step <= self.last_step:
            return None
        self.last_step = step
        return step, params

    def publish_to(self, fleet) -> Optional[int]:
        """Poll; on a fresh publication, start the fleet's staggered
        rollout (``ServingFleet.publish``) versioned by the trainer's
        step. Returns the published step, or None when nothing new."""
        got = self.poll()
        if got is None:
            return None
        step, params = got
        fleet.publish(params, version=step)
        return step
