"""Production serving layer: continuous batching over a paged KV cache.

Four layers (ISSUE 6 / ROADMAP item 2), bottom-up:

- kvcache   — fixed-size device block pool + host free-list allocator;
              sequences of different lengths share one pool through
              per-slot block tables instead of each owning a ``max_len``
              cache (vLLM-style paging, static-shape/one-compile).
- engine    — ``prefill_chunk`` / ``decode_step`` compiled ONCE over a
              fixed slot axis; chunked prefill interleaves with in-flight
              decode; bitwise-parity with ``models.generate`` pinned in
              tests.
- scheduler — Orca-style iteration-level (continuous) batching: FCFS
              admission with worst-case block reservation (never
              deadlocks), retirement frees blocks at the next token
              boundary; ``request_*`` telemetry events.
- frontend  — seeded Poisson load generator (mixed prompt/output length
              mixtures) + ``run_serving`` driver and the latency
              aggregation behind bench.py's serving row and
              ``experiments/obs_report.py``'s serving section.
"""

from .engine import Engine, TokenEvent  # noqa: F401
from .frontend import (ServingReport, aggregate_latency,  # noqa: F401
                       reference_stream, run_serving, synthetic_workload)
from .kvcache import (TRASH_BLOCK, BlockAllocator,  # noqa: F401
                      PagedKVConfig, blocks_for, init_pool,
                      kv_bytes_per_token, naive_cache_bytes, pool_bytes)
from .scheduler import Request, RequestRecord, Scheduler  # noqa: F401
