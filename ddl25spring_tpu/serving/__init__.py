"""Production serving layer: continuous batching over a paged KV cache.

Six layers (ISSUE 6 + ISSUE 11 / ROADMAP item 1), bottom-up:

- kvcache   — fixed-size device block pool + host free-list allocator;
              sequences of different lengths share one pool through
              per-slot block tables instead of each owning a ``max_len``
              cache (vLLM-style paging, static-shape/one-compile).
- engine    — ``prefill_chunk`` / ``decode_step`` (+ ``verify_step``
              when speculating) compiled ONCE over a fixed slot axis;
              chunked prefill interleaves with in-flight decode;
              token-boundary weight hot-swap seam (``swap_params``);
              CoW prefix sharing (``prefix_share``) and bucketed gather
              narrowing (``gather_buckets``); bitwise-parity with
              ``models.generate`` pinned in tests.
- speculate — draft-propose / one-dispatch-verify speculative decoding
              (``SpecConfig``, ``DraftEngine``, ``make_verify_step``):
              greedy streams bitwise ``generate()``'s, stochastic via
              rejection sampling; schema-v7 ``speculate`` events.
- scheduler — Orca-style iteration-level (continuous) batching:
              reservation-based admission (never deadlocks) behind a
              policy seam (FCFS default; size-aware "sjf"; priorities),
              retirement frees blocks at the next token boundary;
              ``request_*`` telemetry events, per-engine tagged.
- frontend  — seeded Poisson load generator, now multi-tenant
              (``TrafficClass`` / ``multi_tenant_workload``: per-class
              rates, SLO targets, admission priorities) + ``run_serving``
              driver and the latency aggregation behind bench.py's
              serving row and ``experiments/obs_report.py``.
- fleet     — N engines behind an SLO-aware ``Router`` (least-loaded /
              predicted-TTFT over slo_monitor-shaped rolling windows)
              with live weight hot-swap rolled out one engine per token
              boundary; ``run_serving_fleet`` driver.
- deploy    — the train→deploy conveyor: ``CheckpointPublisher`` (the
              trainer's ``on_checkpoint`` hook, params-only checkpoint
              stream) and ``WeightPublisher`` (digest-verified,
              restore-at-saved-shapes watcher feeding the fleet).
"""

from .deploy import CheckpointPublisher, WeightPublisher  # noqa: F401
from .engine import Engine, TokenEvent  # noqa: F401
from .fleet import (FleetReport, Router, ServingFleet,  # noqa: F401
                    run_serving_fleet)
from .frontend import (ServingReport, TrafficClass,  # noqa: F401
                       aggregate_latency, class_slos, multi_tenant_workload,
                       reference_stream, run_serving, synthetic_workload)
from .kvcache import (TRASH_BLOCK, BlockAllocator,  # noqa: F401
                      PagedKVConfig, blocks_for, init_pool,
                      kv_bytes_per_token, naive_cache_bytes, pool_bytes)
from .scheduler import Request, RequestRecord, Scheduler  # noqa: F401
from .speculate import DraftEngine, SpecConfig  # noqa: F401
