"""Step-level serving engine: prefill()/decode_step() over a fixed slot axis.

`models/generate.py` fuses prefill + the whole decode horizon into one
compiled scan — perfect for a bench, useless for a server, where the batch
composition changes at every token boundary. This engine refactors the same
math into TWO reusable compiled programs over a fixed slot axis ``[S]``:

- ``prefill_chunk``: one slot's prompt chunk ``[1, Tc]`` through the model,
  writing K/V into the slot's pool blocks; the FINAL chunk also samples the
  first token (TTFT). Chunking lets a long prompt interleave with in-flight
  decode instead of stalling it — the scheduler advances one chunk per
  token boundary.
- ``decode_step``: one token for ALL slots ``[S]`` at once — per-slot
  position, RNG key, temperature and active-mask ride in the slot state, so
  admissions/retirements between steps never recompile anything.
- ``verify_step`` (speculation armed, serving/speculate.py): the decode
  step widened to a ``k+1``-position window per slot — one dispatch
  scores a draft's whole proposal, so decode throughput scales with the
  acceptance rate instead of paying one dispatch per token.

Each is compiled exactly once per engine (static shapes; the pool is
donated so XLA updates blocks in place — with gather narrowing, once per
bucketed table width), and all are built from the same
building blocks as ``generate`` — ``_fuse_blocks``, ``llama.embed/head``,
the fp32-softmax attention layout of ``_attend_cached`` — deliberately
op-for-op, because the acceptance bar is BITWISE: a request decoded here,
at any slot, in any company, must emit exactly the tokens ``generate()``
emits for it alone (tests/test_generate.py, tests/test_serving.py).

The bitwise-parity constraints that shaped the code:
- Every op is row-independent (norms, matmuls, softmax-per-row, per-slot
  RNG), so batch company cannot leak between slots.
- The gathered cache is padded to ``paged.max_seq_len`` and masked by
  absolute position; masked garbage contributes exact zeros through
  softmax (``exp(-inf) = 0``), same as ``generate``'s unwritten tail —
  parity tests run ``generate(max_len=paged.max_seq_len)`` so both sides
  reduce over identically-shaped score rows.
- Per-slot sampling keeps ``generate``'s exact RNG discipline: split the
  slot key every step, sample from the sub-key — so equal seeds give equal
  streams. Temperature is a traced per-slot scalar (greedy selected by a
  ``where``, both branches computed); top_k/top_p stay engine-static, the
  same filters ``_sample`` applies.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import LlamaConfig
from .. import nn
from ..models import generate, llama
from .kvcache import (TRASH_BLOCK, BlockAllocator, PagedKVConfig, blocks_for,
                      init_pool)


def check_swappable(old, new) -> None:
    """Raise unless ``new`` matches ``old`` leaf-for-leaf in tree
    structure, shape and dtype — the equal-tree contract every weight
    hot-swap must satisfy (a mismatch would silently retrace the two
    compiled programs). Shared by ``Engine.swap_params`` (per-engine
    enforcement) and ``ServingFleet.publish`` (fail a bad publish
    ATOMICALLY, before any engine pops from the rollout)."""
    o_leaves, o_def = jax.tree_util.tree_flatten(old)
    n_leaves, n_def = jax.tree_util.tree_flatten(new)
    if o_def != n_def:
        raise ValueError("swap_params: new params tree structure does "
                         "not match the serving engine's")
    for o, n in zip(o_leaves, n_leaves):
        if o.shape != n.shape or o.dtype != n.dtype:
            raise ValueError(
                f"swap_params: leaf mismatch {n.shape}/{n.dtype} vs "
                f"engine's {o.shape}/{o.dtype} — a shape change would "
                "retrace the engine's two compiled programs")


def _match_placement(new, old):
    """Return ``new`` placed EXACTLY like ``old`` (device + committed-ness,
    leaf by leaf). The jit cache key includes argument placement, so a
    hot-swapped tree must be indistinguishable in placement from the boot
    params or both compiled programs would silently retrace — and a tree
    restored from a checkpoint arrives device_put-COMMITTED while
    ``init_llama``'s boot params are uncommitted. Shedding a commitment
    requires a host bounce (there is no uncommit-in-place); that is one
    params-sized copy per publish, trivial next to the disk read that
    produced the tree."""
    def fix(n, o):
        if not isinstance(n, jax.Array) or not isinstance(o, jax.Array):
            return n
        nc = bool(getattr(n, "committed", False))
        oc = bool(getattr(o, "committed", False))
        if oc:
            return n if nc and n.sharding == o.sharding \
                else jax.device_put(n, o.sharding)
        return n if not nc else jnp.asarray(np.asarray(n))
    return jax.tree.map(fix, new, old)


# ------------------------------------------------------------- paged forward

def _attend_paged(q: jnp.ndarray, ck: jnp.ndarray, cv: jnp.ndarray,
                  q_positions: jnp.ndarray) -> jnp.ndarray:
    """``generate._attend_cached`` with a PER-SLOT position mask: q
    [S, Tq, H, Dh] over the gathered cache [S, Tmax, H, Dh], masked to
    ``kpos <= q_position`` per (slot, query-row). Identical layout and op
    sequence (fp32 softmax, heads folded into batch) so per-row numerics
    match the contiguous-cache path bitwise."""
    b, tq, h, dh = q.shape
    tmax = ck.shape[1]
    scale = 1.0 / math.sqrt(dh)
    qm = q.transpose(0, 2, 1, 3).reshape(b * h, tq, dh)
    km = ck.transpose(0, 2, 1, 3).reshape(b * h, tmax, dh).astype(q.dtype)
    vm = cv.transpose(0, 2, 1, 3).reshape(b * h, tmax, dh).astype(q.dtype)
    scores = lax.dot_general(qm, km, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32) * scale
    qpos = jnp.broadcast_to(q_positions[:, None, :], (b, h, tq))
    mask = qpos.reshape(b * h, tq)[:, :, None] >= jnp.arange(tmax)[None, None, :]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = lax.dot_general(probs, vm, (((2,), (1,)), ((0,), (0,))))
    return out.reshape(b, h, tq, dh).transpose(0, 2, 1, 3)


def _apply_rope_slots(x: jnp.ndarray, cos: jnp.ndarray,
                      sin: jnp.ndarray) -> jnp.ndarray:
    """``llama.apply_rope`` with per-slot tables: cos/sin [S, T, half]
    instead of the shared [T, half] (slots sit at different absolute
    positions). Same rotation arithmetic, elementwise."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[:, :, None, :].astype(x.dtype)
    s = sin[:, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)


def _block_paged(block: dict, pk: jnp.ndarray, pv: jnp.ndarray,
                 x: jnp.ndarray, positions: jnp.ndarray,
                 tables: jnp.ndarray, wblk: jnp.ndarray, woff: jnp.ndarray,
                 cfg: LlamaConfig):
    """One pre-fused block over x [S, T, D] at per-slot absolute
    ``positions`` [S, T], writing this call's K/V into pool blocks at
    (``wblk``, ``woff``) [S, T] and attending over each slot's gathered
    block table. The paged twin of ``generate._block_with_cache``; the
    scatter/gather replaces its dynamic_update_slice/full-cache read, the
    math around them is identical."""
    s, t, d = x.shape
    dh = cfg.head_dim
    xn = nn.rmsnorm(block["attn_norm"], x, eps=cfg.norm_eps)
    qkv = xn @ block["w_qkv"].astype(x.dtype)
    dl = qkv.shape[-1] // 3
    h_local = dl // dh
    q = qkv[..., :dl].reshape(s, t, h_local, dh)
    k = qkv[..., dl:2 * dl].reshape(s, t, h_local, dh)
    v = qkv[..., 2 * dl:].reshape(s, t, h_local, dh)
    cos, sin = llama.rope_angles(positions.reshape(-1), dh, cfg.rope_theta)
    cos = cos.reshape(s, t, -1)
    sin = sin.reshape(s, t, -1)
    q = _apply_rope_slots(q, cos, sin)
    k = _apply_rope_slots(k, cos, sin)       # cached K is stored post-RoPE
    # Per-token scatter into the block pool. Distinct (block, offset)
    # targets are guaranteed by block ownership; only TRASH_BLOCK collides
    # (inactive slots, padded tails) and its contents are never read
    # un-masked.
    pk = pk.at[wblk, woff].set(k.astype(pk.dtype))
    pv = pv.at[wblk, woff].set(v.astype(pv.dtype))
    ck = pk[tables].reshape(s, -1, h_local, dh)    # [S, Tmax, H, Dh]
    cv = pv[tables].reshape(s, -1, h_local, dh)
    out = _attend_paged(q, ck, cv, positions)
    x = x + out.reshape(s, t, h_local * dh) @ block["wo"].astype(x.dtype)
    xn = nn.rmsnorm(block["mlp_norm"], x, eps=cfg.norm_eps)
    gu = xn @ block["w_gu"].astype(x.dtype)
    f = gu.shape[-1] // 2
    x = x + (jax.nn.silu(gu[..., :f]) * gu[..., f:]) @ block["w_down"].astype(x.dtype)
    return x, pk, pv


def _forward_paged(params: dict, fused_blocks: dict, tokens: jnp.ndarray,
                   pool: dict, tables: jnp.ndarray, positions: jnp.ndarray,
                   wblk: jnp.ndarray, woff: jnp.ndarray, cfg: LlamaConfig):
    """tokens [S, T] at per-slot absolute ``positions`` [S, T] → (hidden
    [S, T, D], updated pool). One lax.scan over the stacked layers,
    threading each layer's block-pool slice — the paged twin of
    ``generate._forward_fused`` (which threads cache slices)."""
    h = llama.embed(params, tokens, cfg)

    def body(carry, layer):
        block, pk, pv = layer
        out, pk, pv = _block_paged(block, pk, pv, carry, positions,
                                   tables, wblk, woff, cfg)
        return out, (pk, pv)

    h, (pk, pv) = lax.scan(body, h, (fused_blocks, pool["k"], pool["v"]))
    return h, {"k": pk, "v": pv}


def _sample_slot(key, logits: jnp.ndarray, temperature: jnp.ndarray,
                 top_k: Optional[int], top_p: Optional[float]) -> jnp.ndarray:
    """``generate._sample`` with a TRACED per-slot temperature: logits
    [1, V] → token [1]. Greedy (t == 0) is a ``where``-select over both
    branches instead of Python control flow, so one compile serves any
    per-slot mix; the sampled branch applies the SAME ``filter_logits``
    and ``categorical`` ops as ``generate`` (one filter implementation —
    the bitwise-parity bar depends on it)."""
    greedy = jnp.argmax(logits, axis=-1)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = generate.filter_logits(logits / safe_t, top_k, top_p)
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy)


# ------------------------------------------------------------ compiled steps

def make_prefill_chunk(cfg: LlamaConfig, paged: PagedKVConfig,
                       chunk_len: int, top_k: Optional[int],
                       top_p: Optional[float]):
    """One compiled program: one slot's prompt chunk [chunk_len] through the
    model, K/V scattered into the slot's blocks. Also computes the
    next-token sample from the chunk's last VALID row — the host uses it
    only for the final chunk (``generate`` splits its key exactly once
    after prefill, so intermediate chunks must not consume randomness:
    the caller passes the key only when ``is_final``).

    ``write_from`` (CoW prefix sharing, kvcache.py): positions below it
    route their K/V writes to the trash block — the slot READS those
    positions from blocks it shares with an earlier identical prefix, so
    re-writing them would scribble on another request's read-only blocks.
    The recomputed values are bitwise the shared ones (same tokens, same
    positions, same weights), so discarding them changes nothing. 0 (the
    non-sharing case) writes everything, byte-for-byte the old program."""
    bl, mb = paged.block_len, paged.max_blocks_per_seq

    @partial(jax.jit, donate_argnums=(0,))
    def prefill_chunk(pool: dict, params: dict, fused: dict,
                      table_row: jnp.ndarray, tokens: jnp.ndarray,
                      start: jnp.ndarray, n_valid: jnp.ndarray,
                      write_from: jnp.ndarray,
                      key: jnp.ndarray, temperature: jnp.ndarray):
        start = jnp.asarray(start, jnp.int32)
        pos = start + jnp.arange(chunk_len, dtype=jnp.int32)       # [Tc]
        valid = jnp.logical_and(jnp.arange(chunk_len) < n_valid,
                                pos >= write_from)
        blk_idx = jnp.minimum(pos // bl, mb - 1)
        wblk = jnp.where(valid, table_row[blk_idx], TRASH_BLOCK)
        woff = pos % bl
        h, pool = _forward_paged(params, fused, tokens[None], pool,
                                 table_row[None], pos[None],
                                 wblk[None], woff[None], cfg)
        # Logits of the last valid row only — the [1, 1, D] head matmul
        # ``generate`` performs (never the full [Tc, V] logits).
        last = jnp.take_along_axis(
            h, (n_valid - 1).reshape(1, 1, 1).astype(jnp.int32), axis=1)
        logits = llama.head(params, last, cfg)[:, 0, :]            # [1, V]
        key, sub = jax.random.split(key)
        tok = _sample_slot(sub, logits, temperature, top_k, top_p)
        return pool, tok[0], key

    return prefill_chunk


def make_decode_step(cfg: LlamaConfig, paged: PagedKVConfig,
                     num_slots: int, top_k: Optional[int],
                     top_p: Optional[float], *, return_probs: bool = False):
    """One compiled program: one token for ALL ``num_slots`` slots. Each
    slot feeds back its last token at its own position, writes K/V into its
    own blocks (inactive slots write to trash), and samples with its own
    key/temperature. Admission, retirement and raggedness are pure data —
    the program never recompiles. The table WIDTH is read from the
    argument shape, not the pool config: with gather narrowing
    (``Engine(gather_buckets=True)``) the host passes a bucketed slice of
    the block table and each bucket width is its own (once-compiled)
    specialization of this one program.

    ``return_probs=True`` is the DRAFT variant (serving/speculate.py):
    identical cache indexing, key discipline and sampling, but the program
    additionally returns the sampling distribution ``q`` per slot (post
    temperature/top_k/top_p — the ``q`` of the rejection test, so
    acceptance uses exactly the distribution the proposal was drawn from).
    One body serves both so a fix to the paged-cache math can never drift
    between target and draft."""
    bl = paged.block_len

    @partial(jax.jit, donate_argnums=(0,))
    def decode_step(pool: dict, params: dict, fused: dict,
                    tables: jnp.ndarray, last_tok: jnp.ndarray,
                    pos: jnp.ndarray, keys: jnp.ndarray,
                    temps: jnp.ndarray, active: jnp.ndarray):
        mb = tables.shape[1]
        blk_idx = jnp.minimum(pos // bl, mb - 1)
        own = jnp.take_along_axis(tables, blk_idx[:, None], axis=1)[:, 0]
        wblk = jnp.where(active, own, TRASH_BLOCK)
        woff = pos % bl
        h, pool = _forward_paged(params, fused, last_tok[:, None], pool,
                                 tables, pos[:, None],
                                 wblk[:, None], woff[:, None], cfg)
        logits = llama.head(params, h, cfg)[:, 0, :]               # [S, V]
        split = jax.vmap(jax.random.split)(keys)                   # [S, 2, 2]
        subs = split[:, 1]
        # Only ACTIVE slots consume randomness: a slot still mid-prefill
        # (or free) must keep its key untouched, or its stream would start
        # shifted relative to ``generate``'s by however many decode steps
        # happened to run before its admission finished.
        new_keys = jnp.where(active[:, None], split[:, 0], keys)
        toks = jax.vmap(
            lambda k, l, t: _sample_slot(k, l[None], t, top_k, top_p)[0]
        )(subs, logits, temps)
        if not return_probs:
            return pool, toks, new_keys
        # Greedy slots' q is unused (their acceptance is the argmax
        # comparison); it is still computed, ``where``-select style, so
        # one compile serves any per-slot mix.
        safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
        q = jax.nn.softmax(
            generate.filter_logits(logits / safe_t, top_k, top_p), axis=-1)
        return pool, toks, q, new_keys

    return decode_step


# ----------------------------------------------------------------- the engine

class TokenEvent(NamedTuple):
    """One emitted token: ``first`` marks the TTFT token (sampled by the
    final prefill chunk), ``done`` that the slot retired with this token."""
    slot: int
    token: int
    first: bool
    done: bool


class _Slot:
    __slots__ = ("blocks", "prompt", "max_new", "produced", "prefill_off",
                 "phase", "seq", "shared", "prompt_key", "registered")

    def __init__(self, blocks, prompt, max_new, seq, *, shared=0,
                 prompt_key=None):
        self.blocks = blocks          # owned pool block indices (refs held
                                      # on the first ``shared`` of them)
        self.prompt = prompt          # np.int32 [Tp]
        self.max_new = max_new
        self.produced = 0
        self.prefill_off = 0          # tokens of prompt already prefilled
        self.phase = "prefill"        # "prefill" -> "decode"
        self.seq = seq                # admission order (prefill is FCFS by
                                      # THIS, not by slot index — a freed
                                      # low slot must not jump the line)
        self.shared = shared          # leading blocks mapped read-only from
                                      # an identical prompt prefix (CoW)
        self.prompt_key = prompt_key  # tuple(prompt) for prefix-cache keys
        self.registered = shared      # full prompt blocks published into
                                      # the prefix cache so far


class Engine:
    """Slots + compiled steps + block plumbing. Queueing, time and
    telemetry live one layer up (scheduler.py); this class only knows how
    to admit a request into a free slot, advance prefill by one chunk,
    decode one token for everyone, and retire finished slots (freeing
    their blocks immediately).

    ``step()`` is one token boundary: at most one prefill chunk (FCFS over
    mid-prefill slots — the chunked-prefill interleave), then one decode
    step if any slot is decoding. Returns the ``TokenEvent``s produced.
    """

    def __init__(self, params: dict, cfg: LlamaConfig, paged: PagedKVConfig,
                 num_slots: int, *, prefill_chunk: int = 16,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 engine_id: Optional[int] = None,
                 speculate: Optional["SpecConfig"] = None,
                 prefix_share: bool = False,
                 gather_buckets: bool = False):
        if num_slots < 1 or prefill_chunk < 1:
            raise ValueError(f"num_slots={num_slots}, "
                             f"prefill_chunk={prefill_chunk}")
        self.cfg = cfg
        self.paged = paged
        self.num_slots = num_slots
        self.prefill_chunk_len = prefill_chunk
        # Fleet seam (serving/fleet.py): which replica this engine is.
        # Purely a label — it tags the compile-watch names below (so an
        # N-engine run's 2N compile events attribute per engine) and rides
        # through the scheduler into request_*/route/deploy telemetry.
        self.engine_id = engine_id
        self.params = params
        self.fused = generate._fuse_blocks(params["blocks"])  # hoisted once
        self.pool = init_pool(cfg, paged)
        self.allocator = BlockAllocator(paged.num_blocks)
        self._admit_seq = 0
        self.slots: List[Optional[_Slot]] = [None] * num_slots
        # Host-side slot state, shipped to the device each step as COPIES
        # (jnp.array, never jnp.asarray: a zero-copy handoff would freeze
        # these buffers read-only under the host's feet on the CPU
        # backend). Tiny [S] rows; only the pool is device-resident and
        # donated. Keys live device-side: decode returns the split batch.
        self.tables = np.full((num_slots, paged.max_blocks_per_seq),
                              TRASH_BLOCK, np.int32)
        self.pos = np.zeros(num_slots, np.int32)
        self.last_tok = np.zeros(num_slots, np.int32)
        self.temps = np.zeros(num_slots, np.float32)
        self.keys = jnp.zeros((num_slots, 2), jnp.uint32)
        # Copy-on-write prefix sharing (kvcache.py): a host-side map from
        # a prompt's leading n·block_len tokens to the physical block
        # holding tokens [(n-1)·bl, n·bl). Entries are published only once
        # the owning slot's prefill has WRITTEN the block, and evicted
        # when the last reference frees it — sharing is among live
        # requests (the persistent-LRU extension is the documented next
        # step). ``_block_key`` is the eviction reverse map.
        self.prefix_share = prefix_share
        self._prefix_blocks: Dict[tuple, int] = {}
        self._block_key: Dict[int, tuple] = {}
        # Gather narrowing (opt-in): decode/verify gathers walk only a
        # BUCKETED prefix of the block table — the fleet-wide max live
        # block count this dispatch, rounded up to a power of two so the
        # shape set is bounded (one compile per bucket, zero retraces
        # after). Byte savings are accounted analytically per dispatch.
        self.gather_buckets = gather_buckets
        mb = paged.max_blocks_per_seq
        self._buckets = sorted({min(1 << i, mb)
                                for i in range(mb.bit_length() + 1)} | {mb})
        n_shapes = len(self._buckets) if gather_buckets else 1
        self.gather_bytes = 0          # gathered KV bytes, as narrowed
        self.gather_bytes_saved = 0    # bytes the full-width walk would add
        # Compile/retrace observability (telemetry/introspect.py): the
        # engine's contract is a DOCUMENTED program set — two programs
        # (prefill_chunk + decode_step) without speculation, three
        # (+ verify_step; decode_step idles) plus the draft's two with it
        # — admission, retirement and raggedness are data, never shapes.
        # Gather narrowing widens each decode/verify budget to one compile
        # per bucket width. The watches enforce the budgets (growth past
        # them is a flagged retrace) and emit ``compile`` events once the
        # scheduler binds its event stream (introspect.bind_events).
        from ..telemetry import introspect
        tag = "" if engine_id is None else f"[{engine_id}]"
        self._prefill = introspect.watch(
            make_prefill_chunk(cfg, paged, prefill_chunk, top_k, top_p),
            name=f"serving/prefill_chunk{tag}", max_caches=1)
        self._decode = introspect.watch(
            make_decode_step(cfg, paged, num_slots, top_k, top_p),
            name=f"serving/decode_step{tag}", max_caches=n_shapes)
        # Speculative decoding (serving/speculate.py): the draft engine
        # (own pool over the SAME block tables, own two programs) and the
        # one-dispatch k+1-position verify program.
        self.spec = speculate
        self.last_spec: Optional[dict] = None
        self.decode_dispatches = 0     # verify or plain decode calls
        self.decode_tokens = 0         # tokens those dispatches emitted
        self.draft_dispatches = 0
        if speculate is not None:
            from .speculate import DraftEngine, make_verify_step
            self.draft = DraftEngine(
                speculate, cfg, paged, num_slots,
                prefill_chunk=prefill_chunk, top_k=top_k, top_p=top_p,
                engine_id=engine_id, decode_shapes=n_shapes)
            self._verify = introspect.watch(
                make_verify_step(cfg, paged, num_slots, speculate.k,
                                 top_k, top_p),
                name=f"serving/verify_step{tag}", max_caches=n_shapes)
        else:
            self.draft = None
            self._verify = None

    def watches(self) -> list:
        """The engine's CompileWatch set — its documented program budget.
        Two entries without speculation (byte-for-byte the historical
        contract), five with it (prefill + decode + verify + the draft's
        prefill + decode)."""
        ws = [self._prefill, self._decode]
        if self.spec is not None:
            ws += [self._verify, self.draft._prefill, self.draft._decode]
        return ws

    # ------------------------------------------------------------- admission
    def required_blocks(self, prompt_len: int, max_new: int) -> int:
        """Positions written are ``0..prompt_len+max_new-2`` (the final
        sampled token is never fed back — ``generate``'s horizon)."""
        return blocks_for(prompt_len + max_new - 1, self.paged.block_len)

    def _shared_prefix(self, prompt) -> List[int]:
        """Physical blocks an admission of ``prompt`` can map read-only:
        the longest chain of FULL prompt blocks whose exact token prefix
        is already published in the prefix cache (i.e. written by a live
        request). Registration is prefix-ordered, so the walk stops at
        the first miss."""
        if not self.prefix_share:
            return []
        bl = self.paged.block_len
        key = tuple(int(t) for t in prompt)
        shared: List[int] = []
        for n in range(1, len(key) // bl + 1):
            b = self._prefix_blocks.get(key[:n * bl])
            if b is None:
                break
            shared.append(b)
        return shared

    def free_slot(self) -> Optional[int]:
        for s, slot in enumerate(self.slots):
            if slot is None:
                return s
        return None

    def can_admit(self, prompt_len: int, max_new: int,
                  prompt=None) -> bool:
        """``prompt`` (the token ids) lets CoW-sharing engines credit the
        blocks a shared prefix saves; without it the check is the
        conservative full-reservation one (always safe — sharing only
        ever reduces the fresh-block need)."""
        if self.free_slot() is None:
            return False
        need = self.required_blocks(prompt_len, max_new)
        if prompt is not None:
            need -= len(self._shared_prefix(prompt))
        return need <= self.allocator.free_blocks

    def admit(self, prompt, max_new: int, *, temperature: float = 0.0,
              key: Optional[jax.Array] = None) -> int:
        """Place a request into a free slot and reserve its WORST-CASE
        blocks up front. All-or-nothing reservation is the liveness
        guarantee: an admitted request can always run to completion, so
        pool exhaustion can only ever queue admissions, never deadlock
        in-flight work (scheduler.py holds the policy argument).

        With ``prefix_share``, full prompt blocks already written by a
        live request with the identical prefix are mapped READ-ONLY into
        this slot's table (allocator refcount, not a fresh grant) and the
        reservation shrinks by that many blocks; the slot's own writes
        start at the first un-shared position (its prefill passes
        ``write_from``), so a shared block is never written twice — the
        divergent tail always lands in this slot's private blocks."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        tp, mx = len(prompt), int(max_new)
        if tp < 1 or mx < 1:
            raise ValueError(f"empty request: prompt_len={tp}, max_new={mx}")
        if tp + mx - 1 > self.paged.max_seq_len:
            raise ValueError(
                f"request needs {tp + mx - 1} cache positions but the pool "
                f"serves at most max_blocks_per_seq * block_len = "
                f"{self.paged.max_seq_len}")
        s = self.free_slot()
        if s is None:
            raise RuntimeError("no free slot")
        shared = self._shared_prefix(prompt)
        fresh = self.allocator.alloc(self.required_blocks(tp, mx)
                                     - len(shared))
        if fresh is None:
            raise RuntimeError("pool exhausted")
        if shared:
            self.allocator.share(shared)
        blocks = shared + fresh
        self._admit_seq += 1
        self.slots[s] = _Slot(blocks, prompt, mx, self._admit_seq,
                              shared=len(shared),
                              prompt_key=(tuple(int(t) for t in prompt)
                                          if self.prefix_share else None))
        # Skip prefilling the shared region (its K/V is already in the
        # pool, bitwise what this slot would write) — but always run the
        # chunk holding the LAST prompt token: the first-token sample
        # needs its hidden state, which only K/V survives of the shared
        # computation. Writes below write_from go to trash.
        self.slots[s].prefill_off = min(len(shared) * self.paged.block_len,
                                        tp - 1)
        self.tables[s] = TRASH_BLOCK
        self.tables[s, :len(blocks)] = blocks
        self.pos[s] = 0
        self.temps[s] = float(temperature)
        if key is None:
            if temperature > 0:
                raise ValueError("sampling (temperature>0) requires a key")
            key = jax.random.PRNGKey(0)      # unused by greedy (generate's
        self.keys = self.keys.at[s].set(key)  # own placeholder convention)
        if self.draft is not None:
            self.draft.admit_key(s, temperature, key)
        return s

    # ----------------------------------------------------------- one boundary
    @property
    def busy(self) -> bool:
        return any(slot is not None for slot in self.slots)

    def blocks_in_use(self) -> int:
        return self.allocator.in_use

    # ------------------------------------------------------- weight hot-swap
    def swap_params(self, params: dict, *, fused: Optional[dict] = None
                    ) -> None:
        """Swap to new weights at the CURRENT token boundary — the live
        train→deploy seam (serving/deploy.py). Legal between ``step()``
        calls only (the host drives the engine, so outside a ``step()``
        nothing is in flight by construction); in-flight streams are NOT
        dropped — their next token is sampled under the new weights over
        the KV each slot already wrote, and nothing already emitted
        changes (the hot-swap determinism bar in
        tests/test_fleet_serving.py: a same-weights swap is bitwise
        invisible; a new-weights swap changes only tokens sampled after
        the boundary).

        The new tree must match the old one leaf-for-leaf in shape and
        dtype: params are DATA to the two compiled programs, so an equal
        tree swaps with zero recompiles (the engine's two-programs
        contract survives any number of publishes), while a different
        shape would silently retrace — rejected loudly instead. Placement
        is normalized to the boot params' (``_match_placement``) for the
        same reason: a checkpoint-restored tree arrives committed, and
        committed-ness is part of the jit cache key.

        ``fused`` (the ``generate._fuse_blocks`` view of ``params``) can
        be passed precomputed so an N-engine fleet fuses once per publish,
        not once per engine."""
        check_swappable(self.params, params)
        self.params = _match_placement(params, self.params)
        self.fused = (_match_placement(fused, self.fused)
                      if fused is not None
                      else generate._fuse_blocks(self.params["blocks"]))

    def step(self) -> List[TokenEvent]:
        """One token boundary: one prefill chunk (if a slot is mid-prefill),
        then one decode step — or, with speculation, one draft-propose +
        verify round — over the decoding slots."""
        events: List[TokenEvent] = []
        self.last_spec = None
        prefilling = [(sl.seq, i) for i, sl in enumerate(self.slots)
                      if sl is not None and sl.phase == "prefill"]
        if prefilling:
            events.extend(self._advance_prefill(min(prefilling)[1]))
        if any(sl is not None and sl.phase == "decode" for sl in self.slots):
            events.extend(self._advance_spec_decode()
                          if self.spec is not None
                          else self._advance_decode())
        return events

    def _register_prefix_blocks(self, s: int) -> None:
        """Publish the full prompt blocks slot ``s``'s prefill has now
        written (or shares) into the prefix cache, so later admissions
        with the identical prefix can map them. First writer wins; an
        entry for the same prefix already present (the donor, or a
        concurrent identical prompt that couldn't share yet) is kept."""
        slot = self.slots[s]
        bl = self.paged.block_len
        while ((slot.registered + 1) * bl <= slot.prefill_off
               and (slot.registered + 1) * bl <= len(slot.prompt)):
            n = slot.registered + 1
            key = slot.prompt_key[:n * bl]
            block = int(self.tables[s, n - 1])
            if key not in self._prefix_blocks:
                self._prefix_blocks[key] = block
                self._block_key[block] = key
            slot.registered = n

    def _advance_prefill(self, s: int) -> List[TokenEvent]:
        slot = self.slots[s]
        tc = self.prefill_chunk_len
        off = slot.prefill_off
        n_valid = min(tc, len(slot.prompt) - off)
        chunk = np.zeros(tc, np.int32)
        chunk[:n_valid] = slot.prompt[off:off + n_valid]
        is_final = off + n_valid >= len(slot.prompt)
        write_from = slot.shared * self.paged.block_len
        table_row = jnp.array(self.tables[s])
        chunk_j = jnp.array(chunk)
        self.pool, tok, new_key = self._prefill(
            self.pool, self.params, self.fused,
            table_row, chunk_j,
            jnp.int32(off), jnp.int32(n_valid), jnp.int32(write_from),
            self.keys[s], jnp.float32(self.temps[s]))
        if self.draft is not None:
            # Mirror the chunk into the draft pool (same table row, same
            # positions, the draft's weights) so proposals can attend over
            # the full prompt. Shared blocks are shared there too — the
            # donor's draft prefill wrote them — so the same write_from
            # masking applies.
            self.draft.prefill_chunk(table_row, chunk_j, jnp.int32(off),
                                     jnp.int32(n_valid),
                                     jnp.int32(write_from), self.temps[s])
            # The mirror is a real draft dispatch: without it the JSON's
            # draft-cost line under-reports by one dispatch per prefill
            # chunk (~15% on the CI smoke's workload) and a real small
            # draft sized from it would look cheaper than it is.
            self.draft_dispatches += 1
        slot.prefill_off = off + n_valid
        if self.prefix_share:
            self._register_prefix_blocks(s)
        if not is_final:
            # Intermediate chunk: K/V written; the sampled token and split
            # key are discarded so the slot's RNG stream stays exactly
            # generate's (one split for the whole prefill).
            return []
        self.keys = self.keys.at[s].set(new_key)
        first = int(tok)
        slot.phase = "decode"
        slot.produced = 1
        self.pos[s] = len(slot.prompt)
        self.last_tok[s] = first
        done = slot.produced >= slot.max_new
        if done:
            self._retire(s)
        return [TokenEvent(s, first, first=True, done=done)]

    def _gathered_tables(self, active: np.ndarray, tq: int) -> np.ndarray:
        """The block-table slice a decode/verify dispatch gathers through.
        Full width by default; with ``gather_buckets``, narrowed to the
        smallest bucket covering every active slot's LIVE blocks (reads
        reach positions < pos + tq, all ≤ the slot's written-or-writing
        frontier), with the avoided gather traffic counted analytically
        — the decode table's KV read line in ROOFLINE.md is per live
        position, and this is the knob that makes the gather live-length
        instead of worst-case."""
        bl, mb = self.paged.block_len, self.paged.max_blocks_per_seq
        from .kvcache import kv_bytes_per_token
        per_block = bl * kv_bytes_per_token(self.cfg, self.paged.kv_dtype)
        if not self.gather_buckets:
            self.gather_bytes += self.num_slots * mb * per_block
            return self.tables
        need = 1
        for s in np.nonzero(active)[0]:
            need = max(need, -(-(int(self.pos[s]) + tq) // bl))
        # A verify window near the horizon can ask past the table (pos +
        # k + 1 spills over a full-width reservation); the overflow rows
        # are live-masked to trash in-program and the blk_idx clamp tops
        # out at the table width, so the host need caps at mb.
        cols = next(b for b in self._buckets if b >= min(need, mb))
        self.gather_bytes += self.num_slots * cols * per_block
        self.gather_bytes_saved += self.num_slots * (mb - cols) * per_block
        return self.tables[:, :cols]

    def _advance_decode(self) -> List[TokenEvent]:
        active = np.array([sl is not None and sl.phase == "decode"
                           for sl in self.slots])
        tables = self._gathered_tables(active, 1)
        self.pool, toks, new_keys = self._decode(
            self.pool, self.params, self.fused,
            jnp.array(tables), jnp.array(self.last_tok),
            jnp.array(self.pos), self.keys,
            jnp.array(self.temps), jnp.array(active))
        toks = np.asarray(toks)
        self.keys = new_keys
        events = []
        for s in np.nonzero(active)[0]:
            slot = self.slots[s]
            tok = int(toks[s])
            slot.produced += 1
            self.pos[s] += 1
            self.last_tok[s] = tok
            done = slot.produced >= slot.max_new
            if done:
                self._retire(s)
            events.append(TokenEvent(int(s), tok, first=False, done=done))
        self.decode_dispatches += 1
        self.decode_tokens += len(events)
        return events

    def _advance_spec_decode(self) -> List[TokenEvent]:
        """One speculative round (serving/speculate.py): k draft decode
        dispatches propose, one cache-fill dispatch keeps the draft pool
        whole, ONE target verify dispatch scores all k+1 window positions
        and accepts a prefix. Emits ``min(accepted + 1, remaining)``
        tokens per active slot — the greedy ones bitwise ``generate()``'s
        — and records the round's proposal accounting in ``last_spec``
        (the scheduler's ``speculate`` event, schema v7)."""
        k = self.spec.k
        active_l = [sl is not None and sl.phase == "decode"
                    for sl in self.slots]
        active = np.array(active_l)
        remaining = np.array([sl.max_new - sl.produced if a else 0
                              for a, sl in zip(active_l, self.slots)],
                             np.int32)
        live = np.minimum(k + 1, np.maximum(remaining, 1)).astype(np.int32)
        tables = jnp.array(self._gathered_tables(active, k + 1))
        pos = jnp.array(self.pos)
        temps = jnp.array(self.temps)
        active_j = jnp.array(active)
        live_j = jnp.array(live)
        drafts, draft_probs = self.draft.propose(
            tables, jnp.array(self.last_tok), pos, temps, active_j, live_j)
        self.draft_dispatches += k + 1
        window = jnp.concatenate([jnp.array(self.last_tok)[:, None],
                                  drafts], axis=1)
        self.pool, out, accepted, new_keys = self._verify(
            self.pool, self.params, self.fused, tables, window,
            draft_probs, pos, live_j, self.keys, temps, active_j)
        out = np.asarray(out)
        accepted = np.asarray(accepted)
        self.keys = new_keys
        self.decode_dispatches += 1
        events: List[TokenEvent] = []
        n_active = int(active.sum())
        used = proposed = 0
        for s in np.nonzero(active)[0]:
            slot = self.slots[s]
            emit = min(int(accepted[s]) + 1, int(remaining[s]))
            # The draft really proposed min(k, remaining) tokens for this
            # slot — the propose loop masks rows past the live window, so
            # horizon truncation is not a draft failure and must not read
            # as rejection in the acceptance rate.
            proposed += min(k, int(remaining[s]))
            used += min(int(accepted[s]), emit)
            for i in range(emit):
                tok = int(out[s, i])
                slot.produced += 1
                self.pos[s] += 1
                self.last_tok[s] = tok
                done = slot.produced >= slot.max_new
                if done:
                    self._retire(s)
                events.append(TokenEvent(int(s), tok, first=False,
                                         done=done))
        self.decode_tokens += len(events)
        self.last_spec = {"k": k, "slots": n_active,
                          "proposed": proposed, "accepted": used,
                          "rejected": proposed - used,
                          "emitted": len(events)}
        return events

    def retire(self, s: int) -> None:
        """Retire slot ``s`` early, before its ``max_new`` horizon — the
        scheduler's EOS path. The slot's WHOLE reservation (written blocks
        and the never-to-be-written worst-case tail alike) returns to the
        pool at this token boundary. Safe at any phase: the freed blocks'
        stale K/V is unreachable once the table row resets to trash, and
        a future owner overwrites before it reads (position masking)."""
        if self.slots[s] is None:
            raise ValueError(f"retire({s}): slot is not active")
        self._retire(s)

    def _retire(self, s: int) -> None:
        """Free the slot and its blocks IMMEDIATELY (the continuous-batching
        point: the next token boundary can re-use them). Under CoW the
        free is a refcount decrement for shared blocks; blocks that
        actually return to the pool lose their prefix-cache entries (a
        later admission must never map a block the allocator may have
        re-granted)."""
        freed = self.allocator.free(self.slots[s].blocks)
        for b in freed:
            key = self._block_key.pop(b, None)
            if key is not None:
                self._prefix_blocks.pop(key, None)
        self.slots[s] = None
        self.tables[s] = TRASH_BLOCK
        self.pos[s] = 0
        self.temps[s] = 0.0
