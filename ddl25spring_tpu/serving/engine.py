"""Step-level serving engine: prefill()/decode_step() over a fixed slot axis.

`models/generate.py` fuses prefill + the whole decode horizon into one
compiled scan — perfect for a bench, useless for a server, where the batch
composition changes at every token boundary. This engine refactors the same
math into TWO reusable compiled programs over a fixed slot axis ``[S]``:

- ``prefill_chunk``: one slot's prompt chunk ``[1, Tc]`` through the model,
  writing K/V into the slot's pool blocks; the FINAL chunk also samples the
  first token (TTFT). Chunking lets a long prompt interleave with in-flight
  decode instead of stalling it — the scheduler advances one chunk per
  token boundary.
- ``decode_step``: one token for ALL slots ``[S]`` at once — per-slot
  position, RNG key, temperature and active-mask ride in the slot state, so
  admissions/retirements between steps never recompile anything.

Both are compiled exactly once per engine (static shapes; the pool is
donated so XLA updates blocks in place), and both are built from the same
building blocks as ``generate`` — ``_fuse_blocks``, ``llama.embed/head``,
the fp32-softmax attention layout of ``_attend_cached`` — deliberately
op-for-op, because the acceptance bar is BITWISE: a request decoded here,
at any slot, in any company, must emit exactly the tokens ``generate()``
emits for it alone (tests/test_generate.py, tests/test_serving.py).

The bitwise-parity constraints that shaped the code:
- Every op is row-independent (norms, matmuls, softmax-per-row, per-slot
  RNG), so batch company cannot leak between slots.
- The gathered cache is padded to ``paged.max_seq_len`` and masked by
  absolute position; masked garbage contributes exact zeros through
  softmax (``exp(-inf) = 0``), same as ``generate``'s unwritten tail —
  parity tests run ``generate(max_len=paged.max_seq_len)`` so both sides
  reduce over identically-shaped score rows.
- Per-slot sampling keeps ``generate``'s exact RNG discipline: split the
  slot key every step, sample from the sub-key — so equal seeds give equal
  streams. Temperature is a traced per-slot scalar (greedy selected by a
  ``where``, both branches computed); top_k/top_p stay engine-static, the
  same filters ``_sample`` applies.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import LlamaConfig
from .. import nn
from ..models import generate, llama
from .kvcache import (TRASH_BLOCK, BlockAllocator, PagedKVConfig, blocks_for,
                      init_pool)


def check_swappable(old, new) -> None:
    """Raise unless ``new`` matches ``old`` leaf-for-leaf in tree
    structure, shape and dtype — the equal-tree contract every weight
    hot-swap must satisfy (a mismatch would silently retrace the two
    compiled programs). Shared by ``Engine.swap_params`` (per-engine
    enforcement) and ``ServingFleet.publish`` (fail a bad publish
    ATOMICALLY, before any engine pops from the rollout)."""
    o_leaves, o_def = jax.tree_util.tree_flatten(old)
    n_leaves, n_def = jax.tree_util.tree_flatten(new)
    if o_def != n_def:
        raise ValueError("swap_params: new params tree structure does "
                         "not match the serving engine's")
    for o, n in zip(o_leaves, n_leaves):
        if o.shape != n.shape or o.dtype != n.dtype:
            raise ValueError(
                f"swap_params: leaf mismatch {n.shape}/{n.dtype} vs "
                f"engine's {o.shape}/{o.dtype} — a shape change would "
                "retrace the engine's two compiled programs")


def _match_placement(new, old):
    """Return ``new`` placed EXACTLY like ``old`` (device + committed-ness,
    leaf by leaf). The jit cache key includes argument placement, so a
    hot-swapped tree must be indistinguishable in placement from the boot
    params or both compiled programs would silently retrace — and a tree
    restored from a checkpoint arrives device_put-COMMITTED while
    ``init_llama``'s boot params are uncommitted. Shedding a commitment
    requires a host bounce (there is no uncommit-in-place); that is one
    params-sized copy per publish, trivial next to the disk read that
    produced the tree."""
    def fix(n, o):
        if not isinstance(n, jax.Array) or not isinstance(o, jax.Array):
            return n
        nc = bool(getattr(n, "committed", False))
        oc = bool(getattr(o, "committed", False))
        if oc:
            return n if nc and n.sharding == o.sharding \
                else jax.device_put(n, o.sharding)
        return n if not nc else jnp.asarray(np.asarray(n))
    return jax.tree.map(fix, new, old)


# ------------------------------------------------------------- paged forward

def _attend_paged(q: jnp.ndarray, ck: jnp.ndarray, cv: jnp.ndarray,
                  q_positions: jnp.ndarray) -> jnp.ndarray:
    """``generate._attend_cached`` with a PER-SLOT position mask: q
    [S, Tq, H, Dh] over the gathered cache [S, Tmax, H, Dh], masked to
    ``kpos <= q_position`` per (slot, query-row). Identical layout and op
    sequence (fp32 softmax, heads folded into batch) so per-row numerics
    match the contiguous-cache path bitwise."""
    b, tq, h, dh = q.shape
    tmax = ck.shape[1]
    scale = 1.0 / math.sqrt(dh)
    qm = q.transpose(0, 2, 1, 3).reshape(b * h, tq, dh)
    km = ck.transpose(0, 2, 1, 3).reshape(b * h, tmax, dh).astype(q.dtype)
    vm = cv.transpose(0, 2, 1, 3).reshape(b * h, tmax, dh).astype(q.dtype)
    scores = lax.dot_general(qm, km, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32) * scale
    qpos = jnp.broadcast_to(q_positions[:, None, :], (b, h, tq))
    mask = qpos.reshape(b * h, tq)[:, :, None] >= jnp.arange(tmax)[None, None, :]
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = lax.dot_general(probs, vm, (((2,), (1,)), ((0,), (0,))))
    return out.reshape(b, h, tq, dh).transpose(0, 2, 1, 3)


def _apply_rope_slots(x: jnp.ndarray, cos: jnp.ndarray,
                      sin: jnp.ndarray) -> jnp.ndarray:
    """``llama.apply_rope`` with per-slot tables: cos/sin [S, T, half]
    instead of the shared [T, half] (slots sit at different absolute
    positions). Same rotation arithmetic, elementwise."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    c = cos[:, :, None, :].astype(x.dtype)
    s = sin[:, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], axis=-1)


def _block_paged(block: dict, pk: jnp.ndarray, pv: jnp.ndarray,
                 x: jnp.ndarray, positions: jnp.ndarray,
                 tables: jnp.ndarray, wblk: jnp.ndarray, woff: jnp.ndarray,
                 cfg: LlamaConfig):
    """One pre-fused block over x [S, T, D] at per-slot absolute
    ``positions`` [S, T], writing this call's K/V into pool blocks at
    (``wblk``, ``woff``) [S, T] and attending over each slot's gathered
    block table. The paged twin of ``generate._block_with_cache``; the
    scatter/gather replaces its dynamic_update_slice/full-cache read, the
    math around them is identical."""
    s, t, d = x.shape
    dh = cfg.head_dim
    xn = nn.rmsnorm(block["attn_norm"], x, eps=cfg.norm_eps)
    qkv = xn @ block["w_qkv"].astype(x.dtype)
    dl = qkv.shape[-1] // 3
    h_local = dl // dh
    q = qkv[..., :dl].reshape(s, t, h_local, dh)
    k = qkv[..., dl:2 * dl].reshape(s, t, h_local, dh)
    v = qkv[..., 2 * dl:].reshape(s, t, h_local, dh)
    cos, sin = llama.rope_angles(positions.reshape(-1), dh, cfg.rope_theta)
    cos = cos.reshape(s, t, -1)
    sin = sin.reshape(s, t, -1)
    q = _apply_rope_slots(q, cos, sin)
    k = _apply_rope_slots(k, cos, sin)       # cached K is stored post-RoPE
    # Per-token scatter into the block pool. Distinct (block, offset)
    # targets are guaranteed by block ownership; only TRASH_BLOCK collides
    # (inactive slots, padded tails) and its contents are never read
    # un-masked.
    pk = pk.at[wblk, woff].set(k.astype(pk.dtype))
    pv = pv.at[wblk, woff].set(v.astype(pv.dtype))
    ck = pk[tables].reshape(s, -1, h_local, dh)    # [S, Tmax, H, Dh]
    cv = pv[tables].reshape(s, -1, h_local, dh)
    out = _attend_paged(q, ck, cv, positions)
    x = x + out.reshape(s, t, h_local * dh) @ block["wo"].astype(x.dtype)
    xn = nn.rmsnorm(block["mlp_norm"], x, eps=cfg.norm_eps)
    gu = xn @ block["w_gu"].astype(x.dtype)
    f = gu.shape[-1] // 2
    x = x + (jax.nn.silu(gu[..., :f]) * gu[..., f:]) @ block["w_down"].astype(x.dtype)
    return x, pk, pv


def _forward_paged(params: dict, fused_blocks: dict, tokens: jnp.ndarray,
                   pool: dict, tables: jnp.ndarray, positions: jnp.ndarray,
                   wblk: jnp.ndarray, woff: jnp.ndarray, cfg: LlamaConfig):
    """tokens [S, T] at per-slot absolute ``positions`` [S, T] → (hidden
    [S, T, D], updated pool). One lax.scan over the stacked layers,
    threading each layer's block-pool slice — the paged twin of
    ``generate._forward_fused`` (which threads cache slices)."""
    h = llama.embed(params, tokens, cfg)

    def body(carry, layer):
        block, pk, pv = layer
        out, pk, pv = _block_paged(block, pk, pv, carry, positions,
                                   tables, wblk, woff, cfg)
        return out, (pk, pv)

    h, (pk, pv) = lax.scan(body, h, (fused_blocks, pool["k"], pool["v"]))
    return h, {"k": pk, "v": pv}


def _sample_slot(key, logits: jnp.ndarray, temperature: jnp.ndarray,
                 top_k: Optional[int], top_p: Optional[float]) -> jnp.ndarray:
    """``generate._sample`` with a TRACED per-slot temperature: logits
    [1, V] → token [1]. Greedy (t == 0) is a ``where``-select over both
    branches instead of Python control flow, so one compile serves any
    per-slot mix; the sampled branch applies the SAME ``filter_logits``
    and ``categorical`` ops as ``generate`` (one filter implementation —
    the bitwise-parity bar depends on it)."""
    greedy = jnp.argmax(logits, axis=-1)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = generate.filter_logits(logits / safe_t, top_k, top_p)
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy)


# ------------------------------------------------------------ compiled steps

def make_prefill_chunk(cfg: LlamaConfig, paged: PagedKVConfig,
                       chunk_len: int, top_k: Optional[int],
                       top_p: Optional[float]):
    """One compiled program: one slot's prompt chunk [chunk_len] through the
    model, K/V scattered into the slot's blocks. Also computes the
    next-token sample from the chunk's last VALID row — the host uses it
    only for the final chunk (``generate`` splits its key exactly once
    after prefill, so intermediate chunks must not consume randomness:
    the caller passes the key only when ``is_final``)."""
    bl, mb = paged.block_len, paged.max_blocks_per_seq

    @partial(jax.jit, donate_argnums=(0,))
    def prefill_chunk(pool: dict, params: dict, fused: dict,
                      table_row: jnp.ndarray, tokens: jnp.ndarray,
                      start: jnp.ndarray, n_valid: jnp.ndarray,
                      key: jnp.ndarray, temperature: jnp.ndarray):
        start = jnp.asarray(start, jnp.int32)
        pos = start + jnp.arange(chunk_len, dtype=jnp.int32)       # [Tc]
        valid = jnp.arange(chunk_len) < n_valid
        blk_idx = jnp.minimum(pos // bl, mb - 1)
        wblk = jnp.where(valid, table_row[blk_idx], TRASH_BLOCK)
        woff = pos % bl
        h, pool = _forward_paged(params, fused, tokens[None], pool,
                                 table_row[None], pos[None],
                                 wblk[None], woff[None], cfg)
        # Logits of the last valid row only — the [1, 1, D] head matmul
        # ``generate`` performs (never the full [Tc, V] logits).
        last = jnp.take_along_axis(
            h, (n_valid - 1).reshape(1, 1, 1).astype(jnp.int32), axis=1)
        logits = llama.head(params, last, cfg)[:, 0, :]            # [1, V]
        key, sub = jax.random.split(key)
        tok = _sample_slot(sub, logits, temperature, top_k, top_p)
        return pool, tok[0], key

    return prefill_chunk


def make_decode_step(cfg: LlamaConfig, paged: PagedKVConfig,
                     num_slots: int, top_k: Optional[int],
                     top_p: Optional[float]):
    """One compiled program: one token for ALL ``num_slots`` slots. Each
    slot feeds back its last token at its own position, writes K/V into its
    own blocks (inactive slots write to trash), and samples with its own
    key/temperature. Admission, retirement and raggedness are pure data —
    the program never recompiles."""
    bl, mb = paged.block_len, paged.max_blocks_per_seq

    @partial(jax.jit, donate_argnums=(0,))
    def decode_step(pool: dict, params: dict, fused: dict,
                    tables: jnp.ndarray, last_tok: jnp.ndarray,
                    pos: jnp.ndarray, keys: jnp.ndarray,
                    temps: jnp.ndarray, active: jnp.ndarray):
        blk_idx = jnp.minimum(pos // bl, mb - 1)
        own = jnp.take_along_axis(tables, blk_idx[:, None], axis=1)[:, 0]
        wblk = jnp.where(active, own, TRASH_BLOCK)
        woff = pos % bl
        h, pool = _forward_paged(params, fused, last_tok[:, None], pool,
                                 tables, pos[:, None],
                                 wblk[:, None], woff[:, None], cfg)
        logits = llama.head(params, h, cfg)[:, 0, :]               # [S, V]
        split = jax.vmap(jax.random.split)(keys)                   # [S, 2, 2]
        subs = split[:, 1]
        # Only ACTIVE slots consume randomness: a slot still mid-prefill
        # (or free) must keep its key untouched, or its stream would start
        # shifted relative to ``generate``'s by however many decode steps
        # happened to run before its admission finished.
        new_keys = jnp.where(active[:, None], split[:, 0], keys)
        toks = jax.vmap(
            lambda k, l, t: _sample_slot(k, l[None], t, top_k, top_p)[0]
        )(subs, logits, temps)
        return pool, toks, new_keys

    return decode_step


# ----------------------------------------------------------------- the engine

class TokenEvent(NamedTuple):
    """One emitted token: ``first`` marks the TTFT token (sampled by the
    final prefill chunk), ``done`` that the slot retired with this token."""
    slot: int
    token: int
    first: bool
    done: bool


class _Slot:
    __slots__ = ("blocks", "prompt", "max_new", "produced", "prefill_off",
                 "phase", "seq")

    def __init__(self, blocks, prompt, max_new, seq):
        self.blocks = blocks          # owned pool block indices
        self.prompt = prompt          # np.int32 [Tp]
        self.max_new = max_new
        self.produced = 0
        self.prefill_off = 0          # tokens of prompt already prefilled
        self.phase = "prefill"        # "prefill" -> "decode"
        self.seq = seq                # admission order (prefill is FCFS by
                                      # THIS, not by slot index — a freed
                                      # low slot must not jump the line)


class Engine:
    """Slots + compiled steps + block plumbing. Queueing, time and
    telemetry live one layer up (scheduler.py); this class only knows how
    to admit a request into a free slot, advance prefill by one chunk,
    decode one token for everyone, and retire finished slots (freeing
    their blocks immediately).

    ``step()`` is one token boundary: at most one prefill chunk (FCFS over
    mid-prefill slots — the chunked-prefill interleave), then one decode
    step if any slot is decoding. Returns the ``TokenEvent``s produced.
    """

    def __init__(self, params: dict, cfg: LlamaConfig, paged: PagedKVConfig,
                 num_slots: int, *, prefill_chunk: int = 16,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 engine_id: Optional[int] = None):
        if num_slots < 1 or prefill_chunk < 1:
            raise ValueError(f"num_slots={num_slots}, "
                             f"prefill_chunk={prefill_chunk}")
        self.cfg = cfg
        self.paged = paged
        self.num_slots = num_slots
        self.prefill_chunk_len = prefill_chunk
        # Fleet seam (serving/fleet.py): which replica this engine is.
        # Purely a label — it tags the compile-watch names below (so an
        # N-engine run's 2N compile events attribute per engine) and rides
        # through the scheduler into request_*/route/deploy telemetry.
        self.engine_id = engine_id
        self.params = params
        self.fused = generate._fuse_blocks(params["blocks"])  # hoisted once
        self.pool = init_pool(cfg, paged)
        self.allocator = BlockAllocator(paged.num_blocks)
        self._admit_seq = 0
        self.slots: List[Optional[_Slot]] = [None] * num_slots
        # Host-side slot state, shipped to the device each step as COPIES
        # (jnp.array, never jnp.asarray: a zero-copy handoff would freeze
        # these buffers read-only under the host's feet on the CPU
        # backend). Tiny [S] rows; only the pool is device-resident and
        # donated. Keys live device-side: decode returns the split batch.
        self.tables = np.full((num_slots, paged.max_blocks_per_seq),
                              TRASH_BLOCK, np.int32)
        self.pos = np.zeros(num_slots, np.int32)
        self.last_tok = np.zeros(num_slots, np.int32)
        self.temps = np.zeros(num_slots, np.float32)
        self.keys = jnp.zeros((num_slots, 2), jnp.uint32)
        # Compile/retrace observability (telemetry/introspect.py): the
        # engine's contract is EXACTLY two compiled programs — admission,
        # retirement and raggedness are data, never shapes. The watches
        # enforce that as a budget (growth past one cache entry each is a
        # flagged retrace) and emit ``compile`` events once the scheduler
        # binds its event stream (introspect.bind_events).
        from ..telemetry import introspect
        tag = "" if engine_id is None else f"[{engine_id}]"
        self._prefill = introspect.watch(
            make_prefill_chunk(cfg, paged, prefill_chunk, top_k, top_p),
            name=f"serving/prefill_chunk{tag}", max_caches=1)
        self._decode = introspect.watch(
            make_decode_step(cfg, paged, num_slots, top_k, top_p),
            name=f"serving/decode_step{tag}", max_caches=1)

    # ------------------------------------------------------------- admission
    def required_blocks(self, prompt_len: int, max_new: int) -> int:
        """Positions written are ``0..prompt_len+max_new-2`` (the final
        sampled token is never fed back — ``generate``'s horizon)."""
        return blocks_for(prompt_len + max_new - 1, self.paged.block_len)

    def free_slot(self) -> Optional[int]:
        for s, slot in enumerate(self.slots):
            if slot is None:
                return s
        return None

    def can_admit(self, prompt_len: int, max_new: int) -> bool:
        return (self.free_slot() is not None
                and self.required_blocks(prompt_len, max_new)
                <= self.allocator.free_blocks)

    def admit(self, prompt, max_new: int, *, temperature: float = 0.0,
              key: Optional[jax.Array] = None) -> int:
        """Place a request into a free slot and reserve its WORST-CASE
        blocks up front. All-or-nothing reservation is the liveness
        guarantee: an admitted request can always run to completion, so
        pool exhaustion can only ever queue admissions, never deadlock
        in-flight work (scheduler.py holds the policy argument)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        tp, mx = len(prompt), int(max_new)
        if tp < 1 or mx < 1:
            raise ValueError(f"empty request: prompt_len={tp}, max_new={mx}")
        if tp + mx - 1 > self.paged.max_seq_len:
            raise ValueError(
                f"request needs {tp + mx - 1} cache positions but the pool "
                f"serves at most max_blocks_per_seq * block_len = "
                f"{self.paged.max_seq_len}")
        s = self.free_slot()
        if s is None:
            raise RuntimeError("no free slot")
        blocks = self.allocator.alloc(self.required_blocks(tp, mx))
        if blocks is None:
            raise RuntimeError("pool exhausted")
        self._admit_seq += 1
        self.slots[s] = _Slot(blocks, prompt, mx, self._admit_seq)
        self.tables[s] = TRASH_BLOCK
        self.tables[s, :len(blocks)] = blocks
        self.pos[s] = 0
        self.temps[s] = float(temperature)
        if key is None:
            if temperature > 0:
                raise ValueError("sampling (temperature>0) requires a key")
            key = jax.random.PRNGKey(0)      # unused by greedy (generate's
        self.keys = self.keys.at[s].set(key)  # own placeholder convention)
        return s

    # ----------------------------------------------------------- one boundary
    @property
    def busy(self) -> bool:
        return any(slot is not None for slot in self.slots)

    def blocks_in_use(self) -> int:
        return self.allocator.in_use

    # ------------------------------------------------------- weight hot-swap
    def swap_params(self, params: dict, *, fused: Optional[dict] = None
                    ) -> None:
        """Swap to new weights at the CURRENT token boundary — the live
        train→deploy seam (serving/deploy.py). Legal between ``step()``
        calls only (the host drives the engine, so outside a ``step()``
        nothing is in flight by construction); in-flight streams are NOT
        dropped — their next token is sampled under the new weights over
        the KV each slot already wrote, and nothing already emitted
        changes (the hot-swap determinism bar in
        tests/test_fleet_serving.py: a same-weights swap is bitwise
        invisible; a new-weights swap changes only tokens sampled after
        the boundary).

        The new tree must match the old one leaf-for-leaf in shape and
        dtype: params are DATA to the two compiled programs, so an equal
        tree swaps with zero recompiles (the engine's two-programs
        contract survives any number of publishes), while a different
        shape would silently retrace — rejected loudly instead. Placement
        is normalized to the boot params' (``_match_placement``) for the
        same reason: a checkpoint-restored tree arrives committed, and
        committed-ness is part of the jit cache key.

        ``fused`` (the ``generate._fuse_blocks`` view of ``params``) can
        be passed precomputed so an N-engine fleet fuses once per publish,
        not once per engine."""
        check_swappable(self.params, params)
        self.params = _match_placement(params, self.params)
        self.fused = (_match_placement(fused, self.fused)
                      if fused is not None
                      else generate._fuse_blocks(self.params["blocks"]))

    def step(self) -> List[TokenEvent]:
        """One token boundary: one prefill chunk (if a slot is mid-prefill),
        then one decode step over the decoding slots."""
        events: List[TokenEvent] = []
        prefilling = [(sl.seq, i) for i, sl in enumerate(self.slots)
                      if sl is not None and sl.phase == "prefill"]
        if prefilling:
            events.extend(self._advance_prefill(min(prefilling)[1]))
        if any(sl is not None and sl.phase == "decode" for sl in self.slots):
            events.extend(self._advance_decode())
        return events

    def _advance_prefill(self, s: int) -> List[TokenEvent]:
        slot = self.slots[s]
        tc = self.prefill_chunk_len
        off = slot.prefill_off
        n_valid = min(tc, len(slot.prompt) - off)
        chunk = np.zeros(tc, np.int32)
        chunk[:n_valid] = slot.prompt[off:off + n_valid]
        is_final = off + n_valid >= len(slot.prompt)
        self.pool, tok, new_key = self._prefill(
            self.pool, self.params, self.fused,
            jnp.array(self.tables[s]), jnp.array(chunk),
            jnp.int32(off), jnp.int32(n_valid),
            self.keys[s], jnp.float32(self.temps[s]))
        slot.prefill_off = off + n_valid
        if not is_final:
            # Intermediate chunk: K/V written; the sampled token and split
            # key are discarded so the slot's RNG stream stays exactly
            # generate's (one split for the whole prefill).
            return []
        self.keys = self.keys.at[s].set(new_key)
        first = int(tok)
        slot.phase = "decode"
        slot.produced = 1
        self.pos[s] = len(slot.prompt)
        self.last_tok[s] = first
        done = slot.produced >= slot.max_new
        if done:
            self._retire(s)
        return [TokenEvent(s, first, first=True, done=done)]

    def _advance_decode(self) -> List[TokenEvent]:
        active = np.array([sl is not None and sl.phase == "decode"
                           for sl in self.slots])
        self.pool, toks, new_keys = self._decode(
            self.pool, self.params, self.fused,
            jnp.array(self.tables), jnp.array(self.last_tok),
            jnp.array(self.pos), self.keys,
            jnp.array(self.temps), jnp.array(active))
        toks = np.asarray(toks)
        self.keys = new_keys
        events = []
        for s in np.nonzero(active)[0]:
            slot = self.slots[s]
            tok = int(toks[s])
            slot.produced += 1
            self.pos[s] += 1
            self.last_tok[s] = tok
            done = slot.produced >= slot.max_new
            if done:
                self._retire(s)
            events.append(TokenEvent(int(s), tok, first=False, done=done))
        return events

    def retire(self, s: int) -> None:
        """Retire slot ``s`` early, before its ``max_new`` horizon — the
        scheduler's EOS path. The slot's WHOLE reservation (written blocks
        and the never-to-be-written worst-case tail alike) returns to the
        pool at this token boundary. Safe at any phase: the freed blocks'
        stale K/V is unreachable once the table row resets to trash, and
        a future owner overwrites before it reads (position masking)."""
        if self.slots[s] is None:
            raise ValueError(f"retire({s}): slot is not active")
        self._retire(s)

    def _retire(self, s: int) -> None:
        """Free the slot and its blocks IMMEDIATELY (the continuous-batching
        point: the next token boundary can re-use them)."""
        self.allocator.free(self.slots[s].blocks)
        self.slots[s] = None
        self.tables[s] = TRASH_BLOCK
        self.pos[s] = 0
        self.temps[s] = 0.0
