"""Paged KV cache: a fixed-size device block pool + a host-side allocator.

The serving-side answer to `models/generate.py`'s whole-batch cache
(ISSUE 6 tentpole, ROADMAP item 2): `generate()` gives every request its
own ``[L, B, max_len, H, Dh]`` cache sized for the worst case, so N
concurrent mixed-length streams pay N · max_len positions of HBM whether
they use them or not. Here sequences share ONE pool of fixed-size blocks
(vLLM's PagedAttention allocation scheme, mapped onto this repo's
static-shape/one-compile discipline):

- The device side is a pair of static-shape arrays ``[L, num_blocks,
  block_len, H, Dh]`` (layer-major, so the engine's per-layer ``lax.scan``
  threads one block-pool slice per layer exactly like ``generate``'s cache).
  ``kv_dtype`` reuses ``init_cache``'s storage-dtype option: bf16 blocks
  halve the decode loop's dominant HBM stream (experiments/ROOFLINE.md,
  decode section — the batch-32 KV-bound regime is the serving case).
- The host side is a free-list allocator handing out block *indices*; each
  live sequence owns a row of a ``[num_slots, max_blocks_per_seq]`` block
  table mapping its logical positions to pool blocks. Attention gathers a
  sequence's blocks through its table row, so physical placement never
  affects the math (pinned bitwise in tests/test_serving.py).
- Block 0 is reserved as the TRASH block: inactive slots and padded
  prefill tail tokens route their cache *writes* there (a static-shape
  program always writes somewhere), and unallocated table entries point at
  it. Garbage in trash is never read un-masked — decode attention masks by
  absolute position (``kpos <= pos``), the same invariant that makes
  ``generate``'s unwritten cache tail safe.

Sizing math (docs/COMPONENTS.md "Serving" carries the worked example):
one block holds ``2 · L · block_len · H · Dh · itemsize`` bytes of K+V;
a request of prompt ``P`` generating ``M`` tokens writes positions
``0..P+M-2`` (the final sampled token is never fed back — same horizon as
``generate``'s scan) and therefore needs ``ceil((P+M-1)/block_len)``
blocks. The pool is intentionally sized BELOW peak naive demand
(N_concurrent · max_len): admission control queues requests the free list
cannot cover, and retirement frees blocks at the next token boundary.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax.numpy as jnp

from ..config import LlamaConfig

# Block index 0 is never allocated: it absorbs the writes of inactive
# slots / padded prefill tails so every compiled step can write
# unconditionally at a static shape.
TRASH_BLOCK = 0


@dataclass(frozen=True)
class PagedKVConfig:
    """Pool geometry. ``num_blocks`` INCLUDES the reserved trash block, so
    ``num_blocks - 1`` blocks are allocatable. ``max_blocks_per_seq``
    bounds one sequence's block-table row; ``max_seq_len`` is the longest
    prompt+generation the engine can serve (and the padded length every
    attention gather sees — one compile, any mix of live lengths)."""

    num_blocks: int
    block_len: int
    max_blocks_per_seq: int
    kv_dtype: Optional[str] = None

    def __post_init__(self):
        if self.num_blocks < 2:
            raise ValueError(f"num_blocks={self.num_blocks}: need at least "
                             "one allocatable block beside the trash block")
        if self.block_len < 1 or self.max_blocks_per_seq < 1:
            raise ValueError(f"bad pool geometry: {self}")

    @property
    def max_seq_len(self) -> int:
        return self.block_len * self.max_blocks_per_seq


def blocks_for(n_tokens: int, block_len: int) -> int:
    """Blocks needed to hold ``n_tokens`` cache positions."""
    return -(-max(0, n_tokens) // block_len)


def init_pool(cfg: LlamaConfig, paged: PagedKVConfig) -> dict:
    """Zeroed block pool: {"k","v"} each [L, num_blocks, block_len, H, Dh].
    Layer-major for the same reason ``init_cache`` is: the engine scans the
    leading axis, threading one layer's blocks per scan step."""
    dt = jnp.dtype(paged.kv_dtype or cfg.dtype)
    shape = (cfg.n_layers, paged.num_blocks, paged.block_len,
             cfg.num_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}


def kv_bytes_per_token(cfg: LlamaConfig,
                       kv_dtype: Optional[str] = None) -> int:
    """K+V bytes one cache position occupies across all layers."""
    dt = jnp.dtype(kv_dtype or cfg.dtype)
    return 2 * cfg.n_layers * cfg.num_heads * cfg.head_dim * dt.itemsize


def pool_bytes(cfg: LlamaConfig, paged: PagedKVConfig) -> int:
    """Total device bytes of the block pool (the serving KV footprint)."""
    return (paged.num_blocks * paged.block_len
            * kv_bytes_per_token(cfg, paged.kv_dtype))


def naive_cache_bytes(cfg: LlamaConfig, n_streams: int, max_len: int,
                      kv_dtype: Optional[str] = None) -> int:
    """What ``generate`` would allocate for ``n_streams`` concurrent
    requests: one whole ``max_len`` cache each. The smoke asserts
    ``pool_bytes < naive_cache_bytes`` at peak concurrency — the paged
    pool's reason to exist."""
    return n_streams * max_len * kv_bytes_per_token(cfg, kv_dtype)


class BlockAllocator:
    """Host-side free list over block indices ``1..num_blocks-1``, with
    per-block REFERENCE COUNTS for copy-on-write prefix sharing.

    ``alloc`` is all-or-nothing (a sequence's full reservation or None) so
    admission control can never strand a half-provisioned request — the
    liveness argument in scheduler.py rests on this. Lowest-index-first
    hand-out keeps runs reproducible; block identity never reaches the
    math (attention gathers through the table), so the order is a
    debugging nicety, not a correctness requirement.

    Sharing (ROADMAP 2c): ``share`` takes additional references on
    already-allocated blocks — requests whose prompts share a full-block
    prefix map the SAME physical blocks read-only (the engine masks their
    writes to trash), so N identical prefixes cost one block set plus
    refcounts instead of N. ``free`` decrements and returns a block to
    the free list only at zero — and reports which blocks PHYSICALLY
    freed, so the engine can evict their prefix-cache entries. ``in_use``
    and ``peak_in_use`` count physical blocks: the peak DROPPING on a
    shared-prefix workload is the satellite's acceptance bar.
    """

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError(f"num_blocks={num_blocks}: nothing to allocate")
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, 0, -1))   # pop() -> lowest
        self._refs: dict = {}            # block -> live references
        self.peak_in_use = 0

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.capacity - len(self._free)

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    def fragmentation(self) -> dict:
        """Free-list fragmentation census (schema v9 ``memory`` events):
        ``holes`` is the number of maximal contiguous index runs the free
        list has shattered into, ``largest_run`` the longest of them — the
        biggest single reservation the pool could grant contiguously. An
        empty free list is 0 holes / 0 run; a fully-free pool is exactly 1
        hole spanning ``capacity``. O(free) over a sorted copy — called at
        meter cadence (scheduler ticks), never per token."""
        if not self._free:
            return {"holes": 0, "largest_run": 0}
        holes, run, largest = 1, 1, 1
        ordered = sorted(self._free)
        for prev, cur in zip(ordered, ordered[1:]):
            if cur == prev + 1:
                run += 1
            else:
                holes += 1
                run = 1
            largest = max(largest, run)
        return {"holes": holes, "largest_run": largest}

    @property
    def holes(self) -> int:
        return self.fragmentation()["holes"]

    @property
    def largest_run(self) -> int:
        return self.fragmentation()["largest_run"]

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` blocks, or None if the pool cannot cover them (caller
        queues — never a partial grant)."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n)]
        for b in got:
            self._refs[b] = 1
        self.peak_in_use = max(self.peak_in_use, self.in_use)
        return got

    def share(self, blocks: List[int]) -> None:
        """Take one more reference on each (already-allocated) block —
        the CoW mapping step. Never touches the free list, so it can
        never fail for capacity and never moves the physical peak."""
        for b in blocks:
            if self._refs.get(b, 0) < 1:
                raise ValueError(f"share({b}): block is not allocated")
        for b in blocks:
            self._refs[b] += 1

    def free(self, blocks: List[int]) -> List[int]:
        """Drop one reference per block; blocks reaching zero return to
        the free list. Returns the PHYSICALLY freed blocks (refcount hit
        zero) so prefix-cache entries can be evicted with them."""
        for b in blocks:
            if not 1 <= b < self.num_blocks:
                raise ValueError(f"free({b}): not an allocatable block")
        counts: dict = {}
        for b in blocks:
            counts[b] = counts.get(b, 0) + 1
        for b, n in counts.items():
            if self._refs.get(b, 0) < n:
                raise ValueError(f"free({b}): double free")
        freed = []
        for b, n in counts.items():
            self._refs[b] -= n
            if self._refs[b] == 0:
                del self._refs[b]
                freed.append(b)
        # Re-sort so the free list stays lowest-first regardless of
        # retirement order — allocation traces depend only on the
        # alloc/free sequence, not on which request finished first.
        if freed:
            self._free = sorted(set(self._free) | set(freed), reverse=True)
        return freed
